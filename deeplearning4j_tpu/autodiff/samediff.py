"""SameDiff graph: define-then-run symbolic autodiff, compiled whole to XLA.

Reference: org.nd4j.autodiff.samediff.SameDiff / SDVariable /
TrainingConfig; execution in the reference walks the graph op-by-op in an
InferenceSession, and autodiff builds a backward graph by transformation
(SameDiff.calculateGradients).

TPU design: the op list IS a trace recipe. Executing (or differentiating)
the graph builds one pure JAX function over (variables, placeholders) and
compiles it with jax.jit into a single XLA computation — no interpreter
loop, no backward-graph surgery (jax.grad of the traced function), static
shapes so XLA tiles matmuls onto the MXU.
"""

from __future__ import annotations

import json
import zipfile
import io

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.ops_impl import OPS

_STOCHASTIC_OPS = frozenset(
    {"randomNormal", "randomUniform", "randomBernoulli",
     "randomExponential"})
from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.nn import updaters as _upd
from deeplearning4j_tpu.nn import weights as _weights
from deeplearning4j_tpu.ndarray import random as _random


class VariableType:
    """Reference: org.nd4j.autodiff.samediff.VariableType."""

    PLACEHOLDER = "PLACEHOLDER"
    VARIABLE = "VARIABLE"   # trainable
    CONSTANT = "CONSTANT"
    ARRAY = "ARRAY"         # op output


def _unwrap(x):
    if isinstance(x, INDArray):
        return x.jax()
    return jnp.asarray(x)


class SDVariable:
    """Symbolic handle into a SameDiff graph (reference: SDVariable).

    Operator overloads route through sd.math so `a * b + c` builds graph
    nodes exactly like explicit namespace calls.
    """

    def __init__(self, sd, name, vtype):
        self.sd = sd
        self.name = name
        self.variableType = vtype

    # -- graph-building sugar --
    def _bin(self, opname, other, reverse=False):
        other = self.sd._lift(other)
        a, b = (other, self) if reverse else (self, other)
        return self.sd._op(opname, [a, b])

    def __add__(self, o): return self._bin("add", o)
    def __radd__(self, o): return self._bin("add", o, True)
    def __sub__(self, o): return self._bin("sub", o)
    def __rsub__(self, o): return self._bin("sub", o, True)
    def __mul__(self, o): return self._bin("mul", o)
    def __rmul__(self, o): return self._bin("mul", o, True)
    def __truediv__(self, o): return self._bin("div", o)
    def __rtruediv__(self, o): return self._bin("div", o, True)
    def __pow__(self, o): return self._bin("pow", o)
    def __neg__(self): return self.sd._op("neg", [self])
    def __matmul__(self, o): return self._bin("mmul", o)

    def add(self, o): return self._bin("add", o)
    def sub(self, o): return self._bin("sub", o)
    def mul(self, o): return self._bin("mul", o)
    def div(self, o): return self._bin("div", o)
    def rsub(self, o): return self._bin("sub", o, True)
    def rdiv(self, o): return self._bin("div", o, True)
    def mmul(self, o): return self._bin("mmul", o)
    def dot(self, o):
        return self.sd._op("sum", [self._bin("mul", o)])

    def neg(self): return self.sd._op("neg", [self])

    def sum(self, *dimensions, keepDims=False):
        return self.sd._op("sum", [self],
                           {"dimensions": list(dimensions) or None,
                            "keepDims": keepDims})

    def mean(self, *dimensions, keepDims=False):
        return self.sd._op("mean", [self],
                           {"dimensions": list(dimensions) or None,
                            "keepDims": keepDims})

    def std(self, *dimensions):
        return self.sd._op("std", [self],
                           {"dimensions": list(dimensions) or None})

    def norm2(self, *dimensions):
        return self.sd._op("norm2", [self],
                           {"dimensions": list(dimensions) or None})

    def argmax(self, dimension=None):
        return self.sd._op(
            "argmax", [self],
            {"dimensions": None if dimension is None else [dimension]})

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return self.sd._op("reshape", [self], {"shape": list(shape)})

    def permute(self, *dims):
        return self.sd._op("permute", [self], {"dimensions": list(dims)})

    def transpose(self):
        return self.sd._op("transpose", [self])

    def get(self, *idx):
        """Static strided view (reference: SDVariable.get(SDIndex...))."""
        begin, end, strides = [], [], []
        shp = self.shape

        def norm(v, i):
            return v + shp[i] if v < 0 else v

        for i, ix in enumerate(idx):
            if isinstance(ix, slice):
                begin.append(norm(ix.start or 0, i))
                end.append(shp[i] if ix.stop is None else norm(ix.stop, i))
                strides.append(ix.step or 1)
            else:
                p = norm(int(ix), i)
                begin.append(p)
                end.append(p + 1)
                strides.append(1)
        for i in range(len(idx), len(shp)):
            begin.append(0); end.append(shp[i]); strides.append(1)
        out = self.sd._op("stridedSlice", [self],
                          {"begin": begin, "end": end, "strides": strides})
        drop = [i for i, ix in enumerate(idx) if not isinstance(ix, slice)]
        return out if not drop else self.sd._op("squeeze", [out],
                                                {"axis": tuple(drop)})

    def castTo(self, dtype):
        return self.sd._op("cast", [self], {"dtype": str(np.dtype(dtype))})

    # -- state --
    def rename(self, new):
        self.sd._rename(self.name, new)
        return self

    @property
    def shape(self):
        return self.sd._shape_of(self.name)

    def getArr(self):
        """Current value (VARIABLE/CONSTANT) or eval with no placeholders."""
        if self.name in self.sd._arrays:
            return INDArray(self.sd._arrays[self.name])
        return self.eval()

    def setArray(self, arr):
        self.sd._arrays[self.name] = _unwrap(arr)

    def eval(self, placeholders=None):
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def markAsLoss(self):
        self.sd.setLossVariables(self.name)
        return self

    def __repr__(self):
        return f"SDVariable(name='{self.name}', type={self.variableType})"


class _Op:
    __slots__ = ("opName", "inputs", "outputs", "kwargs")

    def __init__(self, opName, inputs, outputs, kwargs):
        self.opName = opName
        self.inputs = inputs      # list[str]
        self.outputs = outputs    # list[str]
        self.kwargs = kwargs      # JSON-able dict


class TrainingConfig:
    """Reference: org.nd4j.autodiff.samediff.TrainingConfig (Builder)."""

    def __init__(self, updater=None, dataSetFeatureMapping=None,
                 dataSetLabelMapping=None, l1=0.0, l2=0.0, weightDecay=0.0,
                 lossVariables=None):
        self.updater = updater or _upd.Adam()
        self.dataSetFeatureMapping = dataSetFeatureMapping or []
        self.dataSetLabelMapping = dataSetLabelMapping or []
        self.l1 = l1
        self.l2 = l2
        self.weightDecay = weightDecay
        self.lossVariables = lossVariables

    class Builder:
        def __init__(self):
            self._kw = {}

        def updater(self, u):
            self._kw["updater"] = u
            return self

        def dataSetFeatureMapping(self, *names):
            self._kw["dataSetFeatureMapping"] = list(names)
            return self

        def dataSetLabelMapping(self, *names):
            self._kw["dataSetLabelMapping"] = list(names)
            return self

        def l1(self, v): self._kw["l1"] = v; return self
        def l2(self, v): self._kw["l2"] = v; return self
        def weightDecay(self, v): self._kw["weightDecay"] = v; return self

        def build(self):
            return TrainingConfig(**self._kw)


class SameDiff:
    """The graph container + compiler front-end (reference: SameDiff.create()).

    Ops are appended in definition order; because a variable must exist
    before it is used, definition order IS a topological order and the
    backward slice of any output set is a valid trace program.
    """

    def __init__(self):
        self._vars = {}        # name -> SDVariable
        self._arrays = {}      # name -> jnp array (VARIABLE/CONSTANT)
        self._ops = []         # list[_Op]
        self._producer = {}    # out name -> op index
        self._counter = 0
        self._scopes = []  # active withNameScope stack
        self._loss_vars = []
        self._tc = None
        self._iteration = 0
        self._jit_cache = {}
        # namespaces (reference: sd.math(), sd.nn(), ...)
        self.math = _MathOps(self)
        self.nn = _NNOps(self)
        self.cnn = _CNNOps(self)
        self.rnn = _RNNOps(self)
        self.loss = _LossOps(self)
        self.image = _ImageOps(self)
        self.linalg = _LinalgOps(self)
        self.bitwise = _BitwiseOps(self)
        self.random = _RandomOps(self)
        self.fft = _FFTOps(self)

    @staticmethod
    def create():
        return SameDiff()

    # ---------- variable creation ----------
    def _scoped(self, name):
        """Apply the active name-scope prefix (reference:
        SameDiff.withNameScope: names become "scope/name")."""
        return "/".join(self._scopes + [name]) if self._scopes else name

    def withNameScope(self, scope):
        """Context manager: variables created inside get "scope/"-prefixed
        names; scopes nest ("outer/inner/x"). Reference:
        SameDiff.withNameScope."""
        sd = self

        class _Scope:
            def __enter__(self_s):
                sd._scopes.append(str(scope))
                return sd

            def __exit__(self_s, *exc):
                sd._scopes.pop()
                return False

        return _Scope()

    def _name(self, base):
        self._counter += 1
        n = f"{base}_{self._counter}"
        while self._scoped(n) in self._vars:
            self._counter += 1
            n = f"{base}_{self._counter}"
        return n

    def _new_var(self, name, vtype):
        name = self._scoped(name)
        if name in self._vars:
            raise ValueError(f"variable '{name}' already exists")
        v = SDVariable(self, name, vtype)
        self._vars[name] = v
        return v

    def placeHolder(self, name, dtype=jnp.float32, *shape):
        v = self._new_var(name, VariableType.PLACEHOLDER)
        v._ph_shape = tuple(shape)
        v._ph_dtype = jnp.dtype(dtype)
        return v

    def var(self, name, *args, weightInit=None, shape=None, dtype=jnp.float32):
        """sd.var("w", 4, 5) / sd.var("w", init_array) — trainable."""
        v = self._new_var(name, VariableType.VARIABLE)
        # v.name, not name: _new_var applies the active name scope
        if len(args) == 1 and not isinstance(args[0], (int, np.integer)):
            self._arrays[v.name] = _unwrap(args[0])
        else:
            shp = tuple(shape) if shape else tuple(int(a) for a in args)
            scheme = weightInit or _weights.WeightInit.XAVIER
            fan_in = shp[0] if shp else 1
            fan_out = shp[-1] if shp else 1
            self._arrays[v.name] = _weights.init(
                _random.getRandom().nextKey(), scheme, shp, fan_in, fan_out,
                dtype)
        return v

    def constant(self, value, name=None):
        name = name or self._name("const")
        v = self._new_var(name, VariableType.CONSTANT)
        self._arrays[v.name] = _unwrap(value)
        return v

    def _lift(self, x):
        if isinstance(x, SDVariable):
            return x
        return self.constant(x)

    def _rename(self, old, new):
        if new in self._vars:
            raise ValueError(f"'{new}' already exists")
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        if old in self._arrays:
            self._arrays[new] = self._arrays.pop(old)
        if old in self._producer:
            self._producer[new] = self._producer.pop(old)
        for op in self._ops:
            op.inputs = [new if n == old else n for n in op.inputs]
            op.outputs = [new if n == old else n for n in op.outputs]
        self._loss_vars = [new if n == old else n for n in self._loss_vars]
        self._jit_cache.clear()

    # ---------- op registration ----------
    def _op(self, opName, inputs, kwargs=None, nOut=1, name=None):
        if opName not in OPS:
            raise ValueError(f"unknown op '{opName}'")
        in_names = [v.name for v in inputs]
        outs = []
        for i in range(nOut):
            base = name if name else opName
            n = base if (name and nOut == 1
                         and self._scoped(name) not in self._vars) \
                else self._name(base)
            # the op table must store the SCOPED name _new_var registers
            outs.append(self._new_var(n, VariableType.ARRAY).name)
        self._ops.append(_Op(opName, in_names, outs, kwargs or {}))
        idx = len(self._ops) - 1
        for n in outs:
            self._producer[n] = idx
        self._jit_cache.clear()
        out_vars = [self._vars[n] for n in outs]
        return out_vars[0] if nOut == 1 else tuple(out_vars)

    def getVariable(self, name):
        return self._vars[name]

    def variables(self):
        return [v for v in self._vars.values()
                if v.variableType == VariableType.VARIABLE]

    def setLossVariables(self, *names):
        self._loss_vars = [n.name if isinstance(n, SDVariable) else n
                           for n in names]

    # ---------- control flow (reference: nd4j-autodiff If / While ops) ----
    def ifCond(self, pred, trueBody, falseBody, inputs=(), nOut=1, name=None):
        """Conditional subgraph (reference: SameDiff.ifCond / the If op).

        pred: scalar SDVariable. trueBody/falseBody: ``lambda sd, *vars:
        SDVariable`` (or tuple of them) built on a fresh sub-SameDiff whose
        placeholders mirror ``inputs``. Lowered to ``lax.cond`` — both
        branches compile into the single XLA computation, one executes.
        Fully differentiable (jax.grad flows through lax.cond)."""
        ins = [self._lift(pred)] + [self._lift(v) for v in inputs]
        return self._op("if_cond", ins,
                        kwargs={"trueBody": trueBody, "falseBody": falseBody,
                                "trueGraph": self._record_body(
                                    trueBody, len(ins) - 1, "ifCond trueBody"),
                                "falseGraph": self._record_body(
                                    falseBody, len(ins) - 1,
                                    "ifCond falseBody")},
                        nOut=nOut, name=name)

    def whileLoop(self, condBody, loopBody, loopVars, maxIterations=None,
                  name=None):
        """While loop over subgraphs (reference: SameDiff.whileLoop / the
        While op). condBody(sd, *vars) -> scalar; loopBody(sd, *vars) ->
        updated vars (same structure as ``loopVars``).

        maxIterations=None lowers to ``lax.while_loop`` — a true dynamic
        trip count, inference-only (reverse-mode AD through an unbounded
        while is impossible). With maxIterations=N it lowers to a bounded
        ``lax.scan`` whose body is masked by the predicate — the TPU-
        idiomatic differentiable form: the EFFECTIVE iteration count stays
        data-dependent while the compiled program is static, so the loop
        trains under jit."""
        ins = [self._lift(v) for v in loopVars]
        return self._op("while_loop", ins,
                        kwargs={"condBody": condBody, "loopBody": loopBody,
                                "condGraph": self._record_body(
                                    condBody, len(ins), "whileLoop condBody"),
                                "loopGraph": self._record_body(
                                    loopBody, len(ins), "whileLoop loopBody"),
                                # coerced HERE (host side): the executor
                                # reads it under trace, where an int() call
                                # would be an implicit host sync (PUR02)
                                "maxIterations": (None if maxIterations is None
                                                  else int(maxIterations))},
                        nOut=len(ins), name=name)

    # aliases in jax idiom
    cond = ifCond
    while_loop = whileLoop

    _BODY_CALLABLE_KEYS = ("trueBody", "falseBody", "condBody", "loopBody")

    @staticmethod
    def _serializable_kwargs(kwargs):
        """Op kwargs minus the in-memory body callables (their recorded
        graph specs — *Graph keys — are the serialized form)."""
        return {k: v for k, v in kwargs.items()
                if k not in SameDiff._BODY_CALLABLE_KEYS}

    @staticmethod
    def _clean_spec_kwargs(kwargs, path, body_store):
        """Deep-copy op kwargs for graph.json: drop body callables,
        validate every (arbitrarily nested) recorded body, and move its
        constant arrays into `body_store` for the npz (JSON holds only
        the npz key — reference: FlatBuffers stores subgraph arrays in
        the same buffer as the main graph's)."""
        out = {}
        for k, v in kwargs.items():
            if k in SameDiff._BODY_CALLABLE_KEYS:
                continue
            if k.endswith("Graph") and isinstance(v, dict):
                if "unrecordable" in v:
                    raise NotImplementedError(
                        "Graph cannot be serialized: a control-flow body "
                        "could not be recorded as a subgraph "
                        f"({v['unrecordable']}). Bodies must be pure "
                        "graph-builders over their SDVariable arguments.")
                spec = dict(v)
                refs = {}
                for n, a in spec["arrays"].items():
                    npz_key = f"__body__/{path}/{k}/{n}"
                    body_store[npz_key] = np.asarray(a)
                    refs[n] = npz_key
                spec["arrays"] = refs
                spec["ops"] = [
                    {"op": o["op"], "inputs": o["inputs"],
                     "outputs": o["outputs"],
                     "kwargs": SameDiff._clean_spec_kwargs(
                         o["kwargs"], f"{path}/{k}/{j}", body_store)}
                    for j, o in enumerate(spec["ops"])]
                out[k] = spec
            else:
                out[k] = v
        return out

    @staticmethod
    def _resolve_spec_kwargs(kwargs, npz):
        """Inverse of _clean_spec_kwargs at load: swap npz keys back to
        arrays, recursively. Mutates the loaded dicts in place."""
        for k, v in kwargs.items():
            if k.endswith("Graph") and isinstance(v, dict) and "arrays" in v:
                v["arrays"] = {n: np.asarray(npz[ref])
                               for n, ref in v["arrays"].items()}
                for o in v["ops"]:
                    SameDiff._resolve_spec_kwargs(o["kwargs"], npz)

    @staticmethod
    def _record_body(build_fn, n_inputs, what=""):
        """Record a control-flow body as a serializable graph spec.

        The body is a graph-builder (it only appends symbolic ops), so it
        can be run once at definition time against shapeless placeholders
        named in0..in{k-1} — the same names _subgraph_fn uses at
        execution, which is what makes replay (_body_from_spec) exact.
        Reference: SameDiff's If/While store their subgraphs in the
        FlatBuffers file; this is the npz+json equivalent."""
        sub = SameDiff()
        phs = [sub.placeHolder(f"in{i}") for i in range(n_inputs)]
        try:
            out = build_fn(sub, *phs)
        except Exception as e:
            # definition must not fail just because the graph won't be
            # serializable; save() raises the clear error instead
            return {"unrecordable": f"{what}: {type(e).__name__}: {e}"}
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return {
            "inputs": [p.name for p in phs],
            "outputs": [o.name for o in outs],
            "variables": [{"name": n, "type": v.variableType}
                          for n, v in sub._vars.items()],
            "ops": [{"op": o.opName, "inputs": o.inputs,
                     "outputs": o.outputs,
                     "kwargs": SameDiff._serializable_kwargs(o.kwargs)}
                    for o in sub._ops],
            "arrays": {n: np.asarray(a) for n, a in sub._arrays.items()},
        }

    @staticmethod
    def _body_from_spec(spec):
        """Inverse of _record_body: a build_fn that replays the recorded
        ops verbatim into the fresh sub-SameDiff _subgraph_fn provides
        (placeholder names match by construction)."""
        def build(sub, *phs):
            for vd in spec["variables"]:
                if vd["name"] not in sub._vars:
                    sub._vars[vd["name"]] = SDVariable(sub, vd["name"],
                                                       vd["type"])
            for n, a in spec["arrays"].items():
                sub._arrays[n] = jnp.asarray(a)
            for od in spec["ops"]:
                sub._ops.append(_Op(od["op"], list(od["inputs"]),
                                    list(od["outputs"]), od["kwargs"]))
                for n in od["outputs"]:
                    sub._producer[n] = len(sub._ops) - 1
            outs = [sub._vars[n] for n in spec["outputs"]]
            return outs[0] if len(outs) == 1 else tuple(outs)

        return build

    def _body(self, op, key):
        """The executable for a control-flow body: the original callable
        if this graph was built in-process, else the recorded spec
        (loaded graphs)."""
        fn = op.kwargs.get(key)
        if fn is not None:
            return fn
        return self._body_from_spec(op.kwargs[key.replace("Body", "Graph")])

    @staticmethod
    def _subgraph_fn(build_fn, args, train=False, rng=None, n_expected=None,
                     what="", dynamic_rng=False):
        """Build `build_fn` as a sub-SameDiff over placeholders shaped like
        `args` (shapes are concrete at trace time) and return a plain
        jnp-level function of the arg values. train/rng thread the outer
        training mode into stochastic ops inside the body.

        dynamic_rng=True: the returned function takes a trailing PRNG-key
        argument instead of closing over `rng` — loop executors thread the
        key through the carry so stochastic ops redraw every iteration."""
        sub = SameDiff()
        phs = [sub.placeHolder(f"in{i}", jnp.asarray(a).dtype,
                               *jnp.asarray(a).shape)
               for i, a in enumerate(args)]
        out = build_fn(sub, *phs)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        if n_expected is not None and len(outs) != n_expected:
            raise ValueError(
                f"{what} returned {len(outs)} output(s) but {n_expected} "
                f"were declared (nOut / len(loopVars))")
        names = [o.name for o in outs]

        def f(*vals, key=None):
            env = sub._base_env()
            for ph, v in zip(phs, vals):
                env[ph.name] = v
            r = sub._run_graph(env, names, train=train,
                               rng=key if dynamic_rng else rng)
            return [r[n] for n in names]

        return f

    def _exec_if_cond(self, op, env, train=False, rng=None, op_idx=0):
        pred, *args = [env[n] for n in op.inputs]
        no = len(op.outputs)
        # decorrelate body draws from outer-graph stochastic ops: a body
        # op at sub-index i would otherwise fold the SAME (rng, i) as an
        # outer op at index i
        if rng is not None:
            rng = jax.random.fold_in(rng, 1_000_000 + op_idx)
        true_f = self._subgraph_fn(self._body(op, "trueBody"), args, train,
                                   rng, no, "ifCond trueBody")
        false_f = self._subgraph_fn(self._body(op, "falseBody"), args, train,
                                    rng, no, "ifCond falseBody")
        res = jax.lax.cond(
            jnp.asarray(pred).reshape(()).astype(bool),
            lambda a: tuple(true_f(*a)),
            lambda a: tuple(false_f(*a)),
            tuple(args))
        return res[0] if len(op.outputs) == 1 else res

    def _exec_while_loop(self, op, env, train=False, rng=None, op_idx=0):
        args = tuple(env[n] for n in op.inputs)
        cond_f = self._subgraph_fn(self._body(op, "condBody"), args, train,
                                   rng, None, "whileLoop condBody",
                                   dynamic_rng=True)
        body_f = self._subgraph_fn(self._body(op, "loopBody"), args, train,
                                   rng, len(op.outputs), "whileLoop loopBody",
                                   dynamic_rng=True)
        max_it = op.kwargs["maxIterations"]
        if max_it is not None:
            # static op attribute, possibly a float from an old saved
            # graph.json — NOT a tracer
            max_it = int(max_it)  # purity-ok[PUR02]: static op kwarg, never traced
        # the PRNG key rides in the carry so stochastic ops inside the
        # body draw fresh values EVERY iteration (a closure-captured key
        # would replay one sample N times). The carry key is folded with
        # a while-op tag so body draws never collide with outer-graph
        # stochastic ops at the same sub-index, and cond/body fold
        # distinct lanes off it per iteration.
        key0 = jax.random.fold_in(
            rng if rng is not None else jax.random.key(0),
            1_000_000 + op_idx)
        carry0 = args + (key0,)

        def pred_of(carry):
            vs, k = carry[:-1], carry[-1]
            return jnp.asarray(
                cond_f(*vs, key=jax.random.fold_in(k, 2))[0]
            ).reshape(()).astype(bool)

        def step(carry):
            vs, k = carry[:-1], carry[-1]
            return tuple(body_f(*vs, key=jax.random.fold_in(k, 3))) + (
                jax.random.fold_in(k, 1),)

        if max_it is None:
            res = jax.lax.while_loop(pred_of, step, carry0)[:-1]
        else:
            def scan_body(carry, _):
                p = pred_of(carry)
                new = step(carry)
                vs = tuple(jnp.where(p, n, v)
                           for n, v in zip(new[:-1], carry[:-1]))
                return vs + (new[-1],), None

            carry, _ = jax.lax.scan(scan_body, carry0, None,
                                    length=max_it)
            res = carry[:-1]
        return res[0] if len(op.outputs) == 1 else res

    # ---------- trace / execution ----------
    def _slice_for(self, out_names):
        """Backward slice: op indices needed to compute out_names, in order."""
        needed = set()
        stack = list(out_names)
        seen = set()
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            if n in self._producer:
                i = self._producer[n]
                needed.add(i)
                stack.extend(self._ops[i].inputs)
        return sorted(needed)

    def _run_graph(self, env, out_names, train=False, rng=None):
        """Pure interpreter over jnp values; called under trace so the whole
        slice becomes one XLA computation. `train`/`rng` thread training
        mode + a per-step PRNG key into stochastic ops (dropout)."""
        for i in self._slice_for(out_names):
            op = self._ops[i]
            if op.opName == "if_cond":
                res = self._exec_if_cond(op, env, train, rng, i)
                for n, r in zip(op.outputs, res if len(op.outputs) > 1
                                else [res]):
                    env[n] = r
                continue
            if op.opName == "while_loop":
                res = self._exec_while_loop(op, env, train, rng, i)
                for n, r in zip(op.outputs, res if len(op.outputs) > 1
                                else [res]):
                    env[n] = r
                continue
            args = [env[n] for n in op.inputs]
            kwargs = op.kwargs
            if op.opName == "dropout":
                kwargs = dict(kwargs, train=train and rng is not None,
                              key=(jax.random.fold_in(rng, i)
                                   if rng is not None else None))
            elif op.opName in _STOCHASTIC_OPS:
                # random-generator ops draw on every execution: per-step
                # rng during fit(), a fixed seeded key for output()
                # (deterministic inference, reference: Nd4j seeded RNG)
                base = rng if rng is not None else jax.random.key(0)
                kwargs = dict(kwargs, key=jax.random.fold_in(base, i))
            res = OPS[op.opName](*args, **kwargs)
            if len(op.outputs) == 1:
                env[op.outputs[0]] = res
            else:
                for n, r in zip(op.outputs, res):
                    env[n] = r
        return {n: env[n] for n in out_names}

    def _base_env(self):
        return dict(self._arrays)

    def _aot_jit(self, fn, entry, donate_argnums=()):
        """jit `fn` through the AOT executable cache (runtime.aot):
        keyed by the graph's structural fingerprint (ops, variables,
        training config — array VALUES ride as arguments and stay out
        of the key) so equal graphs share one executable and
        precompile() can warm-start from disk. The fingerprint is
        snapshotted here — every graph mutation clears _jit_cache, so a
        stale snapshot cannot outlive the program it names."""
        from deeplearning4j_tpu.runtime import aot

        try:
            fp = aot.samediff_fingerprint(self)
        except Exception:
            fp = None
        if fp is None:
            return jax.jit(fn, donate_argnums=donate_argnums)
        return aot.cached_jit(fn, entry=entry, fingerprint=fp,
                              donate_argnums=donate_argnums)

    def precompile(self, features=None, labels=None, data=None,
                   cache=None):
        """AOT warm-start of the training step for one batch signature
        (see MultiLayerNetwork.precompile): pass one example batch —
        (features, labels) arrays or a DataSet — and the fit-step
        executable is compiled (or loaded from the persistent cache)
        without running a step. Returns {entry: {key, status,
        seconds}}."""
        if self._tc is None:
            raise ValueError("setTrainingConfig first")
        tc = self._tc
        loss_names = self._loss_names()
        var_names = sorted(n for n, v in self._vars.items()
                           if v.variableType == VariableType.VARIABLE)
        ckey = ("fit", tuple(var_names), tuple(loss_names), id(tc),
                len(self._ops))
        jstep = self._jit_cache.get(ckey)
        if jstep is None:
            jstep = self._aot_jit(
                self._fit_step_fn(tc, loss_names, tc.updater),
                "fit_step", donate_argnums=(0, 1))
            self._jit_cache[ckey] = jstep
        if not hasattr(jstep, "warm"):
            return {}
        b = data if data is not None else (features, labels)
        phs = self._batch_to_placeholders(b, tc)
        params = {n: self._arrays[n] for n in var_names}
        consts = {n: a for n, a in self._arrays.items()
                  if n not in params}
        state = self._train_state_for(params, tc.updater)
        # fit() passes the python-int iteration and a fold_in key;
        # mirror both exactly or the warm signature misses
        rng = jax.random.fold_in(jax.random.key(0), self._iteration)
        key_, status, secs = jstep.warm(params, state, consts, phs,
                                        self._iteration, rng,
                                        cache=cache)
        # _train_state_for may have materialized fresh updater state;
        # keep it (fit would rebuild the identical thing)
        self._train_state = state
        return {} if status is None else {
            "fit_step": {"key": key_, "status": status,
                         "seconds": round(secs, 3)}}

    def output(self, placeholders, outputs):
        """Compile-and-run the slice for `outputs` (reference:
        SameDiff.output/exec → InferenceSession; here: one jax.jit)."""
        out_names = [o.name if isinstance(o, SDVariable) else o
                     for o in outputs]
        ph = {k: _unwrap(v) for k, v in (placeholders or {}).items()}
        key = (tuple(out_names),
               tuple(sorted((k, v.shape, str(v.dtype)) for k, v in ph.items())),
               len(self._ops))
        fn = self._jit_cache.get(key)
        if fn is None:
            def run(arrays, phs):
                env = dict(arrays)
                env.update(phs)
                return self._run_graph(env, out_names)
            fn = self._aot_jit(run, f"output[{','.join(out_names)}]")
            self._jit_cache[key] = fn
        res = fn(self._arrays, ph)
        return {k: INDArray(v) for k, v in res.items()}

    # alias kept for reference-API parity
    def exec(self, placeholders, *outputs):
        return self.output(placeholders, list(outputs))

    def batchOutput(self):
        sd = self

        class _B:
            def __init__(b):
                b._ph, b._out = {}, []

            def input(b, name, arr):
                b._ph[name] = arr
                return b

            def output(b, *names):
                b._out.extend(n.name if isinstance(n, SDVariable) else n
                              for n in names)
                return b

            def out(b, *names):
                return b.output(*names)

            def exec(b):
                return sd.output(b._ph, b._out)

        return _B()

    def _shape_of(self, name):
        if name in self._arrays:
            return tuple(self._arrays[name].shape)
        v = self._vars[name]
        if v.variableType == VariableType.PLACEHOLDER:
            return v._ph_shape
        # eval_shape the slice with abstract placeholders
        out = self._eval_shapes([name])
        return out[name]

    def _eval_shapes(self, names):
        phs = {n: jax.ShapeDtypeStruct(v._ph_shape, v._ph_dtype)
               for n, v in self._vars.items()
               if v.variableType == VariableType.PLACEHOLDER}

        def run(arrays, p):
            env = dict(arrays)
            env.update(p)
            return self._run_graph(env, names)

        shapes = jax.eval_shape(run, self._arrays, phs)
        return {n: tuple(s.shape) for n, s in shapes.items()}

    # ---------- autodiff ----------
    def _loss_names(self):
        if self._loss_vars:
            return self._loss_vars
        if self._tc and self._tc.lossVariables:
            return self._tc.lossVariables
        raise ValueError("no loss variables set; call setLossVariables()")

    def calculateGradients(self, placeholders, *wrt):
        """Reference: SameDiff.calculateGradients — returns d(loss)/d(wrt).
        TPU: jax.grad of the traced slice, not a backward graph."""
        wrt_names = [w.name if isinstance(w, SDVariable) else w for w in wrt]
        loss_names = self._loss_names()
        ph = {k: _unwrap(v) for k, v in (placeholders or {}).items()}

        # wrt may name stored arrays (VARIABLE/CONSTANT) or placeholders
        # (input gradients, supported by the reference API)
        w_names = [n for n in wrt_names if n in self._arrays]
        p_names = [n for n in wrt_names if n not in self._arrays]
        missing = [n for n in p_names if n not in ph]
        if missing:
            raise ValueError(f"wrt {missing} are placeholders but no value "
                             f"was provided in `placeholders`")

        key = ("grad", tuple(wrt_names), tuple(loss_names),
               tuple(sorted((k, v.shape, str(v.dtype)) for k, v in ph.items())),
               len(self._ops))
        fn = self._jit_cache.get(key)
        if fn is None:
            def loss_fn(w_arrays, ph_wrt, other_arrays, phs):
                env = dict(other_arrays)
                env.update(w_arrays)
                env.update(phs)
                env.update(ph_wrt)
                outs = self._run_graph(env, loss_names)
                return sum(jnp.sum(o) for o in outs.values())

            fn = self._aot_jit(
                jax.grad(loss_fn, argnums=(0, 1)),
                f"grad[{','.join(wrt_names)};{','.join(loss_names)}]")
            self._jit_cache[key] = fn

        w_arrays = {n: self._arrays[n] for n in w_names}
        ph_wrt = {n: ph[n] for n in p_names}
        others = {n: a for n, a in self._arrays.items() if n not in w_arrays}
        ph_rest = {n: a for n, a in ph.items() if n not in ph_wrt}
        gw, gp = fn(w_arrays, ph_wrt, others, ph_rest)
        out = {n: INDArray(g) for n, g in gw.items()}
        out.update({n: INDArray(g) for n, g in gp.items()})
        return out

    def grad(self, name):
        """Gradient variable accessor — evaluates lazily via calculateGradients."""
        return _GradAccessor(self, name)

    # ---------- training ----------
    def setTrainingConfig(self, tc):
        self._tc = tc
        self._train_state = None

    def fit(self, data=None, epochs=1, features=None, labels=None,
            listeners=None):
        """Train with TrainingConfig (reference: SameDiff.fit(DataSet)).
        One jitted step: forward+loss+grad+updater, donated buffers."""
        if self._tc is None:
            raise ValueError("setTrainingConfig first")
        tc = self._tc
        loss_names = self._loss_names()
        var_names = sorted(n for n, v in self._vars.items()
                           if v.variableType == VariableType.VARIABLE)

        if data is not None and features is None:
            batches = data if isinstance(data, (list, tuple)) else [data]
        else:
            batches = [(features, labels)]

        updater = tc.updater

        ckey = ("fit", tuple(var_names), tuple(loss_names), id(tc),
                len(self._ops))
        jstep = self._jit_cache.get(ckey)
        if jstep is None:
            jstep = self._aot_jit(
                self._fit_step_fn(tc, loss_names, updater),
                "fit_step", donate_argnums=(0, 1))
            self._jit_cache[ckey] = jstep

        params = {n: self._arrays[n] for n in var_names}
        consts = {n: a for n, a in self._arrays.items() if n not in params}
        state = self._train_state_for(params, updater)

        history = []
        base_key = jax.random.key(0)
        for _ in range(epochs):
            for b in batches:
                phs = self._batch_to_placeholders(b, tc)
                rng = jax.random.fold_in(base_key, self._iteration)
                loss, params, state = jstep(params, state, consts, phs,
                                            self._iteration, rng)
                # write back per-step: the inputs were donated, so stale
                # self._arrays entries would point at deleted buffers if a
                # listener (or an exception) reads them mid-fit
                self._arrays.update(params)
                self._train_state = state
                self._iteration += 1
                history.append(float(loss))
                for l in (listeners or []):
                    l.iterationDone(self, self._iteration, float(loss))
        self._arrays.update(params)
        self._train_state = state
        return history

    def _fit_step_fn(self, tc, loss_names, updater):
        """Raw (unjitted) train step: forward+loss+grad+updater. Shared
        by fit() (jitted directly, donated buffers) and fitSteps()
        (wrapped in an on-device lax.fori_loop)."""
        def step(params, ustate, consts, phs, it, rng):
            def loss_fn(p):
                env = dict(consts)
                env.update(p)
                env.update(phs)
                outs = self._run_graph(env, loss_names, train=True,
                                       rng=rng)
                # loss-tail policy (round 6): a marked loss variable may
                # be per-example (reduction NONE) in a sub-fp32 graph —
                # accumulate its sum in fp32 INSIDE the reduce (the
                # widening convert fuses; no fp32 activation-scale
                # buffer materialises) so the training loss is fp32
                # regardless of compute dtype
                loss = sum(
                    jnp.sum(o, dtype=jnp.promote_types(o.dtype,
                                                       jnp.float32))
                    for o in outs.values())
                if tc.l2:
                    loss = loss + tc.l2 * sum(
                        jnp.sum(jnp.square(a)) for a in p.values())
                if tc.l1:
                    loss = loss + tc.l1 * sum(
                        jnp.sum(jnp.abs(a)) for a in p.values())
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            if tc.weightDecay:
                grads = {n: g + tc.weightDecay * params[n]
                         for n, g in grads.items()}
            # the weight-update hook (see MultiLayerNetwork._train_step):
            # shardWeightUpdate installs ZeroShardedUpdate here — the
            # optimizer then runs on 1/dp shards of params and updater
            # state (reduce-scatter -> shard update -> all-gather); the
            # default is the shared apply-and-subtract. The hook changes
            # the state SHAPES, so jit's shape-keyed retrace always
            # re-reads it — no stale-cache hazard.
            impl = getattr(self, "_update_impl", None)
            if impl is None:
                from deeplearning4j_tpu.nn.multilayer import \
                    default_param_update
                impl = default_param_update
            new_params, new_state = impl(updater, grads, ustate, it,
                                         params)
            return loss, new_params, new_state

        return step

    def shardWeightUpdate(self, mesh=None, batch_axis=None,
                          min_shard_size=2 ** 16):
        """Enable the ZeRO-style cross-replica sharded weight update
        (Xu et al., arXiv:2004.13336) for this graph's training: the
        updater state is allocated in 1/dp shards over the mesh's data
        axis, gradients reduce-scatter into the matching shards, the
        optimizer updates only the local shard, and the fresh params
        all-gather for the next forward. Pass mesh=None for a
        data-parallel mesh over all local devices. Call BEFORE fit();
        an existing updater state is re-placed sharded bitwise.
        shardWeightUpdate(None) semantics need a mesh with a data axis;
        pass the same mesh your batch placement uses."""
        from deeplearning4j_tpu.parallel import mesh as _pmesh
        from deeplearning4j_tpu.parallel.sharding import ZeroShardedUpdate

        mesh = mesh if mesh is not None else _pmesh.data_parallel_mesh()
        self._update_impl = ZeroShardedUpdate(
            mesh, axis=batch_axis or _pmesh.DATA_AXIS,
            min_shard_size=min_shard_size)
        # the hook changes the traced program: drop cached steps so the
        # AOT fingerprints (which embed the update mode) are re-derived
        self._jit_cache.clear()
        state = getattr(self, "_train_state", None)
        if state is not None:
            self._train_state = self._update_impl.place_state(state)
        return self

    def _train_state_for(self, params, updater):
        state = getattr(self, "_train_state", None)
        impl = getattr(self, "_update_impl", None)
        if state is None:
            pending = getattr(self, "_pending_updater_leaves", None)
            if pending is not None:
                # checkpoints hold the canonical full-shape layout
                treedef = jax.tree_util.tree_structure(
                    jax.eval_shape(updater.init, params))
                state = jax.tree_util.tree_unflatten(
                    treedef, [jnp.asarray(l) for l in pending])
                self._pending_updater_leaves = None
                if impl is not None:
                    state = impl.place_state(state)
            elif impl is not None:
                # ZeRO mode: allocated sharded from init — each chip only
                # ever materialises its 1/dp shard of the moments
                state = impl.init_state(updater, params)
            else:
                state = updater.init(params)
        return state

    def fitSteps(self, features=None, labels=None, numSteps=1, data=None):
        """TPU-native k-step fit: numSteps optimizer steps on one batch
        entirely on device (lax.fori_loop), one host sync per call;
        returns the final loss. Semantics match numSteps fit() calls on
        the same batch — the per-step RNG and iteration counter advance
        through the same streams. See MultiLayerNetwork.fitSteps for the
        rationale (host dispatch latency dominates small graphs)."""
        if self._tc is None:
            raise ValueError("setTrainingConfig first")
        tc = self._tc
        loss_names = self._loss_names()
        var_names = sorted(n for n, v in self._vars.items()
                           if v.variableType == VariableType.VARIABLE)
        updater = tc.updater
        b = data if data is not None else (features, labels)
        phs = self._batch_to_placeholders(b, tc)
        ckey = ("fitSteps", numSteps, tuple(var_names), tuple(loss_names),
                id(tc), len(self._ops))
        jloop = self._jit_cache.get(ckey)
        if jloop is None:
            step = self._fit_step_fn(tc, loss_names, updater)
            base_key = jax.random.key(0)

            def loop(params, ustate, consts, phs, it0):
                def body(i, carry):
                    p, s, _ = carry
                    it = it0 + i
                    loss, p, s = step(p, s, consts, phs, it,
                                      jax.random.fold_in(base_key, it))
                    return (p, s, loss.astype(jnp.float32))

                return jax.lax.fori_loop(
                    0, numSteps, body, (params, ustate, jnp.float32(0)))

            jloop = self._aot_jit(loop, f"fit_steps[{numSteps}]",
                                  donate_argnums=(0, 1))
            self._jit_cache[ckey] = jloop
        params = {n: self._arrays[n] for n in var_names}
        consts = {n: a for n, a in self._arrays.items() if n not in params}
        state = self._train_state_for(params, updater)
        params, state, loss = jloop(params, state, consts, phs,
                                    jnp.asarray(self._iteration, jnp.int32))
        self._arrays.update(params)
        self._train_state = state
        self._iteration += numSteps
        return float(loss)

    def fitDataSet(self, iterator, stepsPerSync=1, epochs=1,
                   listeners=None):
        """Epoch training with one host sync and one device transfer per
        `stepsPerSync` fresh batches — the SameDiff form of
        MultiLayerNetwork.fitDataSet: k batches from the iterator are
        stacked into [k, ...] placeholder buffers and one jitted
        lax.fori_loop indexes batch i per step with the donated
        param/updater-state carry. Staging is double-buffered (block
        n+1's async device_put and dispatch are in flight before the
        host blocks on block n's losses). Per-step RNG and iteration
        streams match fit() exactly; the ragged final stack runs through
        the per-batch fit step, so the k-loop never retraces. Returns
        the loss history (one float per step, fit() parity); the call's
        host-sync count lands on `self._fit_dataset_syncs`."""
        from deeplearning4j_tpu.data.iterators import iter_stacks
        from deeplearning4j_tpu.nn.multilayer import run_staged_blocks

        if self._tc is None:
            raise ValueError("setTrainingConfig first")
        k = int(stepsPerSync)
        if k < 1:
            raise ValueError(f"stepsPerSync must be >= 1, got {k}")
        if epochs > 1 and not hasattr(iterator, "reset"):
            # a plain iterable is exhausted after epoch 1 — later epochs
            # would silently train zero batches and return a short
            # history; the nn fitDataSet paths fail loudly the same way
            # (their fit(iterator) calls reset() unconditionally)
            raise ValueError(
                f"fitDataSet(epochs={epochs}) needs a resettable "
                "iterator (with reset()/hasNext()/next()); a plain "
                "iterable can only run one epoch")
        tc = self._tc
        if k == 1:
            history = []
            for _ in range(epochs):
                if hasattr(iterator, "reset"):
                    iterator.reset()
                for stack in iter_stacks(iterator, 1):
                    history.extend(self.fit(data=stack[0],
                                            listeners=listeners))
            self._fit_dataset_syncs = len(history)  # one per batch
            return history
        loss_names = self._loss_names()
        var_names = sorted(n for n, v in self._vars.items()
                           if v.variableType == VariableType.VARIABLE)
        updater = tc.updater
        ckey = ("fitDataSet", k, tuple(var_names), tuple(loss_names),
                id(tc), len(self._ops))
        jloop = self._jit_cache.get(ckey)
        if jloop is None:
            step = self._fit_step_fn(tc, loss_names, updater)
            base_key = jax.random.key(0)

            def loop(params, ustate, consts, phs_stacked, it0):
                def body(i, carry):
                    p, s, losses = carry
                    it = it0 + i
                    phs = {n: jax.lax.dynamic_index_in_dim(
                        a, i, 0, keepdims=False)
                        for n, a in phs_stacked.items()}
                    loss, p, s = step(p, s, consts, phs, it,
                                      jax.random.fold_in(base_key, it))
                    return (p, s,
                            losses.at[i].set(loss.astype(jnp.float32)))

                return jax.lax.fori_loop(
                    0, k, body,
                    (params, ustate, jnp.zeros((k,), jnp.float32)))

            # RetraceSentinel.install_fit_dataset routes the loop
            # through this hook so compiles are counted exactly; a
            # wrapped loop stays on the plain jit (an AOT cache hit
            # would hide the trace the wrapper exists to count)
            wrap = getattr(self, "_fit_dataset_wrap", None)
            if wrap is not None:
                jloop = jax.jit(wrap(loop), donate_argnums=(0, 1))
            else:
                jloop = self._aot_jit(loop, f"fit_dataset[k={k}]",
                                      donate_argnums=(0, 1))
            self._jit_cache[ckey] = jloop

        history = []
        self._fit_dataset_syncs = 0

        def consume(losses):
            self._fit_dataset_syncs += 1
            vals = np.asarray(losses)   # THE host sync for this block
            for v in vals:
                self._iteration += 1
                history.append(float(v))
                for l in (listeners or []):
                    l.iterationDone(self, self._iteration, float(v))
            for l in (listeners or []):
                getattr(l, "onSyncBoundary", lambda *a: None)(
                    self, self._iteration, vals)

        it_next = 0   # dispatch-side iteration cursor, reset per epoch

        def dispatch(batches):
            nonlocal it_next
            phs_list = [self._batch_to_placeholders(b, tc)
                        for b in batches]
            stacked = jax.device_put(
                {n: np.stack([np.asarray(p[n]) for p in phs_list])
                 for n in phs_list[0]})
            params = {n: self._arrays[n] for n in var_names}
            consts = {n: a for n, a in self._arrays.items()
                      if n not in params}
            state = self._train_state_for(params, updater)
            params, state, losses = jloop(
                params, state, consts, stacked,
                jnp.asarray(it_next, jnp.int32))
            it_next += k
            # write back per block: the inputs were donated, so a
            # stale self._arrays entry would point at a dead buffer
            self._arrays.update(params)
            self._train_state = state
            return losses

        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            it_next = self._iteration
            tail = run_staged_blocks(iterator, k, dispatch, consume)
            for b in tail:   # ragged remainder: per-batch step, no
                history.extend(self.fit(data=b, listeners=listeners))
                self._fit_dataset_syncs += 1   # k-loop retrace
        return history

    def _batch_to_placeholders(self, b, tc, bind_labels=True):
        from deeplearning4j_tpu.data import DataSet
        if isinstance(b, (tuple, list)):
            feats = [b[0]] if not isinstance(b[0], (tuple, list)) else list(b[0])
            labs = [b[1]] if not isinstance(b[1], (tuple, list)) else list(b[1])
        elif isinstance(b, DataSet) or hasattr(b, "getFeatures"):
            # DataSet or any DataSet-like (MultiDataSet): features may be
            # one array or a list of them
            feats = b.getFeatures()
            feats = list(feats) if isinstance(feats, (list, tuple)) \
                else [feats]
            labs = b.getLabels() if bind_labels else None
            labs = (list(labs) if isinstance(labs, (list, tuple))
                    else [labs])
        else:
            raise TypeError(f"cannot map batch of type {type(b)}")
        # LOUD on count mismatches: zip would silently truncate, and a
        # single feature array bound to several placeholder names would
        # train/evaluate a silently wrong model
        if len(feats) != len(tc.dataSetFeatureMapping):
            raise ValueError(
                f"batch has {len(feats)} feature array(s) but "
                f"dataSetFeatureMapping names "
                f"{len(tc.dataSetFeatureMapping)}; for a single feature "
                "array the mapping must have exactly one name")
        if bind_labels and labs[0] is not None and \
                tc.dataSetLabelMapping and \
                len(labs) != len(tc.dataSetLabelMapping):
            raise ValueError(
                f"batch has {len(labs)} label array(s) but "
                f"dataSetLabelMapping names {len(tc.dataSetLabelMapping)}")
        phs = {}
        for name, arr in zip(tc.dataSetFeatureMapping, feats):
            phs[name] = _unwrap(arr)
        if bind_labels:
            for name, arr in zip(tc.dataSetLabelMapping, labs):
                if arr is not None:
                    phs[name] = _unwrap(arr)
        return phs

    def evaluate(self, iterator, outputVariable, *evaluations):
        """Stream a DataSetIterator through the graph and feed any number
        of IEvaluation instances (reference: SameDiff.evaluate(
        DataSetIterator, String, IEvaluation...)). Features bind via the
        TrainingConfig's dataSetFeatureMapping; labels go straight to the
        evaluations."""
        if self._tc is None:
            raise ValueError("setTrainingConfig first (evaluate needs the "
                             "dataSetFeatureMapping to bind features)")
        if not evaluations:
            from deeplearning4j_tpu.evaluation.evaluation import Evaluation
            evaluations = (Evaluation(),)
        out_name = (outputVariable.name
                    if isinstance(outputVariable, SDVariable)
                    else outputVariable)
        iterator.reset()
        while iterator.hasNext():
            ds = iterator.next()
            # bind_labels=False: labels go straight to the IEvaluations
            # (a label-mapping mismatch must not block evaluation)
            phs = self._batch_to_placeholders(ds, self._tc,
                                              bind_labels=False)
            pred = self.output(phs, [out_name])[out_name]
            for e in evaluations:
                e.eval(ds.getLabels(), pred,
                       mask=ds.getLabelsMaskArray())
        return evaluations[0] if len(evaluations) == 1 else evaluations

    # ---------- serialization ----------
    def save(self, path, saveUpdaterState=False):
        """Graph → JSON, arrays → npz, both in one zip (reference:
        SameDiff.save FlatBuffers .fb; format here is portable npz+json)."""
        body_store = {}  # recorded-body constants -> arrays.npz entries
        op_kwargs = [self._clean_spec_kwargs(o.kwargs, f"op{i}", body_store)
                     for i, o in enumerate(self._ops)]
        graph = {
            "variables": [
                {"name": n, "type": v.variableType,
                 "phShape": list(getattr(v, "_ph_shape", ()) or ()),
                 "phDtype": str(getattr(v, "_ph_dtype", "") or "")}
                for n, v in self._vars.items()],
            "ops": [{"op": o.opName, "inputs": o.inputs,
                     "outputs": o.outputs, "kwargs": kw}
                    for o, kw in zip(self._ops, op_kwargs)],
            "lossVariables": self._loss_vars,
            "iteration": self._iteration,
        }
        buf = io.BytesIO()
        np.savez(buf, **{n: np.asarray(a) for n, a in self._arrays.items()},
                 **body_store)
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("graph.json", json.dumps(graph))
            z.writestr("arrays.npz", buf.getvalue())
            if saveUpdaterState and getattr(self, "_train_state", None) is not None:
                sbuf = io.BytesIO()
                state = self._train_state
                impl = getattr(self, "_update_impl", None)
                if impl is not None and self._tc is not None:
                    # ZeRO sharded mode: gather + restore the canonical
                    # full-shape layout, so the checkpoint restores into
                    # any mode bitwise (reshape is lossless)
                    var_names = sorted(
                        n for n, v in self._vars.items()
                        if v.variableType == VariableType.VARIABLE)
                    state = impl.unview_state(
                        state, self._tc.updater,
                        {n: self._arrays[n] for n in var_names})
                leaves, treedef = jax.tree_util.tree_flatten(state)
                np.savez(sbuf, *[np.asarray(l) for l in leaves])
                z.writestr("updater.npz", sbuf.getvalue())

    @staticmethod
    def load(path, loadUpdaterState=False):
        sd = SameDiff()
        with zipfile.ZipFile(path) as z:
            graph = json.loads(z.read("graph.json"))
            npz = np.load(io.BytesIO(z.read("arrays.npz")))
            arrays = {n: jnp.asarray(npz[n]) for n in npz.files
                      if not n.startswith("__body__/")}
            if loadUpdaterState and "updater.npz" in z.namelist():
                snpz = np.load(io.BytesIO(z.read("updater.npz")))
                # leaves in tree_flatten order; restored into the updater's
                # init structure on the first fit() call
                sd._pending_updater_leaves = [snpz[k] for k in snpz.files]
        for vd in graph["variables"]:
            v = SDVariable(sd, vd["name"], vd["type"])
            if vd["type"] == VariableType.PLACEHOLDER:
                v._ph_shape = tuple(vd["phShape"])
                v._ph_dtype = jnp.dtype(vd["phDtype"])
            sd._vars[vd["name"]] = v
        for i, od in enumerate(graph["ops"]):
            kwargs = od["kwargs"]
            SameDiff._resolve_spec_kwargs(kwargs, npz)
            sd._ops.append(_Op(od["op"], od["inputs"], od["outputs"],
                               kwargs))
            for n in od["outputs"]:
                sd._producer[n] = i
        sd._arrays = arrays
        sd._loss_vars = graph.get("lossVariables", [])
        sd._iteration = graph.get("iteration", 0)
        return sd

    def summary(self):
        lines = [f"--- SameDiff: {len(self._vars)} variables, "
                 f"{len(self._ops)} ops ---"]
        for n, v in self._vars.items():
            if v.variableType != VariableType.ARRAY:
                shp = self._arrays[n].shape if n in self._arrays \
                    else getattr(v, "_ph_shape", "?")
                lines.append(f"  {v.variableType:<12} {n:<24} {shp}")
        for o in self._ops:
            lines.append(f"  {o.opName}({', '.join(o.inputs)}) -> "
                         f"{', '.join(o.outputs)}")
        return "\n".join(lines)


class _GradAccessor:
    def __init__(self, sd, name):
        self.sd = sd
        self.name = name.name if isinstance(name, SDVariable) else name

    def eval(self, placeholders=None):
        return self.sd.calculateGradients(placeholders or {},
                                          self.name)[self.name]


# ---------------- op namespaces ----------------
class _NS:
    def __init__(self, sd):
        self.sd = sd

    def _mk(self, opName, inputs, kwargs=None, nOut=1, name=None):
        ins = [self.sd._lift(i) for i in inputs]
        return self.sd._op(opName, ins, kwargs, nOut=nOut, name=name)


def _unary(opName):
    def m(self, x, name=None):
        return self._mk(opName, [x], name=name)
    m.__name__ = opName
    return m


def _binary(opName):
    def m(self, a, b, name=None):
        return self._mk(opName, [a, b], name=name)
    m.__name__ = opName
    return m


def _reduction(opName):
    def m(self, x, *dimensions, keepDims=False, name=None):
        return self._mk(opName, [x],
                        {"dimensions": list(dimensions) or None,
                         "keepDims": keepDims}, name=name)
    m.__name__ = opName
    return m


class _MathOps(_NS):
    """Reference: org.nd4j.autodiff.samediff.ops.SDMath."""

    for _n in ("neg abs sign exp expm1 log log1p log2 sqrt square floor ceil "
               "round sin cos tan asin acos atan sinh cosh tanh asinh acosh "
               "atanh erf erfc reciprocal rsqrt isnan isinf isfinite "
               "lgamma digamma").split():
        locals()[_n] = _unary(_n)
    for _n in ("add sub mul div pow atan2 squaredDifference maximum minimum "
               "floordiv mod eq neq gt gte lt lte and or xor "
               "igamma igammac polygamma zeta").split():
        locals()[_n] = _binary(_n)
    for _n in "sum mean prod max min std variance norm1 norm2 normmax".split():
        locals()[_n] = _reduction(_n)
    del _n

    def logicalNot(self, x, name=None):
        return self._mk("not", [x], name=name)

    def betainc(self, a, b, x, name=None):
        """Regularized incomplete beta I_x(a, b) (reference: SDMath)."""
        return self._mk("betainc", [a, b, x], name=name)

    # -- reduce3-style distance ops (reference: SDMath distance family) --
    def _dist(self, opName, x, y, dimensions, name):
        return self._mk(opName, [x, y],
                        {"dimensions": list(dimensions) or None}, name=name)

    def cosineSimilarity(self, x, y, *dimensions, name=None):
        return self._dist("cosineSimilarity", x, y, dimensions, name)

    def cosineDistance(self, x, y, *dimensions, name=None):
        return self._dist("cosineDistance", x, y, dimensions, name)

    def euclideanDistance(self, x, y, *dimensions, name=None):
        return self._dist("euclideanDistance", x, y, dimensions, name)

    def manhattanDistance(self, x, y, *dimensions, name=None):
        return self._dist("manhattanDistance", x, y, dimensions, name)

    def hammingDistance(self, x, y, *dimensions, name=None):
        return self._dist("hammingDistance", x, y, dimensions, name)

    def jaccardDistance(self, x, y, *dimensions, name=None):
        return self._dist("jaccardDistance", x, y, dimensions, name)

    # -- segment reductions (pass numSegments for jit: static shapes) --
    def _seg(self, opName, data, ids, numSegments, name):
        return self._mk(opName, [data, ids],
                        {"numSegments": numSegments}, name=name)

    def segmentSum(self, data, segmentIds, numSegments=None, name=None):
        return self._seg("segmentSum", data, segmentIds, numSegments, name)

    def segmentMax(self, data, segmentIds, numSegments=None, name=None):
        return self._seg("segmentMax", data, segmentIds, numSegments, name)

    def segmentMin(self, data, segmentIds, numSegments=None, name=None):
        return self._seg("segmentMin", data, segmentIds, numSegments, name)

    def segmentMean(self, data, segmentIds, numSegments=None, name=None):
        return self._seg("segmentMean", data, segmentIds, numSegments, name)

    def segmentProd(self, data, segmentIds, numSegments=None, name=None):
        return self._seg("segmentProd", data, segmentIds, numSegments, name)

    unsortedSegmentSum = segmentSum    # jax segment ops accept any order
    unsortedSegmentMax = segmentMax
    unsortedSegmentMin = segmentMin
    unsortedSegmentMean = segmentMean
    unsortedSegmentProd = segmentProd

    def confusionMatrix(self, labels, pred, numClasses=None, weights=None,
                        name=None):
        ins = [labels, pred] + ([weights] if weights is not None else [])
        kw = {"numClasses": numClasses}
        if weights is None:
            return self._mk("confusionMatrix", ins, kw, name=name)
        return self._mk("confusionMatrixWeighted", ins, kw, name=name)

    def zeroFraction(self, x, name=None):
        return self._mk("zeroFraction", [x], name=name)

    def countNonZero(self, x, *dimensions, keepDims=False, name=None):
        return self._mk("countNonZero", [x],
                        {"dimensions": list(dimensions) or None,
                         "keepDims": keepDims}, name=name)

    def countZero(self, x, *dimensions, keepDims=False, name=None):
        return self._mk("countZero", [x],
                        {"dimensions": list(dimensions) or None,
                         "keepDims": keepDims}, name=name)

    def entropy(self, x, *dimensions, name=None):
        return self._mk("entropy", [x],
                        {"dimensions": list(dimensions) or None}, name=name)

    def shannonEntropy(self, x, *dimensions, name=None):
        return self._mk("shannonEntropy", [x],
                        {"dimensions": list(dimensions) or None}, name=name)

    def matchConditionCount(self, x, condition, value, *dimensions,
                            keepDims=False, name=None):
        return self._mk("matchConditionCount", [x],
                        {"condition": condition, "value": float(value),
                         "dimensions": list(dimensions) or None,
                         "keepDims": keepDims}, name=name)

    def iamax(self, x, dimension=None, name=None):
        return self._mk("iamax", [x],
                        {"dimensions": None if dimension is None
                         else [dimension]}, name=name)

    def linspace(self, start, stop, num, dtype="float32", name=None):
        return self._mk("linspace", [],
                        {"start": float(start), "stop": float(stop),
                         "num": int(num), "dtype": str(dtype)}, name=name)

    def range(self, start, limit, delta=1, dtype="float32", name=None):
        return self._mk("range", [],
                        {"start": start, "limit": limit, "delta": delta,
                         "dtype": str(dtype)}, name=name)

    def meshgrid(self, *xs, indexing="xy", name=None):
        return self._mk("meshgrid", list(xs), {"indexing": indexing},
                        nOut=len(xs), name=name)

    def clipByValue(self, x, clipValueMin, clipValueMax, name=None):
        # bounds kept as-is; the op casts them to x's dtype (int tensors
        # must stay int)
        return self._mk("clipByValue", [x],
                        {"clipValueMin": clipValueMin,
                         "clipValueMax": clipValueMax}, name=name)

    def clipByNorm(self, x, clipValue, *dimensions, name=None):
        return self._mk("clipByNorm", [x],
                        {"clipValue": float(clipValue),
                         "dimensions": list(dimensions) or None}, name=name)

    def sort(self, x, axis=-1, descending=False, name=None):
        return self._mk("sort", [x], {"axis": axis,
                                      "descending": descending}, name=name)

    def topK(self, x, k, sorted=True, name=None):
        """(values, indices) of the k largest along the last axis
        (reference: sd.math.topK → lax.top_k on TPU)."""
        return self._mk("topK", [x], {"k": int(k), "sorted": sorted},
                        nOut=2, name=name)

    def split(self, x, numSplit, axis=0, name=None):
        return self._mk("split", [x], {"numSplit": int(numSplit),
                                       "axis": axis}, nOut=int(numSplit),
                        name=name)

    def where(self, cond, x, y, name=None):
        return self._mk("where", [cond, x, y], name=name)

    def argmax(self, x, dimension=None, name=None):
        return self._mk("argmax", [x],
                        {"dimensions": None if dimension is None
                         else [dimension]}, name=name)

    def argmin(self, x, dimension=None, name=None):
        return self._mk("argmin", [x],
                        {"dimensions": None if dimension is None
                         else [dimension]}, name=name)

    def cumsum(self, x, axis=0, exclusive=False, reverse=False, name=None):
        return self._mk("cumsum", [x], {"axis": axis, "exclusive": exclusive,
                                        "reverse": reverse}, name=name)

    def cumprod(self, x, axis=0, name=None):
        return self._mk("cumprod", [x], {"axis": axis}, name=name)

    def concat(self, dimension, *xs, name=None):
        return self._mk("concat", list(xs), {"dimension": dimension},
                        name=name)

    def stack(self, axis, *xs, name=None):
        return self._mk("stack", list(xs), {"axis": axis}, name=name)

    def unstack(self, x, axis, num, name=None):
        return self._mk("unstack", [x], {"axis": axis, "num": num},
                        nOut=num, name=name)

    def reshape(self, x, shape, name=None):
        return self._mk("reshape", [x], {"shape": list(shape)}, name=name)

    def permute(self, x, *dims, name=None):
        return self._mk("permute", [x], {"dimensions": list(dims)}, name=name)

    def expandDims(self, x, axis, name=None):
        return self._mk("expandDims", [x], {"axis": axis}, name=name)

    def squeeze(self, x, axis, name=None):
        return self._mk("squeeze", [x], {"axis": axis}, name=name)

    def tile(self, x, reps, name=None):
        return self._mk("tile", [x], {"reps": list(reps)}, name=name)

    def reverse(self, x, *dimensions, name=None):
        return self._mk("reverse", [x], {"dimensions": list(dimensions)},
                        name=name)

    def gather(self, x, indices, axis=0, name=None):
        return self._mk("gather", [x, indices], {"axis": axis}, name=name)

    def oneHot(self, x, depth, axis=-1, on=1.0, off=0.0, name=None):
        return self._mk("onehot", [x], {"depth": depth, "axis": axis,
                                        "on": on, "off": off}, name=name)

    def scatterUpdate(self, ref, indices, updates, name=None):
        return self._mk("scatterUpdate", [ref, indices, updates], name=name)

    def scatterAdd(self, ref, indices, updates, name=None):
        return self._mk("scatterAdd", [ref, indices, updates], name=name)

    def pad(self, x, padding, constant=0.0, name=None):
        return self._mk("pad", [x], {"padding": [list(p) for p in padding],
                                     "constant": constant}, name=name)

    def identity(self, x, name=None):
        return self._mk("identity", [x], name=name)

    def cast(self, x, dtype, name=None):
        return self._mk("cast", [x], {"dtype": str(np.dtype(dtype))},
                        name=name)


class _NNOps(_NS):
    """Reference: ops.SDNN."""

    for _n in ("relu relu6 sigmoid softplus softsign elu selu gelu swish "
               "mish hardSigmoid hardTanh").split():
        locals()[_n] = _unary(_n)
    del _n

    def leakyRelu(self, x, alpha=0.01, name=None):
        return self._mk("leakyRelu", [x], {"alpha": alpha}, name=name)

    def softmax(self, x, dimension=-1, name=None):
        return self._mk("softmax", [x], {"dimension": dimension}, name=name)

    def logSoftmax(self, x, dimension=-1, name=None):
        return self._mk("logSoftmax", [x], {"dimension": dimension},
                        name=name)

    def linear(self, x, w, b=None, name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._mk("linear", ins, name=name)

    def reluLayer(self, x, w, b, name=None):
        return self.relu(self.linear(x, w, b), name=name)

    def layerNorm(self, x, gain, bias=None, dimensions=(-1,), name=None):
        ins = [x, gain] + ([bias] if bias is not None else [])
        return self._mk("layerNorm", ins,
                        {"dimensions": list(dimensions)}, name=name)

    def batchNorm(self, x, mean, var, gamma=None, beta=None, epsilon=1e-5,
                  axis=-1, name=None):
        ins = [x, mean, var] + ([gamma] if gamma is not None else []) \
            + ([beta] if beta is not None else [])
        return self._mk("batchNorm", ins, {"epsilon": epsilon, "axis": axis},
                        name=name)

    def dropout(self, x, rate, name=None):
        """Active during fit() (train mode + per-step key threaded by
        _run_graph); identity in output()/eval(), like the reference's
        inference behavior."""
        return self._mk("dropout", [x], {"rate": rate}, name=name)

    def embeddingLookup(self, table, ids, name=None):
        return self._mk("embeddingLookup", [table, ids], name=name)

    def dotProductAttention(self, q, k, v, causal=False, name=None):
        return self._mk("dotProductAttention", [q, k, v],
                        {"causal": causal}, name=name)

    def multiHeadDotProductAttention(self, x, wq, wk, wv, wo, nHeads,
                                     causal=False, name=None):
        return self._mk("multiHeadDotProductAttention",
                        [x, wq, wk, wv, wo],
                        {"nHeads": nHeads, "causal": causal}, name=name)

    def pad(self, x, padding, constant=0.0, name=None):
        return self.sd.math.pad(x, padding, constant, name=name)


class _CNNOps(_NS):
    """Reference: ops.SDCNN."""

    def conv2d(self, x, w, b=None, stride=(1, 1), padding=((0, 0), (0, 0)),
               dilation=(1, 1), name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._mk("conv2d", ins,
                        {"stride": list(stride),
                         "padding": [list(p) for p in padding],
                         "dilation": list(dilation)}, name=name)

    def conv1d(self, x, w, b=None, stride=1, padding=((0, 0),), dilation=1,
               name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._mk("conv1d", ins,
                        {"stride": stride,
                         "padding": [list(p) for p in padding],
                         "dilation": dilation}, name=name)

    def conv3d(self, x, w, b=None, stride=(1, 1, 1),
               padding=((0, 0), (0, 0), (0, 0)), dilation=(1, 1, 1),
               name=None):
        """NDHWC x [B,D,H,W,C], w [kd,kh,kw,I,O] (reference: SDCNN.conv3d
        / libnd4j conv3dnew)."""
        ins = [x, w] + ([b] if b is not None else [])
        return self._mk("conv3d", ins,
                        {"stride": list(stride),
                         "padding": [list(p) for p in padding],
                         "dilation": list(dilation)}, name=name)

    def deconv2d(self, x, w, b=None, stride=(1, 1), padding=((0, 0), (0, 0)),
                 name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._mk("deconv2d", ins,
                        {"stride": list(stride),
                         "padding": [list(p) for p in padding]}, name=name)

    def spaceToDepth(self, x, blockSize=2, name=None):
        return self._mk("spaceToDepth", [x], {"blockSize": int(blockSize)},
                        name=name)

    def depthToSpace(self, x, blockSize=2, name=None):
        return self._mk("depthToSpace", [x], {"blockSize": int(blockSize)},
                        name=name)

    def spaceToBatch(self, x, blockSize=2, padding=((0, 0), (0, 0)),
                     name=None):
        return self._mk("spaceToBatch", [x],
                        {"blockSize": int(blockSize),
                         "padding": [list(q) for q in padding]}, name=name)

    def batchToSpace(self, x, blockSize=2, crops=((0, 0), (0, 0)),
                     name=None):
        return self._mk("batchToSpace", [x],
                        {"blockSize": int(blockSize),
                         "crops": [list(q) for q in crops]}, name=name)

    def maxPooling2d(self, x, kernel, stride=None, padding=((0, 0), (0, 0)),
                     name=None):
        return self._mk("maxPooling2d", [x],
                        {"kernel": list(kernel),
                         "stride": list(stride or kernel),
                         "padding": [list(p) for p in padding]}, name=name)

    def avgPooling2d(self, x, kernel, stride=None, padding=((0, 0), (0, 0)),
                     count_include_pad=True, name=None):
        return self._mk("avgPooling2d", [x],
                        {"kernel": list(kernel),
                         "stride": list(stride or kernel),
                         "padding": [list(p) for p in padding],
                         "count_include_pad": count_include_pad}, name=name)

    def upsampling2d(self, x, size=(2, 2), name=None):
        return self._mk("upsampling2d", [x], {"size": list(size)}, name=name)

    def im2col(self, x, kernel, stride=(1, 1), padding=((0, 0), (0, 0)),
               name=None):
        return self._mk("im2col", [x],
                        {"kernel": list(kernel), "stride": list(stride),
                         "padding": [list(p) for p in padding]}, name=name)


class _RNNOps(_NS):
    """Reference: ops.SDRNN."""

    def lstmLayer(self, x, w, u, b, name=None):
        """-> (h_seq [T,B,H], h_last [B,H], c_last [B,H])."""
        return self._mk("lstmLayer", [x, w, u, b], nOut=3, name=name)

    def gru(self, x, w, u, b, name=None):
        return self._mk("gru", [x, w, u, b], name=name)

    def simpleRnn(self, x, w, u, b, name=None):
        return self._mk("simpleRnn", [x, w, u, b], name=name)


class _LossOps(_NS):
    """Reference: ops.SDLoss. Outputs are auto-marked as loss variables."""

    def _loss(self, opName, inputs, kwargs=None, name=None):
        v = self._mk(opName, inputs, kwargs, name=name)
        self.sd._loss_vars.append(v.name)
        return v

    def meanSquaredError(self, labels, predictions, name=None):
        return self._loss("lossMSE", [labels, predictions], name=name)

    def absoluteDifference(self, labels, predictions, name=None):
        return self._loss("lossMAE", [labels, predictions], name=name)

    def logLoss(self, labels, predictions, name=None):
        return self._loss("lossLog", [labels, predictions], name=name)

    def softmaxCrossEntropy(self, labels, logits, name=None):
        return self._loss("softmaxCrossEntropy", [labels, logits], name=name)

    def sparseSoftmaxCrossEntropy(self, labels, logits, name=None):
        return self._loss("sparseSoftmaxCrossEntropy", [labels, logits],
                          name=name)

    def hingeLoss(self, labels, predictions, name=None):
        return self._loss("lossHinge", [labels, predictions], name=name)

    def huberLoss(self, labels, predictions, delta=1.0, name=None):
        return self._loss("lossHuber", [labels, predictions],
                          {"delta": delta}, name=name)

    def klDivergence(self, labels, predictions, name=None):
        return self._loss("lossKLD", [labels, predictions], name=name)

    def poissonLoss(self, labels, predictions, name=None):
        return self._loss("lossPoisson", [labels, predictions], name=name)

    def sigmoidCrossEntropy(self, labels, logits, labelSmoothing=0.0,
                            name=None):
        return self._loss("sigmoidCrossEntropy", [labels, logits],
                          {"labelSmoothing": float(labelSmoothing)},
                          name=name)

    def weightedCrossEntropyWithLogits(self, labels, logits, weights,
                                       name=None):
        return self._loss("weightedCrossEntropyWithLogits",
                          [labels, logits, weights], name=name)

    def l2Loss(self, x, name=None):
        return self._loss("l2Loss", [x], name=name)

    def meanPairwiseSquaredError(self, labels, predictions, name=None):
        return self._loss("meanPairwiseSquaredError",
                          [labels, predictions], name=name)

    def cosineDistance(self, labels, predictions, dimension=-1, name=None):
        return self._loss("lossCosine", [labels, predictions],
                          {"dimension": dimension}, name=name)


class _ImageOps(_NS):
    """Reference: ops.SDImage."""

    def resizeBilinear(self, x, height, width, name=None):
        return self._mk("resizeBilinear", [x],
                        {"height": height, "width": width}, name=name)

    def resizeNearest(self, x, height, width, name=None):
        return self._mk("resizeNearest", [x],
                        {"height": height, "width": width}, name=name)

    def cropAndResize(self, x, boxes, boxIndices, cropHeight, cropWidth,
                      name=None):
        return self._mk("cropAndResize", [x, boxes, boxIndices],
                        {"cropHeight": cropHeight, "cropWidth": cropWidth},
                        name=name)

    def adjustContrast(self, x, factor, name=None):
        return self._mk("adjustContrast", [x], {"factor": factor}, name=name)

    def hsvToRgb(self, x, name=None):
        return self._mk("hsvToRgb", [x], name=name)

    def rgbToHsv(self, x, name=None):
        return self._mk("rgbToHsv", [x], name=name)

    # block ops live in sd.cnn (reference: SDCNN); aliased here for
    # discoverability alongside the other image transforms
    spaceToDepth = _CNNOps.spaceToDepth
    depthToSpace = _CNNOps.depthToSpace
    spaceToBatch = _CNNOps.spaceToBatch
    batchToSpace = _CNNOps.batchToSpace

    def nonMaxSuppression(self, boxes, scores, maxOutputSize=10,
                          iouThreshold=0.5, scoreThreshold=float("-inf"),
                          name=None):
        return self._mk("nonMaxSuppression", [boxes, scores],
                        {"maxOutputSize": int(maxOutputSize),
                         "iouThreshold": float(iouThreshold),
                         "scoreThreshold": float(scoreThreshold)},
                        name=name)


class _LinalgOps(_NS):
    """Reference: ops.SDLinalg."""

    def mmul(self, a, b, transposeA=False, transposeB=False, name=None):
        return self._mk("mmul", [a, b], {"transposeA": transposeA,
                                         "transposeB": transposeB}, name=name)

    def tensorMmul(self, a, b, dimensionsA, dimensionsB, name=None):
        return self._mk("tensorMmul", [a, b],
                        {"dimensionsA": list(dimensionsA),
                         "dimensionsB": list(dimensionsB)}, name=name)

    def matmul(self, a, b, name=None):
        return self._mk("batchMmul", [a, b], name=name)

    for _n in "cholesky inv det trace cross solve lstsq".split():
        locals()[_n] = _binary(_n) if _n in ("cross", "solve", "lstsq") \
            else _unary(_n)
    del _n

    def lu(self, x, name=None):
        """P, L, U factors. DELIBERATE API change vs SDLinalg.lu (which
        returns a packed LU matrix + permutation-index vector): explicit
        factors reconstruct as P @ L @ U with plain matmuls and avoid
        host-side unpacking."""
        return self._mk("lu", [x], nOut=3, name=name)

    def eigh(self, x, name=None):
        """Eigenvalues + eigenvectors of a symmetric matrix (reference:
        SDLinalg.eig for the self-adjoint case — general eig has no
        TPU-lowerable kernel)."""
        return self._mk("eigh", [x], nOut=2, name=name)

    def svd(self, x, fullUV=False, name=None):
        return self._mk("svd", [x], {"fullUV": fullUV}, nOut=3, name=name)

    def qr(self, x, name=None):
        return self._mk("qr", [x], nOut=2, name=name)


class _FFTOps(_NS):
    """Reference: the Nd4j.fft / spectral op family. Complex arrays are
    first-class (complex64 lowers natively on TPU); real/imag/conj/
    angle/toComplex convert at the boundary."""

    def fft(self, x, numPoints=None, dimension=-1, name=None):
        return self._mk("fft", [x], {"numPoints": numPoints,
                                     "dimension": int(dimension)}, name=name)

    def ifft(self, x, numPoints=None, dimension=-1, name=None):
        return self._mk("ifft", [x], {"numPoints": numPoints,
                                      "dimension": int(dimension)}, name=name)

    def rfft(self, x, numPoints=None, dimension=-1, name=None):
        """Real input -> positive-frequency half spectrum (complex)."""
        return self._mk("rfft", [x], {"numPoints": numPoints,
                                      "dimension": int(dimension)}, name=name)

    def irfft(self, x, numPoints=None, dimension=-1, name=None):
        return self._mk("irfft", [x], {"numPoints": numPoints,
                                       "dimension": int(dimension)}, name=name)

    def fft2(self, x, name=None):
        return self._mk("fft2", [x], name=name)

    def ifft2(self, x, name=None):
        return self._mk("ifft2", [x], name=name)

    for _n in "real imag conj angle".split():
        locals()[_n] = _unary(_n)
    del _n

    def toComplex(self, re, im, name=None):
        return self._mk("toComplex", [re, im], name=name)


class _RandomOps(_NS):
    """Reference: ops.SDRandom. Draws are refreshed per fit() step (the
    trainer's rng threads in) and fixed-seed deterministic for output().
    Non-differentiable leaves, like the reference's random ops."""

    def normal(self, mean, stddev, *shape, name=None):
        return self._mk("randomNormal", [],
                        {"shape": tuple(int(s) for s in shape),
                         "mean": float(mean), "stddev": float(stddev)},
                        name=name)

    def uniform(self, min, max, *shape, name=None):
        return self._mk("randomUniform", [],
                        {"shape": tuple(int(s) for s in shape),
                         "min": float(min), "max": float(max)}, name=name)

    def bernoulli(self, p, *shape, name=None):
        return self._mk("randomBernoulli", [],
                        {"shape": tuple(int(s) for s in shape),
                         "p": float(p)}, name=name)

    def exponential(self, lambda_, *shape, name=None):
        return self._mk("randomExponential", [],
                        {"shape": tuple(int(s) for s in shape),
                         "lambda_": float(lambda_)}, name=name)


class _BitwiseOps(_NS):
    """Reference: ops.SDBitwise."""

    def leftShift(self, a, b, name=None):
        return self._mk("shiftLeft", [a, b], name=name)

    def rightShift(self, a, b, name=None):
        return self._mk("shiftRight", [a, b], name=name)

    def bitwiseAnd(self, a, b, name=None):
        return self._mk("bitwiseAnd", [a, b], name=name)

    def bitwiseOr(self, a, b, name=None):
        return self._mk("bitwiseOr", [a, b], name=name)

    def bitwiseXor(self, a, b, name=None):
        return self._mk("bitwiseXor", [a, b], name=name)

"""Named op registry for SameDiff graphs.

Reference: the DynamicCustomOp / legacy-op zoo in libnd4j that SameDiff
nodes dispatch to (org.nd4j.linalg.api.ops.impl.*). Here each op NAME maps
to a pure function over jnp arrays; XLA is the kernel library, so an "op"
is just a traceable lowering that fuses with its neighbours. Names are kept
serializable (graph JSON stores the op name, not the callable).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops import attention as _attn
from deeplearning4j_tpu.ops import conv as _conv
from deeplearning4j_tpu.ops import pooling as _pool
from deeplearning4j_tpu.ops import rnn as _rnn
from deeplearning4j_tpu.nn import losses as _losses

OPS = {}


def op(name):
    def deco(fn):
        OPS[name] = fn
        return fn
    return deco


def _reg(name, fn):
    OPS[name] = fn


# ---- math: elementwise ----
for _n, _f in {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "floordiv": jnp.floor_divide, "mod": jnp.mod,
    "pow": jnp.power, "squaredDifference": lambda a, b: jnp.square(a - b),
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "neg": jnp.negative, "abs": jnp.abs, "sign": jnp.sign,
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log1p": jnp.log1p,
    "log2": jnp.log2, "sqrt": jnp.sqrt, "rsqrt": lax.rsqrt,
    "square": jnp.square, "reciprocal": jnp.reciprocal,
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "erf": jax.scipy.special.erf, "erfc": jax.scipy.special.erfc,
    "atan2": jnp.arctan2,
    # special functions (reference: nd4j impl.transforms.custom Lgamma/
    # Digamma/Igamma/Igammac/Polygamma/Zeta/BetaInc ops)
    "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "igamma": jax.scipy.special.gammainc,    # regularized lower P(a, x)
    "igammac": jax.scipy.special.gammaincc,  # regularized upper Q(a, x)
    "betainc": jax.scipy.special.betainc,
    "polygamma": lambda n, x: jax.scipy.special.polygamma(
        n.astype(jnp.int32) if hasattr(n, "astype") else n, x),
    "zeta": jax.scipy.special.zeta,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
}.items():
    _reg(_n, _f)

# ---- comparisons / logic ----
for _n, _f in {
    "eq": jnp.equal, "neq": jnp.not_equal, "gt": jnp.greater,
    "gte": jnp.greater_equal, "lt": jnp.less, "lte": jnp.less_equal,
    "and": jnp.logical_and, "or": jnp.logical_or, "xor": jnp.logical_xor,
    "not": jnp.logical_not,
}.items():
    _reg(_n, _f)


@op("where")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


# ---- reductions ----
def _red(fn):
    def run(x, dimensions=None, keepDims=False):
        axis = tuple(dimensions) if dimensions else None
        return fn(x, axis=axis, keepdims=keepDims)
    return run


for _n, _f in {
    "sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod,
    "max": jnp.max, "min": jnp.min, "std": jnp.std, "variance": jnp.var,
    "any": jnp.any, "all": jnp.all,
}.items():
    _reg(_n, _red(_f))


@op("norm1")
def _norm1(x, dimensions=None, keepDims=False):
    return jnp.sum(jnp.abs(x), axis=tuple(dimensions) if dimensions else None,
                   keepdims=keepDims)


@op("norm2")
def _norm2(x, dimensions=None, keepDims=False):
    return jnp.sqrt(jnp.sum(jnp.square(x),
                            axis=tuple(dimensions) if dimensions else None,
                            keepdims=keepDims))


@op("normmax")
def _normmax(x, dimensions=None, keepDims=False):
    return jnp.max(jnp.abs(x), axis=tuple(dimensions) if dimensions else None,
                   keepdims=keepDims)


@op("argmax")
def _argmax(x, dimensions=None, keepDims=False):
    axis = dimensions[0] if dimensions else None
    r = jnp.argmax(x, axis=axis)
    return jnp.expand_dims(r, axis) if (keepDims and axis is not None) else r


@op("argmin")
def _argmin(x, dimensions=None, keepDims=False):
    axis = dimensions[0] if dimensions else None
    r = jnp.argmin(x, axis=axis)
    return jnp.expand_dims(r, axis) if (keepDims and axis is not None) else r


@op("cumsum")
def _cumsum(x, axis=0, exclusive=False, reverse=False):
    if reverse:
        x = jnp.flip(x, axis)
    r = jnp.cumsum(x, axis=axis)
    if exclusive:
        r = r - x
    if reverse:
        r = jnp.flip(r, axis)
    return r


@op("cumprod")
def _cumprod(x, axis=0):
    return jnp.cumprod(x, axis=axis)


# ---- shape ops ----
@op("reshape")
def _reshape(x, shape=None):
    return jnp.reshape(x, tuple(shape))


@op("permute")
def _permute(x, dimensions=None):
    return jnp.transpose(x, tuple(dimensions))


@op("transpose")
def _transpose(x):
    return jnp.transpose(x)


@op("expandDims")
def _expand(x, axis=0):
    return jnp.expand_dims(x, axis)


@op("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


@op("concat")
def _concat(*xs, dimension=0):
    return jnp.concatenate(xs, axis=dimension)


@op("stack")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


@op("unstack")
def _unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    return tuple(jnp.squeeze(s, axis)
                 for s in jnp.split(x, n, axis=axis))


@op("tile")
def _tile(x, reps=None):
    return jnp.tile(x, tuple(reps))


@op("repeat")
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@op("reverse")
def _reverse(x, dimensions=None):
    return jnp.flip(x, tuple(dimensions))


@op("slice")
def _slice(x, begin=None, size=None):
    return lax.dynamic_slice(x, tuple(begin), tuple(size))


@op("stridedSlice")
def _strided_slice(x, begin=None, end=None, strides=None):
    sl = tuple(slice(b, e, s) for b, e, s in
               zip(begin, end, strides or [1] * len(begin)))
    return x[sl]


@op("gather")
def _gather(x, indices, axis=0):
    return jnp.take(x, indices.astype(jnp.int32), axis=axis)


@op("scatterUpdate")
def _scatter_update(ref, indices, updates):
    return ref.at[indices.astype(jnp.int32)].set(updates)


@op("scatterAdd")
def _scatter_add(ref, indices, updates):
    return ref.at[indices.astype(jnp.int32)].add(updates)


@op("onehot")
def _onehot(x, depth=None, axis=-1, on=1.0, off=0.0):
    return jax.nn.one_hot(x.astype(jnp.int32), depth, axis=axis,
                          dtype=jnp.float32) * (on - off) + off


@op("cast")
def _cast(x, dtype=None):
    return x.astype(jnp.dtype(dtype))


@op("shape")
def _shape(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@op("pad")
def _pad(x, padding=None, constant=0.0, mode="CONSTANT"):
    return jnp.pad(x, tuple(tuple(p) for p in padding),
                   mode=mode.lower(), **(
                       {"constant_values": constant}
                       if mode.upper() == "CONSTANT" else {}))


@op("identity")
def _identity(x):
    return x


# ---- linalg ----
@op("mmul")
def _mmul(a, b, transposeA=False, transposeB=False):
    if transposeA:
        a = jnp.swapaxes(a, -1, -2)
    if transposeB:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@op("tensorMmul")
def _tensormmul(a, b, dimensionsA=None, dimensionsB=None):
    return jnp.tensordot(a, b, axes=(tuple(dimensionsA), tuple(dimensionsB)))


@op("batchMmul")
def _batchmmul(a, b):
    return jnp.matmul(a, b)


for _n, _f in {
    "cholesky": jnp.linalg.cholesky, "inv": jnp.linalg.inv,
    "det": jnp.linalg.det, "matrixDiag": jnp.diag, "diagPart": jnp.diagonal,
    "trace": jnp.trace,
}.items():
    _reg(_n, _f)


@op("svd")
def _svd(x, fullUV=False):
    return jnp.linalg.svd(x, full_matrices=fullUV)


@op("qr")
def _qr(x):
    q, r = jnp.linalg.qr(x)
    return q, r


@op("eye")
def _eye(rows=None, cols=None):
    return jnp.eye(rows, cols)


@op("cross")
def _cross(a, b):
    return jnp.cross(a, b)


@op("solve")
def _solve(a, b):
    return jnp.linalg.solve(a, b)


@op("lstsq")
def _lstsq(a, b):
    return jnp.linalg.lstsq(a, b)[0]


# ---- nn ----
for _n, _f in {
    "relu": jax.nn.relu, "relu6": jax.nn.relu6, "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus, "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu, "selu": jax.nn.selu, "gelu": jax.nn.gelu,
    "swish": jax.nn.swish, "hardSigmoid": jax.nn.hard_sigmoid,
    "hardTanh": jax.nn.hard_tanh,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
}.items():
    _reg(_n, _f)


@op("leakyRelu")
def _lrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, alpha)


@op("softmax")
def _softmax(x, dimension=-1):
    return jax.nn.softmax(x, axis=dimension)


@op("logSoftmax")
def _log_softmax(x, dimension=-1):
    return jax.nn.log_softmax(x, axis=dimension)


@op("linear")
def _linear(x, w, b=None):
    y = jnp.matmul(x, w)
    return y if b is None else y + b


@op("layerNorm")
def _layernorm(x, gain, bias=None, dimensions=(-1,)):
    ax = tuple(dimensions)
    mu = jnp.mean(x, axis=ax, keepdims=True)
    var = jnp.var(x, axis=ax, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + 1e-5) * gain
    return y if bias is None else y + bias


@op("dropout")
def _dropout(x, key=None, rate=0.0, train=False):
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@op("dotProductAttention")
def _dpa(q, k, v, mask=None, causal=False):
    return _attn.dot_product_attention(q, k, v, mask=mask, causal=causal)


@op("multiHeadDotProductAttention")
def _mhdpa(x, wq, wk, wv, wo, nHeads=1, causal=False):
    return _attn.multi_head_attention(x, wq, wk, wv, wo, nHeads, causal=causal)


@op("batchNorm")
def _batchnorm(x, mean, var, gamma=None, beta=None, epsilon=1e-5, axis=-1):
    shp = [1] * x.ndim
    shp[axis] = x.shape[axis]
    rs = lambda a: jnp.reshape(a, shp)
    y = (x - rs(mean)) * lax.rsqrt(rs(var) + epsilon)
    if gamma is not None:
        y = y * rs(gamma)
    if beta is not None:
        y = y + rs(beta)
    return y


@op("embeddingLookup")
def _embedding(table, ids):
    return jnp.take(table, ids.astype(jnp.int32), axis=0)


# ---- cnn ----
@op("conv2d")
def _conv2d(x, w, b=None, stride=(1, 1), padding=((0, 0), (0, 0)),
            dilation=(1, 1), groups=1):
    return _conv.conv2d(x, w, b, stride=tuple(stride),
                        padding=tuple(tuple(p) for p in padding),
                        dilation=tuple(dilation), groups=int(groups))


@op("conv1d")
def _conv1d(x, w, b=None, stride=1, padding=((0, 0),), dilation=1):
    return _conv.conv1d(x, w, b, stride=stride,
                        padding=tuple(tuple(p) for p in padding),
                        dilation=dilation)


@op("conv3d")
def _conv3d(x, w, b=None, stride=(1, 1, 1), padding=((0, 0),) * 3,
            dilation=(1, 1, 1)):
    return _conv.conv3d(x, w, b, stride=tuple(stride),
                        padding=tuple(tuple(p) for p in padding),
                        dilation=tuple(dilation))


@op("deconv2d")
def _deconv2d(x, w, b=None, stride=(1, 1), padding=((0, 0), (0, 0)),
              dilation=(1, 1)):
    return _conv.deconv2d(x, w, b, stride=tuple(stride),
                          padding=tuple(tuple(p) for p in padding),
                          dilation=tuple(dilation))


@op("maxPooling2d")
def _maxpool(x, kernel=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0))):
    return _pool.max_pool2d(x, tuple(kernel), tuple(stride),
                            tuple(tuple(p) for p in padding))


@op("avgPooling2d")
def _avgpool(x, kernel=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0)),
             count_include_pad=True):
    return _pool.avg_pool2d(x, tuple(kernel), tuple(stride),
                            tuple(tuple(p) for p in padding),
                            count_include_pad=count_include_pad)


@op("upsampling2d")
def _upsample(x, size=(2, 2)):
    return _pool.upsample2d(x, tuple(size))


@op("im2col")
def _im2col(x, kernel=(3, 3), stride=(1, 1), padding=((0, 0), (0, 0))):
    # NHWC in -> (N, OH, OW, KH, KW, C) patches, one fused XLA gather
    n, h, w, c = x.shape
    kh, kw = kernel
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=tuple(stride),
        padding=tuple(tuple(p) for p in padding),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    oh, ow = patches.shape[1], patches.shape[2]
    # patches feature dim is ordered (C, KH, KW) for NHWC inputs
    return jnp.transpose(patches.reshape(n, oh, ow, c, kh, kw),
                         (0, 1, 2, 4, 5, 3))


# ---- rnn ----
@op("lstmLayer")
def _lstm(x, w, u, b, h0=None, c0=None):
    ys, (h, c) = _rnn.lstm_scan(x, w, u, b, h0=h0, c0=c0)
    return ys, h, c


@op("gru")
def _gru(x, w, u, b, h0=None):
    ys, _h = _rnn.gru_scan(x, w, u, b, h0=h0)
    return ys


@op("simpleRnn")
def _simple_rnn(x, w, u, b, h0=None):
    ys, _h = _rnn.simple_rnn_scan(x, w, u, b, h0=h0)
    return ys


# ---- loss ----
# Dtype policy (round 6, the SameDiff loss tail): per-element loss math
# stays in the graph's compute dtype; the reductions accumulate in
# >= fp32 (`dtype=` on the reduce — XLA fuses the widening convert into
# the reduction, so nothing fp32 materialises at activation scale) and
# the returned loss scalar/per-example vector is fp32(+) for a sub-fp32
# graph. Cross-entropy uses the vector-scale-fp32 log_softmax shared
# with nn/losses so the [.., O] log-prob tensor keeps the input dtype.


def _acc_t(x):
    return jnp.promote_types(x.dtype, jnp.float32)


def _reduce_loss(per_ex, reduction):
    if reduction == "MEAN_BY_WEIGHT" or reduction == "MEAN":
        return jnp.mean(per_ex, dtype=_acc_t(per_ex))
    if reduction == "SUM":
        return jnp.sum(per_ex, dtype=_acc_t(per_ex))
    return per_ex


@op("lossMSE")
def _loss_mse(labels, predictions, reduction="MEAN"):
    per = jnp.mean(jnp.square(predictions - labels), axis=-1,
                   dtype=_acc_t(predictions))
    return _reduce_loss(per, reduction)


@op("lossMAE")
def _loss_mae(labels, predictions, reduction="MEAN"):
    return _reduce_loss(jnp.mean(jnp.abs(predictions - labels), axis=-1,
                                 dtype=_acc_t(predictions)), reduction)


@op("lossLog")
def _loss_log(labels, predictions, reduction="MEAN", epsilon=1e-7):
    p = jnp.clip(predictions, epsilon, 1.0 - epsilon)
    per = -jnp.mean(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p),
                    axis=-1, dtype=_acc_t(predictions))
    return _reduce_loss(per, reduction)


@op("softmaxCrossEntropy")
def _loss_sce(labels, logits, reduction="MEAN"):
    from deeplearning4j_tpu.nn.losses import _log_softmax

    per = -jnp.sum(labels.astype(logits.dtype) * _log_softmax(logits),
                   axis=-1, dtype=_acc_t(logits))
    return _reduce_loss(per, reduction)


@op("sparseSoftmaxCrossEntropy")
def _loss_ssce(labels, logits, reduction="MEAN"):
    from deeplearning4j_tpu.nn.losses import _log_softmax

    lp = _log_softmax(logits)
    per = -jnp.take_along_axis(
        lp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return _reduce_loss(per.astype(_acc_t(logits)), reduction)


@op("lossHinge")
def _loss_hinge(labels, predictions, reduction="MEAN"):
    per = jnp.mean(jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * predictions),
                   axis=-1, dtype=_acc_t(predictions))
    return _reduce_loss(per, reduction)


@op("lossHuber")
def _loss_huber(labels, predictions, delta=1.0, reduction="MEAN"):
    d = jnp.abs(predictions - labels)
    per = jnp.mean(jnp.where(d <= delta, 0.5 * d * d,
                             delta * d - 0.5 * delta * delta), axis=-1,
                   dtype=_acc_t(predictions))
    return _reduce_loss(per, reduction)


@op("lossKLD")
def _loss_kld(labels, predictions, reduction="MEAN", epsilon=1e-7):
    l = jnp.clip(labels, epsilon, 1.0)
    p = jnp.clip(predictions, epsilon, 1.0)
    return _reduce_loss(jnp.sum(l * jnp.log(l / p), axis=-1,
                                dtype=_acc_t(predictions)), reduction)


@op("lossPoisson")
def _loss_poisson(labels, predictions, reduction="MEAN"):
    per = jnp.mean(predictions - labels * jnp.log(predictions + 1e-7),
                   axis=-1, dtype=_acc_t(predictions))
    return _reduce_loss(per, reduction)


@op("lossCosine")
def _loss_cosine(labels, predictions, dimension=-1, reduction="MEAN"):
    ln = labels / (jnp.linalg.norm(labels, axis=dimension, keepdims=True) + 1e-12)
    pn = predictions / (jnp.linalg.norm(predictions, axis=dimension,
                                        keepdims=True) + 1e-12)
    return _reduce_loss(1.0 - jnp.sum(ln * pn, axis=dimension,
                                      dtype=_acc_t(predictions)), reduction)


# ---- bitwise (int ops) ----
for _n, _f in {
    "shiftLeft": jnp.left_shift, "shiftRight": jnp.right_shift,
    "bitwiseAnd": jnp.bitwise_and, "bitwiseOr": jnp.bitwise_or,
    "bitwiseXor": jnp.bitwise_xor, "bitwiseNot": jnp.bitwise_not,
}.items():
    _reg(_n, _f)


# ---- image ----
@op("resizeBilinear")
def _resize_bilinear(x, height=None, width=None, alignCorners=False):
    n, h, w, c = x.shape  # NHWC (framework-wide image layout)
    return jax.image.resize(x, (n, height, width, c), method="bilinear")


@op("resizeNearest")
def _resize_nearest(x, height=None, width=None):
    n, h, w, c = x.shape
    return jax.image.resize(x, (n, height, width, c), method="nearest")


@op("cropAndResize")
def _crop_resize(x, boxes, boxIndices, cropHeight=None, cropWidth=None):
    # boxes: (nBoxes, 4) normalized [y1, x1, y2, x2]; x: NHWC
    n, h, w, c = x.shape

    def one(box, bi):
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        img = x[bi.astype(jnp.int32)]
        ys = y1 * (h - 1) + jnp.linspace(0.0, 1.0, cropHeight) * (y2 - y1) * (h - 1)
        xs = x1 * (w - 1) + jnp.linspace(0.0, 1.0, cropWidth) * (x2 - x1) * (w - 1)
        # bilinear sample (differentiable w.r.t. box coords, matching the
        # reference op's default method)
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
        tl = img[y0i][:, x0i, :]
        tr = img[y0i][:, x1i, :]
        bl = img[y1i][:, x0i, :]
        br = img[y1i][:, x1i, :]
        top = tl * (1 - wx) + tr * wx
        bot = bl * (1 - wx) + br * wx
        return top * (1 - wy) + bot * wy

    return jax.vmap(one)(boxes, boxIndices)


@op("adjustContrast")
def _adjust_contrast(x, factor=1.0):
    mean = jnp.mean(x, axis=(-1, -2), keepdims=True)
    return (x - mean) * factor + mean


@op("hsvToRgb")
def _hsv_to_rgb(x):
    # x: (..., 3) channels-last hsv in [0,1]
    h, s, v = x[..., 0], x[..., 1], x[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


@op("rgbToHsv")
def _rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    h = jnp.where(
        d == 0, 0.0,
        jnp.where(mx == r, ((g - b) / (d + 1e-12)) % 6,
                  jnp.where(mx == g, (b - r) / (d + 1e-12) + 2,
                            (r - g) / (d + 1e-12) + 4))) / 6.0
    s = jnp.where(mx == 0, 0.0, d / (mx + 1e-12))
    return jnp.stack([h, s, mx], axis=-1)


# control-flow sentinels: registered so SameDiff._op accepts the names;
# execution is dispatched specially by SameDiff._run_graph (the bodies are
# sub-SameDiff graphs lowered to lax.cond / lax.while_loop / masked scan)
OPS["if_cond"] = None
OPS["while_loop"] = None


@op("clipByValue")
def _clip_by_value(x, clipValueMin=None, clipValueMax=None):
    # cast bounds to x's dtype: weak-float bounds would silently promote
    # integer tensors to float (DL4J preserves dtype)
    lo = jnp.asarray(clipValueMin, x.dtype)
    hi = jnp.asarray(clipValueMax, x.dtype)
    return jnp.clip(x, lo, hi)


@op("clipByNorm")
def _clip_by_norm(x, clipValue=None, dimensions=None):
    axes = None if not dimensions else tuple(dimensions)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + 1e-12)
    return x * jnp.minimum(1.0, clipValue / n)


@op("sort")
def _sort(x, axis=-1, descending=False):
    y = jnp.sort(x, axis=axis)
    return jnp.flip(y, axis) if descending else y


@op("topK")
def _topk(x, k=1, sorted=True):
    vals, idx = jax.lax.top_k(x, k)
    return vals, idx.astype(jnp.int32)


@op("split")
def _split(x, numSplit=2, axis=0):
    return tuple(jnp.split(x, numSplit, axis=axis))


# ---- random ops (reference: ops.SDRandom / legacy random ops in libnd4j;
# here: counter-based jax.random keyed by the executor — see
# SameDiff._run_graph, which injects `key` per stochastic op) ----

@op("randomNormal")
def _random_normal(shape=None, mean=0.0, stddev=1.0, key=None,
                   dtype="float32"):
    dt = jnp.dtype(dtype)
    return mean + stddev * jax.random.normal(key, tuple(shape), dt)


@op("randomUniform")
def _random_uniform(shape=None, min=0.0, max=1.0, key=None,
                    dtype="float32"):
    dt = jnp.dtype(dtype)
    return jax.random.uniform(key, tuple(shape), dt, minval=min, maxval=max)


@op("randomBernoulli")
def _random_bernoulli(shape=None, p=0.5, key=None, dtype="float32"):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(jnp.dtype(dtype))


@op("randomExponential")
def _random_exponential(shape=None, lambda_=1.0, key=None, dtype="float32"):
    dt = jnp.dtype(dtype)
    return jax.random.exponential(key, tuple(shape)).astype(dt) / lambda_


@op("nonMaxSuppression")
def _non_max_suppression(boxes, scores, maxOutputSize=10, iouThreshold=0.5,
                         scoreThreshold=float("-inf")):
    """Greedy NMS as a fixed-size jittable program (reference: libnd4j
    non_max_suppression / SDImage.nonMaxSuppression). boxes [N,4] as
    (y1,x1,y2,x2), scores [N] -> selected indices [maxOutputSize] int32,
    -1-padded. Data-dependent selection count becomes a static
    maxOutputSize loop with masking — the TPU-compatible form of the
    reference's dynamic-length output."""
    boxes = boxes.astype(jnp.float32)
    n = boxes.shape[0]
    if n == 0:  # no candidates is a normal detection outcome, not an error
        return jnp.full((int(maxOutputSize),), -1, jnp.int32)
    y1, x1, y2, x2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(y2 - y1, 0.0) * jnp.maximum(x2 - x1, 0.0)

    def iou_with(j):
        iy1 = jnp.maximum(y1, y1[j])
        ix1 = jnp.maximum(x1, x1[j])
        iy2 = jnp.minimum(y2, y2[j])
        ix2 = jnp.minimum(x2, x2[j])
        inter = jnp.maximum(iy2 - iy1, 0.0) * jnp.maximum(ix2 - ix1, 0.0)
        return inter / jnp.maximum(area + area[j] - inter, 1e-10)

    def body(i, state):
        sel, alive = state
        masked = jnp.where(alive, scores.astype(jnp.float32), -jnp.inf)
        j = jnp.argmax(masked)
        valid = jnp.isfinite(masked[j])  # anything left to select?
        alive = alive & (iou_with(j) <= iouThreshold) & \
            (jnp.arange(n) != j)
        sel = sel.at[i].set(jnp.where(valid, j, -1).astype(jnp.int32))
        return sel, alive

    sel0 = jnp.full((int(maxOutputSize),), -1, jnp.int32)
    # NaN scores (a diverged detector head) must not poison argmax and
    # suppress the valid boxes — drop them up front
    alive0 = jnp.isfinite(scores)
    if math.isfinite(scoreThreshold):
        alive0 = alive0 & (scores > scoreThreshold)
    sel, _ = lax.fori_loop(0, int(maxOutputSize), body, (sel0, alive0))
    return sel


# ---- reduction-style math long tail (reference: ops.SDMath — distance,
# segment, counting and entropy ops backed by libnd4j reduce3 /
# broadcastable kernels; here they are jnp compositions XLA fuses) ----

def _axes(dimensions):
    return tuple(dimensions) if dimensions else None


def _safe_sqrt(s):
    """sqrt with a zero-safe gradient: d/ds sqrt(0) is inf and the usual
    maximum()-clamp does NOT stop the inf*0=NaN chain under autodiff —
    the sqrt INPUT must be where-guarded."""
    return jnp.where(s > 0, jnp.sqrt(jnp.where(s > 0, s, 1.0)), 0.0)


@op("cosineSimilarity")
def _cosine_sim(x, y, dimensions=None):
    d = _axes(dimensions)
    num = jnp.sum(x * y, axis=d)
    den = _safe_sqrt(jnp.sum(jnp.square(x), axis=d)) * \
        _safe_sqrt(jnp.sum(jnp.square(y), axis=d))
    return jnp.where(den > 1e-12, num / jnp.where(den > 1e-12, den, 1.0),
                     0.0)


@op("cosineDistance")
def _cosine_dist(x, y, dimensions=None):
    return 1.0 - _cosine_sim(x, y, dimensions)


@op("euclideanDistance")
def _euclidean(x, y, dimensions=None):
    # zero-distance rows (converged embeddings) take the 0 subgradient
    return _safe_sqrt(jnp.sum(jnp.square(x - y), axis=_axes(dimensions)))


@op("manhattanDistance")
def _manhattan(x, y, dimensions=None):
    return jnp.sum(jnp.abs(x - y), axis=_axes(dimensions))


@op("hammingDistance")
def _hamming(x, y, dimensions=None):
    return jnp.sum((x != y).astype(jnp.float32), axis=_axes(dimensions))


@op("jaccardDistance")
def _jaccard(x, y, dimensions=None):
    d = _axes(dimensions)
    mins = jnp.sum(jnp.minimum(x, y), axis=d)
    maxs = jnp.sum(jnp.maximum(x, y), axis=d)
    return 1.0 - mins / jnp.maximum(maxs, 1e-12)


def _segment(reducer):
    def run(data, segmentIds, numSegments=None):
        if numSegments is None:
            # the executor compiles every graph (static shapes); a
            # data-dependent segment count cannot exist under trace
            raise ValueError(
                "segment ops require numSegments (the SameDiff executor "
                "compiles graphs with static output shapes)")
        ids = segmentIds.astype(jnp.int32)
        return reducer(data, ids, num_segments=int(numSegments))
    return run


for _n, _f in {
    "segmentSum": jax.ops.segment_sum, "segmentMax": jax.ops.segment_max,
    "segmentMin": jax.ops.segment_min, "segmentProd": jax.ops.segment_prod,
}.items():
    _reg(_n, _segment(_f))


@op("segmentMean")
def _segment_mean(data, segmentIds, numSegments=None):
    if numSegments is None:
        raise ValueError(
            "segment ops require numSegments (the SameDiff executor "
            "compiles graphs with static output shapes)")
    ids = segmentIds.astype(jnp.int32)
    s = jax.ops.segment_sum(data, ids, num_segments=int(numSegments))
    c = jax.ops.segment_sum(jnp.ones_like(data), ids,
                            num_segments=int(numSegments))
    return s / jnp.maximum(c, 1.0)


@op("confusionMatrix")
def _confusion_matrix(labels, pred, numClasses=None, weights=None):
    if numClasses is None:
        raise ValueError(
            "confusionMatrix requires numClasses (the SameDiff executor "
            "compiles graphs with static output shapes)")
    lab = labels.astype(jnp.int32).reshape(-1)
    prd = pred.astype(jnp.int32).reshape(-1)
    w = jnp.ones_like(lab, jnp.float32) if weights is None \
        else weights.reshape(-1).astype(jnp.float32)
    cm = jnp.zeros((int(numClasses), int(numClasses)), jnp.float32)
    return cm.at[lab, prd].add(w)


@op("confusionMatrixWeighted")
def _confusion_matrix_weighted(labels, pred, weights, numClasses=None):
    return _confusion_matrix(labels, pred, numClasses=numClasses,
                             weights=weights)


@op("zeroFraction")
def _zero_fraction(x):
    return jnp.mean((x == 0).astype(jnp.float32))


@op("countNonZero")
def _count_nonzero(x, dimensions=None, keepDims=False):
    return jnp.sum((x != 0).astype(jnp.int64), axis=_axes(dimensions),
                   keepdims=keepDims)


@op("countZero")
def _count_zero(x, dimensions=None, keepDims=False):
    return jnp.sum((x == 0).astype(jnp.int64), axis=_axes(dimensions),
                   keepdims=keepDims)


@op("entropy")
def _entropy(x, dimensions=None):
    xs = jnp.where(x > 0, x, 1.0)  # 0*log(0) = 0 convention
    return -jnp.sum(x * jnp.log(xs), axis=_axes(dimensions))


@op("shannonEntropy")
def _shannon_entropy(x, dimensions=None):
    xs = jnp.where(x > 0, x, 1.0)
    return -jnp.sum(x * jnp.log2(xs), axis=_axes(dimensions))


@op("matchConditionCount")
def _match_condition_count(x, condition="eq", value=0.0,
                           dimensions=None, keepDims=False):
    cmp = {"eq": jnp.equal, "neq": jnp.not_equal, "gt": jnp.greater,
           "gte": jnp.greater_equal, "lt": jnp.less,
           "lte": jnp.less_equal}[condition]
    return jnp.sum(cmp(x, value).astype(jnp.int64),
                   axis=_axes(dimensions), keepdims=keepDims)


@op("iamax")
def _iamax(x, dimensions=None):
    axis = dimensions[0] if dimensions else None
    return jnp.argmax(jnp.abs(x), axis=axis)


@op("linspace")
def _linspace(start=0.0, stop=1.0, num=10, dtype="float32"):
    return jnp.linspace(start, stop, int(num), dtype=jnp.dtype(dtype))


@op("range")
def _range(start=0, limit=None, delta=1, dtype="float32"):
    return jnp.arange(start, limit, delta, dtype=jnp.dtype(dtype))


@op("meshgrid")
def _meshgrid(*xs, indexing="xy"):
    r = jnp.meshgrid(*xs, indexing=indexing)
    return r[0] if len(r) == 1 else tuple(r)


@op("sigmoidCrossEntropy")
def _loss_sigmoid_ce(labels, logits, reduction="MEAN", labelSmoothing=0.0):
    if labelSmoothing:
        labels = labels * (1.0 - labelSmoothing) + 0.5 * labelSmoothing
    # numerically stable BCE-with-logits
    per = jnp.maximum(logits, 0.0) - logits * labels + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _reduce_loss(jnp.mean(per, axis=-1), reduction)


@op("weightedCrossEntropyWithLogits")
def _loss_weighted_ce(labels, logits, weights, reduction="MEAN"):
    """Per-class positive weighting of sigmoid CE (reference:
    SDLoss.weightedCrossEntropyWithLogits / TF semantics: loss =
    (1-l)*x + (1 + l*(w-1)) * log(1+exp(-x)) for x>=0 form)."""
    log_weight = 1.0 + (weights - 1.0) * labels
    per = (1.0 - labels) * logits + log_weight * (
        jnp.log1p(jnp.exp(-jnp.abs(logits))) +
        jnp.maximum(-logits, 0.0))
    return _reduce_loss(jnp.mean(per, axis=-1), reduction)


@op("l2Loss")
def _loss_l2(x):
    return jnp.sum(jnp.square(x)) / 2.0


@op("meanPairwiseSquaredError")
def _loss_mpwse(labels, predictions, reduction="MEAN"):
    """Mean over all within-example pairs of (d_i - d_j)^2 where
    d = predictions - labels (reference: SDLoss.meanPairwiseSquaredError).
    Closed form avoids materialising the NxN pair grid."""
    d = (predictions - labels).reshape(labels.shape[0], -1)
    n = d.shape[-1]
    # centered identity: sum_{i,j}(d_i-d_j)^2 = 2n*sum((d_i-dbar)^2).
    # The raw n*sum(d^2)-(sum d)^2 form cancels catastrophically when d
    # carries a large common offset (uniform bias -> true loss 0)
    dc = d - jnp.mean(d, axis=-1, keepdims=True)
    pair_sum = 2.0 * n * jnp.sum(jnp.square(dc), axis=-1)
    num_pairs = max(n * (n - 1), 1)
    per = pair_sum / num_pairs
    return _reduce_loss(per, reduction)


# ---- block rearrangement ops over NHWC (reference: libnd4j
# space_to_depth / depth_to_space / space_to_batch / batch_to_space;
# pure reshapes+transposes, free under XLA fusion) ----

@op("spaceToDepth")
def _space_to_depth(x, blockSize=2):
    B, H, W, C = x.shape
    b = int(blockSize)
    x = x.reshape(B, H // b, b, W // b, b, C)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(B, H // b, W // b, C * b * b)


@op("depthToSpace")
def _depth_to_space(x, blockSize=2):
    B, H, W, C = x.shape
    b = int(blockSize)
    x = x.reshape(B, H, W, b, b, C // (b * b))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(B, H * b, W * b, C // (b * b))


@op("spaceToBatch")
def _space_to_batch(x, blockSize=2, padding=((0, 0), (0, 0))):
    b = int(blockSize)
    p = tuple(tuple(q) for q in padding)
    x = jnp.pad(x, ((0, 0), p[0], p[1], (0, 0)))
    B, H, W, C = x.shape
    x = x.reshape(B, H // b, b, W // b, b, C)
    x = jnp.transpose(x, (2, 4, 0, 1, 3, 5))
    return x.reshape(B * b * b, H // b, W // b, C)


@op("batchToSpace")
def _batch_to_space(x, blockSize=2, crops=((0, 0), (0, 0))):
    b = int(blockSize)
    Bb, H, W, C = x.shape
    if Bb % (b * b):
        raise ValueError(
            f"batchToSpace needs batch ({Bb}) divisible by "
            f"blockSize^2 ({b * b})")
    B = Bb // (b * b)
    x = x.reshape(b, b, B, H, W, C)
    x = jnp.transpose(x, (2, 3, 0, 4, 1, 5))
    x = x.reshape(B, H * b, W * b, C)
    (ct, cb), (cl, cr) = tuple(tuple(q) for q in crops)
    if ct + cb > x.shape[1] or cl + cr > x.shape[2]:
        raise ValueError(f"crops {crops} exceed the expanded spatial dims "
                         f"{x.shape[1]}x{x.shape[2]}")
    return x[:, ct:x.shape[1] - cb, cl:x.shape[2] - cr, :]


@op("lu")
def _lu(x):
    import jax.scipy.linalg as jsl

    p, l, u = jsl.lu(x)
    return p, l, u


@op("eigh")
def _eigh(x):
    w, v = jnp.linalg.eigh(x)
    return w, v


# ---- fft (reference: the Nd4j.fft / spectral op surface,
# org.nd4j.linalg.api.ops.impl.transforms.custom fft family). XLA has a
# native FFT lowering on TPU (complex64); these are thin named wrappers
# so graphs serialize by op name like everything else. ----
@op("fft")
def _fft(x, numPoints=None, dimension=-1):
    return jnp.fft.fft(x, n=numPoints, axis=dimension)


@op("ifft")
def _ifft(x, numPoints=None, dimension=-1):
    return jnp.fft.ifft(x, n=numPoints, axis=dimension)


@op("rfft")
def _rfft(x, numPoints=None, dimension=-1):
    return jnp.fft.rfft(x, n=numPoints, axis=dimension)


@op("irfft")
def _irfft(x, numPoints=None, dimension=-1):
    return jnp.fft.irfft(x, n=numPoints, axis=dimension)


@op("fft2")
def _fft2(x):
    return jnp.fft.fft2(x)


@op("ifft2")
def _ifft2(x):
    return jnp.fft.ifft2(x)


for _n, _f in {
    "real": jnp.real, "imag": jnp.imag, "conj": jnp.conj,
    "angle": jnp.angle,
}.items():
    _reg(_n, _f)


@op("toComplex")
def _to_complex(re, im):
    return lax.complex(re, im)

"""Utilities: model serialization, workspaces, profiling.

Reference: org.deeplearning4j.util + org.nd4j.linalg.api.memory +
org.nd4j.linalg.profiler.
"""

from deeplearning4j_tpu.util.serializer import ModelSerializer, TrainingCheckpoint
from deeplearning4j_tpu.util.sharded_checkpoint import ShardedModelSerializer
from deeplearning4j_tpu.util.workspace import (
    MemoryWorkspace, WorkspaceConfiguration, WorkspaceManager,
)
from deeplearning4j_tpu.util.profiler import OpProfiler, trace, annotate

__all__ = ["ModelSerializer", "TrainingCheckpoint", "ShardedModelSerializer",
           "MemoryWorkspace",
           "WorkspaceConfiguration", "WorkspaceManager", "OpProfiler",
           "trace", "annotate"]

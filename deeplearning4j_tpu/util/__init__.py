"""Utilities: model serialization, workspaces, profiling.

Reference: org.deeplearning4j.util + org.nd4j.linalg.api.memory +
org.nd4j.linalg.profiler.
"""

from deeplearning4j_tpu.util.serializer import ModelSerializer, TrainingCheckpoint
from deeplearning4j_tpu.util.sharded_checkpoint import (
    ShardedModelSerializer, latest_step, gc_checkpoints, step_path,
    read_manifest,
)
from deeplearning4j_tpu.util.workspace import (
    MemoryWorkspace, WorkspaceConfiguration, WorkspaceManager,
)
from deeplearning4j_tpu.util.profiler import OpProfiler, trace, annotate

__all__ = ["ModelSerializer", "TrainingCheckpoint", "ShardedModelSerializer",
           "latest_step", "gc_checkpoints", "step_path", "read_manifest",
           "MemoryWorkspace",
           "WorkspaceConfiguration", "WorkspaceManager", "OpProfiler",
           "trace", "annotate"]

"""Profiling and per-step timing.

Reference: org.nd4j.linalg.profiler.OpProfiler + PerformanceListener's
timing half. On TPU the unit of work is the jitted step, not the single
op, so the profiler accounts (a) wall time per named section with
compile-time (first call) split from steady-state, and (b) optionally
wraps ``jax.profiler`` traces for inspection in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import threading
import time


class OpProfiler:
    """Singleton section timer (reference: OpProfiler.getInstance()),
    re-implemented as a thin facade over the telemetry registry
    (runtime.telemetry): every steady-state section observation lands
    in the ``dl4j_profiler_section_seconds{section=...}`` histogram and
    the first-call (compile) wall in the
    ``dl4j_profiler_compile_seconds{section=...}`` gauge, so old call
    sites keep their API while /metrics and metrics_snapshot() see the
    same data. Thread-safe (serving worker threads time sections
    concurrently — the old defaultdict mutation raced), clock
    injectable (``OpProfiler(clock=ManualClock())`` in tests)."""

    _instance = None
    _instance_lock = threading.Lock()

    @classmethod
    def getInstance(cls) -> "OpProfiler":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = OpProfiler()
            return cls._instance

    def __init__(self, clock=None, registry=None):
        from deeplearning4j_tpu.runtime import telemetry

        if registry is None:
            registry = telemetry.get_registry()
        self._registry = registry
        self._clock = clock if clock is not None else registry.clock
        self._lock = threading.RLock()
        self._steady = registry.histogram(
            "dl4j_profiler_section_seconds",
            "OpProfiler steady-state section wall (first call excluded)",
            labels=("section",))
        self._compile = registry.gauge(
            "dl4j_profiler_compile_seconds",
            "OpProfiler first-call wall ~ compile time under jit",
            labels=("section",))
        self._first = {}  # section -> first-call wall (compile split)

    def reset(self):
        """Zero this profiler's sections in place (its registry series
        included — handles stay attached, the singleton contract)."""
        with self._lock:
            for name in self._first:
                self._steady.labels(section=name).reset()
                self._compile.labels(section=name).reset()
            self._first = {}
        return self

    @contextlib.contextmanager
    def section(self, name: str):
        from deeplearning4j_tpu.runtime import telemetry

        t0 = self._clock()
        try:
            yield
        finally:
            # the kill switch skips ALL bookkeeping (incl. the
            # first-call split) so disabled-mode readings stay
            # internally consistent: invocations 0, times 0
            if telemetry.enabled():
                dt = self._clock() - t0
                with self._lock:
                    if name not in self._first:
                        self._first[name] = dt
                        self._compile.labels(section=name).set(dt)
                    else:
                        self._steady.labels(section=name).observe(dt)
                self._registry.trace.add(f"profiler.{name}", "profiler",
                                         t0, dt)

    def _steady_child(self, name):
        # READ path: must not create a series for a probed-but-never-
        # timed section name
        return self._steady.labels_get(section=name)

    def timeSpent(self, name: str) -> float:
        """Steady-state seconds (excludes the first, compiling call)."""
        c = self._steady_child(name)
        return c.sum if c is not None else 0.0

    def invocations(self, name: str) -> int:
        with self._lock:
            seen = name in self._first
        c = self._steady_child(name)
        return (c.count if c is not None else 0) + (1 if seen else 0)

    def compileTime(self, name: str) -> float:
        with self._lock:
            return self._first.get(name, 0.0)

    def averageTime(self, name: str) -> float:
        c = self._steady_child(name)
        return c.sum / max(c.count, 1) if c is not None else 0.0

    def printOutDashboard(self) -> str:
        lines = [f"{'section':<28}{'calls':>7}{'compile_s':>11}"
                 f"{'steady_avg_ms':>15}{'total_s':>9}"]
        with self._lock:
            names = sorted(self._first)  # snapshot vs concurrent sections
        for name in names:
            lines.append(f"{name:<28}{self.invocations(name):>7}"
                         f"{self.compileTime(name):>11.3f}"
                         f"{self.averageTime(name) * 1e3:>15.3f}"
                         f"{self.timeSpent(name):>9.3f}")
        out = "\n".join(lines)
        print(out)
        return out


# ----------------------------------------------------------------------
# FLOP accounting / MFU (reference: OpProfiler's op-level flop counters;
# on TPU the XLA compiler already knows the whole-step flop count, so we
# read it from the compiled executable instead of re-deriving per-op)
# ----------------------------------------------------------------------

# bf16 peak TFLOP/s per chip by device kind substring (public TPU specs)
_PEAK_BF16_FLOPS = (
    ("v6", 918e12),        # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),        # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device=None) -> float:
    """Per-chip peak bf16 FLOP/s for the given (default: first) device.
    Returns 0.0 when the device kind is unknown (CPU test meshes)."""
    import jax

    try:
        d = device or jax.devices()[0]
        kind = d.device_kind.lower()
    except Exception:  # fault-ok[FLT01]: 0.0 IS the documented answer for "unknown device" (docstring) — the MFU probe degrades to "no peak known", which callers already handle
        return 0.0
    for sub, peak in _PEAK_BF16_FLOPS:
        if sub in kind:
            return peak
    return 0.0


def compiled_cost(fn, *args, **kwargs) -> dict:
    """FLOPs + HBM bytes of one call of `fn(*args, **kwargs)` as XLA
    compiled it: {'flops': float, 'bytes_accessed': float}. `fn` may
    already be jitted; costs come from lower().compile().cost_analysis()."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    ca = jitted.lower(*args, **kwargs).compile().cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


def mfu(flops_per_step: float, step_time_s: float, device=None) -> float:
    """Model FLOP utilization: achieved FLOP/s over the chip's bf16 peak.
    0.0 when peak is unknown."""
    peak = device_peak_flops(device)
    if not peak or step_time_s <= 0:
        return 0.0
    return flops_per_step / step_time_s / peak


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler device trace around a block — open the dump with
    XProf/TensorBoard. (Reference analogue: ProfilerConfig + nvprof.)"""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (maps to jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)

"""Profiling and per-step timing.

Reference: org.nd4j.linalg.profiler.OpProfiler + PerformanceListener's
timing half. On TPU the unit of work is the jitted step, not the single
op, so the profiler accounts (a) wall time per named section with
compile-time (first call) split from steady-state, and (b) optionally
wraps ``jax.profiler`` traces for inspection in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict


class OpProfiler:
    """Singleton section timer (reference: OpProfiler.getInstance())."""

    _instance = None

    @classmethod
    def getInstance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    def __init__(self):
        self.reset()

    def reset(self):
        self._times = defaultdict(float)
        self._counts = defaultdict(int)
        self._first = {}  # first-call wall time ~ compile time under jit

    @contextlib.contextmanager
    def section(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if name not in self._first:
                self._first[name] = dt
            else:
                self._times[name] += dt
                self._counts[name] += 1

    def timeSpent(self, name: str) -> float:
        """Steady-state seconds (excludes the first, compiling call)."""
        return self._times[name]

    def invocations(self, name: str) -> int:
        return self._counts[name] + (1 if name in self._first else 0)

    def compileTime(self, name: str) -> float:
        return self._first.get(name, 0.0)

    def averageTime(self, name: str) -> float:
        return self._times[name] / max(self._counts[name], 1)

    def printOutDashboard(self) -> str:
        lines = [f"{'section':<28}{'calls':>7}{'compile_s':>11}"
                 f"{'steady_avg_ms':>15}{'total_s':>9}"]
        for name in sorted(self._first):
            lines.append(f"{name:<28}{self.invocations(name):>7}"
                         f"{self.compileTime(name):>11.3f}"
                         f"{self.averageTime(name) * 1e3:>15.3f}"
                         f"{self.timeSpent(name):>9.3f}")
        out = "\n".join(lines)
        print(out)
        return out


# ----------------------------------------------------------------------
# FLOP accounting / MFU (reference: OpProfiler's op-level flop counters;
# on TPU the XLA compiler already knows the whole-step flop count, so we
# read it from the compiled executable instead of re-deriving per-op)
# ----------------------------------------------------------------------

# bf16 peak TFLOP/s per chip by device kind substring (public TPU specs)
_PEAK_BF16_FLOPS = (
    ("v6", 918e12),        # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),        # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device=None) -> float:
    """Per-chip peak bf16 FLOP/s for the given (default: first) device.
    Returns 0.0 when the device kind is unknown (CPU test meshes)."""
    import jax

    try:
        d = device or jax.devices()[0]
        kind = d.device_kind.lower()
    except Exception:
        return 0.0
    for sub, peak in _PEAK_BF16_FLOPS:
        if sub in kind:
            return peak
    return 0.0


def compiled_cost(fn, *args, **kwargs) -> dict:
    """FLOPs + HBM bytes of one call of `fn(*args, **kwargs)` as XLA
    compiled it: {'flops': float, 'bytes_accessed': float}. `fn` may
    already be jitted; costs come from lower().compile().cost_analysis()."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    ca = jitted.lower(*args, **kwargs).compile().cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


def mfu(flops_per_step: float, step_time_s: float, device=None) -> float:
    """Model FLOP utilization: achieved FLOP/s over the chip's bf16 peak.
    0.0 when peak is unknown."""
    peak = device_peak_flops(device)
    if not peak or step_time_s <= 0:
        return 0.0
    return flops_per_step / step_time_s / peak


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler device trace around a block — open the dump with
    XProf/TensorBoard. (Reference analogue: ProfilerConfig + nvprof.)"""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (maps to jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)

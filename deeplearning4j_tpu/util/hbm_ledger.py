"""Per-op HBM traffic ledger + train-step roofline floor.

VERDICT r4 weak #3: the headline diagnosis stopped at "bandwidth-bound,
46.8 GB/step" with no table saying WHICH fusions carry those bytes or
what the unavoidable floor is. This module supplies both:

- `ledger(hlo_text)` walks the compiled module and charges each
  instruction the bytes it moves, following XLA's own HloCostAnalysis
  conventions so the total reproduces
  ``compiled.cost_analysis()["bytes accessed"]`` (validated exact to
  <0.1% on XLA:CPU by tests/test_hbm_ledger.py):

  * a plain instruction is charged its output buffer plus every operand
    buffer (resolved through a module-wide symbol table);
  * a TUPLE-shaped result is priced as its pointer table (8 bytes per
    top-level element, the backend's ShapeSizeBytes convention) — the
    leaf buffers are charged at the get-tuple-element consumers that
    actually read them, never twice;
  * ``call`` / ``while`` / ``conditional`` recurse into their attached
    computations (body + condition once for a while, matching
    HandleWhile's single-iteration convention) instead of being charged
    at the call site;
  * ``dynamic-slice`` / ``dynamic-update-slice`` are in-place: only the
    slice region is charged (2x the update/output plus the scalar
    indices), not the full aliased buffer;
  * ``fusion`` is call-site-priced (parameters + root) with XLA's
    utilization scaling: a fusion whose ROOT is a dynamic-update-slice
    writes only the update region (the aliased operand reads likewise),
    and a parameter consumed exclusively through dynamic-slice is
    charged the slice size, not the full buffer — the in-place loop
    patterns XLA emits for scan/select_and_scatter bodies. Everything
    else inside a fusion stays in registers/VMEM and is free.

- `train_step_floor(net, x_shape)` computes the analytic lower bound on
  HBM bytes for one training step from the MODEL, not the compiler:
  master params + optimizer state + grads at fp32, compute-dtype weight
  copies, the input batch, and the minimal activation traffic of a
  conv net's forward+backward. Measured bytes / floor says how close
  XLA's lowering is to the memory roofline — "within N% of floor" is a
  result; "bandwidth-bound" alone is a stopping excuse.

- `static_memory_terms(...)` is the RESIDENCY (capacity) counterpart of
  the floor's traffic model: per-chip HBM bytes a train step must hold
  live at its high-water mark. The partition-plan analyzer's PAR06 pass
  (analysis/partitioning.py) builds on it to predict OOM before any
  compile.

The floor's activation model, stated so the number is auditable: every
layer boundary activation A is (1) written by the forward, (2) read by
the backward to form the weight gradient, and its gradient G (same
size) is (3) written and (4) read by the adjacent backward step —
4 touches of each boundary buffer at compute dtype. Rematerialisation
can trade (1)/(2) for recompute; XLA fusion can eliminate boundaries
between elementwise neighbours, which is why the floor uses ONLY
conv/dense/pool boundaries (fusable chains of BN/relu/add don't count).
"""

from __future__ import annotations

import re

import numpy as np

from deeplearning4j_tpu.parallel.overlap import _DTYPE_BITS, _SHAPE_RE

# '%name = <result types> opcode(...operands...)'
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*?)\s([a-z][\w\-]*)\((.*)$")
_OPERAND_RE = re.compile(r"%?([\w.\-]+)")

# 'name {' / 'ENTRY name {' / '%name (params) -> result {'
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*)?\{\s*$")

# attached-computation attributes, parsed per key so a comma-list like
# branch_computations={%a, %b} cannot bleed into the next attribute
_ATTACH_RE = re.compile(
    r"(?:calls|to_apply|condition|body|true_computation|false_computation)"
    r"=%?([\w.\-]+)")
_ATTACH_LIST_RE = re.compile(r"branch_computations=\{([^}]*)\}")

# opcodes that don't move HBM bytes themselves (metadata / control flow
# / aliasing views); their operands are charged where actually consumed
_FREE_OPS = {"parameter", "get-tuple-element", "bitcast",
             "constant", "after-all", "partition-id", "replica-id"}

# opcodes charged by recursing into their attached computations
# (HloCostAnalysis HandleCall/HandleWhile/HandleConditional)
_SUBCOMP_OPS = {"call", "while", "conditional"}

_POINTER_SIZE = 8  # bytes per tuple-table entry (CPU/TPU ShapeSizeBytes)


_ANY_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]{0,14})\[[0-9,]*\]")


def _tuple_arity(result_text):
    """Top-level element count of a tuple-shaped result text like
    '(f32[2]{0}, (s32[3]{0}, s32[]))' -> 2; 0 for non-tuple results."""
    s = result_text.strip()
    if not s.startswith("("):
        return 0
    depth = 0
    arity = 1
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                break
        elif ch == "," and depth == 1:
            arity += 1
    return arity


def _result_bytes(result_text):
    # an unrecognized dtype must FAIL, not silently rank as 0 bytes —
    # the whole point is an accurate table on the TPU backend
    for tok in _ANY_SHAPE_RE.findall(result_text):
        if tok not in _DTYPE_BITS and tok != "token":
            raise ValueError(
                f"unknown HLO dtype {tok!r} in {result_text[:80]!r} — "
                "add it to parallel/overlap.py _DTYPE_BITS")
    arity = _tuple_arity(result_text)
    if arity:
        # tuple shape = pointer table; the element buffers are charged
        # at the GTE consumers that read them
        return arity * _POINTER_SIZE
    total = 0
    for dt, dims in _SHAPE_RE.findall(result_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += (n * _DTYPE_BITS[dt] + 7) // 8
    return total


def _result_meta(result_text):
    """(dtype_str, elems) of a single-shape non-tuple result; None for
    tuples, tokens and anything else the classifier cannot reason
    about (attribution then treats the buffer as opaque)."""
    s = result_text.strip()
    if s.startswith("("):
        return None
    found = _SHAPE_RE.findall(result_text)
    if len(found) != 1:
        return None
    dt, dims = found[0]
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return dt, n


def _parse_module(hlo_text):
    """-> (sizes, comp_sizes, computations, entry_name, meta, comp_meta)
    where computations maps name -> [(name, op, out_bytes,
    operand_names, attached_comps, is_root)] and meta/comp_meta carry
    (dtype, elems) per instruction for the attribution classifier.

    HLO instruction names are only guaranteed unique PER COMPUTATION —
    a name reused inside a fusion/called computation must not overwrite
    an ENTRY buffer's size (ADVICE r5 #1) — so sizes are recorded both
    per computation (`comp_sizes`, the authoritative scope for operand
    resolution) and module-wide (`sizes`, the fallback for names a
    computation references but does not define, e.g. cross-computation
    references in synthetic test modules)."""
    sizes = {}
    comp_sizes = {}
    meta = {}
    comp_meta = {}
    comps = {}
    cur = None
    entry = None
    for line in hlo_text.splitlines():
        s = line.strip()
        m = _DEF_RE.match(line)
        if m is None:
            # not an instruction: computation header or closing brace
            cm = _COMP_RE.match(s)
            if cm:
                cur = cm.group(2)
                comps[cur] = []
                comp_sizes[cur] = {}
                comp_meta[cur] = {}
                if cm.group(1):
                    entry = cur
            elif s == "}":
                cur = None
            continue
        name, result, op, rest = m.groups()
        nbytes = _result_bytes(result)
        rmeta = _result_meta(result)
        # module-wide fallback keeps the FIRST definition: a later
        # fusion-internal reuse of an entry name cannot reprice it
        sizes.setdefault(name, nbytes)
        if rmeta is not None:
            meta.setdefault(name, rmeta)
        if cur is not None:
            comp_sizes[cur][name] = nbytes
            if rmeta is not None:
                comp_meta[cur][name] = rmeta
        # operands = instruction names before the first metadata key;
        # stop there to avoid charging called-computation names
        arg_text = rest.split("), ")[0] if "), " in rest else rest
        operands = _OPERAND_RE.findall(arg_text)
        attached = _ATTACH_RE.findall(rest)
        for lst in _ATTACH_LIST_RE.findall(rest):
            attached.extend(t.strip().lstrip("%")
                            for t in lst.split(",") if t.strip())
        if cur is not None:
            comps[cur].append((name, op, nbytes, operands, attached,
                               s.startswith("ROOT ")))
    return sizes, comp_sizes, comps, entry, meta, comp_meta


def _fusion_bytes(fname, callsite_operands, out_bytes, caller_sizes,
                  inner_sizes, comps):
    """(bytes, out, in) of one fusion call site with XLA's utilization
    scaling: an in-place DUS root writes only the update region, and a
    parameter consumed exclusively via dynamic-slice is charged the
    slice size (HloCostAnalysis fusion handling). Falls back to the
    plain parameters+root charge when the fused computation is
    unavailable.

    Two size scopes (HLO names are unique per computation only):
    `caller_sizes` resolves the CALLSITE operands, `inner_sizes` the
    fusion-internal instructions — a shared name must never cross."""
    insts = comps.get(fname)
    known = [t for t in callsite_operands if t in caller_sizes]
    if not insts:
        seen, in_bytes = set(), 0
        for t in known:
            if t not in seen:
                seen.add(t)
                in_bytes += caller_sizes[t]
        return out_bytes + in_bytes, out_bytes, in_bytes

    param_of = {}     # inner parameter name -> callsite operand name
    consumers = {}    # inner name -> [(op, operands)]
    root = None
    for name, op, _, operands, _, is_root in insts:
        if op == "parameter":
            idx = next((int(t) for t in operands if t.isdigit()), None)
            if idx is not None and idx < len(known):
                param_of[name] = known[idx]
        else:
            for t in operands:
                consumers.setdefault(t, []).append((op, operands))
        if is_root:
            root = (name, op, operands)
    if root is None and insts:
        root = (insts[-1][0], insts[-1][1], insts[-1][3])

    dus_aliased = None   # inner name feeding the in-place DUS operand 0
    out_eff = out_bytes
    if root is not None and root[1] == "dynamic-update-slice":
        r_ops = [t for t in root[2] if t in inner_sizes]
        if len(r_ops) >= 2:
            out_eff = inner_sizes[r_ops[1]]    # update region only
            dus_aliased = r_ops[0]

    def data_operand(operands):
        """First operand that names an instruction (the token list also
        carries dtype/dim text, which never resolves in the scope)."""
        return next((t for t in operands if t in inner_sizes), None)

    in_bytes = 0
    for pname, site_name in param_of.items():
        uses = consumers.get(pname, [])
        if pname == dus_aliased:
            in_bytes += out_eff          # aliased: reads the update region
        elif uses and all(op == "dynamic-slice"
                          and data_operand(ops) == pname
                          for op, ops in uses):
            # sliced access only: charge each slice's output, not the
            # full buffer
            in_bytes += sum(b for _n, o, b, ops2, _a, _r in insts
                            if o == "dynamic-slice"
                            and data_operand(ops2) == pname)
        else:
            in_bytes += caller_sizes[site_name]
    return out_eff + in_bytes, out_eff, in_bytes


def _instruction_bytes(op, out_bytes, operands, sizes):
    """(bytes, out, in) for one non-recursive instruction, following the
    HloCostAnalysis special cases for in-place slicing ops."""
    known = [t for t in operands if t in sizes]
    if op == "dynamic-update-slice":
        # operand 0 aliases the output: only the update region moves
        upd = sizes[known[1]] if len(known) > 1 else 0
        idx = sum(sizes[t] for t in known[2:])
        return 2 * upd + idx, upd, upd + idx
    if op == "dynamic-slice":
        idx = sum(sizes[t] for t in known[1:])
        return 2 * out_bytes + idx, out_bytes, out_bytes + idx
    if op == "tuple":
        # gathers pointers only; element buffers charged at consumers
        return out_bytes, out_bytes, 0
    in_bytes = 0
    seen = set()
    for t in known:
        if t not in seen:
            seen.add(t)
            in_bytes += sizes[t]
    return out_bytes + in_bytes, out_bytes, in_bytes


def ledger(hlo_text, top=15):
    """Rank ENTRY instructions by HBM bytes touched.

    Returns {"total_bytes", "by_opcode": {op: bytes}, "top": [
    {"name", "op", "bytes", "out_bytes", "in_bytes"}, ...]}.
    by_opcode attributes bytes to the opcode that actually moves them —
    instructions inside call/while/conditional bodies count under their
    own opcodes, not under the call site's.
    """
    sizes, comp_sizes, comps, entry, _meta, _comp_meta = \
        _parse_module(hlo_text)
    if entry is None:
        # single anonymous/first computation (inline test modules)
        entry = next(iter(comps)) if comps else None

    by_op = {}
    visiting = set()
    scopes = {}

    def scoped(cname):
        """Operand-size scope for one computation: its OWN definitions
        first (HLO names are unique per computation, so a fusion-
        internal name reuse can't misprice an entry instruction —
        ADVICE r5 #1), module-wide first-definition fallback for names
        it references but does not define. ChainMap: two-level lookup
        without copying the module-wide table per computation."""
        from collections import ChainMap

        sc = scopes.get(cname)
        if sc is None:
            sc = ChainMap(comp_sizes.get(cname, {}), sizes)
            scopes[cname] = sc
        return sc

    def inst_bytes(op, out_bytes, operands, attached, sc):
        if op == "fusion" and attached:
            return _fusion_bytes(attached[0], operands, out_bytes, sc,
                                 scoped(attached[0]), comps)
        return _instruction_bytes(op, out_bytes, operands, sc)

    def comp_cost(cname):
        """Total bytes of one computation, recursing through
        call/while/conditional (processed per call site, as
        HloCostAnalysis does); free ops and fusion interiors are never
        charged."""
        if cname in visiting or cname not in comps:
            return 0
        visiting.add(cname)
        sc = scoped(cname)
        total = 0
        for name, op, out_bytes, operands, attached, _root in comps[cname]:
            if op in _FREE_OPS:
                continue
            if op in _SUBCOMP_OPS:
                total += sum(comp_cost(a) for a in attached)
                continue
            nbytes, _, _ = inst_bytes(op, out_bytes, operands, attached, sc)
            total += nbytes
            by_op[op] = by_op.get(op, 0) + nbytes
        visiting.discard(cname)
        return total

    rows = []
    total = 0
    entry_scope = scoped(entry) if entry is not None else dict(sizes)
    for name, op, out_bytes, operands, attached, _root in comps.get(entry, []):
        if op in _FREE_OPS:
            continue
        if op in _SUBCOMP_OPS:
            sub = sum(comp_cost(a) for a in attached)
            total += sub
            rows.append({"name": name, "op": op, "bytes": sub,
                         "out_bytes": 0, "in_bytes": sub})
            continue
        nbytes, ob, ib = inst_bytes(op, out_bytes, operands, attached,
                                    entry_scope)
        total += nbytes
        by_op[op] = by_op.get(op, 0) + nbytes
        rows.append({"name": name, "op": op, "bytes": nbytes,
                     "out_bytes": ob, "in_bytes": ib})
    rows.sort(key=lambda r: -r["bytes"])
    return {"total_bytes": total,
            "by_opcode": dict(sorted(by_op.items(), key=lambda kv: -kv[1])),
            "top": rows[:top]}


def ledger_for_compiled(compiled, top=15):
    return ledger(compiled.as_text(), top=top)


# ---------------------------------------------------------------------
# analytic roofline floor
# ---------------------------------------------------------------------

_BOUNDARY_LAYERS = ("ConvolutionLayer", "Convolution2D", "DenseLayer",
                    "SubsamplingLayer", "SeparableConvolution2D",
                    "DepthwiseConvolution2D", "Deconvolution2D",
                    "OutputLayer")


def _boundary_layer_objects(net):
    if hasattr(net, "layers"):  # MultiLayerNetwork
        layers = list(net.layers)
    else:  # ComputationGraph
        layers = [n.payload for n in net.conf.nodes.values()
                  if getattr(n, "payload", None) is not None]
    return [l for l in layers if type(l).__name__ in _BOUNDARY_LAYERS]


def _input_shapes(net, x_shape):
    """Normalize `x_shape` into {input_name: shape} for a
    ComputationGraph (ADVICE r5 #3: multi-input graphs pass a dict of
    input shapes; a bare tuple keeps working for single-input graphs),
    or return the tuple unchanged for a MultiLayerNetwork."""
    if hasattr(net, "layers"):  # MultiLayerNetwork: one positional input
        if isinstance(x_shape, dict):
            raise ValueError(
                "MultiLayerNetwork takes one input shape tuple, not a "
                "dict")
        return tuple(x_shape)
    names = list(net.conf.networkInputs)
    if isinstance(x_shape, dict):
        missing = [n for n in names if n not in x_shape]
        if missing:
            raise ValueError(
                f"x_shape dict is missing graph input(s) {missing} "
                f"(graph inputs: {names})")
        return {n: tuple(x_shape[n]) for n in names}
    if len(names) == 1:
        return {names[0]: tuple(x_shape)}
    raise ValueError(
        f"graph has {len(names)} inputs ({names}); pass x_shape as a "
        "dict of input shapes, e.g. {name: (B, ...), ...}")


def boundary_activation_elems(net, x_shape):
    """Per-layer boundary activation element counts via jax.eval_shape
    (abstract — nothing executes). Only conv/dense/pool boundaries
    count; elementwise chains between them are fusable and carry no
    unavoidable HBM traffic. Works for MultiLayerNetwork AND
    ComputationGraph by recording each boundary layer's forward output
    shape during the abstract trace; multi-input graphs pass `x_shape`
    as a {input_name: shape} dict."""
    import jax

    shapes = _input_shapes(net, x_shape)
    recorded = []
    wrapped = []
    for layer in _boundary_layer_objects(net):
        orig = layer.forward  # bound method

        def mk(orig):
            def spy(*a, **kw):
                out = orig(*a, **kw)
                h = out[0] if isinstance(out, tuple) else out
                recorded.append(int(np.prod(h.shape)))
                return out
            return spy

        layer.forward = mk(orig)  # instance attr shadows the class method
        wrapped.append(layer)
    try:
        dt = np.dtype(net._compute_dtype)
        if hasattr(net, "layers"):
            x = jax.ShapeDtypeStruct(shapes, dt)
            jax.eval_shape(
                lambda xx: net._forward_infer(net._params, net._states, xx),
                x)
        else:
            xs = {n: jax.ShapeDtypeStruct(s, dt) for n, s in shapes.items()}
            jax.eval_shape(
                lambda inputs: net._forward_infer(net._params, net._states,
                                                  inputs), xs)
    finally:
        for layer in wrapped:
            del layer.__dict__["forward"]
    return recorded


def train_step_floor(net, x_shape, optimizer_slots=1):
    """Analytic lower bound on HBM bytes for one train step.

    optimizer_slots: per-param fp32 state buffers the updater holds
    (1 = momentum/Nesterovs, 2 = Adam).
    Terms, each at its dtype (see module docstring for the activation
    model):
      params:   fp32 master read + write, compute-dtype copy written
                once and read by fwd and bwd (3 touches at compute)
      optimizer: fp32 state read + write per slot
      grads:    fp32 write + read
      input:    batch read once at compute dtype
      acts:     4 touches of every conv/dense/pool boundary buffer
    """
    cb = np.dtype(net._compute_dtype).itemsize
    pb = np.dtype(net._param_dtype).itemsize
    P = int(sum(a.size for a in _tree_leaves(net._params)))
    A = int(sum(boundary_activation_elems(net, x_shape)))
    shapes = _input_shapes(net, x_shape)
    if isinstance(shapes, dict):  # multi-input graph: every batch reads
        Bx = int(sum(np.prod(s) for s in shapes.values()))
    else:
        Bx = int(np.prod(shapes))
    # when compute dtype == param dtype there IS no separate cast copy:
    # fwd+bwd read the master buffers directly (2 reads) — charging the
    # 3-touch copy there would push the "floor" ABOVE real programs
    copy_bytes = 3 * P * cb if cb != pb else 2 * P * pb
    terms = {
        "params_master_rw": 2 * P * pb,
        "params_compute_copy": copy_bytes,
        "optimizer_state_rw": 2 * optimizer_slots * P * pb,
        "grads_wr": 2 * P * pb,
        "input_read": Bx * cb,
        "activations_4touch": 4 * A * cb,
    }
    return {"floor_bytes": int(sum(terms.values())), "terms": terms,
            "param_count": P, "boundary_activation_elems": A}


# ---------------------------------------------------------------------
# static residency (capacity) model — the PAR06 building block
# ---------------------------------------------------------------------

def static_memory_terms(param_elems, opt_state_elems, boundary_act_bytes,
                        compute_itemsize, param_itemsize, input_bytes=0,
                        grad_itemsize=None, weight_update_sharding=1.0):
    """Per-chip HBM RESIDENCY at the train step's high-water mark,
    computed from already-placed (per-chip) element counts — the caller
    (analysis/partitioning.py) applies the sharding plan's division
    first. This is capacity, not traffic: what must fit, vs what the
    floor says must move.

      params:      fp32 master copies
      grads:       one gradient buffer per param (fp32 — the updaters
                   consume fp32 grads)
      optimizer:   the updater's state leaves (exact count, not slots x
                   params — Sgd holds nothing, Adam holds 2x), divided
                   by `weight_update_sharding`
      cast copy:   a compute-dtype copy of the params, only when the
                   compute dtype differs from the param dtype
      activations: every conv/dense/pool boundary buffer simultaneously
                   live at the start of the backward pass (the
                   high-water mark without rematerialisation)
      input:       the device-resident batch

    weight_update_sharding is the ZeRO cross-replica weight-update
    sharding factor (parallel.sharding.ZeroShardedUpdate): under
    weight_update='sharded' each chip holds only 1/dp of the updater
    state — params stay replicated (the forward needs them) and the
    gradient buffer is still materialised whole before its
    reduce-scatter, so ONLY the optimizer term divides. Pass the
    EFFECTIVE factor (opt_state_elems-layout bytes / actual per-chip
    bytes): leaves below min_shard_size or indivisible by dp stay
    replicated, so the effective factor is <= dp (the partition-plan
    analyzer's PAR06 pass computes it exactly from the per-leaf
    eligibility rule). The factor may be BELOW 1: when
    `opt_state_elems` already reflects a tensor-parallel division finer
    than dp (tp > dp), the ZeRO flat view's 1/dp-over-the-data-axis
    layout genuinely holds MORE per chip than the tp layout would — the
    residency model must report that, not clamp it away.
    """
    gb = param_itemsize if grad_itemsize is None else grad_itemsize
    wf = float(weight_update_sharding)
    if wf <= 0.0:
        raise ValueError(
            f"weight_update_sharding must be > 0, got {wf}")
    terms = {
        "params_bytes": int(param_elems * param_itemsize),
        "grads_bytes": int(param_elems * gb),
        "optimizer_state_bytes": int(opt_state_elems * param_itemsize
                                     / wf),
        "params_cast_copy_bytes": (int(param_elems * compute_itemsize)
                                   if compute_itemsize != param_itemsize
                                   else 0),
        "activations_bytes": int(boundary_act_bytes),
        "input_bytes": int(input_bytes),
    }
    terms["total_bytes"] = int(sum(terms.values()))
    terms["weight_update_sharding"] = round(wf, 4)
    return terms


def _tree_leaves(t):
    import jax

    return jax.tree_util.tree_leaves(t)


# ---------------------------------------------------------------------
# attribution engine: name the gap between ledger total and floor
# ---------------------------------------------------------------------
#
# The round-5 ledger proved the flagship moves ~3.95x the analytic floor
# and stopped there. attribute_ledger() finishes the sentence: every
# charged byte is classified into the floor (the bytes the MODEL needs)
# or a named overhead bin (the bytes the LOWERING added), so "35 GB of
# lowering overhead" becomes a per-category bill the next fix can be
# measured against.
#
# Bin conventions (chosen so no charged byte lands in two bins and the
# invariant floor + bins + uncategorized == ledger total holds exactly):
#
#   layout_copies     full bytes of relayout instructions — copy /
#                     copy-start/-done / transpose / pad / reshape /
#                     slice / concatenate / reverse / broadcast — and of
#                     fusions whose ROOT is one (XLA's copy/transpose
#                     fusions). The floor contains no relayouts, so the
#                     whole row is overhead.
#   dtype_widening    the WIDENING EXCESS of buffers wider than the
#                     compute dtype at activation scale: a f32 buffer in
#                     a bf16-policy step is half excess — the floor
#                     already prices the bf16-equivalent touch. Charged
#                     on writes and on every read.
#   grad_double_touch reads BEYOND THE FIRST of compute-dtype
#                     activation-scale buffers (the dX-conv + dW-conv
#                     both re-reading a boundary activation is the
#                     canonical case). The floor's 4-touch model allows
#                     one backward read per buffer; extra reads are
#                     overhead.
#   collective        full bytes of cross-replica traffic (all-reduce /
#                     all-gather / reduce-scatter / collective-permute /
#                     all-to-all) — the data-parallel weight-update bill
#                     (cf. Xu et al., cross-replica sharding of weight
#                     update); the single-chip floor has none.
#
# "Activation scale" = more elements than the largest parameter leaf:
# master params, grads and updater state are at most param-sized, so
# anything bigger must be batch/spatial data. uncategorized is the
# remainder; it holds the floor itself (params/grads/updater/input/
# activation traffic is not re-identified buffer-by-buffer) plus
# whatever the bins cannot name — a large POSITIVE uncategorized on a
# gap-heavy program means the bins missed something and is reported,
# never hidden.

#: cross-replica traffic (async start/done forms included)
_COLLECTIVE_OPS = {
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "all-reduce-start", "all-reduce-done",
    "all-gather-start", "all-gather-done", "collective-permute-start",
    "collective-permute-done", "reduce-scatter-start",
    "reduce-scatter-done",
}

#: pure-relayout opcodes: they move bytes without computing anything the
#: floor model recognises
_LAYOUT_OPS = {"copy", "copy-start", "copy-done", "transpose", "pad",
               "reshape", "slice", "concatenate", "reverse", "broadcast"}

_FLOAT_DTYPES = frozenset(d for d in _DTYPE_BITS
                          if d[0] == "f" or d.startswith("bf"))


def _walk_charged_rows(mod):
    """Every charged instruction as a flat row list, recursing through
    call/while/conditional per call site exactly as ledger() does —
    sum(row bytes) == ledger()['total_bytes'] by construction. Fusions
    are one call-site-priced row annotated with their root opcode (the
    relayout-fusion marker); free ops never appear.

    Row: (scope, name, op, bytes, out_bytes, in_bytes, out_meta,
    reads, root_op) with reads = [(operand, bytes, meta), ...] over the
    distinct resolved operands."""
    from collections import ChainMap

    sizes, comp_sizes, comps, entry, meta, comp_meta = mod
    if entry is None:
        entry = next(iter(comps)) if comps else None

    size_scopes, meta_scopes = {}, {}

    def scoped(cname):
        sc = size_scopes.get(cname)
        if sc is None:
            sc = ChainMap(comp_sizes.get(cname, {}), sizes)
            size_scopes[cname] = sc
            meta_scopes[cname] = ChainMap(comp_meta.get(cname, {}), meta)
        return sc, meta_scopes[cname]

    rows = []
    visiting = set()

    def walk(cname):
        if cname in visiting or cname not in comps:
            return
        visiting.add(cname)
        sc, mc = scoped(cname)
        for name, op, out_bytes, operands, attached, _root in comps[cname]:
            if op in _FREE_OPS:
                continue
            if op in _SUBCOMP_OPS:
                for a in attached:
                    walk(a)
                continue
            root_op = None
            if op == "fusion" and attached:
                insts = comps.get(attached[0]) or ()
                for iname, iop, _b, _o, _a, is_root in insts:
                    if is_root:
                        root_op = iop
                nbytes, ob, ib = _fusion_bytes(
                    attached[0], operands, out_bytes, sc,
                    scoped(attached[0])[0], comps)
            else:
                nbytes, ob, ib = _instruction_bytes(op, out_bytes,
                                                    operands, sc)
            reads, seen = [], set()
            for t in operands:
                if t in sc and t not in seen:
                    seen.add(t)
                    reads.append((t, sc[t], mc.get(t)))
            rows.append((cname, name, op, nbytes, ob, ib,
                         mc.get(name), reads, root_op))
        visiting.discard(cname)

    if entry is not None:
        walk(entry)
    return rows


def _is_scale(m, threshold_elems):
    return m is not None and m[1] > threshold_elems


def attribute_ledger(compiled, net=None, x_shape=None, optimizer_slots=1,
                     compute_dtype=None, act_threshold_elems=None, top=6):
    """Classify every charged byte of a compiled train step into the
    analytic floor vs named lowering-overhead bins (see the bin table
    above). `compiled` is a compiled executable or raw HLO text.

    With `net` (+ `x_shape`) the floor, the compute dtype and the
    activation-scale threshold all come from the model; without a net,
    pass `compute_dtype` and `act_threshold_elems` explicitly and the
    report is bins-only (floor 0). Invariant, exact by construction:

        floor_bytes + sum(bins) + uncategorized_bytes == ledger total
    """
    hlo = compiled if isinstance(compiled, str) else compiled.as_text()
    mod = _parse_module(hlo)
    rows = _walk_charged_rows(mod)
    total = sum(r[3] for r in rows)

    if net is not None:
        if compute_dtype is None:
            compute_dtype = net._compute_dtype
        if act_threshold_elems is None:
            act_threshold_elems = max(
                (int(a.size) for a in _tree_leaves(net._params)), default=0)
    if compute_dtype is None or act_threshold_elems is None:
        raise ValueError(
            "attribute_ledger needs a net (for the compute dtype and the "
            "activation-scale threshold) or explicit compute_dtype= and "
            "act_threshold_elems=")
    cbits = np.dtype(compute_dtype).itemsize * 8
    thr = int(act_threshold_elems)

    floor = None
    if net is not None and x_shape is not None:
        floor = train_step_floor(net, x_shape,
                                 optimizer_slots=optimizer_slots)

    bins = {"layout_copies": 0, "dtype_widening": 0,
            "grad_double_touch": 0, "collective": 0}
    contrib = {k: [] for k in bins}

    def wide_excess(m, nbytes):
        """Excess bytes of one wide-float activation-scale touch."""
        dt = m[0]
        if dt not in _FLOAT_DTYPES or _DTYPE_BITS[dt] <= cbits:
            return 0
        return int(round(nbytes * (1.0 - cbits / _DTYPE_BITS[dt])))

    read_counts = {}  # (scope, operand) -> [count, bytes, meta]
    for scope, name, op, nbytes, ob, ib, out_meta, reads, root_op in rows:
        if op in _COLLECTIVE_OPS or root_op in _COLLECTIVE_OPS:
            bins["collective"] += nbytes
            # param-scale collectives are the dp weight-update bill
            # (gradient all-reduce — Xu et al.); activation-scale ones
            # are tensor/sequence-parallel traffic. The split names
            # which fix applies (cross-replica update sharding vs
            # layout/sharding of activations).
            kind = ("activation" if _is_scale(out_meta, thr)
                    else "weight_update")
            contrib["collective"].append((f"{name} [{kind}]", op, nbytes))
            continue
        if op in _LAYOUT_OPS or (op == "fusion"
                                 and root_op in _LAYOUT_OPS):
            bins["layout_copies"] += nbytes
            contrib["layout_copies"].append((name, op, nbytes))
            continue
        wid = 0
        if _is_scale(out_meta, thr):
            wid += wide_excess(out_meta, ob)
        for t, b, m in reads:
            if _is_scale(m, thr):
                wid += wide_excess(m, b)
        wid = min(wid, nbytes)
        if wid:
            bins["dtype_widening"] += wid
            contrib["dtype_widening"].append((name, op, wid))
        for t, b, m in reads:
            rc = read_counts.get((scope, t))
            if rc is None:
                read_counts[(scope, t)] = [1, b, m]
            else:
                rc[0] += 1

    for (scope, t), (count, b, m) in read_counts.items():
        if count < 2 or not _is_scale(m, thr):
            continue
        dt = m[0]
        if dt in _FLOAT_DTYPES and _DTYPE_BITS[dt] <= cbits:
            extra = (count - 1) * b
            bins["grad_double_touch"] += extra
            contrib["grad_double_touch"].append((t, f"{count} reads",
                                                 extra))

    floor_bytes = floor["floor_bytes"] if floor else 0
    binsum = sum(bins.values())
    gap = total - floor_bytes if floor else None
    rec = {
        "ledger_total_bytes": int(total),
        "floor_bytes": int(floor_bytes),
        "floor_terms": dict(floor["terms"]) if floor else {},
        "bins": {k: int(v) for k, v in bins.items()},
        "bin_top": {
            k: [{"name": n, "op": o, "bytes": int(b)}
                for n, o, b in sorted(v, key=lambda r: -r[2])[:top]]
            for k, v in contrib.items()},
        "uncategorized_bytes": int(total - floor_bytes - binsum),
        "compute_dtype": str(np.dtype(compute_dtype)),
        "act_threshold_elems": thr,
    }
    if gap is not None:
        rec["gap_bytes"] = int(gap)
        rec["named_gap_frac"] = round(binsum / gap, 4) if gap > 0 else None
    # publish the attribution totals as gauges (host-side static
    # analysis): the /metrics view of what the last attributed compile
    # was billed — total, floor and each named overhead bin
    from deeplearning4j_tpu.runtime import telemetry

    _g = telemetry.get_registry().gauge(
        "dl4j_hbm_attributed_bytes",
        "last attribute_ledger bill: charged bytes by bin",
        labels=("bin",))
    _g.labels(bin="total").set(rec["ledger_total_bytes"])
    _g.labels(bin="floor").set(rec["floor_bytes"])
    _g.labels(bin="uncategorized").set(rec["uncategorized_bytes"])
    for b, v in rec["bins"].items():
        _g.labels(bin=b).set(v)
    return rec


def pre_opt_hlo(lowered):
    """Pre-optimization HLO text of a jax Lowered — the MODEL's dtype
    request, before backend passes rewrite it. The dtype-policy audit
    must read THIS form: backend optimization adds widenings the model
    never asked for (XLA:CPU promotes bf16 convolutions to f32 wholesale
    because its conv kernels are fp32-only; TPU does not), and a policy
    gate that flags backend artifacts would be red forever on CI
    hosts."""
    try:
        return lowered.as_text(dialect="hlo")
    except Exception:
        return lowered.compiler_ir(dialect="hlo").as_hlo_text()


def audit_activation_dtypes(compiled, net=None, compute_dtype=None,
                            act_threshold_elems=None):
    """HLO dtype-policy audit: every charged buffer of the step that is
    a FLOAT WIDER than the compute dtype at activation scale — the
    buffers the dtype_widening bin prices. A bf16-policy step that
    honours the round-6 tail policy (fp32 only in vector-scale
    statistics and fused reduce accumulators) returns [].

    `compiled` may be a compiled executable, a raw HLO string, or —
    the form a MODEL-policy CI gate should use — the pre_opt_hlo() text
    of the unoptimized lowering, which excludes backend-forced
    widenings (see pre_opt_hlo).

    Walks the same charged rows as the ledger (entry computation,
    recursing through call/while/conditional; fusion interiors stay in
    registers/VMEM and are exempt — only buffers that reach HBM can
    leak). Returns [{"scope", "name", "op", "dtype", "elems", "bytes"}]
    sorted largest first; assert_activation_dtype_clean raises with the
    offender table so a CI gate reads the leak, not just the failure."""
    hlo = compiled if isinstance(compiled, str) else compiled.as_text()
    if net is not None:
        if compute_dtype is None:
            compute_dtype = net._compute_dtype
        if act_threshold_elems is None:
            act_threshold_elems = max(
                (int(a.size) for a in _tree_leaves(net._params)), default=0)
    if compute_dtype is None or act_threshold_elems is None:
        raise ValueError(
            "audit_activation_dtypes needs a net or explicit "
            "compute_dtype= and act_threshold_elems=")
    cbits = np.dtype(compute_dtype).itemsize * 8
    thr = int(act_threshold_elems)
    mod = _parse_module(hlo)
    _sizes, _csizes, comps, _entry_name, _m, _cm = mod

    consumer_ops = {}  # scope -> {producer: {consumer ops}}

    def consumers(scope, name):
        sc = consumer_ops.get(scope)
        if sc is None:
            sc = {}
            for cn, cop, _b, operands, _a, _r in comps.get(scope, ()):
                for t in operands:
                    sc.setdefault(t, set()).add(cop)
            consumer_ops[scope] = sc
        return sc.get(name, set())

    offenders = []
    for scope, name, op, nbytes, ob, _ib, out_meta, _reads, _root in \
            _walk_charged_rows(mod):
        if not _is_scale(out_meta, thr):
            continue
        dt, elems = out_meta
        if dt not in _FLOAT_DTYPES or _DTYPE_BITS[dt] <= cbits:
            continue
        if op == "convert":
            # the SANCTIONED wide idiom: a widening convert consumed
            # ONLY by reductions is the `jnp.sum(..., dtype=f32)`
            # fused accumulator — backend fusion folds it into the
            # reduce and nothing wide reaches HBM. Any other consumer
            # makes it a real materialisation.
            cons = consumers(scope, name)
            if cons and cons <= {"reduce", "reduce-window"}:
                continue
        offenders.append({"scope": scope, "name": name, "op": op,
                          "dtype": dt, "elems": int(elems),
                          "bytes": int(ob)})
    offenders.sort(key=lambda r: -r["bytes"])
    return offenders


def assert_activation_dtype_clean(compiled, net=None, compute_dtype=None,
                                  act_threshold_elems=None):
    """Raise AssertionError naming every wide-float activation-scale
    buffer in the compiled step (audit_activation_dtypes); the CI form
    of the round-6 acceptance bar 'zero ENTRY-scope f32 activation-
    scale buffers in the bf16 flagship step'."""
    off = audit_activation_dtypes(compiled, net=net,
                                  compute_dtype=compute_dtype,
                                  act_threshold_elems=act_threshold_elems)
    if off:
        lines = [f"  {r['name'][:48]:<50} {r['op']:<16} {r['dtype']:<5} "
                 f"{r['elems']:>12} elems  {r['bytes']:>12} B"
                 for r in off[:12]]
        raise AssertionError(
            f"{len(off)} wide-float activation-scale buffer(s) in a "
            "step whose compute dtype should bound activation widths "
            "(dtype_widening leak):\n" + "\n".join(lines))


def format_attribution(rec, gb=True):
    """Human-readable attribution table (the analysis CLI surface)."""
    unit, div = ("GB", 1e9) if gb else ("MB", 1e6)

    def f(b):
        return f"{b / div:10.3f} {unit}"

    lines = [f"ledger total     {f(rec['ledger_total_bytes'])}",
             f"analytic floor   {f(rec['floor_bytes'])}"]
    for term, b in rec["floor_terms"].items():
        lines.append(f"  floor.{term:<22} {f(b)}")
    if "gap_bytes" in rec:
        lines.append(f"gap (total-floor){f(rec['gap_bytes'])}")
    for name, b in sorted(rec["bins"].items(), key=lambda kv: -kv[1]):
        lines.append(f"  bin.{name:<24} {f(b)}")
        for t in rec["bin_top"].get(name, [])[:3]:
            lines.append(f"      {t['name'][:40]:<42} {t['op'][:16]:<17}"
                         f"{f(t['bytes'])}")
    lines.append(f"uncategorized    {f(rec['uncategorized_bytes'])}")
    if rec.get("named_gap_frac") is not None:
        lines.append(f"named gap fraction  {rec['named_gap_frac']:.1%}")
    return "\n".join(lines)

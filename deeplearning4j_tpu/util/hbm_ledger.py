"""Per-op HBM traffic ledger + train-step roofline floor.

VERDICT r4 weak #3: the headline diagnosis stopped at "bandwidth-bound,
46.8 GB/step" with no table saying WHICH fusions carry those bytes or
what the unavoidable floor is. This module supplies both:

- `ledger(hlo_text)` walks the compiled module's ENTRY computation and
  charges each instruction its output buffer plus every operand buffer
  (resolved through a module-wide symbol table). ENTRY-level operands/
  results are exactly the buffers that cross HBM on TPU — everything
  inside a fusion stays in registers/VMEM — so ranking these is the
  per-op HBM table. (Generalises the HLO-walking approach of
  parallel/overlap.py, which reads schedule structure from the same
  text.)

- `train_step_floor(net, x_shape)` computes the analytic lower bound on
  HBM bytes for one training step from the MODEL, not the compiler:
  master params + optimizer state + grads at fp32, compute-dtype weight
  copies, the input batch, and the minimal activation traffic of a
  conv net's forward+backward. Measured bytes / floor says how close
  XLA's lowering is to the memory roofline — "within N% of floor" is a
  result; "bandwidth-bound" alone is a stopping excuse.

The floor's activation model, stated so the number is auditable: every
layer boundary activation A is (1) written by the forward, (2) read by
the backward to form the weight gradient, and its gradient G (same
size) is (3) written and (4) read by the adjacent backward step —
4 touches of each boundary buffer at compute dtype. Rematerialisation
can trade (1)/(2) for recompute; XLA fusion can eliminate boundaries
between elementwise neighbours, which is why the floor uses ONLY
conv/dense/pool boundaries (fusable chains of BN/relu/add don't count).
"""

from __future__ import annotations

import re

import numpy as np

from deeplearning4j_tpu.parallel.overlap import _DTYPE_BITS, _SHAPE_RE

# '%name = <result types> opcode(...operands...)'
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*?)\s([a-z][\w\-]*)\((.*)$")
_OPERAND_RE = re.compile(r"%?([\w.\-]+)")

# opcodes that don't move HBM bytes themselves (metadata / control flow
# / aliasing views); their operands are charged where actually consumed
_FREE_OPS = {"parameter", "tuple", "get-tuple-element", "bitcast",
             "constant", "after-all", "partition-id", "replica-id"}


_ANY_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]{0,14})\[[0-9,]*\]")


def _result_bytes(result_text):
    # an unrecognized dtype must FAIL, not silently rank as 0 bytes —
    # the whole point is an accurate table on the TPU backend
    for tok in _ANY_SHAPE_RE.findall(result_text):
        if tok not in _DTYPE_BITS and tok != "token":
            raise ValueError(
                f"unknown HLO dtype {tok!r} in {result_text[:80]!r} — "
                "add it to parallel/overlap.py _DTYPE_BITS")
    total = 0
    for dt, dims in _SHAPE_RE.findall(result_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += (n * _DTYPE_BITS[dt] + 7) // 8
    return total


def ledger(hlo_text, top=15):
    """Rank ENTRY instructions by HBM bytes touched.

    Returns {"total_bytes", "by_opcode": {op: bytes}, "top": [
    {"name", "op", "bytes", "out_bytes", "in_bytes"}, ...]}.
    """
    # symbol table over the WHOLE module: entry operands can reference
    # computations' results only via entry-local names, but building it
    # globally is harmless and keeps the parse single-pass
    sizes = {}
    defs = []
    in_entry = False
    for line in hlo_text.splitlines():
        s = line.strip()
        if s.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and s == "}":
            in_entry = False
            continue
        m = _DEF_RE.match(line)
        if not m:
            continue
        name, result, op, rest = m.groups()
        nbytes = _result_bytes(result)
        sizes[name] = nbytes
        if in_entry:
            defs.append((name, op, nbytes, rest))

    rows = []
    by_op = {}
    total = 0
    for name, op, out_bytes, rest in defs:
        if op in _FREE_OPS:
            continue
        # operands = known instruction names referenced before control
        # metadata; stop at the first metadata key to avoid charging
        # called-computation names
        arg_text = rest.split("), ")[0] if "), " in rest else rest
        in_bytes = 0
        seen = set()
        for tok in _OPERAND_RE.findall(arg_text):
            if tok in sizes and tok not in seen:
                seen.add(tok)
                in_bytes += sizes[tok]
        nbytes = out_bytes + in_bytes
        total += nbytes
        by_op[op] = by_op.get(op, 0) + nbytes
        rows.append({"name": name, "op": op, "bytes": nbytes,
                     "out_bytes": out_bytes, "in_bytes": in_bytes})
    rows.sort(key=lambda r: -r["bytes"])
    return {"total_bytes": total,
            "by_opcode": dict(sorted(by_op.items(), key=lambda kv: -kv[1])),
            "top": rows[:top]}


def ledger_for_compiled(compiled, top=15):
    return ledger(compiled.as_text(), top=top)


# ---------------------------------------------------------------------
# analytic roofline floor
# ---------------------------------------------------------------------

_BOUNDARY_LAYERS = ("ConvolutionLayer", "Convolution2D", "DenseLayer",
                    "SubsamplingLayer", "SeparableConvolution2D",
                    "DepthwiseConvolution2D", "Deconvolution2D",
                    "OutputLayer")


def _boundary_layer_objects(net):
    if hasattr(net, "layers"):  # MultiLayerNetwork
        layers = list(net.layers)
    else:  # ComputationGraph
        layers = [n.payload for n in net.conf.nodes.values()
                  if getattr(n, "payload", None) is not None]
    return [l for l in layers if type(l).__name__ in _BOUNDARY_LAYERS]


def boundary_activation_elems(net, x_shape):
    """Per-layer boundary activation element counts via jax.eval_shape
    (abstract — nothing executes). Only conv/dense/pool boundaries
    count; elementwise chains between them are fusable and carry no
    unavoidable HBM traffic. Works for MultiLayerNetwork AND
    ComputationGraph by recording each boundary layer's forward output
    shape during the abstract trace."""
    import jax

    recorded = []
    wrapped = []
    for layer in _boundary_layer_objects(net):
        orig = layer.forward  # bound method

        def mk(orig):
            def spy(*a, **kw):
                out = orig(*a, **kw)
                h = out[0] if isinstance(out, tuple) else out
                recorded.append(int(np.prod(h.shape)))
                return out
            return spy

        layer.forward = mk(orig)  # instance attr shadows the class method
        wrapped.append(layer)
    try:
        x = jax.ShapeDtypeStruct(tuple(x_shape),
                                 np.dtype(net._compute_dtype))
        if hasattr(net, "layers"):
            jax.eval_shape(
                lambda xx: net._forward_infer(net._params, net._states, xx),
                x)
        else:
            name = net.conf.networkInputs[0]
            jax.eval_shape(
                lambda xx: net._forward_infer(net._params, net._states,
                                              {name: xx}), x)
    finally:
        for layer in wrapped:
            del layer.__dict__["forward"]
    return recorded


def train_step_floor(net, x_shape, optimizer_slots=1):
    """Analytic lower bound on HBM bytes for one train step.

    optimizer_slots: per-param fp32 state buffers the updater holds
    (1 = momentum/Nesterovs, 2 = Adam).
    Terms, each at its dtype (see module docstring for the activation
    model):
      params:   fp32 master read + write, compute-dtype copy written
                once and read by fwd and bwd (3 touches at compute)
      optimizer: fp32 state read + write per slot
      grads:    fp32 write + read
      input:    batch read once at compute dtype
      acts:     4 touches of every conv/dense/pool boundary buffer
    """
    cb = np.dtype(net._compute_dtype).itemsize
    pb = np.dtype(net._param_dtype).itemsize
    P = int(sum(a.size for a in _tree_leaves(net._params)))
    A = int(sum(boundary_activation_elems(net, x_shape)))
    Bx = int(np.prod(x_shape))
    # when compute dtype == param dtype there IS no separate cast copy:
    # fwd+bwd read the master buffers directly (2 reads) — charging the
    # 3-touch copy there would push the "floor" ABOVE real programs
    copy_bytes = 3 * P * cb if cb != pb else 2 * P * pb
    terms = {
        "params_master_rw": 2 * P * pb,
        "params_compute_copy": copy_bytes,
        "optimizer_state_rw": 2 * optimizer_slots * P * pb,
        "grads_wr": 2 * P * pb,
        "input_read": Bx * cb,
        "activations_4touch": 4 * A * cb,
    }
    return {"floor_bytes": int(sum(terms.values())), "terms": terms,
            "param_count": P, "boundary_activation_elems": A}


def _tree_leaves(t):
    import jax

    return jax.tree_util.tree_leaves(t)

"""Shared scaffolding for the stdlib HTTP serving tier
(optimize.ui.UIServer, clustering.server.NearestNeighborsServer):
a daemon-threaded ThreadingHTTPServer owner mixin plus a JSON-speaking
BaseHTTPRequestHandler base — one copy of the start/stop/port/body
plumbing so fixes land in one place."""

from __future__ import annotations

import http.server
import json
import threading


class JsonHandler(http.server.BaseHTTPRequestHandler):
    """Request handler base: silenced per-request logging, JSON/body
    writers with correct Content-Length, and strict JSON-object body
    parsing (a list/scalar body is a client error, not a crash)."""

    def log_message(self, *a):
        pass

    def _send(self, code, body, ctype):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _json(self, obj, code=200):
        self._send(code, json.dumps(obj), "application/json")

    def _read_json_object(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        if not isinstance(body, dict):
            raise ValueError(
                f"JSON object body required, got {type(body).__name__}")
        return body


class HttpServerOwner:
    """start/stop/port for a class that owns one loopback HTTP server."""

    _httpd = None
    _thread = None

    @property
    def port(self):
        """Bound port once started (pass port=0 for an ephemeral one)."""
        return self._httpd.server_address[1] if self._httpd else None

    def _serve(self, handler_cls, port):
        if self._httpd is not None:
            return self
        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), handler_cls)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

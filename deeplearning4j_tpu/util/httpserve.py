"""Shared scaffolding for the stdlib HTTP serving tier
(optimize.ui.UIServer, clustering.server.NearestNeighborsServer):
a daemon-threaded ThreadingHTTPServer owner mixin plus a JSON-speaking
BaseHTTPRequestHandler base — one copy of the start/stop/port/body
plumbing so fixes land in one place.

Production hardening (runtime.resilience PR): every server built on
this base gets

* ``GET /healthz`` — readiness probe answering 200 {"status": "ok"}
  while the owner is started and ready, 503 otherwise (pod schedulers
  and load balancers gate traffic on it; flip with setReady(False)
  during index rebuilds / model swaps),
* an optional per-request deadline: ``start(..., requestDeadline=s)``
  runs each handler on a watched worker thread and answers 503
  {"error": "deadline exceeded"} instead of letting a stuck handler
  hang the client connection forever. The late handler's own write is
  suppressed (single-response lock), so the two can never interleave
  on the socket.

Handlers subclass JsonHandler and implement ``handle_GET`` /
``handle_POST`` (NOT do_GET/do_POST — the base owns those to splice in
/healthz and the deadline).
"""

from __future__ import annotations

import http.server
import json
import threading
import time

#: serializes HttpServerOwner start/stop across threads: two
#: concurrent start() calls racing the `_httpd is None` check would
#: each bind a ThreadingHTTPServer and leak one (the THR04 lazy-init
#: shape). One module-level lock is enough — lifecycle flips are rare
#: and never sit on a request path. (HttpServerOwner is a mixin with
#: no __init__ of its own, so a per-instance lock has nowhere safe to
#: be born.)
_LIFECYCLE_LOCK = threading.Lock()


class HttpError(Exception):
    """Typed HTTP failure a handler raises to answer a specific status
    code with a JSON error body — 404 unknown model, 429 queue-full
    backpressure, 504 deadline — instead of the generic 500 the
    dispatch safety net answers for unexpected exceptions."""

    def __init__(self, code, message):
        super().__init__(message)
        self.code = int(code)
        self.message = str(message)


class JsonHandler(http.server.BaseHTTPRequestHandler):
    """Request handler base: silenced per-request logging, JSON/body
    writers with correct Content-Length, strict JSON-object body
    parsing (a list/scalar body is a client error, not a crash), and
    the /healthz + request-deadline dispatch described in the module
    docstring."""

    # per-request response state (instances are per-request, so class
    # attrs are safe defaults)
    _responded = False
    _suppressed = False
    _resp_lock = None
    _t0 = None
    _metric_done = False

    @classmethod
    def metric_route(cls, path):
        """Bounded-cardinality route label for the per-route latency /
        status-code instruments, or None to keep this handler
        uninstrumented (the default — only handlers that opt in, like
        the InferenceServer's, feed the registry)."""
        return None

    def _record_metrics(self, code):
        """First response of the request: per-route latency histogram +
        status-code counter into the process registry (host-side, after
        the handler already produced its answer — never on any model's
        dispatch path)."""
        if self._metric_done or self._t0 is None:
            return
        route = self.metric_route(self.path.split("?", 1)[0])
        if route is None:
            return
        self._metric_done = True
        from deeplearning4j_tpu.runtime import telemetry

        reg = telemetry.get_registry()
        reg.counter("dl4j_http_requests_total",
                    "HTTP responses by route and status code",
                    labels=("route", "code")).labels(
            route=route, code=int(code)).inc()
        reg.histogram("dl4j_http_latency_seconds",
                      "request receipt to response write, per route",
                      labels=("route",)).labels(route=route).observe(
            time.perf_counter() - self._t0)

    def log_message(self, *a):
        pass

    def _send(self, code, body, ctype, _force=False):
        data = body.encode() if isinstance(body, str) else body
        lock = self._resp_lock
        if lock is not None:
            with lock:
                if self._suppressed and not _force:
                    return  # deadline already answered 503 for us
                self._responded = True
        else:
            self._responded = True  # thread-ok[THR01]: no-deadline mode — this request runs on exactly one handler thread; the lock (and its writers) only exist in deadline mode
        self._record_metrics(code)
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _json(self, obj, code=200, _force=False):
        self._send(code, json.dumps(obj), "application/json", _force=_force)

    def _read_json_object(self):
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        if not isinstance(body, dict):
            raise ValueError(
                f"JSON object body required, got {type(body).__name__}")
        return body

    # ----- dispatch ----------------------------------------------------
    def _owner(self):
        return getattr(self.server, "owner", None)

    def do_GET(self):
        self._t0 = time.perf_counter()
        if self.path.split("?", 1)[0] == "/healthz":
            owner = self._owner()
            ready = owner.ready if owner is not None else True
            body = {"status": "ok" if ready else "unready"}
            err = getattr(owner, "_warmup_error", None)
            if not ready and err is not None:
                # an operator must be able to tell "still warming" from
                # "warmup crashed" without shell access to the pod
                body["warmupError"] = err
            return self._json(body, 200 if ready else 503)
        self._dispatch("GET")

    def do_POST(self):
        self._t0 = time.perf_counter()
        self._dispatch("POST")

    def _dispatch(self, method):
        impl = getattr(self, f"handle_{method}", None)
        if impl is None:
            return self._json({"error": f"{method} not supported"}, 501)
        owner = self._owner()
        deadline = getattr(owner, "requestDeadline", None)
        if not deadline:
            # safety net: a handler exception must reach the CLIENT as
            # a status code, not as a dropped connection (HttpError
            # carries its own code; anything else is a 500) — unless a
            # response is already mid-flight, where a second write
            # would interleave on the socket
            try:
                return impl()
            except HttpError as e:
                if not self._responded:  # thread-ok[THR01]: no-deadline mode — one handler thread per request; the lock (and its writers) only exist in deadline mode
                    self._json({"error": e.message}, e.code)
            except Exception as e:
                if not self._responded:  # thread-ok[THR01]: no-deadline mode — one handler thread per request; the lock (and its writers) only exist in deadline mode
                    self._json({"error": f"{type(e).__name__}: {e}"}, 500)
            return None
        # deadline mode: the handler body runs on a watched daemon
        # thread; if it overruns, THIS thread answers 503 and the
        # worker's eventual write is dropped by the response lock. The
        # worker is abandoned, not killed — Python can't safely kill a
        # thread — but the CLIENT is released, which is the contract.
        self._resp_lock = threading.Lock()
        done = threading.Event()

        def run():  # fault-ok[FLT02]: deadline-mode dispatch WRAPPER — impl() is the concrete handler, which owns the request seam (serving/server.py fires server.request before routing)
            try:
                impl()
            except HttpError as e:
                try:
                    self._json({"error": e.message}, e.code)
                except Exception:  # fault-ok[FLT01]: the client hung up mid-error-reply — the connection is gone, there is no one left to classify for
                    pass
            except Exception as e:
                try:
                    # parity with the non-deadline path's 500; the
                    # response lock drops this if the deadline already
                    # answered 503
                    self._json({"error": f"{type(e).__name__}: {e}"}, 500)
                except Exception:  # fault-ok[FLT01]: connection gone (or 503 already sent under the response lock); nothing left to report to
                    pass  # connection is gone; nothing left to report to
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        if not done.wait(float(deadline)):
            with self._resp_lock:
                overrun = not self._responded
                if overrun:
                    self._suppressed = True
            if overrun:
                self._json({"error": "deadline exceeded",
                            "deadlineSec": float(deadline)}, 503,
                           _force=True)
                self.close_connection = True
            else:
                # response is mid-write; give it a grace period, then
                # drop the connection rather than let a later request's
                # response interleave with the still-writing worker
                if not done.wait(5.0):
                    self.close_connection = True


class HttpServerOwner:
    """start/stop/port for a class that owns one loopback HTTP server,
    plus the readiness flag /healthz reports and the per-request
    deadline JsonHandler enforces."""

    _httpd = None
    _thread = None
    _ready = True
    _warmup_error = None    # last warmup failure, surfaced on /healthz
    requestDeadline = None  # seconds; None/0 disables

    @property
    def port(self):
        """Bound port once started (pass port=0 for an ephemeral one)."""
        httpd = self._httpd  # thread-ok[THR01]: atomic reference read; a probe racing stop() sees the old server or None, both valid answers
        return httpd.server_address[1] if httpd else None

    @property
    def ready(self) -> bool:
        """What /healthz answers: started AND not administratively
        drained via setReady(False)."""
        return self._httpd is not None and self._ready  # thread-ok[THR01]: atomic reads; readiness is advisory and a stale answer is indistinguishable from probing a moment earlier

    def setReady(self, ready: bool):
        """Flip readiness without stopping the server (drain traffic
        during an index rebuild / model swap)."""
        with _LIFECYCLE_LOCK:
            self._ready = bool(ready)
        return self

    def _serve(self, handler_cls, port, requestDeadline=None,
               warmup=None):
        """Start serving. `warmup` (optional callable) is the AOT
        warm-start hook: the server binds and answers immediately, but
        /healthz reports 503 until warmup() returns on a background
        thread — a pod scheduler holds traffic exactly until the
        executables are hot (pair with ``model.precompile`` /
        ``ParallelInference.precompile``, docs/COMPILE.md). A warmup
        failure leaves the server unready rather than crashing it."""
        with _LIFECYCLE_LOCK:
            # double-checked under the lifecycle lock: concurrent
            # start() calls must agree on ONE server instead of each
            # binding (and one leaking) — the PR 8 lazy-init shape
            if self._httpd is not None:
                return self
            if requestDeadline is not None:
                self.requestDeadline = float(requestDeadline) or None
            self._warmup_error = None
            self._ready = warmup is None  # restart clears a previous drain
            self._httpd = http.server.ThreadingHTTPServer(
                ("127.0.0.1", port), handler_cls)
            self._httpd.owner = self
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
            # generation tag: a warmup outlives stop()/restart, and a
            # STALE one finishing must not mark the NEW server ready
            # (or stamp its error onto it) — publish only if the
            # server it warmed is still the live one
            httpd = self._httpd
        if warmup is not None:
            def _warm():  # fault-ok[FLT02]: warmup runs a USER callable whose own boundaries carry the seams; its failure is already classified into _warmup_error and surfaced on /healthz
                try:
                    warmup()
                except Exception as e:
                    # stay unready; /healthz carries the reason so 503
                    # "still warming" and 503 "warmup crashed" are
                    # distinguishable from outside the pod
                    with _LIFECYCLE_LOCK:
                        if self._httpd is httpd:
                            self._warmup_error = \
                                f"{type(e).__name__}: {e}"[:500]
                    return
                with _LIFECYCLE_LOCK:
                    if self._httpd is httpd:
                        self._ready = True

            threading.Thread(target=_warm, daemon=True).start()
        return self

    def stop(self):
        with _LIFECYCLE_LOCK:
            httpd = self._httpd
            if httpd is not None:
                # close BEFORE publishing _httpd = None: a restart
                # racing this stop must not observe "no server" while
                # the old socket still listens (bind would raise
                # EADDRINUSE). shutdown() only stops the accept loop
                # (<= its 0.5 s poll; it does not wait for handler
                # threads), so holding the lifecycle lock across it is
                # bounded.
                httpd.shutdown()
                httpd.server_close()
                self._httpd = None
                self._thread = None

"""Sharded (multi-host, per-device) checkpointing via Orbax.

Reference: the reference stack checkpoints through
ModelSerializer/CheckpointListener on a single JVM, and its Spark tier
ships full parameter blobs through the driver. On TPU pods neither
works: parameters live SHARDED across hosts (tensor/pipeline parallel),
and funnelling them through one host at checkpoint time costs a full
DCN gather per save. This module is the TPU-native replacement:
Orbax/TensorStore writes each host's shards in parallel (OCDBT), saves
are optionally async (training continues while the previous step's
state flushes), and restore reshards automatically onto whatever mesh
the restoring job uses — save on dp8, restore on dp2xtp4, or on one
chip.

Format: an Orbax directory holding the array state (params / updater
moments / layer states / counters) plus a `manifest.json` with the
serde-encoded network configuration, so `restore(path)` can rebuild
the net without the caller supplying one (parity with
ModelSerializer.restore's type dispatch).

Durability: writeModel stages the whole checkpoint under a
`<path>.tmp-*` sibling and renames it into place only once every byte
is on disk — the rename IS the commit, so a save preempted at any
point leaves either the previous complete checkpoint or none, never a
half-written directory that restore would then load. latest_step() /
gc_checkpoints() manage a directory of `step_<n>` checkpoints for the
periodic-save / resume-from-latest training loop
(runtime.resilience.ResilientFit; reference: CheckpointListener's
rotation).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil

import jax
import numpy as np

from deeplearning4j_tpu.util import serde

_MANIFEST = "manifest.json"
_STATE_DIR = "state"
_TRAINER_DIR = "trainer"
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"\.tmp-")


class CheckpointDigestError(ValueError):
    """The restored state does not hash to the digest its manifest
    committed with — the checkpoint is silently corrupt (bit rot, a
    torn copy, a tampered file). ResilientFit treats it as ABSENT and
    falls back to the previous snapshot (runtime/resilience.py)."""


def state_digest(state) -> str:
    """sha256 over the state pytree's leaves (dtype + shape + raw
    bytes, in deterministic tree-flatten order). Computed from the
    in-memory state at save time — it rides manifest.json through the
    same atomic commit rename as the arrays it describes — and
    recomputed from the restored state at restore time. Single-host
    only: a multi-host save skips the digest (gathering every remote
    shard through one host at save time would defeat the sharded
    writer), so absence of the manifest key means "not verified",
    never "corrupt".

    Integer leaves are canonicalized to int64 before hashing: the
    restore target is rebuilt through jnp.asarray, which narrows the
    int64 step counters to int32 when jax_enable_x64 is off — a
    LEGITIMATE width coercion, not corruption, and it must not depend
    on whether the saving and restoring interpreters agree on the x64
    flag. Float/bool leaves keep their exact dtype (a bf16/f32 flip IS
    corruption)."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        a = np.asarray(leaf)
        if a.dtype.kind in "iu":
            a = np.asarray(a, np.int64)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _commit(tmp: str, final: str):
    """Rename the staged checkpoint into place (multi-host: process 0
    only — every host wrote its shards into the SAME staging dir).
    Fresh paths (ResilientFit's `step_<n>` scheme) commit in one atomic
    rename. Overwriting an existing checkpoint swaps via a `.old`
    sibling: there is an unavoidable instant with no directory at
    `final` itself, but a COMPLETE copy always exists at `final` or its
    `.old` sibling (which gc_checkpoints deliberately does NOT sweep,
    so a crash inside the swap stays manually recoverable)."""
    import jax

    if jax.process_index() != 0:
        return
    if os.path.isdir(final):
        trash = final + ".old"
        shutil.rmtree(trash, ignore_errors=True)
        os.rename(final, trash)
        os.rename(tmp, final)
        shutil.rmtree(trash, ignore_errors=True)
    else:
        os.rename(tmp, final)


class _AtomicSaveHandle:
    """Async-save handle: joins the Orbax background write, THEN commits
    the staged directory. Until wait_until_finished() returns, restore()
    still sees the previous complete checkpoint (or none)."""

    def __init__(self, ckpt, tmp, final):
        self._ckpt = ckpt
        self._tmp = tmp
        self._final = final
        self._done = False

    def wait_until_finished(self):
        self._ckpt.wait_until_finished()
        if not self._done:
            _commit(self._tmp, self._final)
            self._done = True
        return self


def step_path(directory, step: int) -> str:
    """Canonical `<dir>/step_<n>` checkpoint path for iteration `step`."""
    return os.path.join(os.path.abspath(str(directory)), f"step_{int(step)}")


def complete_steps(directory):
    """Every step number with a COMPLETE checkpoint under `directory`
    (a committed `step_<n>` dir with its manifest), ascending. Staged
    `.tmp-*` leftovers from preempted saves are never candidates — the
    commit rename is what makes a checkpoint visible here. The resume
    fallback chain: ResilientFit walks this newest-first so a
    digest-corrupt latest checkpoint falls back to the previous
    snapshot (runtime/resilience.py)."""
    directory = os.path.abspath(str(directory))
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if not m:
            continue
        if not os.path.exists(os.path.join(directory, name, _MANIFEST)):
            continue
        steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory):
    """Highest complete step under `directory`, or None
    (complete_steps)."""
    steps = complete_steps(directory)
    return steps[-1] if steps else None


def gc_checkpoints(directory, keepLast: int):
    """Keep the newest `keepLast` complete `step_<n>` checkpoints (DL4J
    CheckpointListener keepLast parity) and sweep any `.tmp-*` staging
    leftovers from saves that died before their commit rename. Returns
    the list of deleted paths. keepLast <= 0 keeps everything (still
    sweeps dead staging dirs)."""
    directory = os.path.abspath(str(directory))
    if not os.path.isdir(directory):
        return []
    steps, deleted = [], []
    for name in os.listdir(directory):
        full = os.path.join(directory, name)
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(full, _MANIFEST)):
            steps.append((int(m.group(1)), full))
        elif _TMP_RE.search(name):
            # dead staging dirs only; `.old` siblings are left alone —
            # after a crash mid-overwrite they hold the ONLY complete
            # copy of that checkpoint
            shutil.rmtree(full, ignore_errors=True)
            deleted.append(full)
    if keepLast and keepLast > 0 and len(steps) > keepLast:
        steps.sort()
        for _, full in steps[:-keepLast]:
            shutil.rmtree(full, ignore_errors=True)
            deleted.append(full)
    return deleted


def read_manifest(path) -> dict:
    """The checkpoint's manifest.json (includes any `extra` metadata the
    saver attached — e.g. ResilientFit's mid-epoch resume position)."""
    mpath = os.path.join(os.path.abspath(str(path)), _MANIFEST)
    with open(mpath) as f:
        return json.load(f)


def _net_state(net, saveUpdater=True):
    state = {
        "params": net._params,
        "states": net._strip_carries(net._states),
        # 0-d arrays, not np scalars: StandardCheckpointHandler's
        # save-state validation only admits ndarray/jax.Array leaves
        "counters": {"iteration": np.asarray(net._iteration, np.int64),
                     "epoch": np.asarray(net._epoch, np.int64)},
    }
    if saveUpdater:
        upd = net._upd_states
        # ZeRO sharded weight update (parallel.sharding.ZeroShardedUpdate):
        # the live state holds flat 1/dp-shard views; checkpoints save the
        # CANONICAL full-shape layout (the unview is a gather + lossless
        # reshape), so a sharded-mode save restores into any mode — and a
        # resumed run re-shards it bitwise. The restore target built from a
        # fresh net (no hook installed) matches this canonical form.
        unview = getattr(net, "_upd_state_unview", None)
        if unview is not None:
            upd = unview(upd)
        state["upd_states"] = upd
    return state


class ShardedModelSerializer:
    """writeModel/restore with Orbax-sharded array storage (the
    distributed complement of util.serializer.ModelSerializer)."""

    @staticmethod
    def writeModel(net, path, saveUpdater=True, asyncSave=False, extra=None,
                   trainer_state=None):
        """Save to directory `path`. With asyncSave=True the write
        happens in the background — you MUST call the returned handle's
        .wait_until_finished() to join AND commit it. Sharded arrays
        are written per-shard: on multi-host, each host writes only the
        shards it owns.

        The save is ATOMIC at `path`: everything is staged under a
        `<path>.tmp-stage` sibling (one SHARED staging dir — on
        multi-host, every host writes its shards into it and process 0
        performs the commit rename) and renamed into place only after
        the state is fully flushed. A save killed mid-write can
        therefore never leave a torn "latest" checkpoint for
        restore()/latest_step() to pick up. asyncSave contract: the
        commit happens inside the returned handle's
        wait_until_finished() — an async save that is never joined is
        never committed (the stale staging dir is swept by the next
        save / gc_checkpoints).

        `extra`: optional JSON-serializable dict recorded in the
        manifest (read back via read_manifest) — resume metadata like
        ResilientFit's batch-within-epoch position rides here so it
        commits atomically WITH the state it describes.

        `trainer_state`: optional pytree of TRAINER-owned step state
        saved as a separate item (read back via restore_trainer_state)
        — e.g. the threshold-compression error-feedback residuals a
        bitwise resume needs. Kept out of the net state on purpose:
        the canonical net state must restore into ANY training mode,
        while trainer state only means something to the wrapper that
        wrote it."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(str(path))
        tmp = path + ".tmp-stage"
        if jax.process_index() == 0:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
        conf_arrays = []
        conf_node = serde.encode(net.conf, conf_arrays)
        state = _net_state(net, saveUpdater)
        manifest = {
            "cls": type(net).__name__,
            "conf": conf_node,
            # config-level constants (init values, vertex factors) are
            # small; inline them so restore can rebuild the net BEFORE
            # touching the array store
            "conf_arrays": [{"dtype": str(np.asarray(a).dtype),
                             "data": np.asarray(a).tolist()}
                            for a in conf_arrays],
            "saveUpdater": bool(saveUpdater),
            "trainerState": trainer_state is not None,
        }
        if jax.process_count() == 1:
            # content digest riding the same atomic commit as the
            # state it describes; restore() verifies it
            manifest["digest"] = state_digest(state)
        if extra is not None:
            manifest["extra"] = extra
        if jax.process_index() == 0:
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
        if trainer_state is not None:
            # synchronous side item inside the staging dir: it rides the
            # same atomic commit rename as the main state
            tckpt = ocp.StandardCheckpointer()
            tckpt.save(os.path.join(tmp, _TRAINER_DIR), trainer_state,
                       force=True)
            tckpt.wait_until_finished()
        ckpt = (ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
                if asyncSave else ocp.StandardCheckpointer())
        state_path = os.path.join(tmp, _STATE_DIR)
        ckpt.save(state_path, state, force=True)
        handle = _AtomicSaveHandle(ckpt, tmp, path)
        if not asyncSave:
            handle.wait_until_finished()
        return handle

    @staticmethod
    def restore(path, sharding=None):
        """Rebuild the network from `path`. `sharding`: optional
        jax.sharding.Sharding (e.g. NamedSharding(mesh, P()) to
        replicate onto a new mesh) applied to every restored array —
        omit it to restore with the checkpoint's own layout on the
        current devices. Works across topologies: Orbax reshards from
        however many hosts/devices wrote the checkpoint."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(str(path))
        mpath = os.path.join(path, _MANIFEST)
        if not os.path.exists(mpath):
            raise ValueError(f"no sharded checkpoint at {path} "
                             f"(missing {_MANIFEST})")
        with open(mpath) as f:
            manifest = json.load(f)
        conf_arrays = [np.asarray(d["data"], dtype=d["dtype"])
                       for d in manifest.get("conf_arrays", [])]
        conf = serde.decode(manifest["conf"], conf_arrays)
        if manifest["cls"] == "ComputationGraph":
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            net = ComputationGraph(conf).init()
        else:
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(conf).init()

        # the freshly-initialized net provides the restore target's
        # structure and dtypes; sharding (if given) overrides placement
        target = _net_state(net, manifest["saveUpdater"])

        def _abstract(x):
            x = jax.numpy.asarray(x)
            # default to the fresh target's own placement: explicit
            # shardings make cross-topology restores safe (Orbax warns
            # when it has to guess from the sharding file)
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=sharding if sharding is not None else x.sharding)

        abstract = jax.tree_util.tree_map(_abstract, target)
        ckpt = ocp.StandardCheckpointer()
        state = ckpt.restore(os.path.join(path, _STATE_DIR), abstract)
        ckpt.wait_until_finished()

        want = manifest.get("digest")
        if want is not None and jax.process_count() == 1:
            got = state_digest(state)
            if got != want:
                raise CheckpointDigestError(
                    f"checkpoint {path} fails digest verification "
                    f"(manifest {want[:12]}…, restored {got[:12]}…) — "
                    "silently-corrupt state must not be restored")

        net._params = state["params"]
        net._states = state["states"]
        if manifest["saveUpdater"]:
            net._upd_states = state["upd_states"]
        net._iteration = int(state["counters"]["iteration"])
        net._epoch = int(state["counters"]["epoch"])
        return net


def restore_trainer_state(path, abstract):
    """Restore the optional trainer-state item a writeModel(...,
    trainer_state=...) save carried (e.g. ParallelWrapper's threshold
    error-feedback residuals). `abstract` is the target pytree of
    jax.ShapeDtypeStruct (with shardings) the restoring wrapper builds
    from its freshly-placed state — only the wrapper knows the layout.
    Returns None when the checkpoint has no trainer state."""
    import orbax.checkpoint as ocp

    p = os.path.join(os.path.abspath(str(path)), _TRAINER_DIR)
    if not os.path.isdir(p):
        return None
    ckpt = ocp.StandardCheckpointer()
    out = ckpt.restore(p, abstract)
    ckpt.wait_until_finished()
    return out

"""Sharded (multi-host, per-device) checkpointing via Orbax.

Reference: the reference stack checkpoints through
ModelSerializer/CheckpointListener on a single JVM, and its Spark tier
ships full parameter blobs through the driver. On TPU pods neither
works: parameters live SHARDED across hosts (tensor/pipeline parallel),
and funnelling them through one host at checkpoint time costs a full
DCN gather per save. This module is the TPU-native replacement:
Orbax/TensorStore writes each host's shards in parallel (OCDBT), saves
are optionally async (training continues while the previous step's
state flushes), and restore reshards automatically onto whatever mesh
the restoring job uses — save on dp8, restore on dp2xtp4, or on one
chip.

Format: an Orbax directory holding the array state (params / updater
moments / layer states / counters) plus a `manifest.json` with the
serde-encoded network configuration, so `restore(path)` can rebuild
the net without the caller supplying one (parity with
ModelSerializer.restore's type dispatch).
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from deeplearning4j_tpu.util import serde

_MANIFEST = "manifest.json"
_STATE_DIR = "state"


def _net_state(net, saveUpdater=True):
    state = {
        "params": net._params,
        "states": net._strip_carries(net._states),
        "counters": {"iteration": np.int64(net._iteration),
                     "epoch": np.int64(net._epoch)},
    }
    if saveUpdater:
        state["upd_states"] = net._upd_states
    return state


class ShardedModelSerializer:
    """writeModel/restore with Orbax-sharded array storage (the
    distributed complement of util.serializer.ModelSerializer)."""

    @staticmethod
    def writeModel(net, path, saveUpdater=True, asyncSave=False):
        """Save to directory `path`. With asyncSave=True the write
        happens in the background — call the returned handle's
        .wait_until_finished() (or save again / exit) to join it.
        Sharded arrays are written per-shard: on multi-host, each host
        writes only the shards it owns."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(str(path))
        os.makedirs(path, exist_ok=True)
        conf_arrays = []
        conf_node = serde.encode(net.conf, conf_arrays)
        manifest = {
            "cls": type(net).__name__,
            "conf": conf_node,
            # config-level constants (init values, vertex factors) are
            # small; inline them so restore can rebuild the net BEFORE
            # touching the array store
            "conf_arrays": [{"dtype": str(np.asarray(a).dtype),
                             "data": np.asarray(a).tolist()}
                            for a in conf_arrays],
            "saveUpdater": bool(saveUpdater),
        }
        with open(os.path.join(path, _MANIFEST), "w") as f:
            json.dump(manifest, f)
        ckpt = (ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
                if asyncSave else ocp.StandardCheckpointer())
        state_path = os.path.join(path, _STATE_DIR)
        ckpt.save(state_path, _net_state(net, saveUpdater), force=True)
        if not asyncSave:
            ckpt.wait_until_finished()
        return ckpt

    @staticmethod
    def restore(path, sharding=None):
        """Rebuild the network from `path`. `sharding`: optional
        jax.sharding.Sharding (e.g. NamedSharding(mesh, P()) to
        replicate onto a new mesh) applied to every restored array —
        omit it to restore with the checkpoint's own layout on the
        current devices. Works across topologies: Orbax reshards from
        however many hosts/devices wrote the checkpoint."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(str(path))
        mpath = os.path.join(path, _MANIFEST)
        if not os.path.exists(mpath):
            raise ValueError(f"no sharded checkpoint at {path} "
                             f"(missing {_MANIFEST})")
        with open(mpath) as f:
            manifest = json.load(f)
        conf_arrays = [np.asarray(d["data"], dtype=d["dtype"])
                       for d in manifest.get("conf_arrays", [])]
        conf = serde.decode(manifest["conf"], conf_arrays)
        if manifest["cls"] == "ComputationGraph":
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            net = ComputationGraph(conf).init()
        else:
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(conf).init()

        # the freshly-initialized net provides the restore target's
        # structure and dtypes; sharding (if given) overrides placement
        target = _net_state(net, manifest["saveUpdater"])

        def _abstract(x):
            x = jax.numpy.asarray(x)
            # default to the fresh target's own placement: explicit
            # shardings make cross-topology restores safe (Orbax warns
            # when it has to guess from the sharding file)
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=sharding if sharding is not None else x.sharding)

        abstract = jax.tree_util.tree_map(_abstract, target)
        ckpt = ocp.StandardCheckpointer()
        state = ckpt.restore(os.path.join(path, _STATE_DIR), abstract)
        ckpt.wait_until_finished()

        net._params = state["params"]
        net._states = state["states"]
        if manifest["saveUpdater"]:
            net._upd_states = state["upd_states"]
        net._iteration = int(state["counters"]["iteration"])
        net._epoch = int(state["counters"]["epoch"])
        return net

"""Pytree helpers shared across the framework."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def device_copy_tree(tree):
    """Device copy (HBM→HBM, no host round-trip) of every array leaf.

    Required wherever saved parameters must outlive a jitted train step:
    the fused step donates its param/state buffers to XLA
    (donate_argnums), so bare references are invalidated by the next
    iteration on TPU."""
    return jax.tree_util.tree_map(jnp.copy, tree)

"""ModelSerializer — save/restore networks and full training state.

Reference: org.deeplearning4j.util.ModelSerializer (writeModel /
restoreMultiLayerNetwork / restoreComputationGraph, with updater state and
an optional attached normalizer) and the CheckpointListener's full
checkpoint. Format: a single .npz holding one JSON manifest (config +
structure, via util.serde's tagged codec) plus the flat array table —
params, updater moments and BN running stats never round-trip through
text. Restoring re-jits on first use; nothing about XLA executables is
(or needs to be) persisted.
"""

from __future__ import annotations

import json

import numpy as np

from deeplearning4j_tpu.util import serde


def _net_payload(net, saveUpdater: bool) -> dict:
    upd = net._upd_states \
        if saveUpdater and getattr(net, "_solver", None) is None else None
    # ZeRO sharded weight update: the live state holds flat 1/dp-shard
    # views; save the CANONICAL full-shape layout (lossless reshape) so
    # the file restores into any mode — same contract as
    # sharded_checkpoint._net_state
    unview = getattr(net, "_upd_state_unview", None)
    if upd is not None and unview is not None:
        upd = unview(upd)
    return {
        "conf": net.conf,
        "params": net._params,
        "states": net._strip_carries(net._states),
        # solver (LBFGS/CG) memory is optax state — batch-local and
        # out-of-package for the codec; restore re-inits it (initFrom)
        "upd_states": upd,
        "iteration": net._iteration,
        "epoch": net._epoch,
    }


def _norm_path(path) -> str:
    """np.savez appends '.npz' to extensionless paths; mirror that on load
    so save(p) / load(p) agree for any p."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def _save_npz(path, manifest: dict, arrays: list):
    np.savez_compressed(_norm_path(path), manifest=np.frombuffer(
        json.dumps(manifest).encode(), np.uint8),
        **{f"arr_{i}": a for i, a in enumerate(arrays)})


def _load_npz(path):
    z = np.load(_norm_path(path), allow_pickle=False)
    manifest = json.loads(bytes(z["manifest"]).decode())
    n = sum(1 for k in z.files if k.startswith("arr_"))
    arrays = [z[f"arr_{i}"] for i in range(n)]
    return manifest, arrays


class ModelSerializer:
    @staticmethod
    def writeModel(net, path, saveUpdater: bool = True, normalizer=None):
        """Reference: ModelSerializer.writeModel(model, file, saveUpdater
        [, dataNormalization])."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        arrays: list = []
        manifest = {
            "format": 1,
            "model_type": ("ComputationGraph"
                           if isinstance(net, ComputationGraph)
                           else "MultiLayerNetwork"),
            "net": serde.encode(_net_payload(net, saveUpdater), arrays),
            "normalizer": (serde.encode(normalizer, arrays)
                           if normalizer is not None else None),
        }
        _save_npz(path, manifest, arrays)

    # -- restore -------------------------------------------------------
    @staticmethod
    def _restore(path, expect_type: str, loadUpdater: bool, loaded=None):
        manifest, arrays = loaded if loaded is not None else _load_npz(path)
        if manifest["model_type"] != expect_type:
            raise ValueError(f"{path} holds a {manifest['model_type']}, "
                             f"not a {expect_type}")
        payload = serde.decode(manifest["net"], arrays)
        conf = payload["conf"]
        if expect_type == "ComputationGraph":
            from deeplearning4j_tpu.nn.graph import ComputationGraph

            net = ComputationGraph(conf)
        else:
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            net = MultiLayerNetwork(conf)
        upd = payload["upd_states"] if loadUpdater else None
        net.initFrom(payload["params"], payload["states"], upd)
        net._iteration = payload["iteration"]
        net._epoch = payload["epoch"]
        return net

    @staticmethod
    def restore(path, loadUpdater: bool = True):
        """Type-dispatching restore: returns whichever network class the
        file holds (callers that know the type can use the explicit
        restoreMultiLayerNetwork/restoreComputationGraph)."""
        loaded = _load_npz(path)
        return ModelSerializer._restore(path, loaded[0]["model_type"],
                                        loadUpdater, loaded=loaded)

    @staticmethod
    def restoreMultiLayerNetwork(path, loadUpdater: bool = True):
        return ModelSerializer._restore(path, "MultiLayerNetwork", loadUpdater)

    @staticmethod
    def restoreComputationGraph(path, loadUpdater: bool = True):
        return ModelSerializer._restore(path, "ComputationGraph", loadUpdater)

    @staticmethod
    def restoreNormalizer(path):
        manifest, arrays = _load_npz(path)
        if manifest.get("normalizer") is None:
            return None
        return serde.decode(manifest["normalizer"], arrays)

    @staticmethod
    def addNormalizerToModel(path, normalizer):
        """Attach a fitted normalizer to an existing model file."""
        manifest, arrays = _load_npz(path)
        manifest["normalizer"] = serde.encode(normalizer, arrays)
        _save_npz(path, manifest, arrays)


class TrainingCheckpoint:
    """Full fault-tolerance checkpoint (reference: Spark training-master
    restart + CheckpointListener): model + updater + iteration/epoch —
    everything needed to resume training bit-for-bit, since the per-step
    dropout/shuffle rng is derived from (seed, iteration)."""

    @staticmethod
    def save(net, path, normalizer=None, extra: dict = None):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        arrays: list = []
        manifest = {
            "format": 1,
            "checkpoint": True,
            "model_type": ("ComputationGraph"
                           if isinstance(net, ComputationGraph)
                           else "MultiLayerNetwork"),
            "net": serde.encode(_net_payload(net, True), arrays),
            "normalizer": (serde.encode(normalizer, arrays)
                           if normalizer is not None else None),
            "extra": extra or {},
        }
        _save_npz(path, manifest, arrays)

    @staticmethod
    def load(path):
        """Returns (net, normalizer, extra)."""
        loaded = _load_npz(path)
        manifest, arrays = loaded
        net = ModelSerializer._restore(path, manifest["model_type"], True,
                                       loaded=loaded)
        norm = (serde.decode(manifest["normalizer"], arrays)
                if manifest.get("normalizer") is not None else None)
        return net, norm, manifest.get("extra", {})

"""Tagged-tree codec: framework object graphs <-> JSON + array table.

Reference: the FlatBuffers/JSON config serialization inside
ModelSerializer / MultiLayerConfiguration.toJson. Configs here are plain
Python objects (layer configs, updaters, schedules, vertices) whose
attributes are primitives, tuples, dicts, other config objects, or device
arrays. The codec walks that graph producing a JSON-able structure; device
arrays are pulled out into a side table (saved as npz entries) and
replaced by index placeholders so weights never round-trip through JSON
text. Decoding only instantiates classes from inside this package —
loading a checkpoint never executes arbitrary pickled code.
"""

from __future__ import annotations

import importlib

import numpy as np

_PKG = "deeplearning4j_tpu"


def _in_pkg(mod_name: str) -> bool:
    # exact-package check: "deeplearning4j_tpu_evil" must NOT pass
    return mod_name == _PKG or mod_name.startswith(_PKG + ".")


def encode(obj, arrays: list):
    """Recursively encode; device/numpy arrays land in `arrays`."""
    import jax

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (jax.Array, np.ndarray)):
        arrays.append(np.asarray(obj))
        return {"__a": len(arrays) - 1}
    if isinstance(obj, list):
        return [encode(v, arrays) for v in obj]
    if isinstance(obj, tuple):
        return {"__t": [encode(v, arrays) for v in obj]}
    if isinstance(obj, (set, frozenset)):
        # sorted for deterministic output (members are config scalars)
        return {"__s": [encode(v, arrays) for v in sorted(obj, key=repr)]}
    if isinstance(obj, dict):
        return {"__d": [[encode(k, arrays), encode(v, arrays)]
                        for k, v in obj.items()]}
    from deeplearning4j_tpu.ndarray.dtype import DataType

    if isinstance(obj, DataType):
        return {"__dt": obj.name}
    cls = type(obj)
    import types

    if isinstance(obj, (types.FunctionType, types.LambdaType,
                        types.BuiltinFunctionType, types.MethodType)):
        raise TypeError(
            "cannot serialize a Python function inside a network config "
            "(e.g. SameDiffLambdaLayer(lambdaFn=...)): custom-code layers "
            "have no portable serialized form. Rebuild the net from code "
            "and restore the trained weights with initFrom / "
            "ModelSerializer's params-only path")
    if not _in_pkg(cls.__module__):
        raise TypeError(f"cannot serialize {cls.__module__}.{cls.__name__}: "
                        f"only {_PKG} config objects are supported")
    attrs = {k: encode(v, arrays) for k, v in vars(obj).items()}
    return {"__o": f"{cls.__module__}:{cls.__qualname__}", "attrs": attrs}


def decode(node, arrays):
    import jax.numpy as jnp

    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, list):
        return [decode(v, arrays) for v in node]
    if "__a" in node:
        a = np.asarray(arrays[node["__a"]])
        return jnp.asarray(a)
    if "__t" in node:
        return tuple(decode(v, arrays) for v in node["__t"])
    if "__s" in node:
        return {decode(v, arrays) for v in node["__s"]}
    if "__d" in node:
        return {decode(k, arrays): decode(v, arrays) for k, v in node["__d"]}
    if "__dt" in node:
        from deeplearning4j_tpu.ndarray.dtype import DataType

        return DataType._registry[node["__dt"]]
    if "__o" in node:
        mod_name, qual = node["__o"].split(":")
        if not _in_pkg(mod_name):
            raise ValueError(f"refusing to instantiate {node['__o']}: "
                             f"outside {_PKG}")
        target = importlib.import_module(mod_name)
        for part in qual.split("."):
            target = getattr(target, part)
        obj = object.__new__(target)
        obj.__dict__.update({k: decode(v, arrays)
                             for k, v in node["attrs"].items()})
        return obj
    raise ValueError(f"unknown node {node!r}")


def to_json(obj) -> str:
    """Array-free JSON for configuration objects (shared by
    MultiLayerConfiguration.toJson / ComputationGraphConfiguration.toJson)."""
    import json

    arrays: list = []
    tree = encode(obj, arrays)
    if arrays:
        raise ValueError("configuration unexpectedly contains arrays")
    return json.dumps(tree)


def from_json(text: str, expected_cls=None):
    import json

    obj = decode(json.loads(text), [])
    if expected_cls is not None and not isinstance(obj, expected_cls):
        raise TypeError(f"JSON holds a {type(obj).__name__}, expected "
                        f"{expected_cls.__name__}")
    return obj

"""Memory workspaces — scoped-semantics shim over XLA's allocator.

Reference: org.nd4j.linalg.api.memory.MemoryWorkspace +
Nd4j.getWorkspaceManager(). The reference needs arena allocators because
every op materialises its output buffer and the JVM GC can't keep up with
device memory churn. Under XLA, intermediates inside a jitted computation
never materialise (the compiler plans one arena per executable) and train
steps donate their input buffers, so the optimisation the workspace API
exists for is already the default. The API is kept for source
compatibility: scopes still nest, validate, and track a high-water mark,
which makes porting reference code (try-with-resources blocks) mechanical.
"""

from __future__ import annotations

import threading

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


class MemoryWorkspace:
    """Context-manager workspace scope (reference: try (MemoryWorkspace ws =
    ...getAndActivateWorkspace(id)) { ... })."""

    def __init__(self, id: str = "WS", config=None):
        self.id = id
        self.config = config
        self._entered = False

    def __enter__(self):
        _stack().append(self)
        self._entered = True
        return self

    def __exit__(self, *exc):
        st = _stack()
        if not st or st[-1] is not self:
            raise RuntimeError(f"workspace scope corruption: closing {self.id} "
                               f"but top of stack is "
                               f"{st[-1].id if st else 'empty'}")
        st.pop()
        self._entered = False
        return False

    def notifyScopeEntered(self):
        return self.__enter__()

    def notifyScopeLeft(self):
        return self.__exit__()

    def isScopeActive(self) -> bool:
        return self._entered


class WorkspaceConfiguration:
    """Accepted-and-ignored knobs (initialSize, policyAllocation...) — XLA
    owns allocation; kept so reference configs parse."""

    def __init__(self, **kwargs):
        self.options = dict(kwargs)


class WorkspaceManager:
    """Reference: Nd4j.getWorkspaceManager()."""

    @staticmethod
    def getAndActivateWorkspace(id: str = "WS", config=None) -> MemoryWorkspace:
        ws = MemoryWorkspace(id, config)
        ws.__enter__()
        return ws

    @staticmethod
    def getCurrentWorkspace():
        st = _stack()
        return st[-1] if st else None

    @staticmethod
    def scopeOutOfWorkspaces():
        """Null scope: detached from any workspace (no-op under XLA)."""
        class _Null:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return _Null()

"""Decode-feedback samplers with per-slot seeded RNG streams.

The PR 15 remainder: generation feedback beyond greedy one-hot. A
sampler is a host-side callable ``sample(logits_row, rng) -> token``
over a decode step's fp32 logits; the rng is a per-request
``numpy.random.Generator`` the scheduler seeds as ``default_rng((seed,
stream_id))`` with stream ids assigned in submit order — so sampling
is DETERMINISTIC per (seed, stream): the bitwise-vs-serial gate holds
with temperature sampling exactly as it does with greedy, because the
serial oracle replays the same stream (tests/test_paged_serving.py).

``greedy_sampler`` ignores its rng (argmax — the default, mirroring
``greedy_onehot_feedback`` on the RNN path, which stays). The RNN
path's one-hot twin of a sampler is ``sampled_onehot_feedback``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["greedy_sampler", "temperature_sampler", "stream_rng",
           "sampled_onehot_feedback"]


def stream_rng(seed, stream_id):
    """The per-slot RNG stream: deterministic in (seed, stream_id),
    independent across streams (numpy SeedSequence spawning under
    ``default_rng`` key tuples)."""
    return np.random.default_rng((int(seed), int(stream_id)))


def greedy_sampler():
    """argmax over the logits row — deterministic, rng unused."""

    def sample(logits, rng):
        return int(np.argmax(logits))

    return sample


def temperature_sampler(temperature=1.0, top_k=None):
    """Softmax sampling at ``temperature``, optionally truncated to
    the ``top_k`` highest-logit tokens. temperature -> 0 degenerates
    to greedy (and temperature=0 is accepted as exactly that). The
    draw comes from the caller-provided per-slot rng stream, so equal
    (seed, stream) always yields the same token for the same logits."""
    temperature = float(temperature)
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and int(top_k) < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    top_k = None if top_k is None else int(top_k)

    def sample(logits, rng):
        z = np.asarray(logits, np.float64)
        if temperature == 0:
            return int(np.argmax(z))
        z = z / temperature
        if top_k is not None and top_k < z.shape[0]:
            # keep the k largest; ties break by index like argpartition
            cut = np.argpartition(z, -top_k)[:-top_k]
            z = z.copy()
            z[cut] = -np.inf
        z = z - np.max(z)
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(p.shape[0], p=p))

    return sample


def sampled_onehot_feedback(vocab, sampler, rng):
    """RNN-path twin: wrap a token sampler as a one-hot feedback
    closure for ``SequenceScheduler`` (the sampled token's one-hot row
    is the next input). Deterministic per the sampler's rng stream."""
    eye = np.eye(int(vocab), dtype=np.float32)

    def feedback(out_row):
        return eye[sampler(np.asarray(out_row, np.float32), rng)]

    return feedback

"""Failure-domain primitives for the serving fleet.

Four small, clock-injectable, individually-testable pieces the
``FleetRouter`` composes (serving/fleet.py, docs/SERVING.md "Failure
domains"):

* ``CircuitBreaker`` — closed/open/half-open per REPLICA over a
  sliding failure-rate window. Consulted by the router's least-loaded
  ranking: an open breaker removes the replica from organic traffic
  for ``open_for_s``, then half-open admits traffic again and
  ``close_after`` consecutive successes re-close it (one failure
  re-opens). All transitions are pure functions of the injected clock
  and the recorded outcomes — ManualClock tests predict them exactly.
* ``ReplicaHealth`` — the per-replica wrapper: breaker + quarantine.
  A QUARANTINED replica serves only health probes; ``note_probe``
  re-admits it after ``readmit_after`` consecutive probe successes
  (and resets the breaker, so re-admission starts clean).
* ``RetryBudget`` — a deterministic token bucket capping failover
  retries at ``ratio`` x requests (+ a small burst): every request
  deposits ``ratio`` tokens, every retry spends one, an empty bucket
  fails fast. This is what keeps a brown-out from amplifying into a
  retry storm — fleet-wide retry amplification is bounded by
  ratio + burst/requests.
* ``BrownoutController`` — admission-time load shedding: estimated
  queue delay (queued work x per-item service estimate) vs the
  request's deadline; a request that cannot make its deadline is shed
  BEFORE it occupies queue space, so overload degrades p50 instead of
  detonating p99. The estimate is conservative on purpose (sheds only
  when the deadline is already hopeless by the measured estimate).

Nothing here imports jax and nothing spawns threads; all state is
lock-guarded (the THREADED_TIER lint gate covers this module through
the ``serving`` roster entry, analysis/threads.py).
"""

from __future__ import annotations

import threading
from collections import deque

__all__ = ["BrownoutController", "CircuitBreaker", "ReplicaHealth",
           "RetryBudget"]

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


def _mono():
    import time

    return time.monotonic()


class CircuitBreaker:
    """Per-replica circuit breaker (module docstring).

    window:        sliding outcome window length.
    failure_ratio: trip threshold over the window.
    min_samples:   outcomes required before the ratio can trip (a
                   single early failure must not open a cold breaker).
    open_for_s:    how long OPEN rejects before HALF_OPEN admits again.
    close_after:   consecutive HALF_OPEN successes that re-close.
    clock:         injectable monotonic clock (ManualClock in tests).
    """

    def __init__(self, *, window=16, failure_ratio=0.5, min_samples=4,
                 open_for_s=5.0, close_after=2, clock=None):
        if not 0.0 < float(failure_ratio) <= 1.0:
            raise ValueError(
                f"failure_ratio must be in (0, 1], got {failure_ratio}")
        self.window = int(window)
        self.failure_ratio = float(failure_ratio)
        self.min_samples = int(min_samples)
        self.open_for_s = float(open_for_s)
        self.close_after = int(close_after)
        self._clock = clock if clock is not None else _mono
        self._lock = threading.Lock()
        self._outcomes = deque(maxlen=self.window)  # True = success
        self._state = CLOSED
        self._opened_at = None
        self._half_open_ok = 0
        self.opened_total = 0

    # -- state -----------------------------------------------------------
    def _state_locked(self, now):
        """Resolve the time-driven OPEN -> HALF_OPEN transition."""
        if self._state == OPEN \
                and now - self._opened_at >= self.open_for_s:
            self._state = HALF_OPEN
            self._half_open_ok = 0
        return self._state

    @property
    def state(self):
        with self._lock:
            return self._state_locked(self._clock())

    def allow(self):
        """May organic traffic reach the replica right now? CLOSED and
        HALF_OPEN admit; OPEN rejects until open_for_s elapses."""
        with self._lock:
            return self._state_locked(self._clock()) != OPEN

    # -- outcomes --------------------------------------------------------
    def record(self, ok):
        """Record one dispatch outcome; returns the post-record state."""
        ok = bool(ok)
        with self._lock:
            state = self._state_locked(self._clock())
            if state == HALF_OPEN:
                if ok:
                    self._half_open_ok += 1
                    if self._half_open_ok >= self.close_after:
                        self._state = CLOSED
                        self._outcomes.clear()
                else:
                    self._trip_locked()
                return self._state
            self._outcomes.append(ok)
            if not ok and len(self._outcomes) >= self.min_samples:
                failures = sum(1 for o in self._outcomes if not o)
                if failures / len(self._outcomes) \
                        >= self.failure_ratio:
                    self._trip_locked()
            return self._state

    def _trip_locked(self):
        self._state = OPEN
        self._opened_at = self._clock()
        self._outcomes.clear()
        self._half_open_ok = 0
        self.opened_total += 1

    def reset(self):
        """Force CLOSED with a clean window (the re-admission path)."""
        with self._lock:
            self._state = CLOSED
            self._outcomes.clear()
            self._half_open_ok = 0

    def snapshot(self):
        with self._lock:
            state = self._state_locked(self._clock())
            return {"state": state,
                    "window": list(self._outcomes),
                    "opened_total": self.opened_total}


class ReplicaHealth:
    """Breaker + quarantine for one replica (module docstring)."""

    def __init__(self, *, readmit_after=3, breaker=None, clock=None,
                 **breaker_kw):
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker(clock=clock, **breaker_kw)
        self.readmit_after = int(readmit_after)
        self._lock = threading.Lock()
        self._quarantined = False
        self._probe_ok = 0

    @property
    def quarantined(self):
        with self._lock:
            return self._quarantined

    def admissible(self):
        """May the router rank this replica for organic traffic?"""
        return not self.quarantined and self.breaker.allow()

    def quarantine(self):
        """Remove from organic traffic; only probes reach it now."""
        with self._lock:
            self._quarantined = True
            self._probe_ok = 0

    def readmit(self):
        with self._lock:
            self._quarantined = False
            self._probe_ok = 0
        self.breaker.reset()

    def note_probe(self, ok):
        """Record one health-probe outcome against a quarantined
        replica. Returns True when this probe completed re-admission
        (readmit_after consecutive successes; any failure resets the
        streak)."""
        with self._lock:
            if not self._quarantined:
                return False
            self._probe_ok = self._probe_ok + 1 if ok else 0
            if self._probe_ok < self.readmit_after:
                return False
        self.readmit()
        return True

    def record(self, ok):
        """Record one organic dispatch outcome (feeds the breaker)."""
        return self.breaker.record(ok)

    def snapshot(self):
        with self._lock:
            q, streak = self._quarantined, self._probe_ok
        return {"quarantined": q, "probe_streak": streak,
                **self.breaker.snapshot()}


class RetryBudget:
    """Deterministic ratio-capped retry tokens (module docstring).

    ratio: tokens deposited per request (retries allowed per request,
           long-run).
    burst: bucket cap AND the initial balance — a cold fleet can still
           fail over its first few requests.
    """

    def __init__(self, ratio=0.2, burst=10.0):
        if float(ratio) < 0.0:
            raise ValueError(f"ratio must be >= 0, got {ratio}")
        self.ratio = float(ratio)
        self.burst = float(burst)
        self._lock = threading.Lock()
        self._tokens = self.burst
        self.requests = 0
        self.spent = 0
        self.denied = 0

    def note_request(self):
        """Deposit for one admitted request."""
        with self._lock:
            self.requests += 1
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def try_spend(self):
        """Take one retry token; False = budget exhausted, fail fast."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False

    def snapshot(self):
        with self._lock:
            return {"tokens": self._tokens, "requests": self.requests,
                    "spent": self.spent, "denied": self.denied,
                    "ratio": self.ratio, "burst": self.burst}


class BrownoutController:
    """Admission-time deadline-hopeless shedding (module docstring).

    est_item_s: per-queued-item service estimate; None = use the
                measured estimate the caller passes per decision (and
                never shed while neither exists — no data, no shed).
    margin:     multiplier on the estimate; > 1 sheds EARLIER (more
                aggressively), < 1 later. Kept at 1.0 by default so
                the controller sheds only what the measurement already
                calls hopeless.
    """

    def __init__(self, est_item_s=None, margin=1.0):
        self.est_item_s = None if est_item_s is None \
            else float(est_item_s)
        self.margin = float(margin)
        self._lock = threading.Lock()
        self.shed = 0
        self.admitted = 0

    def estimate_wait_s(self, queued_work, measured_item_s=None):
        """Queue-delay estimate for `queued_work` items ahead, or None
        when no per-item estimate exists yet."""
        est = self.est_item_s if self.est_item_s is not None \
            else measured_item_s
        if est is None:
            return None
        return float(queued_work) * float(est) * self.margin

    def should_shed(self, queued_work, deadline_s,
                    measured_item_s=None):
        """True when the estimated queue delay alone already exceeds
        the request's deadline — the request is hopeless BEFORE it
        wastes queue space. No deadline or no estimate = admit."""
        if deadline_s is None:
            self._note(False)
            return False
        wait = self.estimate_wait_s(queued_work, measured_item_s)
        hopeless = wait is not None and wait > float(deadline_s)
        self._note(hopeless)
        return hopeless

    def _note(self, shed):
        with self._lock:
            if shed:
                self.shed += 1
            else:
                self.admitted += 1

    def snapshot(self):
        with self._lock:
            return {"shed": self.shed, "admitted": self.admitted,
                    "est_item_s": self.est_item_s,
                    "margin": self.margin}

"""Continuous-batching model server.

The serving tier that amortizes XLA dispatches across concurrent
requests (the classic throughput lever of large-scale serving systems,
arXiv:1605.08695, applied on top of the one-executable-per-bucket
compilation model of arXiv:1810.09868):

* ``queue``   — bounded request queue + dynamic micro-batcher: coalesce
  waiting requests up to the nearest batch bucket (or a max-wait
  deadline), pad, run ONE dispatch through the per-bucket AOT
  executable cache, slice results back per request. Injectable clock
  so latency-path tests run deterministically without sleeps.
* ``host``    — multi-model host: model name -> (network, dtype policy,
  optional weight-only int8, batch buckets), each precompiled at
  registration, with a rolling model swap that warms the new version's
  executables while the old one keeps serving.
* ``server``  — the HTTP front (``InferenceServer``): /healthz-gated
  readiness, queue-full backpressure as 429, per-request deadlines as
  504.
* ``loadgen`` — open-loop (Poisson-arrival) load generator recording
  requests/sec, p50/p99 latency and batch occupancy — the `serving`
  bench headline.

See docs/SERVING.md.
"""

from deeplearning4j_tpu.serving.queue import (  # noqa: F401
    DeadlineExceededError, InferenceRequest, ManualClock, MicroBatcher,
    QueueFullError, ServingClosedError,
)
from deeplearning4j_tpu.serving.host import (  # noqa: F401
    ModelHost, ServedModel,
)
from deeplearning4j_tpu.serving.server import InferenceServer  # noqa: F401

__all__ = [
    "DeadlineExceededError", "InferenceRequest", "ManualClock",
    "MicroBatcher", "QueueFullError", "ServingClosedError",
    "ModelHost", "ServedModel", "InferenceServer",
]

"""Continuous-batching model server + the sequence/fleet tier.

The serving tier that amortizes XLA dispatches across concurrent
requests (the classic throughput lever of large-scale serving systems,
arXiv:1605.08695, applied on top of the one-executable-per-bucket
compilation model of arXiv:1810.09868):

* ``queue``    — bounded request queue + dynamic micro-batcher: coalesce
  waiting requests up to the nearest batch bucket (or a max-wait
  deadline), pad, run ONE dispatch through the per-bucket AOT
  executable cache, slice results back per request. Injectable clock
  so latency-path tests run deterministically without sleeps.
* ``sequence`` — iteration-level continuous batching for STATEFUL
  models: a slot table of active sequences with carried hidden/cell
  state, the batch re-formed every decode step (early-exit slots
  refilled from the queue mid-sequence), one executable per slot
  bucket, per-step deadlines. The KV-slot twin
  (``PagedSequenceScheduler``) serves token-prompt transformer models
  over the paged KV cache, interleaving bounded prefill chunks with
  the decode batch.
* ``kvcache``  — the paged KV cache itself: fixed-size KV blocks in a
  device-resident pool, per-slot block tables, allocation/free at
  step boundaries, copy-on-write prefix sharing; pool exhaustion is
  the typed ``KVCacheFullError`` (429).
* ``sampling`` — host-side decode samplers (greedy, temperature/top-k)
  with deterministic per-(seed, stream) RNG streams.
* ``host``     — multi-model host: model name -> (network, dtype policy,
  optional weight-only int8, batch buckets), each precompiled at
  registration, with a rolling model swap that warms the new version's
  executables while the old one keeps serving; sequence models ride in
  a parallel table behind the same contract.
* ``fleet``    — N ModelHost replicas behind a least-loaded router:
  per-model SLOs, queue-depth-driven autoscale DECISIONS (callback
  surface), fleet-wide zero-5xx rolling swaps, load scenarios.
* ``breaker``  — the failure-domain primitives the fleet composes:
  per-replica circuit breaker (closed/open/half-open), quarantine +
  probe re-admission, ratio-capped retry budget, brownout admission
  control. Proven against the deterministic chaos harness
  (runtime/chaos.py).
* ``server``   — the HTTP front (``InferenceServer``): /healthz-gated
  readiness, queue-full backpressure as 429, per-request deadlines as
  504, ``:predict`` (one-shot) and ``:generate`` (sequence) routes.
* ``loadgen``  — open-loop (Poisson-arrival) and closed-loop (blocking
  clients + think time) load generators recording requests/sec,
  p50/p99 latency, per-error-class counts and batch occupancy.

See docs/SERVING.md.
"""

from deeplearning4j_tpu.serving.breaker import (  # noqa: F401
    BrownoutController, CircuitBreaker, ReplicaHealth, RetryBudget,
)
from deeplearning4j_tpu.serving.queue import (  # noqa: F401
    DeadlineExceededError, InferenceRequest, ManualClock, MicroBatcher,
    QueueFullError, RequestCancelledError, ServingClosedError,
)
from deeplearning4j_tpu.serving.kvcache import (  # noqa: F401
    KVCacheFullError, PagedKVCache,
)
from deeplearning4j_tpu.serving.sampling import (  # noqa: F401
    greedy_sampler, sampled_onehot_feedback, stream_rng,
    temperature_sampler,
)
from deeplearning4j_tpu.serving.sequence import (  # noqa: F401
    GenerationRequest, PagedSequenceScheduler, SequenceRequest,
    SequenceScheduler, greedy_onehot_feedback,
)
from deeplearning4j_tpu.serving.host import (  # noqa: F401
    ModelHost, ServedModel, ServedSequenceModel,
)
from deeplearning4j_tpu.serving.fleet import (  # noqa: F401
    FleetRouter, ModelSLO,
)
from deeplearning4j_tpu.serving.server import InferenceServer  # noqa: F401

__all__ = [
    "DeadlineExceededError", "InferenceRequest", "ManualClock",
    "MicroBatcher", "QueueFullError", "RequestCancelledError",
    "ServingClosedError",
    "SequenceRequest", "SequenceScheduler", "greedy_onehot_feedback",
    "GenerationRequest", "PagedSequenceScheduler",
    "KVCacheFullError", "PagedKVCache",
    "greedy_sampler", "temperature_sampler", "stream_rng",
    "sampled_onehot_feedback",
    "ModelHost", "ServedModel", "ServedSequenceModel",
    "FleetRouter", "ModelSLO", "InferenceServer",
    "BrownoutController", "CircuitBreaker", "ReplicaHealth",
    "RetryBudget",
]

"""Multi-host serving fleet: N ModelHost replicas behind one router.

One ``ModelHost`` is one serving process's worth of models (queue +
micro-batcher + warm executables per model). Production traffic from
millions of users needs N of them — and the pieces that make N hosts a
FLEET are host-side and statically testable: least-loaded dispatch,
per-model SLOs, queue-depth-driven autoscaling *decisions* (a callback
surface — the fleet layer decides, an operator/orchestrator actuates;
no real processes are spawned here), and rolling swaps that stay
zero-5xx fleet-wide because each replica's swap already is
(serving/host.py).

* ``FleetRouter.submit`` picks the replica with the LEAST total queued
  work for the target model (queue depth + live slot count for
  sequence models) and fails over to the next-least-loaded on
  ``QueueFullError`` — a single saturated replica sheds to its peers
  before the client ever sees a 429; only a fleet-wide full queue
  surfaces backpressure.
* ``register``/``register_sequence`` fan a model out to every replica;
  ``swap_all`` rolls a new version across replicas ONE AT A TIME (the
  remaining replicas keep serving, each per-replica swap is itself
  warm-then-flip) — fleet-wide zero-5xx rolling deploys.
* ``set_slo`` declares per-model targets (p99 ms, queue-depth bounds,
  replica min/max); ``autoscale_tick`` turns the live queue depths +
  measured p99 into scale decisions and invokes every ``on_scale``
  callback with a structured record.
* ``metrics_snapshot`` is the fleet view: per-replica queue depth +
  slot occupancy + per-model fleet aggregates, additive over the
  per-host PR 13 snapshot schema.

Failure domains (docs/SERVING.md "Failure domains"): every replica
carries a ``ReplicaHealth`` — a closed/open/half-open circuit breaker
over its dispatch outcomes plus a quarantine flag — consulted by the
least-loaded ranking, so a replica that WEDGES or THROWS (not just one
that politely raises QueueFullError) is evicted from organic traffic
and re-admitted only after the breaker's half-open probe successes (or
``probe_tick`` health canaries for a quarantined replica). Failover
covers every dispatch-path error class (counted per class in
``dl4j_fleet_failovers_total``) under a per-model ratio-capped
``RetryBudget`` so a brown-out cannot amplify into a retry storm;
``set_hedge`` arms tail-latency hedging for idempotent one-shot
``:predict`` (second replica fired at the p95 mark, first response
wins, loser cancelled); ``set_brownout`` sheds deadline-hopeless
requests at admission. All of it is exercised by the deterministic
chaos harness (runtime/chaos.py, seam ``fleet.dispatch``).

Load scenarios (the bench `serving_fleet` leg's vocabulary): diurnal
ramp (open-loop rate swept through a day curve), hot-model skew (one
model takes most of the traffic), slow-client storm (closed-loop
clients with think time holding results). Each records fleet
requests/sec, p50/p99 and per-error-class counts.

See docs/SERVING.md "Sequence serving + the fleet".
"""

from __future__ import annotations

import itertools
import threading

import numpy as np

from deeplearning4j_tpu.runtime import telemetry
from deeplearning4j_tpu.runtime.chaos import fault_point
from deeplearning4j_tpu.serving.breaker import (
    BrownoutController, ReplicaHealth, RetryBudget,
)
from deeplearning4j_tpu.serving.queue import (
    DeadlineExceededError, QueueFullError, ServingClosedError,
)

__all__ = ["FleetRouter", "ModelSLO", "scenario_diurnal_ramp",
           "scenario_hot_model_skew", "scenario_slow_client_storm"]

#: breaker-state gauge encoding (dl4j_fleet_breaker_state)
_BREAKER_STATES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

_REPLICA_SEQ = itertools.count(1)


class ModelSLO:
    """Per-model service-level objective + autoscale thresholds.

    p99_ms:       latency target; a measured fleet p99 above it votes
                  scale_up.
    queue_high:   mean per-replica queued work above this votes
                  scale_up.
    queue_low:    mean per-replica queued work below this votes
                  scale_down (never below min_replicas).
    min_replicas/max_replicas: the decision clamp.
    """

    __slots__ = ("p99_ms", "queue_high", "queue_low", "min_replicas",
                 "max_replicas")

    def __init__(self, p99_ms=None, queue_high=8.0, queue_low=1.0,
                 min_replicas=1, max_replicas=8):
        if float(queue_low) > float(queue_high):
            raise ValueError(
                f"queue_low {queue_low} > queue_high {queue_high}: the "
                "scale-down band must sit below the scale-up band")
        self.p99_ms = None if p99_ms is None else float(p99_ms)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)

    def as_dict(self):
        return {"p99_ms": self.p99_ms, "queue_high": self.queue_high,
                "queue_low": self.queue_low,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas}


class FleetRouter:
    """Least-loaded router over N ModelHost replicas (module
    docstring). Thread-safe: the replica table and SLO book are
    lock-guarded; dispatches run outside the lock."""

    def __init__(self, replicas=(), clock=None, breaker=None,
                 readmit_after=3, retry_ratio=0.2, retry_burst=10.0):
        """breaker: dict of CircuitBreaker kwargs applied to every
        replica's health record (window/failure_ratio/min_samples/
        open_for_s/close_after), or False to disable breaker +
        quarantine gating entirely. retry_ratio/retry_burst: the
        per-model RetryBudget (serving/breaker.py)."""
        self._lock = threading.Lock()
        self._replicas = {}        # id -> ModelHost
        self._health = {}          # id -> ReplicaHealth
        self._slos = {}            # model name -> ModelSLO
        self._scale_cbs = []
        self._budgets = {}         # model name -> RetryBudget
        self._hedge = {}           # model name -> {"after_s": ...}
        self._brownouts = {}       # model name -> BrownoutController
        self._probes = {}          # model name -> canary features
        self._clock = clock
        self._breaker_kw = None if breaker is False else dict(breaker
                                                              or {})
        self._readmit_after = int(readmit_after)
        self._retry_ratio = float(retry_ratio)
        self._retry_burst = float(retry_burst)
        reg = telemetry.get_registry()
        self._registry = reg
        self._m_requests = reg.counter(
            "dl4j_fleet_requests_total",
            "requests routed by the fleet router",
            labels=("model",))
        self._m_failover = reg.counter(
            "dl4j_fleet_failovers_total",
            "requests shed to a peer replica, by error class",
            labels=("model", "error"))
        self._m_latency = reg.histogram(
            "dl4j_fleet_request_seconds",
            "router-measured request latency (the SLO p99 source)",
            labels=("model",))
        self._m_replicas = reg.gauge(
            "dl4j_fleet_replicas", "replicas registered to the fleet")
        self._m_breaker = reg.gauge(
            "dl4j_fleet_breaker_state",
            "per-replica breaker state (0 closed, 1 half-open, 2 open)",
            labels=("replica",))
        self._m_hedges = reg.counter(
            "dl4j_fleet_hedges_total",
            "hedged second dispatches fired", labels=("model",))
        self._m_hedge_wins = reg.counter(
            "dl4j_fleet_hedge_wins_total",
            "hedged dispatches won by the second replica",
            labels=("model",))
        self._m_shed = reg.counter(
            "dl4j_fleet_brownout_shed_total",
            "requests shed at admission (deadline already unmeetable)",
            labels=("model",))
        self._m_probes = reg.counter(
            "dl4j_fleet_probes_total",
            "health-probe canaries against quarantined replicas",
            labels=("model", "outcome"))
        for host in replicas:
            self.add_replica(host)

    # -- replica lifecycle ----------------------------------------------
    def add_replica(self, host, replica_id=None):
        """Attach one ModelHost; returns its replica id."""
        rid = str(replica_id) if replica_id else \
            f"replica{next(_REPLICA_SEQ)}"
        with self._lock:
            if rid in self._replicas:
                raise ValueError(f"replica {rid!r} already attached")
            self._replicas[rid] = host
            if self._breaker_kw is not None:
                self._health[rid] = ReplicaHealth(
                    readmit_after=self._readmit_after,
                    clock=self._now, **self._breaker_kw)
            self._m_replicas.set(len(self._replicas))
        self._m_breaker.labels(replica=rid).set(0.0)
        return rid

    def remove_replica(self, replica_id, drain=True):
        """Detach + close one replica (drain=True completes its queued
        work — the scale-down path)."""
        with self._lock:
            host = self._replicas.pop(replica_id, None)
            self._health.pop(replica_id, None)
            self._m_replicas.set(len(self._replicas))
        self._m_breaker.remove(replica=replica_id)
        if host is None:
            raise KeyError(f"unknown replica {replica_id!r} "
                           f"(attached: {self.replica_ids()})")
        host.close(drain=drain)
        return host

    def replica_ids(self):
        with self._lock:
            return sorted(self._replicas)

    def _hosts(self):
        with self._lock:
            return list(self._replicas.items())

    # -- model fan-out ---------------------------------------------------
    def register(self, name, network, **kw):
        """Register a one-shot model on EVERY replica (equal configs
        share bucket executables through the AOT session cache, so N
        replicas warm for the price of one compile set)."""
        return {rid: host.register(name, network, **kw)
                for rid, host in self._hosts()}

    def register_sequence(self, name, network, **kw):
        """Register a sequence (iteration-level) model on every
        replica."""
        return {rid: host.register_sequence(name, network, **kw)
                for rid, host in self._hosts()}

    def swap_all(self, name, network, **overrides):
        """Fleet-wide rolling deploy: swap replicas ONE AT A TIME.
        While replica i warms+flips, the other N-1 keep serving the old
        version; each per-replica swap is itself warm-then-flip with a
        drain (serving/host.py), so no request anywhere sees a cold
        compile or a 5xx. Covers one-shot AND sequence models (each
        host routes by its own registration kind)."""
        out = {}
        for rid, host in self._hosts():
            kind = host.kind(name)
            if kind == "sequence":
                out[rid] = host.swap_sequence(name, network, **overrides)
            elif kind == "oneshot":
                out[rid] = host.swap(name, network, **overrides)
            else:
                raise KeyError(
                    f"replica {rid!r} does not serve model {name!r} — "
                    "register it fleet-wide before swap_all")
        return out

    # -- dispatch --------------------------------------------------------
    @staticmethod
    def _queued_work(host, name):
        """Outstanding work this replica holds for `name`: one-shot
        requests queued or inside a running dispatch, or queue depth +
        live slots for a sequence model (the least-loaded ranking
        key); None when the replica does not serve the model. A
        point-in-time probe — routing tolerates staleness."""
        return host.queued_work(name)

    def health(self, replica_id):
        """The replica's ReplicaHealth (breaker + quarantine), or None
        when breaker gating is disabled (breaker=False)."""
        with self._lock:
            return self._health.get(replica_id)

    def _note_outcome(self, rid, ok):
        """Feed one dispatch outcome into the replica's breaker and
        mirror the resulting state into the breaker gauge."""
        h = self.health(rid)
        if h is None:
            return
        state = h.record(ok)
        self._m_breaker.labels(replica=rid).set(
            _BREAKER_STATES.get(state, 0.0))

    def _ranked(self, name):
        """(replica_id, host) pairs serving `name`, least loaded
        first. Replicas whose breaker is OPEN or that are QUARANTINED
        are excluded from organic traffic — unless that would empty
        the list, in which case the router FAILS OPEN and ranks the
        barred replicas anyway (a wrongly-tripped fleet must degrade,
        not hard-down; docs/SERVING.md "Failure domains")."""
        ranked, barred = [], []
        for rid, host in self._hosts():
            load = self._queued_work(host, name)
            if load is None:
                continue
            h = self.health(rid)
            if h is None or h.admissible():
                ranked.append((load, rid, host))
            else:
                barred.append((load, rid, host))
        if not ranked and not barred:
            raise KeyError(
                f"no replica serves model {name!r} "
                f"(replicas: {self.replica_ids()})")
        if not ranked:
            ranked = barred  # fail open: serving beats a hard down
        ranked.sort(key=lambda t: (t[0], t[1]))
        return [(rid, host) for _, rid, host in ranked]

    def _budget(self, name):
        with self._lock:
            b = self._budgets.get(name)
            if b is None:
                b = self._budgets[name] = RetryBudget(
                    ratio=self._retry_ratio, burst=self._retry_burst)
        return b

    # -- admission: brownout ---------------------------------------------
    def set_brownout(self, name, est_item_s=None, margin=1.0,
                     enabled=True):
        """Arm (or disarm) admission-time shedding for `name`: a
        deadline-carrying request whose estimated queue delay (least
        queued work x per-item estimate) already exceeds its deadline
        is rejected NOW with DeadlineExceededError instead of wasting
        queue space. est_item_s=None uses the measured mean request
        latency (dl4j_fleet_request_seconds)."""
        with self._lock:
            if not enabled:
                return self._brownouts.pop(name, None)
            bo = self._brownouts[name] = BrownoutController(
                est_item_s=est_item_s, margin=margin)
        return bo

    def _admit(self, name, deadline_s, least_load):
        with self._lock:
            bo = self._brownouts.get(name)
        if bo is None or deadline_s is None:
            return
        child = self._m_latency.labels_get(model=name)
        measured = child.mean() if child is not None else None
        if bo.should_shed(least_load, deadline_s, measured):
            self._m_shed.labels(model=name).inc()
            raise DeadlineExceededError(
                f"brownout: ~{bo.estimate_wait_s(least_load, measured):.3f}s "
                f"of queued work ahead exceeds the {deadline_s:.3f}s "
                f"deadline — shed at admission")

    # -- dispatch --------------------------------------------------------
    def submit(self, name, features, deadline_s=None):
        """Route one one-shot request to the least-loaded admissible
        replica; fail over on ANY dispatch-path error (not just
        QueueFullError) within the per-model retry budget. Only a
        fleet-wide failure re-raises. With set_hedge armed, a second
        replica is fired at the p95 mark and the first response
        wins."""
        with self._lock:
            hedge = self._hedge.get(name)
        if hedge is not None:
            return self._submit_hedged(name, features, deadline_s,
                                       hedge)
        t0 = self._now()
        out = self._failover(
            name, lambda host: host.submit(name, features,
                                           deadline_s=deadline_s),
            deadline_s=deadline_s)
        # observed only for COMPLETED requests: a 429 storm's fast
        # failures must not dilute the p99 the autoscaler votes on
        self._m_latency.labels(model=name).observe(self._now() - t0)
        return out

    def submit_sequence(self, name, features, deadline_s=None,
                        extra_steps=0, wait=True, timeout=None):
        """Route one sequence to the least-loaded replica's slot
        scheduler (same failover discipline as submit; sequences are
        stateful mid-decode, so they are never hedged)."""
        t0 = self._now()
        out = self._failover(
            name, lambda host: host.submit_sequence(
                name, features, deadline_s=deadline_s,
                extra_steps=extra_steps, wait=wait, timeout=timeout),
            deadline_s=deadline_s)
        if wait:
            # wait=False returns at enqueue — that sample would read
            # sub-ms and suppress the autoscaler's p99 scale-up vote
            self._m_latency.labels(model=name).observe(self._now() - t0)
        return out

    def _failover(self, name, call, deadline_s=None, want_rid=False):
        """Try replicas least-loaded first. Error classification:

        * QueueFullError / ServingClosedError — backpressure or a
          replica mid-retirement: fail over (budget-capped) but do NOT
          charge the replica's breaker; load is not a fault.
        * DeadlineExceededError, ValueError, KeyError — the REQUEST's
          own problem (deadline spent, malformed, unknown model): no
          failover, no breaker charge; re-raise immediately.
        * anything else — a replica fault: charge the breaker, fail
          over (budget-capped). Only a fleet-wide failure surfaces.
        """
        self._m_requests.labels(model=name).inc()
        budget = self._budget(name)
        budget.note_request()
        ranked = self._ranked(name)
        self._admit(name, deadline_s,
                    self._queued_work(ranked[0][1], name) or 0)
        last = None
        for i, (rid, host) in enumerate(ranked):
            try:
                # the routing chaos seam: an injected raise here is a
                # dispatch-path fault on THIS replica (runtime/chaos.py)
                fault_point("fleet.dispatch")
                out = call(host)
            except (QueueFullError, ServingClosedError) as e:
                last = e
                if i + 1 < len(ranked) and budget.try_spend():
                    self._m_failover.labels(
                        model=name, error=type(e).__name__).inc()
                    continue
                raise
            except (DeadlineExceededError, ValueError, KeyError):
                raise
            except Exception as e:
                last = e
                self._note_outcome(rid, False)
                if i + 1 < len(ranked) and budget.try_spend():
                    self._m_failover.labels(
                        model=name, error=type(e).__name__).inc()
                    continue
                raise
            else:
                self._note_outcome(rid, True)
                return (out, rid) if want_rid else out
        raise last

    # -- hedged dispatch -------------------------------------------------
    def set_hedge(self, name, after_s=None, enabled=True):
        """Arm (or disarm) tail-latency hedging for idempotent one-shot
        `name`: when the primary has not answered within the hedge
        mark, fire the SAME request at the next-ranked replica — first
        response wins, the loser is cancelled. after_s=None uses the
        live p95 of dl4j_fleet_request_seconds (falling back to 50 ms
        until enough samples exist). Hedges spend the same retry
        budget as failovers, so a brown-out cannot double the load."""
        with self._lock:
            if not enabled:
                return self._hedge.pop(name, None)
            self._hedge[name] = {"after_s": None if after_s is None
                                 else float(after_s)}

    def _hedge_after(self, name, conf):
        if conf["after_s"] is not None:
            return conf["after_s"]
        child = self._m_latency.labels_get(model=name)
        p95 = child.percentile(95) if child is not None else None
        return 0.05 if p95 is None else p95

    def _submit_hedged(self, name, features, deadline_s, conf):
        t0 = self._now()
        # completion wakeup: every leg notifies this condition the
        # moment it finishes (add_done_callback), so the race loop
        # below sleeps on a bounded CV wait instead of busy-spinning
        done = threading.Condition()

        def _wake(_req):  # idempotent — add_done_callback may re-call
            with done:
                done.notify_all()

        req1, rid1 = self._failover(
            name, lambda host: host.submit(name, features,
                                           deadline_s=deadline_s,
                                           wait=False),
            deadline_s=deadline_s, want_rid=True)
        req1.add_done_callback(_wake)
        legs = [(rid1, req1)]
        hedge_after = self._hedge_after(name, conf)
        if not req1.wait_done(hedge_after):
            # primary is past the hedge mark: fire the second replica
            # (next-ranked, never the same one) if budget allows
            cand = next(((rid, h) for rid, h in self._ranked(name)
                         if rid != rid1), None)
            if cand is not None and self._budget(name).try_spend():
                rid2, host2 = cand
                rem = None if deadline_s is None else \
                    max(1e-3, deadline_s - (self._now() - t0))
                try:
                    req2 = host2.submit(name, features, deadline_s=rem,
                                        wait=False)
                except Exception as e:
                    # hedge enqueue refused: the primary races on
                    # alone, but the refusal is COUNTED under its
                    # error class and — unless it is backpressure —
                    # charged to the refusing replica, same as any
                    # dispatch fault (a silently swallowed refusal
                    # here hid dead hedge replicas from the breaker)
                    req2 = None
                    if not isinstance(e, (QueueFullError,
                                          ServingClosedError)):
                        self._note_outcome(rid2, False)
                    self._m_failover.labels(
                        model=name, error=type(e).__name__).inc()
                if req2 is not None:
                    self._m_hedges.labels(model=name).inc()
                    req2.add_done_callback(_wake)
                    legs.append((rid2, req2))
        # first COMPLETED-with-result leg wins; a leg that completes
        # with an error is charged to its replica and dropped so the
        # other leg keeps racing (hedging covers faults for free)
        last_err = None
        while legs:
            for rid, req in list(legs):
                if not req.done:
                    continue
                if req.error is not None:
                    self._note_outcome(rid, False)
                    legs.remove((rid, req))
                    last_err = req.error
                    continue
                for orid, other in legs:    # cancel the loser(s)
                    if other is not req:
                        other.cancel()
                self._note_outcome(rid, True)
                if req is not req1:
                    self._m_hedge_wins.labels(model=name).inc()
                self._m_latency.labels(model=name).observe(
                    self._now() - t0)
                return req.result
            if deadline_s is not None \
                    and self._now() - t0 > deadline_s + 1.0:
                # backstop only: each leg's own deadline releases it
                # (the queue.py wait contract) long before this fires
                for _, req in legs:
                    req.cancel()
                raise DeadlineExceededError(
                    f"hedged request exceeded {deadline_s:.3f}s")
            with done:
                # bounded wait: a completing leg's callback wakes this
                # immediately (no lost wakeup — the re-check holds the
                # condition lock the callback must take to notify);
                # the 50 ms bound only paces the deadline backstop
                if not any(r.done for _, r in legs):
                    done.wait(0.05)
        raise last_err

    # -- health probes / quarantine --------------------------------------
    def quarantine(self, replica_id):
        """Remove a replica from organic traffic; it serves only
        probe_tick canaries until readmit_after consecutive successes
        re-admit it (breaker reset on re-admission)."""
        h = self.health(replica_id)
        if h is None:
            raise RuntimeError(
                "breaker gating disabled (breaker=False) — "
                "quarantine needs ReplicaHealth")
        h.quarantine()
        self._m_breaker.labels(replica=replica_id).set(
            _BREAKER_STATES["open"])
        return h

    def set_probe(self, name, features, deadline_s=1.0):
        """Register the canary request probe_tick sends for `name`."""
        with self._lock:
            self._probes[name] = (np.asarray(features),
                                  float(deadline_s))

    def probe_tick(self):
        """Send one canary per (quarantined replica, probed model it
        serves). Returns structured probe results; a replica whose
        consecutive-success streak reaches readmit_after is re-admitted
        (and its breaker reset). Call this from the operator loop the
        same way as autoscale_tick."""
        with self._lock:
            probes = dict(self._probes)
        results = []
        for rid, host in self._hosts():
            h = self.health(rid)
            if h is None or not h.quarantined:
                continue
            for name, (feats, deadline_s) in probes.items():
                if host.queued_work(name) is None:
                    continue
                try:
                    host.submit(name, feats, deadline_s=deadline_s)
                    ok = True
                except Exception:  # fault-ok[FLT01]: the outcome IS the classification — it feeds dl4j_fleet_probes_total{outcome=fail} and the readmission streak just below; a failing canary is the signal probe_tick measures
                    ok = False
                readmitted = h.note_probe(ok)
                self._m_probes.labels(
                    model=name, outcome="ok" if ok else "fail").inc()
                if readmitted:
                    self._m_breaker.labels(replica=rid).set(
                        _BREAKER_STATES["closed"])
                results.append({"replica": rid, "model": name,
                                "ok": ok, "readmitted": readmitted})
        return results

    def _now(self):
        return self._clock() if self._clock is not None \
            else self._registry.clock()

    # -- SLOs + autoscale decisions --------------------------------------
    def set_slo(self, name, **kw):
        """Declare the SLO for one model (ModelSLO kwargs)."""
        slo = ModelSLO(**kw)
        with self._lock:
            self._slos[name] = slo
        return slo

    def slos(self):
        with self._lock:
            return {n: s.as_dict() for n, s in self._slos.items()}

    def on_scale(self, callback):
        """Register a scale-decision callback:
        ``callback(decision_dict)``. The fleet layer only DECIDES —
        spawning/retiring replica processes is the operator's
        (orchestrator's) actuation, wired through this surface."""
        with self._lock:
            self._scale_cbs.append(callback)
        return callback

    def autoscale_tick(self):
        """Evaluate every SLO'd model against the live fleet state and
        emit scale decisions. Returns the decision list; each decision
        was also passed to every on_scale callback.

        Votes: mean per-replica queued work > queue_high -> up;
        measured fleet p99 above the SLO target -> up; queued work <
        queue_low -> down. The desired count is clamped to
        [min_replicas, max_replicas]; "hold" decisions are returned but
        NOT dispatched to callbacks (callbacks see actionable deltas
        only)."""
        with self._lock:
            slos = dict(self._slos)
            cbs = list(self._scale_cbs)
        decisions = []
        for name, slo in slos.items():
            loads = []
            for _, host in self._hosts():
                load = self._queued_work(host, name)
                if load is not None:
                    loads.append(load)
            if not loads:
                continue
            n = len(loads)
            mean_load = sum(loads) / n
            child = self._m_latency.labels_get(model=name)
            p99_ms = None
            if child is not None:
                p99 = child.percentile(99)
                p99_ms = None if p99 is None else p99 * 1000.0
            reasons = []
            if mean_load > slo.queue_high:
                reasons.append(
                    f"mean queued work {mean_load:.1f} > "
                    f"queue_high {slo.queue_high:g}")
            if slo.p99_ms is not None and p99_ms is not None \
                    and p99_ms > slo.p99_ms:
                reasons.append(
                    f"p99 {p99_ms:.1f}ms > slo {slo.p99_ms:g}ms")
            if reasons:
                desired = n + 1
            elif mean_load < slo.queue_low:
                desired = n - 1
                reasons.append(
                    f"mean queued work {mean_load:.1f} < "
                    f"queue_low {slo.queue_low:g}")
            else:
                desired = n
            # the replica bounds outrank the votes — and when a clamp
            # changes the direction (n already past a bound), the bound
            # must be the recorded justification, not the vote
            bounded = max(slo.min_replicas,
                          min(desired, slo.max_replicas))
            if bounded != desired:
                clamp = (f"replica bound: desired {desired} clamped "
                         f"to {bounded} (min {slo.min_replicas:g}, "
                         f"max {slo.max_replicas:g})")
                if (bounded > n) != (desired > n) or bounded == n:
                    reasons = [clamp]
                else:
                    reasons.append(clamp)
                desired = bounded
            decision = {
                "model": name,
                "replicas": n,
                "desired_replicas": desired,
                "action": ("scale_up" if desired > n else
                           "scale_down" if desired < n else "hold"),
                "mean_queued_work": round(mean_load, 2),
                "p99_ms": None if p99_ms is None else round(p99_ms, 2),
                "reasons": reasons,
                "slo": slo.as_dict(),
            }
            decisions.append(decision)
            if decision["action"] != "hold":
                self._registry.event("fleet.scale_decision", "serving",
                                     **{k: v for k, v in decision.items()
                                        if k not in ("slo", "reasons")})
                for cb in cbs:
                    cb(decision)
        return decisions

    # -- observability / lifecycle ---------------------------------------
    def metrics_snapshot(self):
        """The fleet view: per-replica queue depth + slot occupancy,
        per-model fleet aggregates, the SLO book, and the process
        registry — additive over the per-host snapshot schema
        (docs/OBSERVABILITY.md)."""
        per_replica = {}
        fleet_models = {}
        for rid, host in self._hosts():
            snap = host.metrics_snapshot()
            replica_depth = 0
            for name, view in snap["models"].items():
                replica_depth += view["queue_depth"]
                agg = fleet_models.setdefault(
                    name, {"kind": "oneshot", "queue_depth": 0,
                           "replicas": 0})
                agg["queue_depth"] += view["queue_depth"]
                agg["replicas"] += 1
            for name, view in snap.get("sequences", {}).items():
                replica_depth += view["queue_depth"]
                agg = fleet_models.setdefault(
                    name, {"kind": "sequence", "queue_depth": 0,
                           "active_slots": 0, "replicas": 0})
                agg["queue_depth"] += view["queue_depth"]
                agg["active_slots"] = agg.get("active_slots", 0) \
                    + view["active_slots"]
                agg["replicas"] += 1
            per_replica[rid] = {
                "queue_depth": replica_depth,
                "models": snap["models"],
                "sequences": snap.get("sequences", {}),
            }
        return {"registry": telemetry.get_registry().snapshot(),
                "replicas": per_replica,
                "models": fleet_models,
                "slos": self.slos()}

    def close(self, drain=True):
        with self._lock:
            hosts = list(self._replicas.values())
            self._replicas.clear()
            self._m_replicas.set(0)
        for host in hosts:
            host.close(drain=drain)


# ----------------------------------------------------------------------
# fleet load scenarios (the bench `serving_fleet` vocabulary)
# ----------------------------------------------------------------------

def scenario_diurnal_ramp(submit, make_request, *, base_rate,
                          peak_rate, phases=5, requests_per_phase=64,
                          seed=0, max_clients=16):
    """Open-loop rate swept low -> peak -> low (a day curve compressed
    into `phases` phases). Records per-phase rps/p50/p99 + error
    classes and the whole-run aggregate."""
    from deeplearning4j_tpu.serving import loadgen

    if phases < 3:
        # 2 phases would put both samples at the triangle's feet —
        # base_rate twice, peak_rate never driven
        raise ValueError(f"need >= 3 phases for a ramp, got {phases}")
    # triangle curve: up to the peak and back down
    half = (phases - 1) / 2.0
    rates = [base_rate + (peak_rate - base_rate)
             * (1.0 - abs(i - half) / half) for i in range(phases)]
    recs = []
    for i, rate in enumerate(rates):
        recs.append(dict(loadgen.run_open_loop(
            submit, make_request, rate=rate,
            n_requests=requests_per_phase, seed=seed + i,
            max_clients=max_clients), phase=i,
            rate_rps=round(rate, 1)))
    total = sum(r["completed"] for r in recs)
    dur = sum(r["duration_s"] for r in recs)
    errors = {}
    for r in recs:
        for k, v in r["errors"].items():
            errors[k] = errors.get(k, 0) + v
    p99s = [r["p99_ms"] for r in recs if r.get("p99_ms") is not None]
    return {"scenario": "diurnal_ramp", "phases": recs,
            "completed": total, "errors": errors,
            "requests_per_sec": round(total / dur, 2) if dur else None,
            "p99_ms": max(p99s) if p99s else None}


def scenario_hot_model_skew(submit_for, make_request, *, models,
                            hot_fraction=0.8, rate=200.0,
                            n_requests=128, seed=0, max_clients=16):
    """One model takes `hot_fraction` of the traffic, the rest split
    the remainder — the skew that makes per-model least-loaded routing
    earn its keep. submit_for(name) -> submit callable. Records
    per-model rps/p99 + error classes."""
    from deeplearning4j_tpu.serving import loadgen

    models = list(models)
    if len(models) < 2:
        raise ValueError("hot-model skew needs >= 2 models")
    hot, rest = models[0], models[1:]
    rng = np.random.RandomState(seed)
    picks = [hot if rng.rand() < hot_fraction
             else rest[rng.randint(len(rest))]
             for _ in range(n_requests)]

    # route by request index: the loadgen drives (name, features)
    # tuples so the per-model split is part of the seeded schedule
    rec_by_model = {m: {"lat": [], "errors": {}} for m in models}
    lock = threading.Lock()

    def tagged_make(i):
        return (picks[i], make_request(i))

    def tagged_submit(req):
        name, x = req
        import time as _t

        t0 = _t.monotonic()
        try:
            submit_for(name)(x)
            with lock:
                rec_by_model[name]["lat"].append(_t.monotonic() - t0)
        except Exception as e:
            with lock:
                errs = rec_by_model[name]["errors"]
                errs[type(e).__name__] = errs.get(type(e).__name__,
                                                  0) + 1
            raise

    rec = loadgen.run_open_loop(tagged_submit, tagged_make, rate=rate,
                                n_requests=n_requests, seed=seed,
                                max_clients=max_clients)
    per_model = {}
    for m in models:
        lat = sorted(rec_by_model[m]["lat"])
        per_model[m] = {
            "requests": len(lat)
            + sum(rec_by_model[m]["errors"].values()),
            "errors": rec_by_model[m]["errors"],
            "p99_ms": None if not lat else round(
                loadgen.percentile(lat, 99) * 1000.0, 3),
        }
    return {"scenario": "hot_model_skew", "hot_model": hot,
            "hot_fraction": hot_fraction, "per_model": per_model,
            **{k: rec[k] for k in ("requests", "completed", "errors",
                                   "requests_per_sec", "p50_ms",
                                   "p99_ms") if k in rec}}


def scenario_slow_client_storm(submit, make_request, *, n_clients=24,
                               requests_per_client=8,
                               think_time_s=0.01, seed=0,
                               timeout_s=120.0, hedged_submit=None,
                               hedge_stats=None):
    """A storm of CLOSED-LOOP clients that block on each response and
    think before the next request — the slow-client population an
    open loop cannot model (loadgen.run_closed_loop). Records
    rps/p50/p99 + error classes.

    hedged_submit: optional second submit callable with tail-latency
    hedging armed (FleetRouter.set_hedge) — the SAME seeded storm
    reruns through it and the record gains a ``hedged`` sub-record
    with the hedge fire-rate and the p99 delta (negative = hedging
    won; docs/SERVING.md "Failure domains" explains when it loses).
    hedge_stats: zero-arg callable returning the cumulative
    hedges-fired count (e.g. the dl4j_fleet_hedges_total child's
    ``.value``) so the scenario can report the fire-rate."""
    from deeplearning4j_tpu.serving import loadgen

    rec = loadgen.run_closed_loop(
        submit, make_request, n_clients=n_clients,
        requests_per_client=requests_per_client,
        think_time_s=think_time_s, seed=seed, timeout_s=timeout_s)
    out = dict(rec, scenario="slow_client_storm")
    if hedged_submit is not None:
        fired0 = hedge_stats() if hedge_stats is not None else None
        hrec = loadgen.run_closed_loop(
            hedged_submit, make_request, n_clients=n_clients,
            requests_per_client=requests_per_client,
            think_time_s=think_time_s, seed=seed, timeout_s=timeout_s)
        hedged = {k: hrec[k] for k in ("requests", "completed",
                                       "errors", "requests_per_sec",
                                       "p50_ms", "p99_ms")
                  if k in hrec}
        if fired0 is not None:
            fired = hedge_stats() - fired0
            hedged["hedges_fired"] = int(fired)
            hedged["hedge_rate"] = round(
                fired / max(1, hrec.get("requests", 0)), 4)
        if "p99_ms" in hrec and "p99_ms" in rec:
            hedged["p99_delta_ms"] = round(
                hrec["p99_ms"] - rec["p99_ms"], 3)
        out["hedged"] = hedged
    return out

"""Iteration-level continuous batching for stateful (recurrent) models.

The one-shot tier (serving/queue.py) coalesces REQUESTS; sequence
workloads need the batch re-formed every DECODE STEP — the Orca
iteration-level scheduling insight, applied to the stack's stateful
``rnnTimeStep`` path. A run-to-completion (gang) batch pads every
sequence to the longest in its batch and holds finished slots hostage
until the stragglers drain; re-batching per step lets an early-exit
slot be refilled from the queue MID-SEQUENCE, so the device always
steps a full-as-possible batch of live tokens.

Mechanics (``SequenceScheduler``):

* a **slot table** of active sequences, each carrying its own per-layer
  hidden/cell state as host arrays. Every iteration the scheduler
  GATHERS the live carries into one ``[S, H]`` batch per layer/key
  (zero rows for empty slots), steps the model ONCE via the functional
  ``MultiLayerNetwork.rnnStepBatched`` (nn/multilayer.py), and
  SCATTERS the outputs + new carries back per slot. Rows are
  independent, so one executable per **slot bucket** serves any
  occupancy — padding can never perturb a live slot, and per-slot
  output is bitwise what serial ``rnnTimeStep`` produces
  (tests/test_sequence_serving.py gates it). Known limit, the PR 8
  precedent: when a sequence's steps SPAN different slot buckets, the
  bucket change can alter XLA's dot lowering and round 1 ulp apart —
  within a fixed bucket parity is structural and bitwise; pin
  ``slot_buckets`` to one size where bitwise reproducibility across
  occupancy changes matters more than padded-row compute.
* slot counts are **bucketed** (``slot_buckets``) through the AOT
  executable cache exactly like the one-shot tier's batch buckets: the
  compile budget is ``len(slot_buckets)``, ``warm()`` precompiles every
  bucket, and a warmed scheduler serves any mix of sequence lengths
  with ZERO steady-state compiles (CompileWatch-gated).
* admission: ``submit`` appends to a bounded FIFO (``QueueFullError``
  past ``queue_limit`` — backpressure, never a hang); free slots are
  refilled from the queue at every iteration boundary
  (``admission="step"``). ``admission="gang"`` is the deliberate
  run-to-completion baseline — refill only when the table drains — so
  the iteration-level win is measurable as an A/B on the SAME code
  path (bench serving_fleet, the >=2x tier-1 gate).
* per-request **deadlines are honored per step**: an expired sequence —
  queued OR mid-flight — is failed at the next iteration boundary and
  its slot refilled; the caller side of the contract is
  ``SequenceRequest.wait`` (the release rules are stated once, on
  ``queue.InferenceRequest.wait``).
* the clock is injectable (``queue.ManualClock``) and the scheduler can
  be driven synchronously via ``poll()``/``drain()`` with
  ``start_thread=False`` — the same zero-sleep deterministic test seam
  the MicroBatcher exposes.

Generation mode: a request may ask for ``extra_steps`` beyond its
prompt; the next input row is then ``feedback(last_output_row)`` — the
host-side closed loop of a char-rnn sampler (greedy argmax one-hot by
default when the scheduler's ``feedback`` is set).

See docs/SERVING.md "Sequence serving + the fleet".
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np

from deeplearning4j_tpu.runtime import telemetry
from deeplearning4j_tpu.runtime.chaos import \
    fault_point as _chaos_fault_point
from deeplearning4j_tpu.runtime.chaos import register_seam
from deeplearning4j_tpu.serving.kvcache import (
    KVCacheFullError, PagedKVCache,
)
from deeplearning4j_tpu.serving.queue import (
    DeadlineExceededError, QueueFullError, ServingClosedError,
    occupancy_summary_from,
)

__all__ = ["SequenceRequest", "SequenceScheduler", "GenerationRequest",
           "PagedSequenceScheduler", "greedy_onehot_feedback"]

#: unique default metric label for anonymous schedulers
_SCHED_SEQ = itertools.count(1)

#: default slot-count buckets: one executable per bucket, ever
DEFAULT_SLOT_BUCKETS = (1, 2, 4, 8)

#: the stats keys the dict view carries
_STAT_KEYS = ("sequences", "completed", "dispatches", "slot_steps",
              "expired", "rejected", "errors", "refills")

#: chunked-prefill chaos seam (PagedSequenceScheduler): fires before
#: each prompt chunk dispatch, so a ChaosPlan can fail/wedge/corrupt a
#: prefill exactly where production would (runtime/chaos.py)
PREFILL_SEAM = register_seam("sequence.prefill")

#: the registry families both scheduler classes record into (and
#: release per-instance series from at close())
_SEQ_METRIC_FAMILIES = (
    "dl4j_seq_sequences_total", "dl4j_seq_completed_total",
    "dl4j_seq_dispatches_total", "dl4j_seq_slot_steps_total",
    "dl4j_seq_expired_total", "dl4j_seq_rejected_total",
    "dl4j_seq_errors_total", "dl4j_seq_refills_total",
    "dl4j_seq_queue_depth", "dl4j_seq_active_slots",
    "dl4j_seq_queue_wait_seconds", "dl4j_seq_slot_occupancy",
)


def _seq_metrics(reg, name):
    """The dl4j_seq_* instrument set, labelled for one scheduler
    instance — shared by the carry-slot and KV-slot schedulers so both
    report through the same families (docs/OBSERVABILITY.md)."""
    lab = {"model": name}
    return {
        "sequences": reg.counter(
            "dl4j_seq_sequences_total",
            "sequences accepted into the sequence queue",
            labels=("model",)).labels(**lab),
        "completed": reg.counter(
            "dl4j_seq_completed_total",
            "sequences completed (all steps served)",
            labels=("model",)).labels(**lab),
        "dispatches": reg.counter(
            "dl4j_seq_dispatches_total",
            "slot-batched decode-step dispatches",
            labels=("model",)).labels(**lab),
        "slot_steps": reg.counter(
            "dl4j_seq_slot_steps_total",
            "live slot-steps served (occupancy x dispatches)",
            labels=("model",)).labels(**lab),
        "expired": reg.counter(
            "dl4j_seq_expired_total",
            "sequences failed by a per-step deadline expiry (504)",
            labels=("model",)).labels(**lab),
        "rejected": reg.counter(
            "dl4j_seq_rejected_total",
            "sequences rejected on a full queue (429)",
            labels=("model",)).labels(**lab),
        "errors": reg.counter(
            "dl4j_seq_errors_total",
            "sequences failed by a dispatch error",
            labels=("model",)).labels(**lab),
        "refills": reg.counter(
            "dl4j_seq_refills_total",
            "mid-sequence slot refills (admissions while other "
            "slots were mid-flight)",
            labels=("model",)).labels(**lab),
        "depth": reg.gauge(
            "dl4j_seq_queue_depth",
            "sequences waiting for a slot",
            labels=("model",)).labels(**lab),
        "active": reg.gauge(
            "dl4j_seq_active_slots",
            "slots occupied by live sequences",
            labels=("model",)).labels(**lab),
        "wait": reg.histogram(
            "dl4j_seq_queue_wait_seconds",
            "enqueue-to-first-step wait per sequence",
            labels=("model",)).labels(**lab),
        "occupancy": reg.histogram(
            "dl4j_seq_slot_occupancy",
            "live-slots/bucket fill fraction per decode step",
            labels=("model",),
            buckets=(0.25, 0.5, 0.75, 1.0)).labels(**lab),
    }


def greedy_onehot_feedback(vocab):
    """feedback closure for one-hot token models: argmax the output
    row, feed the matching one-hot back as the next input (greedy
    char-rnn sampling — deterministic, so generation tests stay
    bitwise)."""
    eye = np.eye(int(vocab), dtype=np.float32)

    def feedback(out_row):
        return eye[int(np.argmax(out_row))]

    return feedback


class SequenceRequest:
    """One sequence: prompt features [T, F] consumed one timestep per
    scheduler iteration, plus optional generation steps.

    total steps = T + extra_steps; step t consumes ``features[t]`` for
    t < T and ``feedback(outputs[t-1])`` after. The result is the
    stacked per-step output [total, O]. ``wait`` follows the serving
    tier's one release contract — see ``queue.InferenceRequest.wait``
    (dispatch failure, per-step deadline expiry, or caller-timeout
    release while the scheduler is mid-step)."""

    __slots__ = ("features", "steps", "extra_steps", "feedback",
                 "enqueued_at", "deadline", "started_at", "steps_done",
                 "outputs", "carry", "result", "error", "_event")

    def __init__(self, features, enqueued_at, deadline=None,
                 extra_steps=0, feedback=None):
        self.features = features            # [T, F] float32
        self.steps = int(features.shape[0]) + int(extra_steps)
        self.extra_steps = int(extra_steps)
        self.feedback = feedback
        self.enqueued_at = float(enqueued_at)
        self.deadline = None if deadline is None else float(deadline)
        self.started_at = None              # first-step admission time
        self.steps_done = 0
        self.outputs = []                   # per-step [O] rows
        self.carry = None                   # per-layer {key: [H]} rows
        self.result = None
        self.error = None
        self._event = threading.Event()

    @property
    def done(self):
        return self._event.is_set()

    def next_input(self):
        """The feature row this sequence consumes at its next step."""
        t = self.steps_done
        if t < self.features.shape[0]:
            return self.features[t]
        if self.feedback is None:
            raise RuntimeError(
                "generation step with no feedback fn (extra_steps > 0 "
                "needs a request- or scheduler-level feedback)")
        return np.asarray(self.feedback(self.outputs[-1]),
                          np.float32)

    def finish(self, result):
        self.result = result
        self._event.set()

    def fail(self, exc):
        self.error = exc
        self._event.set()

    def wait(self, timeout=None):
        """Block for the stacked [steps, O] output. Release rules are
        the serving tier's single wait contract —
        ``queue.InferenceRequest.wait``."""
        if not self._event.wait(timeout):
            raise DeadlineExceededError(f"no result within {timeout:.3f}s")
        if self.error is not None:
            raise self.error
        return self.result


class SequenceScheduler:
    """Iteration-level slot scheduler over one recurrent model (module
    docstring).

    model:        an initialized MultiLayerNetwork with >=1 recurrent
                  layer (validated eagerly via ``rnnCarrySpec``).
    slot_buckets: slot-count executable buckets; max(slot_buckets) is
                  the table capacity.
    queue_limit:  bound on WAITING sequences (QueueFullError past it).
    admission:    "step" (refill free slots every iteration — the
                  iteration-level discipline) or "gang" (refill only
                  when the table is empty — the run-to-completion
                  baseline the >=2x gate measures against).
    feedback:     scheduler-level generation feedback
                  (out_row [O]) -> next input row [F]; a request's own
                  feedback overrides it.
    clock/start_thread/name: the MicroBatcher test seam — inject
                  ManualClock and drive ``poll()``/``drain()`` with no
                  thread for deterministic tests.
    """

    def __init__(self, model, *, slot_buckets=None, queue_limit=64,
                 admission="step", feedback=None, clock=None,
                 start_thread=True, name=None):
        if admission not in ("step", "gang"):
            raise ValueError(
                f"admission must be 'step' (iteration-level) or 'gang' "
                f"(run-to-completion baseline), got {admission!r}")
        if int(queue_limit) < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self.model = model
        self._spec = model.rnnCarrySpec()   # validates the net, eagerly
        # carries cross the jit boundary UNCAST (unlike x, which
        # _entry casts in-graph): host-side slot state must live in
        # the model's compute dtype or per-step outputs diverge from
        # serial rnnTimeStep on non-f32 policies
        self._carry_dtype = np.dtype(model._compute_dtype)
        buckets = slot_buckets or DEFAULT_SLOT_BUCKETS
        self.slot_buckets = tuple(sorted(int(b) for b in buckets))
        if self.slot_buckets[0] < 1:
            raise ValueError(f"slot buckets must be >= 1, got {buckets}")
        self.max_slots = self.slot_buckets[-1]
        self.queue_limit = int(queue_limit)
        self.admission = admission
        self.feedback = feedback
        self.clock = clock if clock is not None else time.monotonic
        it = model.conf.inputType
        #: per-step feature width the submit contract validates
        self.feature_size = int(it.size)
        self._cond = threading.Condition()
        # one iteration at a time: the background loop and a concurrent
        # close(drain=True)/poll() caller must never both snapshot the
        # slot table and double-step a sequence
        self._step_lock = threading.Lock()
        self._pending = deque()
        self._active = []                   # the slot table
        self._staging = {}                  # S -> reused gather buffers
        #: host bytes served from the staging pool instead of fresh
        #: np.zeros (the bench decode leg's alloc-reduction record)
        self.staging_reuse_bytes = 0
        self._closed = False
        self.name = str(name) if name else f"seq{next(_SCHED_SEQ)}"
        #: (active_slots, bucket) per dispatch — the occupancy record
        self.occupancy = []
        reg = telemetry.get_registry()
        self._registry = reg
        self._m = _seq_metrics(reg, self.name)
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    # -- submit ---------------------------------------------------------
    def submit(self, features, deadline=None, extra_steps=0,
               feedback=None, wait=True, timeout=None):
        """Enqueue one sequence of per-step features [T, F] (T >= 1).

        deadline: absolute time on this scheduler's clock; checked at
        every STEP boundary, queued or mid-flight. extra_steps: closed-
        loop generation steps past the prompt (needs a feedback fn).
        wait=True blocks for the stacked [T+extra, O] result; False
        returns the SequenceRequest.
        """
        features = np.asarray(features, np.float32)
        if features.ndim != 2 or features.shape[0] < 1:
            raise ValueError(
                f"features must be [steps, {self.feature_size}] with "
                f"steps >= 1, got shape {features.shape}")
        if features.shape[1] != self.feature_size:
            raise ValueError(
                f"per-step feature width {features.shape[1]} does not "
                f"match the model's {self.feature_size}")
        fb = feedback if feedback is not None else self.feedback
        if int(extra_steps) > 0 and fb is None:
            raise ValueError(
                "extra_steps > 0 needs a feedback fn (request- or "
                "scheduler-level) to close the generation loop")
        with self._cond:
            if self._closed:
                raise ServingClosedError("sequence scheduler is closed")
            if len(self._pending) >= self.queue_limit:
                self._m["rejected"].inc()
                raise QueueFullError(
                    f"sequence queue full ({len(self._pending)} waiting, "
                    f"queueLimit={self.queue_limit})")
            req = SequenceRequest(features, self.clock(), deadline,
                                  extra_steps=extra_steps, feedback=fb)
            self._pending.append(req)
            self._m["sequences"].inc()
            self._m["depth"].set(len(self._pending))
            self._cond.notify()
        if wait:
            return req.wait(timeout)
        return req

    # -- scheduling core (lock held) ------------------------------------
    def _expire_locked(self, now):
        """Fail every sequence — queued or MID-FLIGHT — whose deadline
        has passed: the per-step deadline contract. A mid-flight expiry
        frees its slot this same iteration."""
        keep = deque()
        for req in self._pending:
            if req.deadline is not None and now >= req.deadline:
                self._m["expired"].inc()
                req.fail(DeadlineExceededError(
                    f"deadline passed {now - req.deadline:.3f}s before "
                    "a slot was granted"))
            else:
                keep.append(req)
        self._pending = keep
        live = []
        for req in self._active:
            if req.deadline is not None and now >= req.deadline:
                self._m["expired"].inc()
                req.fail(DeadlineExceededError(
                    f"deadline passed at step {req.steps_done}/"
                    f"{req.steps} — slot released mid-sequence"))
            else:
                live.append(req)
        self._active = live
        self._m["depth"].set(len(self._pending))
        self._m["active"].set(len(self._active))

    def _refill_locked(self, now):
        """Admit queued sequences into free slots. admission="step"
        refills at every iteration boundary (slots freed by early exit
        or expiry are re-used MID-SEQUENCE); "gang" only admits into an
        empty table — the run-to-completion baseline."""
        if self.admission == "gang" and self._active:
            return
        midrun = any(r.steps_done > 0 for r in self._active)
        while self._pending and len(self._active) < self.max_slots:
            req = self._pending.popleft()
            req.started_at = now
            req.carry = [{k: np.zeros((self._carry_width(i),),
                                      self._carry_dtype)
                          for k in keys}
                         for i, keys in enumerate(self._spec)]
            self._active.append(req)
            self._m["wait"].observe(now - req.enqueued_at)
            if midrun:
                self._m["refills"].inc()
        self._m["depth"].set(len(self._pending))
        self._m["active"].set(len(self._active))

    def _carry_width(self, layer_idx):
        return int(getattr(self.model.layers[layer_idx], "nOut"))

    def bucket_for(self, n):
        """Smallest slot bucket >= n live slots (the executable that
        serves this iteration)."""
        for b in self.slot_buckets:
            if n <= b:
                return b
        return self.slot_buckets[-1]

    # -- one iteration (dispatch outside the lock) ----------------------
    def _staging_for(self, S):
        """Per-bucket gather/scatter staging buffers, allocated once
        and reused every iteration (the dispatch copies them to device
        via jnp.asarray, so host-side reuse can never alias a live
        step). Before this, _gather paid a fresh np.zeros per column
        per step — pure allocator churn the bench decode leg now
        counts as staging_reuse_bytes."""
        st = self._staging.get(S)
        if st is None:
            x = np.zeros((S, self.feature_size), np.float32)
            carries = [{k: np.zeros((S, self._carry_width(li)),
                                    self._carry_dtype) for k in keys}
                       for li, keys in enumerate(self._spec)]
            st = (x, carries)
            self._staging[S] = st
        else:
            self.staging_reuse_bytes += (
                st[0].nbytes
                + sum(c.nbytes for d in st[1] for c in d.values()))
        return st

    def _gather(self, batch, S, rows):
        """Stack the batch's validated next-input rows + carries into
        the fixed [S, ...] bucket signature (zero rows pad the empty
        slots). Buffers come from the per-bucket staging pool; rows
        past the live batch are re-zeroed so a previous iteration's
        occupancy can never leak into the padding."""
        n = len(rows)
        x, carries = self._staging_for(S)
        for i, row in enumerate(rows):
            x[i] = row
        x[n:] = 0.0
        for li, keys in enumerate(self._spec):
            d = carries[li]
            for k in keys:
                col = d[k]
                for i, req in enumerate(batch):
                    col[i] = req.carry[li][k]
                col[n:] = 0
        return x, carries

    def _step_once(self):
        """One scheduler iteration: expire -> refill -> gather ->
        dispatch ONE slot-batched decode step -> scatter. Returns the
        number of live slots stepped (0 = idle). Serialized by the
        step lock — concurrent drivers (background loop vs a draining
        close) take turns instead of double-stepping a sequence."""
        with self._step_lock:
            return self._iterate_locked()  # fault-ok[FLT04]: the step lock is the scheduler's own serialization contract — sequence.step firing under it IS the wedged-scheduler fault the harness injects, and waiters are released by deadline expiry (the wait contract), never by this lock

    def _iterate_locked(self):
        # *_locked: called with the STEP lock held (one driver at a
        # time); the condition lock is still taken around each shared-
        # state section below
        with self._cond:
            now = self.clock()
            self._expire_locked(now)
            self._refill_locked(now)
            batch = list(self._active)
        if not batch:
            return 0
        # pull next-input rows BEFORE the padded gather: a raising (or
        # wrong-width) feedback fails ITS request and frees the slot —
        # it must never kill the scheduler thread (the wait contract:
        # no path leaves a caller blocked on a dead dispatcher)
        rows, bad = [], []
        for req in batch:
            try:
                row = np.asarray(req.next_input(),
                                 dtype=np.float32).reshape(-1)
                if row.shape[0] != self.feature_size:
                    raise ValueError(
                        f"feedback row has width {row.shape[0]}, "
                        f"model feature size is {self.feature_size}")
                rows.append(row)
            except Exception as e:
                bad.append((req, e))
        if bad:
            failed = {r for r, _ in bad}
            with self._cond:
                self._m["errors"].inc(len(bad))
                for req, e in bad:
                    req.fail(e)
                self._active = [r for r in self._active
                                if r not in failed]
                self._m["active"].set(len(self._active))
            batch = [r for r in batch if r not in failed]
            if not batch:
                return len(bad)     # progress: drain must not stall
        S = self.bucket_for(len(batch))
        x, carries = self._gather(batch, S, rows)
        t0 = self.clock()
        self._m["dispatches"].inc()
        self._m["slot_steps"].inc(len(batch))
        self._m["occupancy"].observe(len(batch) / S)
        self.occupancy.append((len(batch), S))
        try:
            # chaos seam INSIDE the step-failure try: an injected raise
            # fails this slot batch the way an organic step error does
            # (runtime/chaos.py)
            x = _chaos_fault_point("sequence.step", x)
            out, new_carries = self.model.rnnStepBatched(x, carries)
            out = np.asarray(out)
            # ONE device->host pull per carry array per iteration; the
            # per-slot scatter below then slices host rows (a per-slot
            # np.asarray of a jax row would pay S separate transfers)
            new_carries = [{k: np.asarray(v) for k, v in d.items()}
                           for d in new_carries]
        except Exception as e:
            with self._cond:
                self._m["errors"].inc(len(batch))
                for req in batch:
                    req.fail(e)
                self._active = [r for r in self._active
                                if r not in batch]
                self._m["active"].set(len(self._active))
            return 0
        finally:
            self._registry.add_span(
                "sequence.step", "serving", t0, self.clock() - t0,
                model=self.name, slots=len(batch), bucket=S)
        # scatter: per-slot output row + refreshed carry rows
        finished = []
        with self._cond:
            for i, req in enumerate(batch):
                if req.done:        # expired/failed between gather+now
                    continue
                req.outputs.append(out[i])
                req.carry = [{k: new_carries[li][k][i] for k in keys}
                             for li, keys in enumerate(self._spec)]
                req.steps_done += 1
                if req.steps_done >= req.steps:
                    finished.append(req)
            if finished:
                self._active = [r for r in self._active
                                if r not in finished]
                self._m["completed"].inc(len(finished))
                self._m["active"].set(len(self._active))
        for req in finished:        # release waiters outside the lock
            req.finish(np.stack(req.outputs, axis=0))
        return len(batch)

    # -- drivers --------------------------------------------------------
    def poll(self):
        """One synchronous scheduler iteration (the thread-less test
        seam): expire, refill, step the slot batch once. Returns the
        number of live slots stepped — 0 means idle (nothing queued or
        active). Deterministic under ManualClock: no sleeps, no
        background thread."""
        return self._step_once()

    def drain(self):
        """Run iterations until the table AND queue are empty (ignores
        nothing — deadlines still expire per step on the clock)."""
        while self._step_once():
            pass
        return self

    def _loop(self):
        while True:
            with self._cond:
                if self._closed and not self._pending \
                        and not self._active:
                    return
                if not self._pending and not self._active:
                    self._cond.wait(0.05)
                    continue
            try:
                self._step_once()
            except Exception as e:
                # defensive: an unexpected scheduler bug must release
                # every waiter, never leave them blocked on a dead
                # thread; the loop stays up for new submits
                self._fail_all(e)

    def _fail_all(self, exc):
        """Fail every queued + active sequence with `exc` and clear
        the table (the scheduler-bug escape hatch)."""
        with self._cond:
            n = len(self._pending) + len(self._active)
            if n:
                self._m["errors"].inc(n)
            while self._pending:
                self._pending.popleft().fail(exc)
            for req in self._active:
                req.fail(exc)
            self._active = []
            self._m["depth"].set(0)
            self._m["active"].set(0)

    # -- introspection / lifecycle --------------------------------------
    @property
    def depth(self):
        """Sequences waiting for a slot."""
        with self._cond:
            return len(self._pending)

    @property
    def active_slots(self):
        with self._cond:
            return len(self._active)

    @property
    def stats(self):
        """Dict view over the registry counters (dl4j_seq_*)."""
        return {k: int(self._m[k].value) for k in _STAT_KEYS}

    def occupancy_summary(self):
        """Mean live-slots/bucket + quartile histogram over every
        decode step so far (the 'is the table sized right' signal —
        docs/SERVING.md)."""
        return occupancy_summary_from(self.occupancy, "mean_live_slots")

    def warm(self, cache=None):
        """Precompile the decode-step executable for EVERY slot bucket
        (hits are free) so a serving process steps its first sequence
        hot. Returns {bucket: {key, status, seconds}}. The warm
        signature mirrors the live dispatch EXACTLY (host-numpy
        carries, like _gather builds) — a mismatched container type
        would change the AOT signature and demote the first real step
        to a fresh compile."""
        import jax.numpy as jnp

        report = {}
        for S in self.slot_buckets:
            x = jnp.asarray(np.zeros((S, self.feature_size), np.float32))
            carries = [{k: np.zeros((S, self._carry_width(li)),
                                    self._carry_dtype) for k in keys}
                       for li, keys in enumerate(self._spec)]
            key, status, secs = self.model._jit_rnn_step.warm(
                self.model._params,
                self.model._strip_carries(self.model._states),
                carries, x, cache=cache)
            if status is not None:
                report[int(S)] = {"key": key, "status": status,
                                  "seconds": round(secs, 3)}
        return report

    def close(self, drain=True):
        """Stop accepting. drain=True serves everything already queued
        or mid-flight to completion; drain=False fails them with
        ServingClosedError."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._pending:
                    self._pending.popleft().fail(
                        ServingClosedError("scheduler closed before "
                                           "a slot was granted"))
                for req in self._active:
                    req.fail(ServingClosedError(
                        "scheduler closed mid-sequence"))
                self._active = []
                self._m["depth"].set(0)
                self._m["active"].set(0)
            self._cond.notify_all()
        if drain:
            self.drain()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # release this instance's registry series (MicroBatcher.close
        # precedent: per-instance series must not accumulate forever)
        reg = self._registry
        for metric in _SEQ_METRIC_FAMILIES:
            fam = reg.get(metric)
            if fam is not None:
                fam.remove(model=self.name)
        return self


class GenerationRequest:
    """One token-prompt generation request on the KV-slot path.

    The prompt is consumed in page-sized prefill chunks; generation
    then appends one token per decode iteration until ``max_new``
    tokens have been sampled. ``pages``/``block_row``/``seq_len`` are
    the slot's KV state (owned page ids, logical-block -> physical-page
    row, live KV rows). ``wait`` follows the serving tier's one release
    contract — see ``queue.InferenceRequest.wait``."""

    __slots__ = ("tokens", "max_new", "sampler", "rng", "stream_id",
                 "enqueued_at", "deadline", "started_at", "prefilled",
                 "seq_len", "pages", "block_row", "out_tokens",
                 "logits_rows", "logits", "result", "error", "_event")

    def __init__(self, tokens, enqueued_at, deadline=None, max_new=1,
                 sampler=None, rng=None, stream_id=0):
        self.tokens = tokens                # [T] int32 prompt
        self.max_new = int(max_new)
        self.sampler = sampler
        self.rng = rng
        self.stream_id = int(stream_id)
        self.enqueued_at = float(enqueued_at)
        self.deadline = None if deadline is None else float(deadline)
        self.started_at = None
        self.prefilled = 0                  # prompt tokens with KV live
        self.seq_len = 0                    # total KV rows live
        self.pages = []                     # owned page ids (in order)
        self.block_row = None               # [MP] int32
        self.out_tokens = []                # sampled tokens, in order
        self.logits_rows = []               # fp32 [V] per sampled token
        self.logits = None                  # stacked at finish
        self.result = None
        self.error = None
        self._event = threading.Event()

    @property
    def done(self):
        return self._event.is_set()

    def finish(self, result):
        self.logits = (np.stack(self.logits_rows, axis=0)
                       if self.logits_rows else None)
        self.result = result
        self._event.set()

    def fail(self, exc):
        self.error = exc
        self._event.set()

    def wait(self, timeout=None):
        """Block for the sampled token ids [max_new] (int64). Release
        rules are the serving tier's single wait contract —
        ``queue.InferenceRequest.wait``."""
        if not self._event.wait(timeout):
            raise DeadlineExceededError(f"no result within {timeout:.3f}s")
        if self.error is not None:
            raise self.error
        return self.result


class PagedSequenceScheduler:
    """Iteration-level KV-slot scheduler over one paged-attention LM
    (``nn.transformer.CausalTransformerLM`` or any ``kind ==
    "paged_lm"`` twin).

    The carry-slot scheduler above gathers/scatters h/c rows; here the
    per-slot state is KV in a bounded ``PagedKVCache`` instead, and
    every iteration interleaves at most ONE page-sized prefill chunk
    (bounded work — a long prompt can never stall the running batch)
    with one slot-batched decode step over every fully-prefilled slot.
    Admission, buckets, per-step deadlines, ManualClock/poll()/drain(),
    and the dl4j_seq_* metric families are the same discipline as
    ``SequenceScheduler``; pool exhaustion surfaces as the typed
    ``KVCacheFullError`` (429), never a hang. Prefix sharing
    (``prefix_sharing=True``) adopts a registered prompt's pages
    copy-on-write at admission.

    Sampling is host-side: ``sampler(logits_row, rng) -> token`` with a
    per-request ``stream_rng(sampler_seed, stream_id)`` stream, stream
    ids assigned in submit order — deterministic per (seed, stream), so
    the bitwise-vs-serial gate holds with temperature sampling too.
    """

    def __init__(self, model, *, num_pages, slot_buckets=None,
                 queue_limit=64, admission="step", sampler=None,
                 sampler_seed=0, prefix_sharing=True, clock=None,
                 start_thread=True, name=None):
        from deeplearning4j_tpu.serving.sampling import greedy_sampler

        if getattr(model, "kind", None) != "paged_lm":
            raise ValueError(
                "PagedSequenceScheduler needs a paged-LM step twin "
                f"(kind == 'paged_lm'), got {type(model).__name__}")
        if admission not in ("step", "gang"):
            raise ValueError(
                f"admission must be 'step' (iteration-level) or 'gang' "
                f"(run-to-completion baseline), got {admission!r}")
        if int(queue_limit) < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self.model = model
        self.vocab = int(model.vocab)
        buckets = slot_buckets or DEFAULT_SLOT_BUCKETS
        self.slot_buckets = tuple(sorted(int(b) for b in buckets))
        if self.slot_buckets[0] < 1:
            raise ValueError(f"slot buckets must be >= 1, got {buckets}")
        self.max_slots = self.slot_buckets[-1]
        self.queue_limit = int(queue_limit)
        self.admission = admission
        self.sampler = sampler if sampler is not None else greedy_sampler()
        self.sampler_seed = int(sampler_seed)
        self.prefix_sharing = bool(prefix_sharing)
        self.clock = clock if clock is not None else time.monotonic
        self.name = str(name) if name else f"seq{next(_SCHED_SEQ)}"
        self.cache = PagedKVCache(
            n_layers=model.n_layers, n_heads=model.n_heads,
            head_dim=model.head_dim, page_size=model.page_size,
            num_pages=num_pages, dtype=model._compute_dtype,
            model=self.name)
        self._mp = int(model.max_pages_per_slot)
        self._cond = threading.Condition()
        self._step_lock = threading.Lock()
        self._pending = deque()
        self._active = []                   # the KV-slot table
        self._staging = {}                  # S -> reused decode buffers
        #: host bytes served from the staging pool instead of fresh
        #: np.zeros (the bench decode leg's alloc-reduction record)
        self.staging_reuse_bytes = 0
        self._stream_ids = itertools.count(0)
        self._closed = False
        #: (live_decode_slots, bucket) per decode dispatch
        self.occupancy = []
        #: prompt chunks prefilled (the interleave record)
        self.prefill_chunks = 0
        reg = telemetry.get_registry()
        self._registry = reg
        self._m = _seq_metrics(reg, self.name)
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    # -- submit ---------------------------------------------------------
    def submit(self, tokens, deadline=None, max_new_tokens=1,
               sampler=None, wait=True, timeout=None):
        """Enqueue one token prompt [T] (T >= 1, ids in [0, vocab)).

        max_new_tokens >= 1 tokens are generated (the first is sampled
        from the prompt's final logits, so KV grows by T + max_new - 1
        rows, bounded by the model's max_context). deadline: absolute
        time on this scheduler's clock, checked per step. wait=True
        blocks for the sampled token ids; False returns the
        GenerationRequest. A prompt that could NEVER fit the pool is
        rejected up front with KVCacheFullError (429)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.shape[0] < 1:
            raise ValueError("prompt must have >= 1 token")
        if np.any(tokens < 0) or np.any(tokens >= self.vocab):
            raise ValueError(
                f"prompt token ids must be in [0, {self.vocab})")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        total = tokens.shape[0] + max_new - 1
        if total > self.model.max_context:
            raise ValueError(
                f"prompt + generation needs {total} KV rows, model "
                f"max_context is {self.model.max_context}")
        if self.cache.pages_for(total) > self.cache.capacity:
            raise KVCacheFullError(
                f"sequence needs {self.cache.pages_for(total)} pages, "
                f"pool capacity is {self.cache.capacity} — unservable "
                f"at any load")
        with self._cond:
            if self._closed:
                raise ServingClosedError("sequence scheduler is closed")
            if len(self._pending) >= self.queue_limit:
                self._m["rejected"].inc()
                raise QueueFullError(
                    f"sequence queue full ({len(self._pending)} waiting, "
                    f"queueLimit={self.queue_limit})")
            sid = next(self._stream_ids)
            from deeplearning4j_tpu.serving.sampling import stream_rng
            req = GenerationRequest(
                tokens, self.clock(), deadline, max_new=max_new,
                sampler=sampler if sampler is not None else self.sampler,
                rng=stream_rng(self.sampler_seed, sid), stream_id=sid)
            self._pending.append(req)
            self._m["sequences"].inc()
            self._m["depth"].set(len(self._pending))
            self._cond.notify()
        if wait:
            return req.wait(timeout)
        return req

    # -- scheduling core ------------------------------------------------
    def _release_req(self, req):
        """Return a request's pages to the pool (slot teardown)."""
        if req.pages:
            self.cache.release(req.pages)
            req.pages = []

    def _expire_locked(self, now):
        keep = deque()
        for req in self._pending:
            if req.deadline is not None and now >= req.deadline:
                self._m["expired"].inc()
                req.fail(DeadlineExceededError(
                    f"deadline passed {now - req.deadline:.3f}s before "
                    "a slot was granted"))
            else:
                keep.append(req)
        self._pending = keep
        live = []
        for req in self._active:
            if req.deadline is not None and now >= req.deadline:
                self._m["expired"].inc()
                self._release_req(req)
                req.fail(DeadlineExceededError(
                    f"deadline passed at {len(req.out_tokens)}/"
                    f"{req.max_new} tokens — slot released "
                    "mid-generation"))
            else:
                live.append(req)
        self._active = live
        self._m["depth"].set(len(self._pending))
        self._m["active"].set(len(self._active))

    def _refill_locked(self, now):
        """Admit queued prompts into free KV slots; prefix sharing
        adopts registered pages copy-on-write here. An exact-prompt
        adoption may complete the prompt outright — its first token is
        sampled from the registered logits (returned for the caller to
        process OUTSIDE this lock)."""
        adopted_done = []
        if self.admission == "gang" and self._active:
            return adopted_done
        midrun = any(r.seq_len > 0 for r in self._active)
        while self._pending and len(self._active) < self.max_slots:
            req = self._pending.popleft()
            req.started_at = now
            req.block_row = np.zeros((self._mp,), np.int32)
            logits = None
            if self.prefix_sharing:
                pages, n_shared, logits = self.cache.match_prefix(
                    req.tokens)
                if pages:
                    req.pages = list(pages)
                    req.block_row[:len(pages)] = pages
                    req.prefilled = req.seq_len = int(n_shared)
            self._active.append(req)
            self._m["wait"].observe(now - req.enqueued_at)
            if midrun:
                self._m["refills"].inc()
            if logits is not None:
                adopted_done.append((req, logits))
        self._m["depth"].set(len(self._pending))
        self._m["active"].set(len(self._active))
        return adopted_done

    def bucket_for(self, n):
        """Smallest slot bucket >= n live slots."""
        for b in self.slot_buckets:
            if n <= b:
                return b
        return self.slot_buckets[-1]

    def _fail_req(self, req, exc):
        """Fail one mid-flight request and free its slot + pages."""
        self._release_req(req)
        with self._cond:
            self._m["errors"].inc()
            req.fail(exc)
            self._active = [r for r in self._active if r is not req]
            self._m["active"].set(len(self._active))

    def _complete_prompt(self, req, last_logits):
        """The prompt is fully in KV: sample the first generated token
        from its final-position logits. Returns True if that already
        finishes the request (max_new == 1)."""
        row = np.asarray(last_logits, np.float32)
        req.logits_rows.append(row)
        req.out_tokens.append(int(req.sampler(row, req.rng)))
        if len(req.out_tokens) >= req.max_new:
            self._finish_req(req)
            return True
        return False

    def _finish_req(self, req):
        self._release_req(req)
        with self._cond:
            self._active = [r for r in self._active if r is not req]
            self._m["completed"].inc()
            self._m["active"].set(len(self._active))
        req.finish(np.asarray(req.out_tokens, np.int64))

    def _prefill_one(self, req):
        """Dispatch ONE page-sized prompt chunk for one slot: allocate
        the chunk's page, append its K/V, attend causally over the
        table so far. Completing the prompt registers it for prefix
        sharing and samples the first token. Returns True on progress;
        a pool-exhausted or chaos-injected failure fails THIS request
        only (typed, 429 at the HTTP tier)."""
        import jax.numpy as jnp

        page = self.model.page_size
        T = int(req.tokens.shape[0])
        t0 = req.prefilled
        n_valid = min(page, T - t0)
        t0c = self.clock()
        try:
            pg = self.cache.alloc(1)[0]
            req.pages.append(pg)
            req.block_row[t0 // page] = pg
            chunk = np.zeros((page,), np.int32)
            chunk[:n_valid] = req.tokens[t0:t0 + n_valid]
            # chaos seam INSIDE the failure try: an injected raise
            # fails this prefill like an organic dispatch error
            chunk = _chaos_fault_point("sequence.prefill", chunk)
            logits, kps, vps = self.model._jit_prefill(
                self.model._params, chunk, jnp.asarray(t0, jnp.int32),
                jnp.asarray(n_valid, jnp.int32), self.cache.k_pools,
                self.cache.v_pools, req.block_row)
            self.cache.k_pools, self.cache.v_pools = kps, vps
        except Exception as e:
            self._fail_req(req, e)
            return True                     # progress: the slot freed
        finally:
            self._registry.add_span(
                "sequence.prefill", "serving", t0c,
                self.clock() - t0c, model=self.name, chunk=n_valid)
        req.prefilled += n_valid
        req.seq_len = req.prefilled
        self.prefill_chunks += 1
        if req.prefilled >= T:
            last = np.asarray(logits)
            if self.prefix_sharing:
                self.cache.register_prefix(req.tokens, req.pages, last)
            self._complete_prompt(req, last)
        return True

    def _staging_for(self, S):
        """Per-bucket decode staging buffers (tokens, seq lens, block
        tables), allocated once and reused every iteration — the same
        alloc-churn fix as the carry path's _gather pool."""
        st = self._staging.get(S)
        if st is None:
            st = (np.zeros((S,), np.int32), np.zeros((S,), np.int32),
                  np.zeros((S, self._mp), np.int32))
            self._staging[S] = st
        else:
            self.staging_reuse_bytes += sum(a.nbytes for a in st)
        return st

    def _decode_batch(self, batch):
        """One slot-batched decode step over every fully-prefilled
        slot: per-slot page prep (CoW fork / fresh page at a page
        boundary — a pool-exhausted slot fails alone), padded gather,
        ONE dispatch, scatter + sample."""
        import jax.numpy as jnp

        ready = []
        for req in batch:
            try:
                idx = req.seq_len // self.model.page_size
                if req.seq_len % self.model.page_size == 0 \
                        and req.block_row[idx] == 0:
                    pg = self.cache.alloc(1)[0]
                    req.pages.append(pg)
                    req.block_row[idx] = pg
                else:
                    old = int(req.block_row[idx])
                    pg = self.cache.ensure_private(old)
                    if pg != old:
                        req.block_row[idx] = pg
                        req.pages = [pg if p == old else p
                                     for p in req.pages]
                ready.append(req)
            except Exception as e:
                self._fail_req(req, e)
        if not ready:
            return 0
        S = self.bucket_for(len(ready))
        tok, sls, bts = self._staging_for(S)
        n = len(ready)
        for i, req in enumerate(ready):
            tok[i] = req.out_tokens[-1]
            sls[i] = req.seq_len
            bts[i] = req.block_row
        tok[n:] = 0
        sls[n:] = 0
        bts[n:] = 0
        t0c = self.clock()
        self._m["dispatches"].inc()
        self._m["slot_steps"].inc(n)
        self._m["occupancy"].observe(n / S)
        self.occupancy.append((n, S))
        try:
            tok = _chaos_fault_point("sequence.step", tok)
            out, kps, vps = self.model._jit_decode(
                self.model._params, tok, self.cache.k_pools,
                self.cache.v_pools, bts, sls)
            self.cache.k_pools, self.cache.v_pools = kps, vps
            out = np.asarray(out)
        except Exception as e:
            with self._cond:
                self._m["errors"].inc(len(ready))
                for req in ready:
                    self._release_req(req)
                    req.fail(e)
                self._active = [r for r in self._active
                                if r not in ready]
                self._m["active"].set(len(self._active))
            return 0
        finally:
            self._registry.add_span(
                "sequence.step", "serving", t0c, self.clock() - t0c,
                model=self.name, slots=n, bucket=S)
        finished = []
        for i, req in enumerate(ready):
            if req.done:                # expired between gather + now
                continue
            req.seq_len += 1
            row = out[i].astype(np.float32, copy=False)
            req.logits_rows.append(row)
            req.out_tokens.append(int(req.sampler(row, req.rng)))
            if len(req.out_tokens) >= req.max_new:
                finished.append(req)
        for req in finished:
            self._finish_req(req)
        return n

    def _step_once(self):
        with self._step_lock:
            return self._iterate_locked()  # fault-ok[FLT04]: the step lock is the scheduler's own serialization contract — a seam firing under it IS the wedged-scheduler fault the harness injects, and waiters are released by deadline expiry (the wait contract), never by this lock

    def _iterate_locked(self):
        """One iteration: expire -> refill (prefix adoption) -> at most
        ONE prefill chunk -> one slot-batched decode step. Returns the
        progress count (0 = idle)."""
        with self._cond:
            now = self.clock()
            self._expire_locked(now)
            adopted = self._refill_locked(now)
        progress = 0
        for req, logits in adopted:       # exact-prefix admissions
            self._complete_prompt(req, logits)
            progress += 1
        with self._cond:
            batch = list(self._active)
        if not batch:
            return progress
        pre = next((r for r in batch
                    if not r.done and r.prefilled < r.tokens.shape[0]),
                   None)
        if pre is not None:
            self._prefill_one(pre)
            progress += 1
        decode = [r for r in batch
                  if not r.done and r.prefilled >= r.tokens.shape[0]]
        if decode:
            progress += self._decode_batch(decode)
        return progress

    # -- drivers --------------------------------------------------------
    def poll(self):
        """One synchronous scheduler iteration (the thread-less test
        seam). Returns the progress count — 0 means idle."""
        return self._step_once()

    def drain(self):
        """Run iterations until the table AND queue are empty."""
        while self._step_once():
            pass
        return self

    def _loop(self):
        while True:
            with self._cond:
                if self._closed and not self._pending \
                        and not self._active:
                    return
                if not self._pending and not self._active:
                    self._cond.wait(0.05)
                    continue
            try:
                self._step_once()
            except Exception as e:
                self._fail_all(e)

    def _fail_all(self, exc):
        with self._cond:
            n = len(self._pending) + len(self._active)
            if n:
                self._m["errors"].inc(n)
            while self._pending:
                self._pending.popleft().fail(exc)
            for req in self._active:
                self._release_req(req)
                req.fail(exc)
            self._active = []
            self._m["depth"].set(0)
            self._m["active"].set(0)

    # -- introspection / lifecycle --------------------------------------
    @property
    def depth(self):
        with self._cond:
            return len(self._pending)

    @property
    def active_slots(self):
        with self._cond:
            return len(self._active)

    @property
    def stats(self):
        """Dict view over the registry counters (dl4j_seq_*)."""
        return {k: int(self._m[k].value) for k in _STAT_KEYS}

    def occupancy_summary(self):
        return occupancy_summary_from(self.occupancy, "mean_live_slots")

    def warm(self, cache=None):
        """Precompile the decode executable for EVERY slot bucket plus
        the (bucket-independent) prefill chunk executable, so a serving
        process generates its first token hot. Returns {bucket: {...},
        "prefill": {...}} for fresh compiles. Signatures mirror the
        live dispatch EXACTLY (host-numpy staging arrays + the live
        pool handles)."""
        import jax.numpy as jnp

        report = {}
        for S in self.slot_buckets:
            tok = np.zeros((S,), np.int32)
            sls = np.zeros((S,), np.int32)
            bts = np.zeros((S, self._mp), np.int32)
            key, status, secs = self.model._jit_decode.warm(
                self.model._params, tok, self.cache.k_pools,
                self.cache.v_pools, bts, sls, cache=cache)
            if status is not None:
                report[int(S)] = {"key": key, "status": status,
                                  "seconds": round(secs, 3)}
        chunk = np.zeros((self.model.page_size,), np.int32)
        bt = np.zeros((self._mp,), np.int32)
        key, status, secs = self.model._jit_prefill.warm(
            self.model._params, chunk, jnp.asarray(0, jnp.int32),
            jnp.asarray(1, jnp.int32), self.cache.k_pools,
            self.cache.v_pools, bt, cache=cache)
        if status is not None:
            report["prefill"] = {"key": key, "status": status,
                                 "seconds": round(secs, 3)}
        return report

    def close(self, drain=True):
        """Stop accepting. drain=True serves everything queued or
        mid-flight to completion; drain=False fails them with
        ServingClosedError and frees their pages."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._pending:
                    self._pending.popleft().fail(
                        ServingClosedError("scheduler closed before "
                                           "a slot was granted"))
                for req in self._active:
                    self._release_req(req)
                    req.fail(ServingClosedError(
                        "scheduler closed mid-generation"))
                self._active = []
                self._m["depth"].set(0)
                self._m["active"].set(0)
            self._cond.notify_all()
        if drain:
            self.drain()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.cache.close()
        reg = self._registry
        for metric in _SEQ_METRIC_FAMILIES:
            fam = reg.get(metric)
            if fam is not None:
                fam.remove(model=self.name)
        return self

"""Load generators for the serving tier: open loop AND closed loop.

Open-loop means arrivals are scheduled by an external clock,
independent of completions — the honest way to measure a server
(a closed loop throttles itself to the server's pace and hides
queueing collapse). Inter-arrival gaps are exponential (Poisson
process) drawn from a SEEDED rng, so a run is reproducible; per-request
latency is measured from the SCHEDULED arrival (so pacer slip and
queueing both count against the server, the open-loop convention).

``run_closed_loop`` models the population an open loop cannot: clients
that BLOCK on each response (and optionally think before the next
request) — the slow-client storm of serving/fleet.py's scenarios.
Closed-loop latency runs submit→completion per request, and think
times are seeded jitter so a storm replays exactly.

Both record per-ERROR-CLASS counts (exception type name -> count) in
the summarize() record. The measured products — requests/sec
sustained, p50/p99 latency, error classes, and the dispatcher's
batch-occupancy histogram — are the `serving`/`serving_fleet` bench
records (bench.py, docs/SERVING.md).
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = ["arrival_offsets", "percentile", "summarize",
           "run_open_loop", "run_closed_loop"]


def arrival_offsets(rate, n, seed=0):
    """n Poisson-process arrival offsets (seconds from t0) at `rate`
    requests/sec: cumulative sum of seeded exponential gaps."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0 req/s, got {rate}")
    rng = np.random.RandomState(seed)
    return np.cumsum(rng.exponential(1.0 / float(rate), int(n)))


def percentile(values, q):
    """Linear-interpolated percentile (q in [0, 100]) of a sequence.
    Delegates to the ONE shared implementation the telemetry
    histograms read out through (runtime.telemetry.percentile — same
    'linear' method numpy defaults to; oracle-gated in
    tests/test_telemetry.py)."""
    from deeplearning4j_tpu.runtime.telemetry import percentile as _p

    return _p(values, q)


def summarize(latencies_s, duration_s, errors=None, scheduled=None):
    """Reduce per-request latencies to the serving record: sustained
    requests/sec (completed / wall duration) + latency percentiles in
    ms + error counts by type."""
    lat = np.asarray(sorted(latencies_s), np.float64)
    n_err = sum((errors or {}).values())
    rec = {
        "requests": int(len(lat) + n_err if scheduled is None
                        else scheduled),
        "completed": int(len(lat)),
        "errors": dict(errors or {}),
        "duration_s": round(float(duration_s), 4),
        "requests_per_sec": round(len(lat) / duration_s, 2)
        if duration_s > 0 else None,
    }
    if len(lat):
        rec.update(
            p50_ms=round(percentile(lat, 50) * 1000.0, 3),
            p99_ms=round(percentile(lat, 99) * 1000.0, 3),
            mean_ms=round(float(lat.mean()) * 1000.0, 3),
            max_ms=round(float(lat.max()) * 1000.0, 3),
        )
    return rec


def run_open_loop(submit, make_request, *, rate, n_requests, seed=0,
                  max_clients=16, timeout_s=120.0, clock=time.monotonic,
                  sleep=time.sleep):
    """Drive `submit` (callable(features) -> result, raising on
    failure) with `n_requests` Poisson arrivals at `rate` req/s.

    make_request: i -> features array for request i (seed your own rng
    so the workload is reproducible).
    A pool of `max_clients` persistent client threads consumes the
    arrival schedule — the bounded concurrent-clients population of a
    real front-end (a "limited open loop": admission is bounded, but
    latency for request i still runs from its SCHEDULED arrival to
    completion, so falling behind the schedule shows up as queueing
    latency, never as a silently slower arrival rate). A request that
    raises is counted by exception type in the summarize() record.
    """
    offsets = arrival_offsets(rate, n_requests, seed=seed)
    lat = [None] * n_requests
    errors = {}
    state_lock = threading.Lock()
    next_i = [0]
    t0 = [None]

    def client():  # fault-ok[FLT02]: the load generator is the traffic SOURCE — faults are injected at the serving seams it drives (queue.dispatch, server.request), not inside the measurement loop itself
        while True:
            with state_lock:
                i = next_i[0]
                if i >= n_requests:
                    return
                next_i[0] = i + 1
            sched_abs = t0[0] + offsets[i]
            delay = sched_abs - clock()
            if delay > 0:
                sleep(delay)
            try:
                submit(make_request(i))
                lat[i] = clock() - sched_abs
            except Exception as e:
                with state_lock:
                    key = type(e).__name__
                    errors[key] = errors.get(key, 0) + 1

    workers = [threading.Thread(target=client, daemon=True)
               for _ in range(min(int(max_clients), int(n_requests)))]
    t0[0] = clock()
    for w in workers:
        w.start()
    deadline = clock() + timeout_s
    for w in workers:
        w.join(timeout=max(0.0, deadline - clock()))
    duration = clock() - t0[0]
    # one consistent snapshot: abandoned = whatever is neither a
    # completed latency sample nor a counted error, so
    # completed + errors == scheduled even if a straggler finishes
    # between the join timeout and this accounting
    done = [v for v in lat if v is not None]
    with state_lock:
        errs = dict(errors)
    missing = n_requests - len(done) - sum(errs.values())
    if missing > 0:
        errs["TimeoutAbandoned"] = missing
    return summarize(done, duration, errors=errs,
                     scheduled=n_requests)


def run_closed_loop(submit, make_request, *, n_clients,
                    requests_per_client, think_time_s=0.0, seed=0,
                    timeout_s=120.0, clock=time.monotonic,
                    sleep=time.sleep):
    """Drive `submit` with `n_clients` CLOSED-LOOP clients: each sends
    one request, BLOCKS on the response, thinks, repeats — the
    self-throttling population (slow clients holding results) an open
    loop cannot model, and the load shape of the fleet's slow-client
    storm scenario (serving/fleet.py).

    make_request: (client, i) -> features for that client's i-th
    request. think_time_s: mean think pause between a response and the
    next request, drawn as SEEDED exponential jitter per client so a
    storm replays exactly (0 = a tight closed loop). Latency is
    submit→completion (the closed-loop convention — there is no
    external schedule to slip against); a request that raises is
    counted by exception type and the client moves on. The record is
    summarize() plus ``mode``/``clients`` fields.
    """
    n_clients = int(n_clients)
    per = int(requests_per_client)
    lat = []
    errors = {}
    state_lock = threading.Lock()

    def client(c):  # fault-ok[FLT02]: traffic source, not a served boundary — the submit() it calls crosses the real seams (queue.dispatch et al.) where injection belongs
        rng = np.random.RandomState(seed + c)
        for i in range(per):
            t0 = clock()
            try:
                x = make_request(c, i)
                submit(x)
                done = clock() - t0
                with state_lock:
                    lat.append(done)
            except Exception as e:
                with state_lock:
                    key = type(e).__name__
                    errors[key] = errors.get(key, 0) + 1
            if think_time_s > 0:
                sleep(float(rng.exponential(think_time_s)))

    workers = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(n_clients)]
    t0 = clock()
    for w in workers:
        w.start()
    deadline = clock() + timeout_s
    for w in workers:
        w.join(timeout=max(0.0, deadline - clock()))
    duration = clock() - t0
    with state_lock:
        done, errs = list(lat), dict(errors)
    scheduled = n_clients * per
    missing = scheduled - len(done) - sum(errs.values())
    if missing > 0:
        errs["TimeoutAbandoned"] = missing
    rec = summarize(done, duration, errors=errs, scheduled=scheduled)
    rec["mode"] = "closed"
    rec["clients"] = n_clients
    rec["think_time_s"] = float(think_time_s)
    return rec

"""HTTP front for the continuous-batching model host.

``InferenceServer`` puts a ``ModelHost`` behind the shared stdlib
serving scaffold (util/httpserve): a threaded loopback HTTP server
whose per-connection handler threads ARE the concurrent clients the
micro-batcher coalesces — every in-flight ``:predict`` enqueues into
the model's bounded queue and blocks for its slice of a coalesced
dispatch.

Routes:

* ``GET /healthz``                 — readiness (503 until the warmup
  hook — ``ModelHost.warm_all`` by default — reports every model's
  bucket executables hot; the pod scheduler gate, docs/COMPILE.md).
* ``GET /metrics``                 — Prometheus text exposition of the
  process-wide telemetry registry (serving + training + AOT
  instruments; runtime/telemetry.py, docs/OBSERVABILITY.md).
* ``GET /v1/models``               — the multi-model policy table
  (sequence models ride along with ``"kind": "sequence"`` rows).
* ``GET /v1/models/<name>``        — one model's policy row (404).
* ``POST /v1/models/<name>:predict`` — body
  ``{"instances": [...], "deadlineMs": optional}`` ->
  ``{"predictions": [...], "model": ..., "version": ..., "rows": n}``.
* ``POST /v1/models/<name>:generate`` — the SEQUENCE route
  (iteration-level slot scheduler, serving/sequence.py): body
  ``{"steps": [[...], ...], "extraSteps": optional, "deadlineMs":
  optional}`` -> ``{"outputs": [[...], ...], "steps": n}``; the
  deadline is honored per decode STEP.

Backpressure contract (docs/SERVING.md): queue full -> 429, deadline
exceeded -> 504, unknown model -> 404, malformed request -> 400,
draining/closed -> 503. Never a hang: every failure mode has a status
code and the client is always released.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.runtime.chaos import fault_point
from deeplearning4j_tpu.serving.queue import (
    DeadlineExceededError, QueueFullError, ServingClosedError,
)
from deeplearning4j_tpu.util.httpserve import (
    HttpError, HttpServerOwner, JsonHandler,
)

__all__ = ["InferenceServer"]


class _InferenceHandler(JsonHandler):
    @classmethod
    def metric_route(cls, path):
        """Bounded route labels for dl4j_http_* instruments (model
        names collapse into one 'predict'/'model' label so request
        cardinality can never grow the registry)."""
        path = path.rstrip("/") or "/"
        if path == "/healthz":
            return "healthz"
        if path == "/metrics":
            return "metrics"
        if path == "/v1/models":
            return "models"
        if path.endswith(":predict"):
            return "predict"
        if path.endswith(":generate"):
            return "generate"
        if path.startswith("/v1/models/"):
            return "model"
        return "other"

    def handle_GET(self):
        # same chaos seam as the POST boundary (ordinals interleave in
        # request order): an injected raise surfaces as this handler's
        # 500, the read path's client-visible failure mode — before
        # this seam landed, GET routes were the one HTTP boundary a
        # ChaosPlan could never exercise
        fault_point("server.request")
        host = self._owner().host
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/metrics":
            # Prometheus text exposition of the process registry:
            # serving (queue depth/occupancy/wait/latency/429s) AND
            # training (step wall, compile, retry/skip/checkpoint)
            # instruments — whatever this process has recorded
            from deeplearning4j_tpu.runtime import telemetry

            return self._send(
                200, telemetry.get_registry().prometheus(),
                "text/plain; version=0.0.4; charset=utf-8")
        if path == "/v1/models":
            return self._json({"models": host.describe()})
        if path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):]
            try:
                return self._json(host.model(name).policy())
            except KeyError as e:
                raise HttpError(404, str(e))
        raise HttpError(404, f"no route {path}")

    def handle_POST(self):
        # chaos seam for the HTTP boundary itself: an injected raise
        # here surfaces as the handler's 500 — the client-visible
        # failure mode the fleet's failover must absorb upstream
        fault_point("server.request")
        host = self._owner().host
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/models/") and path.endswith(":generate"):
            return self._handle_generate(
                host, path[len("/v1/models/"):-len(":generate")])
        if not (path.startswith("/v1/models/")
                and path.endswith(":predict")):
            raise HttpError(404, f"no route {path}")
        name = path[len("/v1/models/"):-len(":predict")]
        try:
            body = self._read_json_object()
        except ValueError as e:
            raise HttpError(400, str(e))
        instances = body.get("instances")
        if instances is None:
            raise HttpError(400, 'body must carry "instances": [...]')
        try:
            feats = np.asarray(instances, dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise HttpError(400, f"instances not array-like: {e}")
        deadline_ms = body.get("deadlineMs")
        try:
            deadline_s = None if deadline_ms is None \
                else float(deadline_ms) / 1000.0
        except (TypeError, ValueError) as e:
            raise HttpError(400, f"deadlineMs not numeric: {e}")
        try:
            try:
                sm = host.model(name)
                out = sm.submit(feats, deadline_s=deadline_s)
            except ServingClosedError:
                # lost the resolve/enqueue race against a rolling swap:
                # re-route to the freshly installed version (the host's
                # zero-5xx swap contract, serving/host.py submit)
                sm = host.model(name)
                out = sm.submit(feats, deadline_s=deadline_s)
        except KeyError as e:
            raise HttpError(404, str(e))
        except ValueError as e:       # shape/rows contract violations
            raise HttpError(400, str(e))
        except QueueFullError as e:   # backpressure, never a hang
            raise HttpError(429, str(e))
        except DeadlineExceededError as e:
            raise HttpError(504, str(e))
        except ServingClosedError as e:
            raise HttpError(503, str(e))
        preds = [np.asarray(o).tolist() for o in out] \
            if isinstance(out, list) else np.asarray(out).tolist()
        return self._json({"predictions": preds, "model": sm.name,
                           "version": sm.version, "rows": len(feats)})

    def _handle_generate(self, host, name):
        """POST :generate — one sequence through the iteration-level
        slot scheduler; same backpressure contract as :predict (429/
        504/503/400/404), the deadline honored per decode step.

        Two body shapes: ``{"steps": [[...], ...], "extraSteps"}``
        routes per-step features to the carry-slot (RNN) scheduler;
        ``{"tokens": [...], "maxNewTokens"}`` routes a token prompt to
        the paged KV scheduler -> ``{"tokens": [...], "steps": n}``.
        A KV-pool-exhausted prompt is a 429 (KVCacheFullError —
        admission backpressure, exactly like a full queue)."""
        from deeplearning4j_tpu.serving.kvcache import KVCacheFullError

        try:
            body = self._read_json_object()
        except ValueError as e:
            raise HttpError(400, str(e))
        steps = body.get("steps")
        tokens = body.get("tokens")
        if steps is None and tokens is None:
            raise HttpError(
                400, 'body must carry "steps": [[...], ...] (feature '
                'sequence) or "tokens": [...] (paged token prompt)')
        deadline_ms = body.get("deadlineMs")
        try:
            deadline_s = None if deadline_ms is None \
                else float(deadline_ms) / 1000.0
            extra = int(body.get("extraSteps", 0))
            max_new = int(body.get("maxNewTokens", 1))
        except (TypeError, ValueError) as e:
            raise HttpError(
                400, f"deadlineMs/extraSteps/maxNewTokens not "
                f"numeric: {e}")
        try:
            if tokens is not None:
                try:
                    toks = np.asarray(tokens, dtype=np.int32)
                except (TypeError, ValueError) as e:
                    raise HttpError(400, f"tokens not array-like: {e}")
                out = host.generate(name, toks, deadline_s=deadline_s,
                                    max_new_tokens=max_new)
            else:
                try:
                    feats = np.asarray(steps, dtype=np.float32)
                except (TypeError, ValueError) as e:
                    raise HttpError(400, f"steps not array-like: {e}")
                out = host.submit_sequence(name, feats,
                                           deadline_s=deadline_s,
                                           extra_steps=extra)
            sm = host.sequence_model(name)  # post-submit: live version
        except KeyError as e:
            raise HttpError(404, str(e))
        except ValueError as e:
            raise HttpError(400, str(e))
        except KVCacheFullError as e:  # pool exhausted: backpressure
            raise HttpError(429, str(e))
        except QueueFullError as e:
            raise HttpError(429, str(e))
        except DeadlineExceededError as e:
            raise HttpError(504, str(e))
        except ServingClosedError as e:
            raise HttpError(503, str(e))
        out = np.asarray(out)
        if tokens is not None:
            return self._json({"tokens": [int(t) for t in out],
                               "model": sm.name, "version": sm.version,
                               "steps": int(out.shape[0])})
        return self._json({"outputs": out.tolist(), "model": sm.name,
                           "version": sm.version,
                           "steps": int(out.shape[0])})


class InferenceServer(HttpServerOwner):
    """Loopback HTTP server over a ModelHost (module docstring)."""

    def __init__(self, host):
        self.host = host

    def start(self, port=0, requestDeadline=None, warmup=True):
        """Bind and serve. warmup=True gates /healthz on
        ``host.warm_all()`` (503 until every registered model's bucket
        executables are hot — cheap when registration already
        precompiled); pass a callable for a custom hook or
        warmup=None/False to report ready immediately."""
        w = self.host.warm_all if warmup is True else (warmup or None)
        return self._serve(_InferenceHandler, port,
                           requestDeadline=requestDeadline, warmup=w)

    def stop(self, close_host=False):
        """Stop the HTTP listener. close_host=True also drains and
        closes every model's queue (the full-shutdown path); the
        default leaves the host reusable behind a new listener."""
        super().stop()
        if close_host:
            self.host.close(drain=True)

"""Bounded request queue + dynamic micro-batcher.

Reference: upstream ParallelInference's worker queue exists because each
cuda device needs its own host thread and model replica; here the queue
exists for a different reason — THROUGHPUT. Each XLA dispatch costs the
same host overhead whether it carries 1 row or 64, so a server facing
many small concurrent requests should coalesce them into one padded
device batch and pay the dispatch once per micro-batch, not once per
request (arXiv:1605.08695's batching lever on top of the
one-executable-per-bucket model of arXiv:1810.09868).

Mechanics:

* ``submit`` appends to a bounded FIFO; a queue at ``queue_limit``
  raises ``QueueFullError`` — backpressure the HTTP tier answers as
  429, never a hang.
* the scheduler coalesces the FIFO prefix up to ``max_rows`` (the
  largest batch bucket). It dispatches immediately when the prefix
  fills a full bucket, and otherwise holds the batch open at most
  ``max_wait`` seconds measured from the OLDEST waiting request — the
  latency/occupancy tradeoff knob (docs/SERVING.md).
* per-request deadlines are honored end-to-end: an expired request is
  failed with ``DeadlineExceededError`` instead of wasting bucket rows,
  and ``InferenceRequest.wait(timeout)`` bounds the caller side too.
* the clock is injectable (``ManualClock``) and the scheduler can be
  driven synchronously via ``poll()`` — tier-1 latency-path tests run
  deterministically with no background thread and no sleeps.

The batcher never pads: it hands the host-concatenated rows to the
``dispatch`` callable (``ParallelInference._dispatch_coalesced``),
which owns bucket padding, mesh placement and the per-bucket AOT
executable cache.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np

from deeplearning4j_tpu.runtime import telemetry
from deeplearning4j_tpu.runtime.chaos import fault_point

__all__ = [
    "QueueFullError", "DeadlineExceededError", "ServingClosedError",
    "RequestCancelledError", "InferenceRequest", "MicroBatcher",
    "ManualClock",
]

#: unique default metric label for anonymous batchers (each instance is
#: its own time series so per-instance stats read through cleanly)
_BATCHER_SEQ = itertools.count(1)

#: the stats keys the deprecated dict view carries (and the per-model
#: counter instruments behind them)
_STAT_KEYS = ("requests", "rows", "dispatches", "dispatched_rows",
              "coalesced", "expired", "rejected", "errors")


class QueueFullError(RuntimeError):
    """Request queue at queue_limit — backpressure (HTTP 429)."""


class DeadlineExceededError(RuntimeError):
    """Per-request deadline expired before a result (HTTP 504)."""


class ServingClosedError(RuntimeError):
    """Submitted to a closed/draining batcher (HTTP 503)."""


class RequestCancelledError(RuntimeError):
    """The submitter cancelled a still-pending request (e.g. the
    losing leg of a hedged dispatch, serving/fleet.py) — the scheduler
    drops it before it wastes bucket rows."""


class ManualClock:
    """Deterministic monotonic clock: latency-path tests advance time
    explicitly instead of sleeping. Pair with a thread-less batcher
    (``start_thread=False``) driven via ``poll()``."""

    def __init__(self, start=0.0):
        self.now = float(start)

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += float(seconds)
        return self.now


class InferenceRequest:
    """One enqueued request: features [rows, ...], bookkeeping times,
    and the completion event the submitting thread blocks on."""

    __slots__ = ("features", "rows", "enqueued_at", "deadline",
                 "result", "error", "_event", "_cbs")

    def __init__(self, features, enqueued_at, deadline=None):
        self.features = features
        self.rows = int(features.shape[0])
        self.enqueued_at = float(enqueued_at)
        self.deadline = None if deadline is None else float(deadline)
        self.result = None
        self.error = None
        self._event = threading.Event()
        self._cbs = []

    @property
    def done(self):
        return self._event.is_set()

    def add_done_callback(self, cb):
        """Run ``cb(self)`` once the request completes (result, error
        or cancellation); if it already has, run it now on the caller.
        Callbacks run on the completing thread — keep them tiny and
        non-blocking (the hedged-dispatch wakeup just notifies a
        condition). Ordering is append-then-recheck so a completion
        racing the registration can never be missed, at the cost that
        a callback may run twice in that race — callbacks MUST be
        idempotent."""
        self._cbs.append(cb)
        if self._event.is_set():
            cb(self)

    def _notify(self):
        for cb in list(self._cbs):
            cb(self)

    def finish(self, result):
        self.result = result
        self._event.set()
        self._notify()

    def fail(self, exc):
        self.error = exc
        self._event.set()
        self._notify()

    def wait_done(self, timeout=None):
        """Block up to `timeout` for completion WITHOUT raising or
        consuming the outcome: True = a result or error is set. The
        hedged-dispatch primitive — the router polls two in-flight
        requests and only the winner's ``wait()`` re-raises."""
        return self._event.wait(timeout)

    def cancel(self, exc=None):
        """Best-effort cancellation: a still-pending request is failed
        with RequestCancelledError (the scheduler then drops it before
        it wastes bucket rows — _take_batch_locked skips done
        requests); a request already completed, or already inside a
        running dispatch, keeps its outcome and its late result is
        simply discarded. Returns True when THIS call cancelled it."""
        if self._event.is_set():
            return False
        self.fail(exc if exc is not None else RequestCancelledError(
            "request cancelled by submitter"))
        return True

    def wait(self, timeout=None):
        """Block until the batch carrying this request completes.

        THE serving tier's release contract, stated once (ServedModel,
        the HTTP 504 path and SequenceRequest.wait all defer here). The
        caller is released by exactly one of:

        1. result — the dispatch carrying this request completed;
        2. the dispatch's failure, re-raised (HTTP 500);
        3. DeadlineExceededError set by the SCHEDULER — the deadline
           passed while the request was still queued (it never wasted
           bucket rows) or, for sequences, at a step boundary;
        4. DeadlineExceededError raised HERE when `timeout` elapses
           first — the MID-DISPATCH release: even when the dispatcher
           is wedged inside a batch that includes this request, the
           client is released at its deadline (HTTP 504) while the
           batch itself runs to completion in the background. A
           released request's late result is discarded, never
           delivered.

        There is no path that leaves the caller blocked forever short
        of timeout=None with a dispatcher that never returns."""
        if not self._event.wait(timeout):
            raise DeadlineExceededError(
                f"no result within {timeout:.3f}s")
        if self.error is not None:
            raise self.error
        return self.result


class MicroBatcher:
    """Dynamic micro-batcher over a bounded FIFO (module docstring).

    dispatch:     callable(features [R, ...]) -> output [R, ...] or a
                  list of such arrays (multi-output graphs); row i of
                  every output must correspond to input row i.
    max_rows:     coalescing ceiling — the largest batch bucket.
    queue_limit:  bound on WAITING requests; beyond it submit raises
                  QueueFullError (HTTP 429).
    max_wait:     seconds the oldest waiting request may age before a
                  partial batch dispatches anyway.
    bucket_for:   rows -> dispatch bucket (occupancy accounting only;
                  e.g. ParallelInference._target_batch).
    trailing_shape/feature_dtype: optional per-example contract checked
                  at submit time — a malformed request is ITS error
                  (HTTP 400), never a poisoned coalesced batch.
    clock:        injectable monotonic clock.
    start_thread: run the background scheduler thread. False = the
                  owner drives `poll()`/`flush()` explicitly
                  (deterministic tests).
    name:         the `model` label on this batcher's registry
                  instruments (serving host passes "model:vN"); default
                  a unique per-instance label so anonymous batchers
                  never share series.
    """

    def __init__(self, dispatch, *, max_rows, queue_limit=64,
                 max_wait=0.002, bucket_for=None, trailing_shape=None,
                 feature_dtype=None, clock=None, start_thread=True,
                 name=None):
        if int(queue_limit) < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        if int(max_rows) < 1:
            raise ValueError(f"max_rows must be >= 1, got {max_rows}")
        self._dispatch = dispatch
        self.max_rows = int(max_rows)
        self.queue_limit = int(queue_limit)
        self.max_wait = float(max_wait)
        self.clock = clock if clock is not None else time.monotonic
        self._bucket_for = bucket_for or (lambda rows: rows)
        self.trailing_shape = None if trailing_shape is None \
            else tuple(trailing_shape)
        self.feature_dtype = feature_dtype
        self._cond = threading.Condition()
        self._pending = deque()
        self._inflight = 0      # requests popped into a running dispatch
        self._closed = False
        self.name = str(name) if name else f"batcher{next(_BATCHER_SEQ)}"
        # per-instance registry instruments (counters/gauge/histograms
        # labeled model=<name>); the legacy `stats` dict survives as a
        # read-through property over the counter children
        reg = telemetry.get_registry()
        lab = {"model": self.name}
        self._registry = reg
        self._m = {
            "requests": reg.counter(
                "dl4j_serving_requests_total",
                "requests accepted into the serving queue",
                labels=("model",)).labels(**lab),
            "rows": reg.counter(
                "dl4j_serving_rows_total",
                "feature rows accepted into the serving queue",
                labels=("model",)).labels(**lab),
            "dispatches": reg.counter(
                "dl4j_serving_dispatches_total",
                "coalesced micro-batch dispatches",
                labels=("model",)).labels(**lab),
            "dispatched_rows": reg.counter(
                "dl4j_serving_dispatched_rows_total",
                "rows carried by dispatched micro-batches",
                labels=("model",)).labels(**lab),
            "coalesced": reg.counter(
                "dl4j_serving_coalesced_total",
                "requests coalesced into dispatched micro-batches",
                labels=("model",)).labels(**lab),
            "expired": reg.counter(
                "dl4j_serving_expired_total",
                "requests whose deadline passed before dispatch (504)",
                labels=("model",)).labels(**lab),
            "rejected": reg.counter(
                "dl4j_serving_rejected_total",
                "requests rejected on a full queue (429)",
                labels=("model",)).labels(**lab),
            "errors": reg.counter(
                "dl4j_serving_errors_total",
                "requests failed by a dispatch error",
                labels=("model",)).labels(**lab),
            "depth": reg.gauge(
                "dl4j_serving_queue_depth",
                "requests currently waiting in the serving queue",
                labels=("model",)).labels(**lab),
            "wait": reg.histogram(
                "dl4j_serving_queue_wait_seconds",
                "enqueue-to-dispatch wait per request",
                labels=("model",)).labels(**lab),
            "occupancy": reg.histogram(
                "dl4j_serving_batch_occupancy",
                "rows/bucket fill fraction per dispatch",
                labels=("model",),
                buckets=(0.25, 0.5, 0.75, 1.0)).labels(**lab),
        }
        #: (rows, bucket) per dispatch — the occupancy record the
        #: serving bench histograms
        self.occupancy = []
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    # -- submit ---------------------------------------------------------
    def submit(self, features, deadline=None, wait=True, timeout=None):
        """Enqueue one request of features [rows, ...].

        deadline: absolute time (per this batcher's clock) after which
        the request must not be dispatched; compute as
        ``batcher.clock() + seconds``.
        wait=True blocks for the result (timeout bounds the block and
        raises DeadlineExceededError); wait=False returns the
        InferenceRequest for the caller to ``wait()`` on.
        """
        features = np.asarray(features)
        if features.ndim < 1 or features.shape[0] < 1:
            raise ValueError(
                f"features must be [rows, ...] with rows >= 1, got "
                f"shape {features.shape}")
        if self.trailing_shape is not None \
                and tuple(features.shape[1:]) != self.trailing_shape:
            raise ValueError(
                f"per-example shape {tuple(features.shape[1:])} does not "
                f"match the model's {self.trailing_shape}")
        if self.feature_dtype is not None:
            features = features.astype(self.feature_dtype, copy=False)
        with self._cond:
            if self._closed:
                raise ServingClosedError("batcher is closed")
            if len(self._pending) >= self.queue_limit:
                self._m["rejected"].inc()
                raise QueueFullError(
                    f"request queue full ({len(self._pending)} waiting, "
                    f"queueLimit={self.queue_limit})")
            req = InferenceRequest(features, self.clock(), deadline)
            self._pending.append(req)
            self._m["requests"].inc()
            self._m["rows"].inc(req.rows)
            self._m["depth"].set(len(self._pending))
            self._cond.notify()
        if wait:
            return req.wait(timeout)
        return req

    # -- scheduling core (lock held) ------------------------------------
    def _expire_locked(self, now):
        """Fail every WAITING request whose deadline has passed — an
        expired request must not waste bucket rows. Requests keep FIFO
        order; expiry can strike anywhere in the queue."""
        if not self._pending:
            return
        keep = deque()
        for req in self._pending:
            if req.deadline is not None and now >= req.deadline:
                self._m["expired"].inc()
                req.fail(DeadlineExceededError(
                    f"deadline passed {now - req.deadline:.3f}s before "
                    "dispatch"))
            else:
                keep.append(req)
        self._pending = keep
        self._m["depth"].set(len(self._pending))

    def _wait_needed_locked(self, now):
        """None = idle (nothing pending); 0 = dispatch now; > 0 =
        seconds until the oldest request's max-wait expires."""
        if not self._pending:
            return None
        if self._closed:
            return 0.0  # draining: flush immediately
        rows = 0
        for req in self._pending:
            rows += req.rows
            if rows >= self.max_rows:
                return 0.0  # a full bucket never waits
        return max(0.0, self.max_wait
                   - (now - self._pending[0].enqueued_at))

    def _take_batch_locked(self):
        """Pop the FIFO prefix that fits max_rows (at least one request
        — an oversized single request dispatches alone; the dispatch
        side handles overflow buckets)."""
        batch, rows = [], 0
        while self._pending:
            req = self._pending[0]
            if req.done:
                # cancelled (hedge loser) or released: already failed,
                # must not waste bucket rows
                self._pending.popleft()
                continue
            if batch and rows + req.rows > self.max_rows:
                break
            batch.append(self._pending.popleft())
            rows += req.rows
        # popped requests stay visible as load (`outstanding`) until
        # their dispatch returns — a wedged dispatch must not make the
        # batcher read idle to the fleet's least-loaded ranking
        self._inflight += len(batch)
        self._m["depth"].set(len(self._pending))
        return batch

    # -- dispatch (lock NOT held) ---------------------------------------
    def _run_batch(self, batch):
        try:
            self._dispatch_batch(batch)
        finally:
            with self._cond:
                self._inflight -= len(batch)

    def _dispatch_batch(self, batch):
        rows = sum(r.rows for r in batch)
        bucket = int(self._bucket_for(rows))
        taken = self.clock()
        oldest = min(r.enqueued_at for r in batch)
        self._m["dispatches"].inc()
        self._m["dispatched_rows"].inc(rows)
        self._m["coalesced"].inc(len(batch))
        self._m["occupancy"].observe(rows / bucket if bucket else 1.0)
        for r in batch:
            self._m["wait"].observe(taken - r.enqueued_at)
        # enqueue→coalesce→dispatch→reply span chain on THIS batcher's
        # clock (ManualClock-driven tests get deterministic traces)
        self._registry.add_span(
            "serving.coalesce", "serving", oldest, taken - oldest,
            model=self.name, requests=len(batch), rows=rows)
        self.occupancy.append((rows, bucket))
        try:
            feats = batch[0].features if len(batch) == 1 else \
                np.concatenate([r.features for r in batch], axis=0)
            # chaos seam INSIDE the batch-failure try: an injected
            # raise fails this batch exactly the way an organic
            # dispatch error does (runtime/chaos.py)
            feats = fault_point("queue.dispatch", feats)
            outs = self._dispatch(feats)
        except Exception as e:
            self._m["errors"].inc(len(batch))
            for r in batch:
                r.fail(e)
            return
        finally:
            self._registry.add_span(
                "serving.dispatch", "serving", taken,
                self.clock() - taken, model=self.name, rows=rows,
                bucket=bucket)
        t_reply = self.clock()
        multi = isinstance(outs, (list, tuple))
        outs_list = [np.asarray(o) for o in (outs if multi else [outs])]
        off = 0
        for r in batch:
            sl = [o[off:off + r.rows] for o in outs_list]
            off += r.rows
            r.finish(sl if multi else sl[0])
        self._registry.add_span(
            "serving.reply", "serving", t_reply,
            self.clock() - t_reply, model=self.name,
            requests=len(batch))

    # -- drivers --------------------------------------------------------
    def poll(self, now=None):
        """One synchronous scheduler pass: expire, then dispatch every
        batch that is due at `now` (default: the clock). Returns the
        seconds until the next max-wait expiry, or None when nothing is
        waiting. This is the thread-less driver deterministic tests
        (and flush) use."""
        while True:
            with self._cond:
                t = self.clock() if now is None else float(now)
                self._expire_locked(t)
                wait_s = self._wait_needed_locked(t)
                if wait_s is None or wait_s > 0:
                    return wait_s
                batch = self._take_batch_locked()
            if batch:
                self._run_batch(batch)

    def flush(self):
        """Dispatch everything pending NOW, regardless of max-wait."""
        while True:
            with self._cond:
                self._expire_locked(self.clock())
                if not self._pending:
                    return
                batch = self._take_batch_locked()
            if batch:    # may be empty when every waiter was cancelled
                self._run_batch(batch)

    def _loop(self):
        """Background scheduler. Uses the real condition-variable clock
        for its timed waits — with an injected ManualClock, drive
        poll() directly instead of starting the thread."""
        while True:
            batch = None
            with self._cond:
                if self._closed and not self._pending:
                    return
                if not self._pending:
                    self._cond.wait(0.05)
                    continue
                now = self.clock()
                self._expire_locked(now)
                wait_s = self._wait_needed_locked(now)
                if wait_s is not None and wait_s > 0:
                    # bounded: re-evaluates on notify (new arrivals may
                    # complete a bucket) or when the max-wait expires
                    self._cond.wait(wait_s)
                    continue
                if wait_s is not None:
                    batch = self._take_batch_locked()
            if batch:
                self._run_batch(batch)

    @property
    def depth(self):
        """Requests currently waiting (the queue-limit denominator)."""
        with self._cond:
            return len(self._pending)

    @property
    def outstanding(self):
        """Requests this batcher still owes a reply: queued + popped
        into a dispatch that has not returned. The load signal
        (ModelHost.queued_work / fleet least-loaded ranking) — `depth`
        alone reads 0 while a wedged dispatch holds a whole batch."""
        with self._cond:
            return len(self._pending) + self._inflight

    def close(self, drain=True):
        """Stop accepting. drain=True completes everything already
        queued (the rolling-swap contract: enqueued requests finish on
        the version they were enqueued against); drain=False fails them
        with ServingClosedError."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._pending:
                    self._pending.popleft().fail(
                        ServingClosedError("batcher closed before "
                                           "dispatch"))
                self._m["depth"].set(0)
            self._cond.notify_all()
        if drain:
            self.flush()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # release this instance's registry series: a long-lived server
        # rolling swaps (model:v1, v2, ...) or a process creating many
        # anonymous batchers must not grow every future /metrics scrape
        # with dead series. The cached self._m handles stay usable
        # (the stats read-through keeps working after close) — they are
        # just detached from exposition.
        reg = self._registry
        for metric in ("dl4j_serving_requests_total",
                       "dl4j_serving_rows_total",
                       "dl4j_serving_dispatches_total",
                       "dl4j_serving_dispatched_rows_total",
                       "dl4j_serving_coalesced_total",
                       "dl4j_serving_expired_total",
                       "dl4j_serving_rejected_total",
                       "dl4j_serving_errors_total",
                       "dl4j_serving_queue_depth",
                       "dl4j_serving_queue_wait_seconds",
                       "dl4j_serving_batch_occupancy"):
            fam = reg.get(metric)
            if fam is not None:
                fam.remove(model=self.name)
        return self

    # -- reporting ------------------------------------------------------
    @property
    def stats(self):
        """DEPRECATED read-through view over the registry counters
        (runtime.telemetry): the historical dict keys, computed on
        access. New code should read the `dl4j_serving_*` instruments
        (labeled model=<name>) via /metrics or metrics_snapshot()."""
        return {k: int(self._m[k].value) for k in _STAT_KEYS}

    def occupancy_summary(self):
        """Occupancy of every dispatch so far: mean rows/bucket plus a
        quartile histogram — the 'is max_wait tuned right' signal
        (docs/SERVING.md). Computed from the `self.occupancy` record
        (bench code assigns it directly); live dispatches additionally
        feed the registry's dl4j_serving_batch_occupancy histogram,
        whose quartile bucket edges mirror this binning."""
        return occupancy_summary_from(self.occupancy,
                                      "mean_rows_per_dispatch")


def occupancy_summary_from(records, rows_key):
    """Mean/quartile-histogram occupancy math over (rows, bucket)
    records — shared by MicroBatcher dispatches and the sequence
    scheduler's decode steps (`rows_key` names the per-tier mean:
    rows per dispatch vs live slots per step). One binning; the two
    tiers must never diverge."""
    if not records:
        return {"dispatches": 0, "mean_occupancy": None,
                "histogram": {}}
    occ = [rows / bucket for rows, bucket in records]
    hist = {"0-25%": 0, "25-50%": 0, "50-75%": 0, "75-100%": 0}
    for o in occ:
        if o <= 0.25:
            hist["0-25%"] += 1
        elif o <= 0.5:
            hist["25-50%"] += 1
        elif o <= 0.75:
            hist["50-75%"] += 1
        else:
            hist["75-100%"] += 1
    return {"dispatches": len(occ),
            "mean_occupancy": round(sum(occ) / len(occ), 4),
            rows_key: round(sum(r for r, _ in records)
                            / len(records), 2),
            "histogram": hist}

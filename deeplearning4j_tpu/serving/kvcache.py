"""Paged KV cache: fixed-size KV blocks on bounded HBM.

The dense serving cache reserves ``slots x max_context`` KV rows up
front, so HBM residency is paid for context nobody is using; the
vLLM/PagedAttention shape bounds it by the tokens actually alive:
the pool is ``num_pages`` fixed-size pages per layer, device-resident
(``[L, P, page, H, Dh]`` for K and V), and each slot maps logical KV
block j -> physical page through its **block table** row. The
attention kernels (ops/pallas_attention.py ``paged_flash_decode`` /
``paged_flash_prefill``) gather K/V through that table; ``page_size``
doubles as the kernel block_k so paged attention is bitwise the dense
flash kernel on the same tokens.

``PagedKVCache`` is the HOST-side manager plus the device pools:

* **allocation/free at step boundaries**: a free list over page ids
  (page 0 is the reserved null page padded slots point at — never
  allocated, never read: a zero-length slot masks every key).
  Exhaustion raises the typed ``KVCacheFullError`` (429 at the HTTP
  tier) — admission control, never a swallowed except or a hang.
* **copy-on-write prefix sharing**: ``register_prefix`` publishes a
  finished prompt's pages into an LRU registry (one refcount each);
  ``match_prefix`` lets a later request with the same prompt prefix
  adopt the full pages outright — full prompt pages are immutable
  after prefill, so sharing them is free — and an exact-prompt match
  also shares the partial tail page, which the first generated-token
  append then forks (``ensure_private``: device page copy + block-
  table rewrite). Registry entries are evicted LRU when the free list
  runs dry, BEFORE admission fails.
* the pools cross the jit boundary functionally: the model step
  functions take the pool arrays and return the updated ones (append
  is an in-graph ``.at[].set``); the cache just holds the live
  reference between steps.

Telemetry: ``dl4j_kv_pages_in_use{model}`` and
``dl4j_kv_prefix_shared_pages{model}`` gauges (docs/OBSERVABILITY.md).
Thread safety: guarded by the owning scheduler's step lock (the same
single-driver contract as the slot table) — not internally locked.

See docs/SERVING.md "Paged KV cache".
"""

from __future__ import annotations

from collections import OrderedDict, deque

import numpy as np

from deeplearning4j_tpu.runtime import telemetry
from deeplearning4j_tpu.runtime.chaos import fault_point, register_seam

__all__ = ["KVCacheFullError", "PagedKVCache"]

#: page-allocation chaos seam: fired on every alloc (and on the CoW
#: fork's copy-target alloc), so a ChaosPlan can exhaust/fail paging
#: exactly where production would (runtime/chaos.py)
PAGE_ALLOC_SEAM = register_seam("kv.page_alloc")


class KVCacheFullError(RuntimeError):
    """KV page pool exhausted: the request cannot be admitted (or a
    mid-generation append cannot be served) without evicting live
    state. Surfaces as HTTP 429 — backpressure, never a hang."""


class PagedKVCache:
    """Device-resident paged KV pool + host-side block-table manager
    (module docstring). One instance per PagedSequenceScheduler."""

    def __init__(self, *, n_layers, n_heads, head_dim, page_size,
                 num_pages, dtype=np.float32, model="kv"):
        import jax.numpy as jnp

        if int(num_pages) < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {num_pages}")
        if int(page_size) < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.dtype = jnp.dtype(dtype)
        self.model = str(model)
        shape = (self.n_layers, self.num_pages, self.page_size,
                 self.n_heads, self.head_dim)
        #: the live pool arrays; the model's jitted step functions
        #: consume and REPLACE these (functional update, optionally
        #: donated on TPU)
        self.k_pools = jnp.zeros(shape, self.dtype)
        self.v_pools = jnp.zeros(shape, self.dtype)
        self._free = deque(range(1, self.num_pages))
        self._ref = np.zeros((self.num_pages,), np.int32)
        self._ref[0] = 1                  # the null page, pinned
        #: prompt-token tuple -> list of page ids, LRU order
        self._prefixes = OrderedDict()
        reg = telemetry.get_registry()
        self._registry = reg
        lab = {"model": self.model}
        self._g_in_use = reg.gauge(
            "dl4j_kv_pages_in_use",
            "KV pool pages allocated (live slots + prefix registry)",
            labels=("model",)).labels(**lab)
        self._g_shared = reg.gauge(
            "dl4j_kv_prefix_shared_pages",
            "KV pool pages held by the copy-on-write prefix registry",
            labels=("model",)).labels(**lab)
        self._g_in_use.set(0)
        self._g_shared.set(0)

    # -- accounting ------------------------------------------------------
    @property
    def pages_in_use(self):
        """Allocated pages (null page excluded)."""
        return self.num_pages - 1 - len(self._free)

    @property
    def capacity(self):
        """Allocatable pages (null page excluded)."""
        return self.num_pages - 1

    def page_bytes(self):
        """HBM bytes one page costs across every layer, K and V."""
        return (2 * self.n_layers * self.page_size * self.n_heads
                * self.head_dim * self.dtype.itemsize)

    def bytes_in_use(self):
        """HBM attributable to live tokens: allocated pages x page
        cost — the paged side of the bench residency A/B (the pool
        arrays themselves are num_pages x that, but num_pages is the
        operator's bound, sized to live load, not slots x
        max_context)."""
        return self.pages_in_use * self.page_bytes()

    def pages_for(self, n_tokens):
        """Pages a sequence of n_tokens occupies."""
        return -(-int(n_tokens) // self.page_size)

    # -- allocation ------------------------------------------------------
    def alloc(self, n=1):
        """Take n pages off the free list (refcount 1 each). Evicts
        LRU prefix-registry entries first when short; raises the typed
        KVCacheFullError when live slots alone hold the pool."""
        n = int(n)
        fault_point("kv.page_alloc", n)
        while len(self._free) < n and self._prefixes:
            self._evict_lru_prefix()
        if len(self._free) < n:
            raise KVCacheFullError(
                f"KV pool exhausted: {n} page(s) requested, "
                f"{len(self._free)} free of {self.capacity} "
                f"(page_size={self.page_size})")
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self._g_in_use.set(self.pages_in_use)
        return pages

    def retain(self, pages):
        """Add one reference to each page (prefix adoption)."""
        for p in pages:
            self._ref[p] += 1

    def release(self, pages):
        """Drop one reference per page; pages at refcount 0 return to
        the free list (slot teardown / registry eviction)."""
        for p in pages:
            if p == 0:
                continue
            self._ref[p] -= 1
            if self._ref[p] <= 0:
                self._ref[p] = 0
                self._free.append(p)
        self._g_in_use.set(self.pages_in_use)

    def is_shared(self, page):
        return self._ref[page] > 1

    def ensure_private(self, page):
        """The copy-on-write fork: return a page safe to append into.
        Unshared pages come back unchanged; a shared page is copied
        into a fresh page on device (one .at[].set per pool) and the
        shared original keeps its other holders."""
        if not self.is_shared(page):
            return page
        new = self.alloc(1)[0]
        self.k_pools = self.k_pools.at[:, new].set(self.k_pools[:, page])
        self.v_pools = self.v_pools.at[:, new].set(self.v_pools[:, page])
        self.release([page])
        return new

    # -- copy-on-write prefix registry -----------------------------------
    def _shared_pages_total(self):
        return sum(len(e[0]) for e in self._prefixes.values())

    def _evict_lru_prefix(self):
        _, (pages, _) = self._prefixes.popitem(last=False)
        self.release(pages)
        self._g_shared.set(self._shared_pages_total())

    def register_prefix(self, tokens, pages, last_logits=None):
        """Publish a fully-prefilled prompt's pages for sharing. The
        registry holds one reference per page, so a finished slot's
        release never frees them; pages under the registry are COW-
        protected for the owner's own decode appends too (the tail
        page is forked on the first generated token). ``last_logits``
        (the prompt's final-position logits row) lets an EXACT-prompt
        adopter skip prefill entirely and still sample its first
        token."""
        key = tuple(int(t) for t in tokens)
        if not key or key in self._prefixes:
            return
        pages = list(pages)
        self.retain(pages)
        logits = None if last_logits is None else np.asarray(last_logits)
        self._prefixes[key] = (pages, logits)
        self._g_shared.set(self._shared_pages_total())

    def match_prefix(self, tokens):
        """Longest registered prompt that prefixes `tokens` ->
        (pages_to_adopt, shared_token_count, last_logits_or_None) with
        one reference taken per adopted page, or ([], 0, None). Full
        pages of the match are always adoptable (immutable after
        prefill); the partial tail page — and the stored last-position
        logits — only on an EXACT prompt match, where the adopter's
        appends land in the tail page: exactly the CoW fork case. The
        remainder of the prompt always starts on a page boundary, so
        chunked prefill resumes cleanly."""
        key = tuple(int(t) for t in tokens)
        best = None
        for rk in self._prefixes:
            if len(rk) <= len(key) and key[:len(rk)] == rk:
                if best is None or len(rk) > len(best):
                    best = rk
        if best is None:
            return [], 0, None
        pages, logits = self._prefixes[best]
        self._prefixes.move_to_end(best)          # LRU touch
        exact = len(best) == len(key)
        n_full = len(best) // self.page_size
        if exact and logits is not None:
            shared = list(pages)
            n_tokens = len(best)
        else:
            # no stored logits -> treat an exact match like a partial
            # one (re-prefill the tail) so the first token is sampleable
            shared = list(pages[:n_full])
            n_tokens = n_full * self.page_size
            logits = None
            if n_tokens >= len(key):
                # the whole prompt would be adopted with no logits to
                # sample from: hold back the last page so prefill has
                # >= 1 token left to run
                shared = shared[:-1]
                n_tokens -= self.page_size
        if not shared:
            return [], 0, None
        self.retain(shared)
        return shared, n_tokens, logits

    # -- lifecycle -------------------------------------------------------
    def close(self):
        """Release the registry and this instance's gauge series."""
        while self._prefixes:
            self._evict_lru_prefix()
        for metric in ("dl4j_kv_pages_in_use",
                       "dl4j_kv_prefix_shared_pages"):
            fam = self._registry.get(metric)
            if fam is not None:
                fam.remove(model=self.model)
        return self

"""Multi-model host: name -> served version, with rolling swap.

Each registered model is a ``ServedModel``: the network, its dtype /
quantization policy, its batch buckets, and a BATCHED-mode
``ParallelInference`` (bounded queue + micro-batcher + per-bucket AOT
executable cache). Registration precompiles every bucket, so the first
real request of a model's life is served by a hot executable.

Rolling swap (``swap``): the replacement version is built and its
executables are WARMED while the current version keeps serving; only
then is the routing entry replaced (an atomic assignment under the
host lock), and the old version drains its already-queued requests
through its own hot executables. The request path never sees a cold
compile and never sees a gap — the /healthz the HTTP tier reports
stays ready throughout (docs/SERVING.md "Rolling swap").
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = ["ServedModel", "ServedSequenceModel", "ModelHost"]


class ServedModel:
    """One (name, version) entry: network + policy + its BATCHED-mode
    ParallelInference. Build through ModelHost.register/swap."""

    def __init__(self, name, version, network, mesh=None,
                 batchBuckets=None, int8=False, queueLimit=64,
                 maxWaitMs=2.0, clock=None):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        self.name = str(name)
        self.version = int(version)
        self.network = network
        self.int8 = bool(int8)
        self.pi = ParallelInference(
            network, mesh=mesh, batchBuckets=batchBuckets,
            inferenceMode="BATCHED", queueLimit=queueLimit,
            maxWaitMs=maxWaitMs, int8=int8, clock=clock,
            metricsName=f"{self.name}:v{self.version}")

    @property
    def batcher(self):
        return self.pi._ensure_batcher()

    def warm(self, cache=None):
        """Precompile every batch bucket (hits are free). Returns the
        per-bucket {key, status, seconds} report."""
        return self.pi.precompile(cache=cache)

    def submit(self, features, deadline_s=None, wait=True):
        """Queue one request (features [rows, ...]) and block for its
        sliced result. deadline_s bounds the WHOLE request (queue wait
        + dispatch): expiry raises DeadlineExceededError whether the
        request was still queued or the dispatcher is busy. May raise
        QueueFullError (backpressure). wait=False returns the
        InferenceRequest at enqueue — the fleet's hedged-dispatch
        handle (serving/fleet.py)."""
        from deeplearning4j_tpu.runtime.chaos import fault_point

        b = self.batcher
        features = fault_point("host.submit", features)
        deadline = None if deadline_s is None else \
            b.clock() + float(deadline_s)
        return b.submit(features, deadline=deadline, wait=wait,
                        timeout=deadline_s)

    def policy(self):
        """The policy row the multi-model table reports."""
        import jax.numpy as jnp

        return {
            "model": self.name,
            "version": self.version,
            "dtype": jnp.dtype(self.network._compute_dtype).name,
            "int8": self.int8,
            "batchBuckets": list(self.pi.batchBuckets or ()),
            "queueLimit": self.pi.queueLimit,
            "maxWaitMs": self.pi.maxWaitMs,
            "exampleShape": list(self.pi.example_shape() or ()),
            "mesh": dict(
                (k, int(v)) for k, v in self.pi.mesh.shape.items()),
        }

    def close(self, drain=True):
        self.pi.close(drain=drain)
        return self


class ServedSequenceModel:
    """One (name, version) SEQUENCE entry: network + its iteration-
    level slot scheduler (serving/sequence.py). A network with
    ``kind == "paged_lm"`` (nn/transformer.py) is served behind the
    KV-slot ``PagedSequenceScheduler`` instead of the h/c carry
    scheduler — token prompts in, sampled tokens out, KV on a bounded
    paged pool. Build through ModelHost.register_sequence/
    swap_sequence."""

    def __init__(self, name, version, network, slotBuckets=None,
                 queueLimit=64, feedback=None, clock=None,
                 numPages=64, sampler=None, samplerSeed=0,
                 prefixSharing=True):
        from deeplearning4j_tpu.serving.sequence import (
            PagedSequenceScheduler, SequenceScheduler,
        )

        self.name = str(name)
        self.version = int(version)
        self.network = network
        self.paged = getattr(network, "kind", None) == "paged_lm"
        if self.paged:
            self.scheduler = PagedSequenceScheduler(
                network, num_pages=numPages, slot_buckets=slotBuckets,
                queue_limit=queueLimit, sampler=sampler,
                sampler_seed=samplerSeed, prefix_sharing=prefixSharing,
                clock=clock, start_thread=clock is None,
                name=f"{self.name}:v{self.version}")
        else:
            self.scheduler = SequenceScheduler(
                network, slot_buckets=slotBuckets,
                queue_limit=queueLimit, feedback=feedback, clock=clock,
                start_thread=clock is None,
                name=f"{self.name}:v{self.version}")

    def warm(self, cache=None):
        """Precompile the decode step for every slot bucket."""
        return self.scheduler.warm(cache=cache)

    def submit(self, features, deadline_s=None, extra_steps=0,
               wait=True, timeout=None):
        from deeplearning4j_tpu.runtime.chaos import fault_point

        if self.paged:
            raise ValueError(
                f"model {self.name!r} is a paged token model — use "
                "generate()/submit_tokens() with a token prompt")
        sched = self.scheduler
        features = fault_point("host.submit_sequence", features)
        deadline = None if deadline_s is None else \
            sched.clock() + float(deadline_s)
        return sched.submit(features, deadline=deadline,
                            extra_steps=extra_steps, wait=wait,
                            timeout=deadline_s if timeout is None
                            else timeout)

    def submit_tokens(self, tokens, deadline_s=None, max_new_tokens=1,
                      wait=True, timeout=None):
        """Queue one token prompt on the paged scheduler (the
        :generate token path). Same deadline/wait contract as
        submit()."""
        from deeplearning4j_tpu.runtime.chaos import fault_point

        if not self.paged:
            raise ValueError(
                f"model {self.name!r} serves per-step features, not "
                "token prompts — use submit()")
        sched = self.scheduler
        tokens = fault_point("host.submit_sequence", tokens)
        deadline = None if deadline_s is None else \
            sched.clock() + float(deadline_s)
        return sched.submit(tokens, deadline=deadline,
                            max_new_tokens=max_new_tokens, wait=wait,
                            timeout=deadline_s if timeout is None
                            else timeout)

    def policy(self):
        import jax.numpy as jnp

        pol = {
            "model": self.name,
            "version": self.version,
            "kind": "sequence",
            "dtype": jnp.dtype(self.network._compute_dtype).name,
            "slotBuckets": list(self.scheduler.slot_buckets),
            "queueLimit": self.scheduler.queue_limit,
        }
        if self.paged:
            cache = self.scheduler.cache
            pol.update({
                "paged": True,
                "vocab": self.scheduler.vocab,
                "maxContext": self.network.max_context,
                "pageSize": cache.page_size,
                "numPages": cache.num_pages,
                "prefixSharing": self.scheduler.prefix_sharing,
            })
        else:
            pol["featureSize"] = self.scheduler.feature_size
        return pol

    def close(self, drain=True):
        self.scheduler.close(drain=drain)
        return self


class ModelHost:
    """name -> ServedModel routing table (module docstring), plus a
    parallel table of sequence (iteration-level) models — one host =
    one serving process's worth of models; serving/fleet.py stacks N
    hosts behind a router."""

    def __init__(self, mesh=None, clock=None):
        self._mesh = mesh
        self._clock = clock
        self._models = {}
        self._sequences = {}        # name -> ServedSequenceModel
        self._registering = set()   # names reserved mid-register
        self._lock = threading.Lock()

    # -- registration / swap --------------------------------------------
    def register(self, name, network, *, batchBuckets=None, int8=False,
                 queueLimit=64, maxWaitMs=2.0, precompile=True):
        """Serve `network` as `name` (version 1). precompile=True (the
        production default) warms every bucket executable before the
        model is routable."""
        with self._lock:
            if name in self._models or name in self._sequences \
                    or name in self._registering:
                raise ValueError(
                    f"model {name!r} is already registered — use "
                    "swap() to roll a new version")
            # reserved so a concurrent register() of the same name
            # raises instead of silently overwriting the loser
            self._registering.add(name)
        try:
            sm = ServedModel(name, 1, network, mesh=self._mesh,
                             batchBuckets=batchBuckets, int8=int8,
                             queueLimit=queueLimit, maxWaitMs=maxWaitMs,
                             clock=self._clock)
            report = sm.warm() if precompile else None
            with self._lock:
                self._models[name] = sm
        finally:
            with self._lock:
                self._registering.discard(name)
        return {"model": name, "version": sm.version, "warm": report}

    def swap(self, name, network, **overrides):
        """Rolling swap to a new version of `name`.

        Sequence: (1) build the replacement with the current policy
        (override any knob by keyword), (2) WARM its bucket executables
        while the current version keeps serving, (3) install it
        atomically, (4) drain the old version — requests already queued
        complete on the version they were enqueued against, through its
        own hot executables. No cold compile ever lands on the request
        path and no request is dropped.
        """
        with self._lock:
            old = self._models.get(name)
            if old is None:
                raise KeyError(
                    f"unknown model {name!r}: register() it first "
                    f"(registered: {sorted(self._models)})")
        pol = old.policy()
        kw = {"batchBuckets": tuple(pol["batchBuckets"]) or None,
              "int8": pol["int8"], "queueLimit": pol["queueLimit"],
              "maxWaitMs": pol["maxWaitMs"]}
        kw.update(overrides)
        new = ServedModel(name, old.version + 1, network,
                          mesh=self._mesh, clock=self._clock, **kw)
        t0 = time.perf_counter()
        report = new.warm()          # old version is still serving
        warm_s = time.perf_counter() - t0
        with self._lock:
            self._models[name] = new  # atomic routing flip
        old.close(drain=True)         # queued requests finish on OLD
        return {"model": name, "version": new.version,
                "warm": report, "warm_s": round(warm_s, 3)}

    # -- sequence (iteration-level) models -------------------------------
    def register_sequence(self, name, network, *, slotBuckets=None,
                          queueLimit=64, feedback=None, precompile=True,
                          numPages=64, sampler=None, samplerSeed=0,
                          prefixSharing=True):
        """Serve a recurrent `network` as the SEQUENCE model `name`
        (version 1) behind an iteration-level slot scheduler
        (serving/sequence.py) — or, for a ``kind == "paged_lm"``
        network, the KV-slot paged scheduler (numPages/sampler/
        samplerSeed/prefixSharing apply there; feedback applies only to
        the carry path). precompile=True warms the decode-step
        executable for every slot bucket before the model is
        routable."""
        with self._lock:
            if name in self._models or name in self._sequences \
                    or name in self._registering:
                raise ValueError(
                    f"model {name!r} is already registered — use "
                    "swap_sequence() to roll a new version")
            self._registering.add(name)
        try:
            sm = ServedSequenceModel(name, 1, network,
                                     slotBuckets=slotBuckets,
                                     queueLimit=queueLimit,
                                     feedback=feedback,
                                     clock=self._clock,
                                     numPages=numPages, sampler=sampler,
                                     samplerSeed=samplerSeed,
                                     prefixSharing=prefixSharing)
            try:
                report = sm.warm() if precompile else None
            except Exception:
                # the ctor already started the scheduler thread and
                # registered telemetry series — a failed warm must not
                # leak either
                sm.close(drain=False)
                raise
            with self._lock:
                self._sequences[name] = sm
        finally:
            with self._lock:
                self._registering.discard(name)
        return {"model": name, "version": sm.version, "warm": report}

    def swap_sequence(self, name, network, **overrides):
        """Rolling swap of a sequence model: build + WARM the new
        version's slot-bucket executables while the current one keeps
        stepping, flip atomically, drain the old scheduler (sequences
        already admitted or queued finish on the version they were
        enqueued against)."""
        with self._lock:
            old = self._sequences.get(name)
            if old is None:
                raise KeyError(
                    f"unknown sequence model {name!r}: "
                    "register_sequence() it first (registered: "
                    f"{sorted(self._sequences)})")
        pol = old.policy()
        kw = {"slotBuckets": tuple(pol["slotBuckets"]) or None,
              "queueLimit": pol["queueLimit"]}
        if old.paged:
            kw.update({"numPages": pol["numPages"],
                       "sampler": old.scheduler.sampler,
                       "samplerSeed": old.scheduler.sampler_seed,
                       "prefixSharing": pol["prefixSharing"]})
        else:
            kw["feedback"] = old.scheduler.feedback
        kw.update(overrides)
        new = ServedSequenceModel(name, old.version + 1, network,
                                  clock=self._clock, **kw)
        t0 = time.perf_counter()
        try:
            report = new.warm()       # old version keeps stepping
        except Exception:
            new.close(drain=False)    # old version stays routed
            raise
        warm_s = time.perf_counter() - t0
        with self._lock:
            self._sequences[name] = new   # atomic routing flip
        old.close(drain=True)
        return {"model": name, "version": new.version,
                "warm": report, "warm_s": round(warm_s, 3)}

    def sequence_model(self, name):
        with self._lock:
            sm = self._sequences.get(name)
            registered = sorted(self._sequences)
        if sm is None:
            raise KeyError(
                f"unknown sequence model {name!r} (registered: "
                f"{registered})")
        return sm

    def submit_sequence(self, name, features, deadline_s=None,
                        extra_steps=0, wait=True, timeout=None):
        """Route one sequence ([T, F] per-step features) to `name`'s
        slot scheduler. Same swap re-route contract as submit(): a
        request losing the resolve/enqueue race against a
        swap_sequence lands on the new version, never a 5xx."""
        from deeplearning4j_tpu.serving.queue import ServingClosedError

        feats = np.asarray(features)
        try:
            return self.sequence_model(name).submit(
                feats, deadline_s=deadline_s, extra_steps=extra_steps,
                wait=wait, timeout=timeout)
        except ServingClosedError:
            return self.sequence_model(name).submit(
                feats, deadline_s=deadline_s, extra_steps=extra_steps,
                wait=wait, timeout=timeout)

    def generate(self, name, tokens, deadline_s=None, max_new_tokens=1,
                 wait=True, timeout=None):
        """Route one token prompt to `name`'s PAGED sequence scheduler
        (:generate with a "tokens" body). Same swap re-route contract
        as submit_sequence."""
        from deeplearning4j_tpu.serving.queue import ServingClosedError

        toks = np.asarray(tokens)
        try:
            return self.sequence_model(name).submit_tokens(
                toks, deadline_s=deadline_s,
                max_new_tokens=max_new_tokens, wait=wait,
                timeout=timeout)
        except ServingClosedError:
            return self.sequence_model(name).submit_tokens(
                toks, deadline_s=deadline_s,
                max_new_tokens=max_new_tokens, wait=wait,
                timeout=timeout)

    def queued_work(self, name):
        """Outstanding work this host holds for `name` — one-shot
        requests queued OR inside a running dispatch (a wedged batch
        must read as load, not idleness), or queue depth + live slots
        for a sequence model; None when the model is not served here.
        The fleet router's least-loaded ranking key (a point-in-time
        read)."""
        with self._lock:
            sm = self._models.get(name)
            seq = self._sequences.get(name)
        if sm is not None:
            b = sm.pi._batcher  # thread-ok[THR01]: atomic reference read — an idle model (no batcher yet) just reports 0
            return 0 if b is None else b.outstanding
        if seq is not None:
            return seq.scheduler.depth + seq.scheduler.active_slots
        return None

    def kind(self, name):
        """'oneshot' | 'sequence' | None when `name` is not served
        here — the fleet's swap_all dispatch key."""
        with self._lock:
            if name in self._models:
                return "oneshot"
            if name in self._sequences:
                return "sequence"
        return None

    # -- request path ---------------------------------------------------
    def model(self, name):
        with self._lock:
            sm = self._models.get(name)
        if sm is None:
            raise KeyError(
                f"unknown model {name!r} (registered: "
                f"{sorted(self.names())})")
        return sm

    def submit(self, name, features, deadline_s=None, wait=True):
        """Route one request. Once ENQUEUED, a request completes on the
        version it was enqueued against even if a swap lands mid-flight
        (the drain contract). A request that instead loses the
        resolve/enqueue race against a swap — the old version closed
        between routing and enqueue — is transparently re-routed to the
        new version: a rolling swap must never surface as a 5xx.
        wait=False returns the InferenceRequest at enqueue (the swap
        re-route still covers the ENQUEUE race; the returned handle
        then completes on its version)."""
        from deeplearning4j_tpu.serving.queue import ServingClosedError

        feats = np.asarray(features)
        try:
            return self.model(name).submit(feats, deadline_s=deadline_s,
                                           wait=wait)
        except ServingClosedError:
            return self.model(name).submit(feats, deadline_s=deadline_s,
                                           wait=wait)

    # -- introspection / lifecycle --------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._models) + sorted(self._sequences)

    def __contains__(self, name):
        with self._lock:
            return name in self._models or name in self._sequences

    def describe(self):
        """The multi-model policy table (docs/SERVING.md); sequence
        models ride along with ``"kind": "sequence"`` rows."""
        with self._lock:
            models = list(self._models.values())
            seqs = list(self._sequences.values())
        table = {sm.name: sm.policy() for sm in models}
        table.update({sm.name: sm.policy() for sm in seqs})
        return table

    def metrics_snapshot(self):
        """One JSON-safe observability snapshot: the process-wide
        registry (training + serving + AOT instruments, the same data
        /metrics exposes) plus a per-served-model serving view (queue
        stats, depth, occupancy). The programmatic twin of
        ``GET /metrics`` (docs/OBSERVABILITY.md).

        Schema: the PR 13 keys (``registry``, ``models``) are stable —
        bench.py consumes them unchanged; the fleet view is ADDITIVE:
        ``sequences`` (per sequence model: queue depth + live slots +
        slot-occupancy summary, the per-replica row
        serving/fleet.py aggregates)."""
        from deeplearning4j_tpu.runtime import telemetry

        with self._lock:
            models = list(self._models.values())
            seqs = list(self._sequences.values())
        per_model = {}
        for sm in models:
            # a snapshot is a READ: never build the lazy batcher (that
            # would spawn its scheduler thread, or raise on a closed
            # instance racing a swap) — an idle model reports as such
            b = sm.pi._batcher
            if b is None:
                per_model[sm.name] = {"version": sm.version,
                                      "stats": None, "queue_depth": 0,
                                      "occupancy": {"dispatches": 0,
                                                    "mean_occupancy":
                                                        None,
                                                    "histogram": {}}}
                continue
            per_model[sm.name] = {
                "version": sm.version,
                "stats": dict(b.stats),
                "queue_depth": b.depth,
                "occupancy": b.occupancy_summary(),
            }
        per_seq = {}
        for sm in seqs:
            sched = sm.scheduler
            per_seq[sm.name] = {
                "version": sm.version,
                "stats": dict(sched.stats),
                "queue_depth": sched.depth,
                "active_slots": sched.active_slots,
                "slot_occupancy": sched.occupancy_summary(),
            }
        return {"registry": telemetry.get_registry().snapshot(),
                "models": per_model,
                "sequences": per_seq}

    def warm_all(self):
        """(Re)warm every registered model (one-shot AND sequence) —
        the HTTP tier's /healthz warmup hook: cache hits are cheap, so
        gating readiness on this is safe even when registration
        already precompiled."""
        with self._lock:
            models = list(self._models.values())
            seqs = list(self._sequences.values())
        out = {sm.name: sm.warm() for sm in models}
        out.update({sm.name: sm.warm() for sm in seqs})
        return out

    def close(self, drain=True):
        with self._lock:
            models = list(self._models.values())
            seqs = list(self._sequences.values())
            self._models.clear()
            self._sequences.clear()
        for sm in models:
            sm.close(drain=drain)
        for sm in seqs:
            sm.close(drain=drain)

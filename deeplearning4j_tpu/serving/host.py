"""Multi-model host: name -> served version, with rolling swap.

Each registered model is a ``ServedModel``: the network, its dtype /
quantization policy, its batch buckets, and a BATCHED-mode
``ParallelInference`` (bounded queue + micro-batcher + per-bucket AOT
executable cache). Registration precompiles every bucket, so the first
real request of a model's life is served by a hot executable.

Rolling swap (``swap``): the replacement version is built and its
executables are WARMED while the current version keeps serving; only
then is the routing entry replaced (an atomic assignment under the
host lock), and the old version drains its already-queued requests
through its own hot executables. The request path never sees a cold
compile and never sees a gap — the /healthz the HTTP tier reports
stays ready throughout (docs/SERVING.md "Rolling swap").
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = ["ServedModel", "ModelHost"]


class ServedModel:
    """One (name, version) entry: network + policy + its BATCHED-mode
    ParallelInference. Build through ModelHost.register/swap."""

    def __init__(self, name, version, network, mesh=None,
                 batchBuckets=None, int8=False, queueLimit=64,
                 maxWaitMs=2.0, clock=None):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        self.name = str(name)
        self.version = int(version)
        self.network = network
        self.int8 = bool(int8)
        self.pi = ParallelInference(
            network, mesh=mesh, batchBuckets=batchBuckets,
            inferenceMode="BATCHED", queueLimit=queueLimit,
            maxWaitMs=maxWaitMs, int8=int8, clock=clock,
            metricsName=f"{self.name}:v{self.version}")

    @property
    def batcher(self):
        return self.pi._ensure_batcher()

    def warm(self, cache=None):
        """Precompile every batch bucket (hits are free). Returns the
        per-bucket {key, status, seconds} report."""
        return self.pi.precompile(cache=cache)

    def submit(self, features, deadline_s=None):
        """Queue one request (features [rows, ...]) and block for its
        sliced result. deadline_s bounds the WHOLE request (queue wait
        + dispatch): expiry raises DeadlineExceededError whether the
        request was still queued or the dispatcher is busy. May raise
        QueueFullError (backpressure)."""
        b = self.batcher
        deadline = None if deadline_s is None else \
            b.clock() + float(deadline_s)
        return b.submit(features, deadline=deadline, timeout=deadline_s)

    def policy(self):
        """The policy row the multi-model table reports."""
        import jax.numpy as jnp

        return {
            "model": self.name,
            "version": self.version,
            "dtype": jnp.dtype(self.network._compute_dtype).name,
            "int8": self.int8,
            "batchBuckets": list(self.pi.batchBuckets or ()),
            "queueLimit": self.pi.queueLimit,
            "maxWaitMs": self.pi.maxWaitMs,
            "exampleShape": list(self.pi.example_shape() or ()),
            "mesh": dict(
                (k, int(v)) for k, v in self.pi.mesh.shape.items()),
        }

    def close(self, drain=True):
        self.pi.close(drain=drain)
        return self


class ModelHost:
    """name -> ServedModel routing table (module docstring)."""

    def __init__(self, mesh=None, clock=None):
        self._mesh = mesh
        self._clock = clock
        self._models = {}
        self._registering = set()   # names reserved mid-register
        self._lock = threading.Lock()

    # -- registration / swap --------------------------------------------
    def register(self, name, network, *, batchBuckets=None, int8=False,
                 queueLimit=64, maxWaitMs=2.0, precompile=True):
        """Serve `network` as `name` (version 1). precompile=True (the
        production default) warms every bucket executable before the
        model is routable."""
        with self._lock:
            if name in self._models or name in self._registering:
                raise ValueError(
                    f"model {name!r} is already registered — use "
                    "swap() to roll a new version")
            # reserved so a concurrent register() of the same name
            # raises instead of silently overwriting the loser
            self._registering.add(name)
        try:
            sm = ServedModel(name, 1, network, mesh=self._mesh,
                             batchBuckets=batchBuckets, int8=int8,
                             queueLimit=queueLimit, maxWaitMs=maxWaitMs,
                             clock=self._clock)
            report = sm.warm() if precompile else None
            with self._lock:
                self._models[name] = sm
        finally:
            with self._lock:
                self._registering.discard(name)
        return {"model": name, "version": sm.version, "warm": report}

    def swap(self, name, network, **overrides):
        """Rolling swap to a new version of `name`.

        Sequence: (1) build the replacement with the current policy
        (override any knob by keyword), (2) WARM its bucket executables
        while the current version keeps serving, (3) install it
        atomically, (4) drain the old version — requests already queued
        complete on the version they were enqueued against, through its
        own hot executables. No cold compile ever lands on the request
        path and no request is dropped.
        """
        with self._lock:
            old = self._models.get(name)
            if old is None:
                raise KeyError(
                    f"unknown model {name!r}: register() it first "
                    f"(registered: {sorted(self._models)})")
        pol = old.policy()
        kw = {"batchBuckets": tuple(pol["batchBuckets"]) or None,
              "int8": pol["int8"], "queueLimit": pol["queueLimit"],
              "maxWaitMs": pol["maxWaitMs"]}
        kw.update(overrides)
        new = ServedModel(name, old.version + 1, network,
                          mesh=self._mesh, clock=self._clock, **kw)
        t0 = time.perf_counter()
        report = new.warm()          # old version is still serving
        warm_s = time.perf_counter() - t0
        with self._lock:
            self._models[name] = new  # atomic routing flip
        old.close(drain=True)         # queued requests finish on OLD
        return {"model": name, "version": new.version,
                "warm": report, "warm_s": round(warm_s, 3)}

    # -- request path ---------------------------------------------------
    def model(self, name):
        with self._lock:
            sm = self._models.get(name)
        if sm is None:
            raise KeyError(
                f"unknown model {name!r} (registered: "
                f"{sorted(self.names())})")
        return sm

    def submit(self, name, features, deadline_s=None):
        """Route one request. Once ENQUEUED, a request completes on the
        version it was enqueued against even if a swap lands mid-flight
        (the drain contract). A request that instead loses the
        resolve/enqueue race against a swap — the old version closed
        between routing and enqueue — is transparently re-routed to the
        new version: a rolling swap must never surface as a 5xx."""
        from deeplearning4j_tpu.serving.queue import ServingClosedError

        feats = np.asarray(features)
        try:
            return self.model(name).submit(feats, deadline_s=deadline_s)
        except ServingClosedError:
            return self.model(name).submit(feats, deadline_s=deadline_s)

    # -- introspection / lifecycle --------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._models)

    def __contains__(self, name):
        with self._lock:
            return name in self._models

    def describe(self):
        """The multi-model policy table (docs/SERVING.md)."""
        with self._lock:
            models = list(self._models.values())
        return {sm.name: sm.policy() for sm in models}

    def metrics_snapshot(self):
        """One JSON-safe observability snapshot: the process-wide
        registry (training + serving + AOT instruments, the same data
        /metrics exposes) plus a per-served-model serving view (queue
        stats, depth, occupancy). The programmatic twin of
        ``GET /metrics`` (docs/OBSERVABILITY.md)."""
        from deeplearning4j_tpu.runtime import telemetry

        with self._lock:
            models = list(self._models.values())
        per_model = {}
        for sm in models:
            # a snapshot is a READ: never build the lazy batcher (that
            # would spawn its scheduler thread, or raise on a closed
            # instance racing a swap) — an idle model reports as such
            b = sm.pi._batcher
            if b is None:
                per_model[sm.name] = {"version": sm.version,
                                      "stats": None, "queue_depth": 0,
                                      "occupancy": {"dispatches": 0,
                                                    "mean_occupancy":
                                                        None,
                                                    "histogram": {}}}
                continue
            per_model[sm.name] = {
                "version": sm.version,
                "stats": dict(b.stats),
                "queue_depth": b.depth,
                "occupancy": b.occupancy_summary(),
            }
        return {"registry": telemetry.get_registry().snapshot(),
                "models": per_model}

    def warm_all(self):
        """(Re)warm every registered model — the HTTP tier's /healthz
        warmup hook: cache hits are cheap, so gating readiness on this
        is safe even when registration already precompiled."""
        with self._lock:
            models = list(self._models.values())
        return {sm.name: sm.warm() for sm in models}

    def close(self, drain=True):
        with self._lock:
            models = list(self._models.values())
            self._models.clear()
        for sm in models:
            sm.close(drain=drain)

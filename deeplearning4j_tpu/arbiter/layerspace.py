"""Network configuration spaces — the arbiter DSL over layer configs.

Reference: arbiter-deeplearning4j org.deeplearning4j.arbiter.
MultiLayerSpace + layers.DenseLayerSpace/OutputLayerSpace/
ConvolutionLayerSpace (Builder DSL where any hyperparameter can be a
fixed value or a ParameterSpace). Upstream materializes a
MultiLayerConfiguration from a double[] chromosome; here the space
flattens to the SAME named-ParameterSpace dict every generator
(random/grid/genetic) already consumes, and `modelBuilder` closes the
loop for LocalOptimizationRunner — so one DSL serves all three search
strategies with no chromosome plumbing.

    space = (MultiLayerSpace.Builder()
             .seed(7)
             .learningRate(ContinuousParameterSpace(1e-4, 1e-1, log=True))
             .addLayer(DenseLayerSpace(nIn=6,
                                       nOut=IntegerParameterSpace(4, 32),
                                       activation=DiscreteParameterSpace(
                                           "relu", "tanh")))
             .addLayer(OutputLayerSpace(nOut=2, activation="softmax"))
             .build())
    gen = RandomSearchGenerator(space.parameterSpaces())
    runner = LocalOptimizationRunner(conf, space.modelBuilder, train)
"""

from __future__ import annotations

from deeplearning4j_tpu.arbiter.spaces import ParameterSpace


class LayerSpace:
    """One layer whose constructor kwargs may be fixed values or
    ParameterSpaces. Generic form: LayerSpace(DenseLayer, nOut=...);
    the named subclasses below mirror the upstream class names."""

    def __init__(self, layer_cls, **kwargs):
        self.layer_cls = layer_cls
        self.kwargs = kwargs

    def _spaces(self, index):
        return {f"{index}_{k}": v for k, v in self.kwargs.items()
                if isinstance(v, ParameterSpace)}

    def materialize(self, index, candidate):
        kw = {k: (candidate[f"{index}_{k}"]
                  if isinstance(v, ParameterSpace) else v)
              for k, v in self.kwargs.items()}
        return self.layer_cls(**kw)


class DenseLayerSpace(LayerSpace):
    def __init__(self, **kwargs):
        from deeplearning4j_tpu.nn import DenseLayer

        super().__init__(DenseLayer, **kwargs)


class OutputLayerSpace(LayerSpace):
    def __init__(self, **kwargs):
        from deeplearning4j_tpu.nn import OutputLayer

        super().__init__(OutputLayer, **kwargs)


class ConvolutionLayerSpace(LayerSpace):
    def __init__(self, **kwargs):
        from deeplearning4j_tpu.nn import ConvolutionLayer

        super().__init__(ConvolutionLayer, **kwargs)


class MultiLayerSpace:
    """Built space: parameterSpaces() feeds any candidate generator;
    modelBuilder(candidate) is the LocalOptimizationRunner callback."""

    class Builder:
        def __init__(self):
            self._layers = []
            self._seed = 12345
            self._lr = 1e-3
            self._updater_factory = None
            self._input_type = None

        def seed(self, s):
            self._seed = int(s)
            return self

        def learningRate(self, lr):
            """Fixed float or a ParameterSpace (exposed as 'learningRate'
            in the candidate dict)."""
            self._lr = lr
            return self

        def updater(self, factory):
            """Callable lr -> updater instance (default: Adam)."""
            self._updater_factory = factory
            return self

        def addLayer(self, layer_space):
            if not isinstance(layer_space, LayerSpace):
                raise TypeError("addLayer expects a LayerSpace")
            self._layers.append(layer_space)
            return self

        def setInputType(self, input_type):
            self._input_type = input_type
            return self

        def build(self):
            if not self._layers:
                raise ValueError("MultiLayerSpace needs at least one layer")
            return MultiLayerSpace(self)

    def __init__(self, b):
        self._layers = list(b._layers)
        self._seed = b._seed
        self._lr = b._lr
        self._updater_factory = b._updater_factory
        self._input_type = b._input_type

    def parameterSpaces(self) -> dict:
        out = {}
        if isinstance(self._lr, ParameterSpace):
            out["learningRate"] = self._lr
        for i, ls in enumerate(self._layers):
            out.update(ls._spaces(i))
        if not out:
            raise ValueError(
                "no ParameterSpaces in this MultiLayerSpace — every "
                "hyperparameter is fixed, there is nothing to search")
        return out

    def modelBuilder(self, candidate: dict):
        from deeplearning4j_tpu.nn import (
            Adam, MultiLayerNetwork, NeuralNetConfiguration)

        lr = candidate.get("learningRate", self._lr)
        factory = self._updater_factory or Adam
        builder = (NeuralNetConfiguration.Builder()
                   .seed(self._seed).updater(factory(lr)).list())
        for i, ls in enumerate(self._layers):
            builder.layer(ls.materialize(i, candidate))
        if self._input_type is not None:
            builder.setInputType(self._input_type)
        return MultiLayerNetwork(builder.build()).init()


class ComputationGraphSpace:
    """Graph-topology search space (reference: arbiter-deeplearning4j
    org.deeplearning4j.arbiter.ComputationGraphSpace). Same flattening
    contract as MultiLayerSpace, but hyperparameters are keyed by vertex
    NAME ("dense_nOut") instead of layer index."""

    class Builder:
        def __init__(self):
            self._inputs = []
            self._layers = []      # (name, LayerSpace, input names)
            self._outputs = []
            self._input_types = None
            self._seed = 12345
            self._lr = 1e-3
            self._updater_factory = None

        def seed(self, s):
            self._seed = int(s)
            return self

        def learningRate(self, lr):
            self._lr = lr
            return self

        def updater(self, factory):
            self._updater_factory = factory
            return self

        def addInputs(self, *names):
            self._inputs.extend(names)
            return self

        def addLayer(self, name, layer_space, *inputs):
            if not isinstance(layer_space, LayerSpace):
                raise TypeError("addLayer expects a LayerSpace")
            self._layers.append((name, layer_space, inputs))
            return self

        def setOutputs(self, *names):
            self._outputs = list(names)
            return self

        def setInputTypes(self, *types):
            self._input_types = types
            return self

        def build(self):
            if not self._inputs or not self._outputs or not self._layers:
                raise ValueError("ComputationGraphSpace needs addInputs, "
                                 "addLayer, and setOutputs")
            return ComputationGraphSpace(self)

    def __init__(self, b):
        self._inputs = list(b._inputs)
        self._layers = list(b._layers)
        self._outputs = list(b._outputs)
        self._input_types = b._input_types
        self._seed = b._seed
        self._lr = b._lr
        self._updater_factory = b._updater_factory

    def parameterSpaces(self) -> dict:
        out = {}
        if isinstance(self._lr, ParameterSpace):
            out["learningRate"] = self._lr
        for name, ls, _ in self._layers:
            out.update(ls._spaces(name))
        if not out:
            raise ValueError(
                "no ParameterSpaces in this ComputationGraphSpace — every "
                "hyperparameter is fixed, there is nothing to search")
        return out

    def modelBuilder(self, candidate: dict):
        from deeplearning4j_tpu.nn import (
            Adam, ComputationGraph, NeuralNetConfiguration)

        lr = candidate.get("learningRate", self._lr)
        factory = self._updater_factory or Adam
        gb = (NeuralNetConfiguration.Builder()
              .seed(self._seed).updater(factory(lr)).graphBuilder()
              .addInputs(*self._inputs))
        for name, ls, inputs in self._layers:
            gb.addLayer(name, ls.materialize(name, candidate), *inputs)
        gb.setOutputs(*self._outputs)
        if self._input_types is not None:
            gb.setInputTypes(*self._input_types)
        return ComputationGraph(gb.build()).init()

"""Arbiter — hyperparameter optimization.

Reference: the Arbiter module (org.deeplearning4j.arbiter): ParameterSpace,
CandidateGenerator (random/grid/genetic), ScoreFunction, termination conditions and
LocalOptimizationRunner.
"""

from deeplearning4j_tpu.arbiter.spaces import (
    ParameterSpace,
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    IntegerParameterSpace,
)
from deeplearning4j_tpu.arbiter.layerspace import (
    LayerSpace,
    DenseLayerSpace,
    OutputLayerSpace,
    ConvolutionLayerSpace,
    MultiLayerSpace,
    ComputationGraphSpace,
)
from deeplearning4j_tpu.arbiter.optimize import (
    RandomSearchGenerator,
    GridSearchCandidateGenerator,
    GeneticSearchCandidateGenerator,
    TestSetLossScoreFunction,
    EvaluationScoreFunction,
    MaxCandidatesCondition,
    MaxTimeCondition,
    OptimizationConfiguration,
    LocalOptimizationRunner,
    OptimizationResult,
    CandidateResult,
)

__all__ = [
    "ParameterSpace", "ContinuousParameterSpace", "DiscreteParameterSpace",
    "IntegerParameterSpace", "RandomSearchGenerator",
    "GridSearchCandidateGenerator", "GeneticSearchCandidateGenerator",
    "TestSetLossScoreFunction",
    "EvaluationScoreFunction", "MaxCandidatesCondition", "MaxTimeCondition",
    "OptimizationConfiguration", "LocalOptimizationRunner",
    "OptimizationResult", "CandidateResult", "LayerSpace",
    "DenseLayerSpace", "OutputLayerSpace", "ConvolutionLayerSpace",
    "MultiLayerSpace", "ComputationGraphSpace",
]

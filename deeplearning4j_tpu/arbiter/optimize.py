"""Hyperparameter search: generators, score functions, runner.

Reference: org.deeplearning4j.arbiter.optimize — CandidateGenerator
(RandomSearchGenerator, GridSearchCandidateGenerator), ScoreFunction
(TestSetLossScoreFunction, EvaluationScoreFunction), termination conditions
(MaxCandidatesCondition, MaxTimeCondition) and LocalOptimizationRunner.

Design difference from the reference: instead of the MultiLayerSpace config
DSL, a candidate is a plain dict sampled from named ParameterSpaces and the
user supplies `modelBuilder(candidate) -> MultiLayerNetwork/ComputationGraph`.
That keeps the search loop orthogonal to the (already fluent) config builders
— and under jit, candidates with identical layer shapes reuse the same
compiled train step, so a sweep over learning rates costs ONE XLA compile.
"""

from __future__ import annotations

import itertools
import time

from deeplearning4j_tpu.arbiter.spaces import ParameterSpace


# ---------------------------------------------------------------------------
# candidate generators
# ---------------------------------------------------------------------------

class RandomSearchGenerator:
    def __init__(self, parameterSpaces: dict, seed: int = 12345):
        import numpy as np

        for k, v in parameterSpaces.items():
            if not isinstance(v, ParameterSpace):
                raise TypeError(f"space '{k}' is not a ParameterSpace")
        self.spaces = dict(parameterSpaces)
        self._rng = np.random.RandomState(seed)

    def hasMore(self) -> bool:
        return True  # bounded by termination conditions

    def next(self) -> dict:
        return {k: s.sample(self._rng) for k, s in self.spaces.items()}


class GeneticSearchCandidateGenerator:
    """Population-based search (reference: arbiter.optimize.generator.
    GeneticSearchCandidateGenerator + the genetic package's
    ChromosomeFactory / GeneticSelectionOperator / crossover + mutation
    operators). A genome is one unit-interval coordinate per named
    space, decoded through ParameterSpace.from_unit — crossover and
    mutation stay space-agnostic.

    Generation 0 is uniform-random. Breeding: tournament selection over
    every scored individual so far ((mu+lambda)-style — elites persist
    in the parent pool instead of being re-emitted for re-evaluation,
    unlike upstream's explicit elitism, which re-scores survivors),
    uniform crossover, per-gene gaussian mutation. The runner feeds
    scores back through reportResult(); without feedback it degrades to
    random search (a loud degradation: breeding raises)."""

    def __init__(self, parameterSpaces: dict, populationSize: int = 20,
                 crossoverRate: float = 0.85, mutationRate: float = 0.15,
                 mutationStdev: float = 0.15, tournamentSize: int = 3,
                 seed: int = 12345):
        import numpy as np

        for k, v in parameterSpaces.items():
            if not isinstance(v, ParameterSpace):
                raise TypeError(f"space '{k}' is not a ParameterSpace")
        if populationSize < 2:
            raise ValueError("populationSize must be >= 2")
        if tournamentSize < 1:
            raise ValueError("tournamentSize must be >= 1")
        self.spaces = dict(parameterSpaces)
        self._names = list(self.spaces)
        self.populationSize = int(populationSize)
        self.crossoverRate = float(crossoverRate)
        self.mutationRate = float(mutationRate)
        self.mutationStdev = float(mutationStdev)
        self.tournamentSize = int(tournamentSize)
        self._rng = np.random.RandomState(seed)
        self._pending = [self._rng.uniform(size=len(self._names))
                         for _ in range(self.populationSize)]
        self._awaiting = []   # emitted genomes, FIFO, waiting on scores
        self._scored = []     # (genome, fitness) — fitness maximized
        self.generation = 0

    def hasMore(self) -> bool:
        return True  # bounded by termination conditions

    def _decode(self, genome) -> dict:
        return {k: self.spaces[k].from_unit(u)
                for k, u in zip(self._names, genome)}

    def next(self) -> dict:
        if not self._pending:
            self._breed()
        g = self._pending.pop(0)
        self._awaiting.append(g)
        return self._decode(g)

    def reportResult(self, candidate: dict, score: float, minimize: bool):
        """Fitness feedback from the runner, FIFO-paired with next().
        Failed candidates arrive as +/-inf and become -inf fitness."""
        import math as _math

        if not self._awaiting:
            raise RuntimeError("reportResult without an outstanding "
                               "candidate (next() not called?)")
        g = self._awaiting.pop(0)
        if candidate != self._decode(g):
            raise ValueError(
                "reportResult candidate does not match the oldest "
                "outstanding next() candidate — results must be "
                "reported in emission order (FIFO)")
        fit = -score if minimize else score
        if not _math.isfinite(fit):
            fit = float("-inf")
        self._scored.append((g, fit))

    def _breed(self):
        import numpy as np

        if not self._scored:
            raise RuntimeError(
                "GeneticSearchCandidateGenerator needs score feedback to "
                "breed generation 1+ — run it under a runner that calls "
                "reportResult (LocalOptimizationRunner does)")
        rng = self._rng
        n_genes = len(self._names)
        # (mu+lambda) truncation: parents come from the best
        # populationSize individuals EVER scored, not the whole history
        # — tournament over an ever-growing pool dilutes selection
        # pressure to nothing by late generations
        pool = sorted(self._scored, key=lambda gf: gf[1],
                      reverse=True)[:self.populationSize]
        # anneal the mutation step: explore early, refine late
        stdev = self.mutationStdev / (1.0 + 0.3 * self.generation)

        def tournament():
            idx = rng.randint(0, len(pool),
                              size=min(self.tournamentSize, len(pool)))
            best = max(idx, key=lambda i: pool[i][1])
            return pool[best][0]

        offspring = []
        while len(offspring) < self.populationSize:
            a, b = tournament(), tournament()
            if rng.rand() < self.crossoverRate:
                pick = rng.rand(n_genes) < 0.5  # uniform crossover
                child = np.where(pick, a, b).astype(float)
            else:
                child = np.array(a, dtype=float)
            mut = rng.rand(n_genes) < self.mutationRate
            child = child + mut * rng.normal(0.0, stdev, size=n_genes)
            # decode clamps to [0,1]; clamp here too so genomes stay in
            # the unit cube for future crossovers
            offspring.append(np.clip(child, 0.0, 1.0))
        self._pending = offspring
        self.generation += 1


class GridSearchCandidateGenerator:
    def __init__(self, parameterSpaces: dict, discretizationCount: int = 3):
        self.spaces = dict(parameterSpaces)
        axes = [(k, s.grid(discretizationCount)) for k, s in self.spaces.items()]
        names = [k for k, _ in axes]
        self._candidates = [dict(zip(names, combo))
                            for combo in itertools.product(*(vs for _, vs in axes))]
        self._i = 0

    def __len__(self):
        return len(self._candidates)

    def hasMore(self) -> bool:
        return self._i < len(self._candidates)

    def next(self) -> dict:
        c = self._candidates[self._i]
        self._i += 1
        return c


# ---------------------------------------------------------------------------
# score functions
# ---------------------------------------------------------------------------

class TestSetLossScoreFunction:
    """Held-out loss; minimized (reference:
    arbiter.scoring.impl.TestSetLossScoreFunction)."""

    __test__ = False  # not a pytest class despite the Test prefix

    def __init__(self, testData):
        self.testData = testData

    def minimize(self) -> bool:
        return True

    def score(self, model) -> float:
        from deeplearning4j_tpu.optimize.earlystopping import DataSetLossCalculator

        return DataSetLossCalculator(self.testData).calculateScore(model)


class EvaluationScoreFunction:
    """Held-out classification metric; maximized (reference:
    arbiter.scoring.impl.EvaluationScoreFunction)."""

    def __init__(self, testData, metric: str = "accuracy"):
        self.testData = testData
        self.metric = metric

    def minimize(self) -> bool:
        return False

    def score(self, model) -> float:
        e = model.evaluate(self.testData)
        return float(getattr(e, self.metric)())


# ---------------------------------------------------------------------------
# termination conditions
# ---------------------------------------------------------------------------

class MaxCandidatesCondition:
    def __init__(self, maxCandidates: int):
        self.maxCandidates = int(maxCandidates)

    def initialize(self):
        pass

    def terminate(self, numCandidates: int) -> bool:
        return numCandidates >= self.maxCandidates


class MaxTimeCondition:
    def __init__(self, duration: float, unit: str = "seconds"):
        mult = {"seconds": 1.0, "minutes": 60.0, "hours": 3600.0}[unit]
        self.maxSeconds = float(duration) * mult
        self._start = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, numCandidates: int) -> bool:
        return (time.perf_counter() - self._start) >= self.maxSeconds


# ---------------------------------------------------------------------------
# configuration + runner
# ---------------------------------------------------------------------------

class CandidateResult:
    def __init__(self, index, candidate, score, model=None, error=None):
        self.index = index
        self.candidate = candidate
        self.score = score
        self.model = model
        self.error = error

    def __repr__(self):
        return f"CandidateResult(#{self.index}, {self.candidate}, score={self.score})"


class OptimizationResult:
    def __init__(self, best: CandidateResult, results: list):
        self.best = best
        self.results = results

    def bestCandidate(self) -> dict:
        return self.best.candidate

    def bestScore(self) -> float:
        return self.best.score

    def bestModel(self):
        return self.best.model


class OptimizationConfiguration:
    class Builder:
        def __init__(self):
            self._gen = None
            self._score = None
            self._conds = [MaxCandidatesCondition(10)]
            self._epochs = 1

        def candidateGenerator(self, gen):
            self._gen = gen
            return self

        def scoreFunction(self, fn):
            self._score = fn
            return self

        def terminationConditions(self, *conds):
            self._conds = list(conds)
            return self

        def epochsPerCandidate(self, n: int):
            self._epochs = int(n)
            return self

        def build(self):
            if self._gen is None or self._score is None:
                raise ValueError("candidateGenerator and scoreFunction are required")
            return OptimizationConfiguration(self)

    def __init__(self, b):
        self.candidateGenerator = b._gen
        self.scoreFunction = b._score
        self.terminationConditions = b._conds
        self.epochsPerCandidate = b._epochs


class LocalOptimizationRunner:
    """Sequential candidate evaluation on the local chip (reference:
    arbiter LocalOptimizationRunner). A failed candidate records its error
    and the search continues, like the reference's failed-candidate status."""

    def __init__(self, configuration: OptimizationConfiguration, modelBuilder,
                 trainData):
        self.conf = configuration
        self.modelBuilder = modelBuilder
        self.trainData = trainData

    def execute(self) -> OptimizationResult:
        conf = self.conf
        for c in conf.terminationConditions:
            c.initialize()
        results = []
        best = None
        minimize = conf.scoreFunction.minimize()
        n = 0
        while conf.candidateGenerator.hasMore():
            if any(c.terminate(n) for c in conf.terminationConditions):
                break
            candidate = conf.candidateGenerator.next()
            try:
                model = self.modelBuilder(candidate)
                model.fit(self.trainData, epochs=conf.epochsPerCandidate)
                score = conf.scoreFunction.score(model)
                res = CandidateResult(n, candidate, score, model)
            except Exception as e:  # candidate failure != search failure
                res = CandidateResult(n, candidate,
                                      float("inf") if minimize else float("-inf"),
                                      error=e)
            results.append(res)
            if hasattr(conf.candidateGenerator, "reportResult"):
                # feedback-driven generators (genetic) learn from every
                # candidate, including failures (scored +/-inf above)
                conf.candidateGenerator.reportResult(
                    candidate, res.score, minimize)
            if res.error is None and (
                    best is None or
                    (res.score < best.score if minimize else res.score > best.score)):
                best = res
            n += 1
        if best is None:
            raise RuntimeError(
                "no candidate completed successfully; first error: "
                f"{results[0].error if results else 'no candidates generated'}")
        return OptimizationResult(best, results)

"""Hyperparameter search: generators, score functions, runner.

Reference: org.deeplearning4j.arbiter.optimize — CandidateGenerator
(RandomSearchGenerator, GridSearchCandidateGenerator), ScoreFunction
(TestSetLossScoreFunction, EvaluationScoreFunction), termination conditions
(MaxCandidatesCondition, MaxTimeCondition) and LocalOptimizationRunner.

Design difference from the reference: instead of the MultiLayerSpace config
DSL, a candidate is a plain dict sampled from named ParameterSpaces and the
user supplies `modelBuilder(candidate) -> MultiLayerNetwork/ComputationGraph`.
That keeps the search loop orthogonal to the (already fluent) config builders
— and under jit, candidates with identical layer shapes reuse the same
compiled train step, so a sweep over learning rates costs ONE XLA compile.
"""

from __future__ import annotations

import itertools
import time

from deeplearning4j_tpu.arbiter.spaces import ParameterSpace


# ---------------------------------------------------------------------------
# candidate generators
# ---------------------------------------------------------------------------

class RandomSearchGenerator:
    def __init__(self, parameterSpaces: dict, seed: int = 12345):
        import numpy as np

        for k, v in parameterSpaces.items():
            if not isinstance(v, ParameterSpace):
                raise TypeError(f"space '{k}' is not a ParameterSpace")
        self.spaces = dict(parameterSpaces)
        self._rng = np.random.RandomState(seed)

    def hasMore(self) -> bool:
        return True  # bounded by termination conditions

    def next(self) -> dict:
        return {k: s.sample(self._rng) for k, s in self.spaces.items()}


class GridSearchCandidateGenerator:
    def __init__(self, parameterSpaces: dict, discretizationCount: int = 3):
        self.spaces = dict(parameterSpaces)
        axes = [(k, s.grid(discretizationCount)) for k, s in self.spaces.items()]
        names = [k for k, _ in axes]
        self._candidates = [dict(zip(names, combo))
                            for combo in itertools.product(*(vs for _, vs in axes))]
        self._i = 0

    def __len__(self):
        return len(self._candidates)

    def hasMore(self) -> bool:
        return self._i < len(self._candidates)

    def next(self) -> dict:
        c = self._candidates[self._i]
        self._i += 1
        return c


# ---------------------------------------------------------------------------
# score functions
# ---------------------------------------------------------------------------

class TestSetLossScoreFunction:
    """Held-out loss; minimized (reference:
    arbiter.scoring.impl.TestSetLossScoreFunction)."""

    __test__ = False  # not a pytest class despite the Test prefix

    def __init__(self, testData):
        self.testData = testData

    def minimize(self) -> bool:
        return True

    def score(self, model) -> float:
        from deeplearning4j_tpu.optimize.earlystopping import DataSetLossCalculator

        return DataSetLossCalculator(self.testData).calculateScore(model)


class EvaluationScoreFunction:
    """Held-out classification metric; maximized (reference:
    arbiter.scoring.impl.EvaluationScoreFunction)."""

    def __init__(self, testData, metric: str = "accuracy"):
        self.testData = testData
        self.metric = metric

    def minimize(self) -> bool:
        return False

    def score(self, model) -> float:
        e = model.evaluate(self.testData)
        return float(getattr(e, self.metric)())


# ---------------------------------------------------------------------------
# termination conditions
# ---------------------------------------------------------------------------

class MaxCandidatesCondition:
    def __init__(self, maxCandidates: int):
        self.maxCandidates = int(maxCandidates)

    def initialize(self):
        pass

    def terminate(self, numCandidates: int) -> bool:
        return numCandidates >= self.maxCandidates


class MaxTimeCondition:
    def __init__(self, duration: float, unit: str = "seconds"):
        mult = {"seconds": 1.0, "minutes": 60.0, "hours": 3600.0}[unit]
        self.maxSeconds = float(duration) * mult
        self._start = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, numCandidates: int) -> bool:
        return (time.perf_counter() - self._start) >= self.maxSeconds


# ---------------------------------------------------------------------------
# configuration + runner
# ---------------------------------------------------------------------------

class CandidateResult:
    def __init__(self, index, candidate, score, model=None, error=None):
        self.index = index
        self.candidate = candidate
        self.score = score
        self.model = model
        self.error = error

    def __repr__(self):
        return f"CandidateResult(#{self.index}, {self.candidate}, score={self.score})"


class OptimizationResult:
    def __init__(self, best: CandidateResult, results: list):
        self.best = best
        self.results = results

    def bestCandidate(self) -> dict:
        return self.best.candidate

    def bestScore(self) -> float:
        return self.best.score

    def bestModel(self):
        return self.best.model


class OptimizationConfiguration:
    class Builder:
        def __init__(self):
            self._gen = None
            self._score = None
            self._conds = [MaxCandidatesCondition(10)]
            self._epochs = 1

        def candidateGenerator(self, gen):
            self._gen = gen
            return self

        def scoreFunction(self, fn):
            self._score = fn
            return self

        def terminationConditions(self, *conds):
            self._conds = list(conds)
            return self

        def epochsPerCandidate(self, n: int):
            self._epochs = int(n)
            return self

        def build(self):
            if self._gen is None or self._score is None:
                raise ValueError("candidateGenerator and scoreFunction are required")
            return OptimizationConfiguration(self)

    def __init__(self, b):
        self.candidateGenerator = b._gen
        self.scoreFunction = b._score
        self.terminationConditions = b._conds
        self.epochsPerCandidate = b._epochs


class LocalOptimizationRunner:
    """Sequential candidate evaluation on the local chip (reference:
    arbiter LocalOptimizationRunner). A failed candidate records its error
    and the search continues, like the reference's failed-candidate status."""

    def __init__(self, configuration: OptimizationConfiguration, modelBuilder,
                 trainData):
        self.conf = configuration
        self.modelBuilder = modelBuilder
        self.trainData = trainData

    def execute(self) -> OptimizationResult:
        conf = self.conf
        for c in conf.terminationConditions:
            c.initialize()
        results = []
        best = None
        minimize = conf.scoreFunction.minimize()
        n = 0
        while conf.candidateGenerator.hasMore():
            if any(c.terminate(n) for c in conf.terminationConditions):
                break
            candidate = conf.candidateGenerator.next()
            try:
                model = self.modelBuilder(candidate)
                model.fit(self.trainData, epochs=conf.epochsPerCandidate)
                score = conf.scoreFunction.score(model)
                res = CandidateResult(n, candidate, score, model)
            except Exception as e:  # candidate failure != search failure
                res = CandidateResult(n, candidate,
                                      float("inf") if minimize else float("-inf"),
                                      error=e)
            results.append(res)
            if res.error is None and (
                    best is None or
                    (res.score < best.score if minimize else res.score > best.score)):
                best = res
            n += 1
        if best is None:
            raise RuntimeError(
                "no candidate completed successfully; first error: "
                f"{results[0].error if results else 'no candidates generated'}")
        return OptimizationResult(best, results)

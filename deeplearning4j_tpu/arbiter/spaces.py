"""Hyperparameter spaces.

Reference: org.deeplearning4j.arbiter.optimize.parameter —
ContinuousParameterSpace, DiscreteParameterSpace, IntegerParameterSpace.
Each space can draw a random sample or enumerate a grid discretization.
"""

from __future__ import annotations

import math

import numpy as np


class ParameterSpace:
    def sample(self, rng: np.random.RandomState):
        raise NotImplementedError

    def grid(self, n: int) -> list:
        """n representative values for grid search."""
        raise NotImplementedError

    def from_unit(self, u: float):
        """Decode a unit-interval coordinate u to a value; u is clamped
        to [0, 1] (mutation/crossover arithmetic can overshoot).

        The genetic generator represents every candidate as a genome of
        unit coordinates (one per space) so crossover/mutation are
        space-agnostic; each space owns its decode (reference analog:
        arbiter's genetic ChromosomeFactory over double[] genes)."""
        raise NotImplementedError

    @staticmethod
    def _clamp_unit(u):
        return min(max(float(u), 0.0), 1.0)


class ContinuousParameterSpace(ParameterSpace):
    """Uniform (or log-uniform) float range."""

    def __init__(self, minValue: float, maxValue: float, log: bool = False):
        if log and minValue <= 0:
            raise ValueError("log-scale space needs minValue > 0")
        self.min = float(minValue)
        self.max = float(maxValue)
        self.log = log

    def sample(self, rng):
        if self.log:
            return float(math.exp(rng.uniform(math.log(self.min), math.log(self.max))))
        return float(rng.uniform(self.min, self.max))

    def grid(self, n):
        if n == 1:
            if self.log:  # geometric mean is the log-scale center
                return [float(math.sqrt(self.min * self.max))]
            return [0.5 * (self.min + self.max)]
        if self.log:
            return [float(v) for v in np.geomspace(self.min, self.max, n)]
        return [float(v) for v in np.linspace(self.min, self.max, n)]

    def from_unit(self, u):
        u = self._clamp_unit(u)
        if self.log:
            return float(math.exp(math.log(self.min)
                                  + u * (math.log(self.max)
                                         - math.log(self.min))))
        return float(self.min + u * (self.max - self.min))


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, *values):
        if len(values) == 1 and isinstance(values[0], (list, tuple)):
            values = tuple(values[0])
        self.values = list(values)

    def sample(self, rng):
        return self.values[rng.randint(0, len(self.values))]

    def grid(self, n):
        return list(self.values)

    def from_unit(self, u):
        # u == 1.0 maps to the last value, not one past it
        u = self._clamp_unit(u)
        return self.values[min(int(u * len(self.values)),
                               len(self.values) - 1)]


class IntegerParameterSpace(ParameterSpace):
    """Uniform integer range, inclusive on both ends."""

    def __init__(self, minValue: int, maxValue: int):
        self.min = int(minValue)
        self.max = int(maxValue)

    def sample(self, rng):
        return int(rng.randint(self.min, self.max + 1))

    def grid(self, n):
        if n >= self.max - self.min + 1:
            return list(range(self.min, self.max + 1))
        return [int(round(v)) for v in np.linspace(self.min, self.max, n)]

    def from_unit(self, u):
        u = self._clamp_unit(u)
        span = self.max - self.min + 1
        return int(self.min + min(int(u * span), span - 1))

"""The fault matrix: retry backoff, preemption resume, NaN-step guard,
data-path retry, and serving-tier health/deadline behavior
(runtime.resilience + util.sharded_checkpoint + util.httpserve).

Every fault here is INJECTED deterministically (FaultInjector /
seeded RetryPolicy) — no sleeps-and-hope, no real process kills: a
simulated preemption is the Preemption exception escaping fit(), and a
restart is a fresh net + ResilientFit pointed at the same checkpoint
dir.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSetIterator, RetryingDataSetIterator
from deeplearning4j_tpu.nn import (
    NeuralNetConfiguration, InputType, MultiLayerNetwork, DenseLayer,
    OutputLayer, Adam,
)
from deeplearning4j_tpu.optimize import ResilienceListener
from deeplearning4j_tpu.runtime.resilience import (
    FaultInjector, NonFiniteStepError, Preemption, ResilientFit,
    RetryPolicy, retry,
)
from deeplearning4j_tpu.util import sharded_checkpoint as ck

pytestmark = pytest.mark.faults


def _mlp(seed=42):
    return (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(1e-2)).activation("relu")
            .list()
            .layer(DenseLayer(nOut=16))
            .layer(OutputLayer(nOut=3, activation="softmax"))
            .setInputType(InputType.feedForward(4))
            .build())


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype("float32")
    y = np.eye(3, dtype="float32")[rng.randint(0, 3, n)]
    return x, y


def _iter(n=64, batch=16, seed=0):
    x, y = _data(n, seed)
    return DataSetIterator(x, y, batch)  # deterministic order: replayable


def _tree_equal(a, b):
    import jax

    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for u, v in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


_FAST = RetryPolicy(maxRetries=3, initialDelay=0.001, maxDelay=0.004,
                    sleep=lambda s: None)


# ----------------------------------------------------------------------
# retry backoff
# ----------------------------------------------------------------------
class TestRetry:
    def test_deterministic_jitter_and_bounds(self):
        p = RetryPolicy(maxRetries=6, initialDelay=0.05, maxDelay=0.4,
                        multiplier=2.0, jitter=0.5, seed=11)
        d1, d2 = p.delays(), RetryPolicy(
            maxRetries=6, initialDelay=0.05, maxDelay=0.4, multiplier=2.0,
            jitter=0.5, seed=11).delays()
        assert d1 == d2  # same seed -> same schedule
        assert d1 != RetryPolicy(maxRetries=6, initialDelay=0.05,
                                 maxDelay=0.4, seed=12).delays()
        for k, d in enumerate(d1, start=1):
            base = min(0.4, 0.05 * 2.0 ** (k - 1))
            assert base * 0.5 <= d <= base  # jitter band
        assert all(d <= 0.4 for d in d1)  # cap holds past the knee

    def test_retry_succeeds_after_transients_then_gives_up(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise IOError("transient")
            return "ok"

        seen = []
        assert retry(flaky, _FAST,
                     on_retry=lambda a, e, d: seen.append((a, d))) == "ok"
        assert [a for a, _ in seen] == [1, 2]
        assert seen == [(a, d) for (a, _), d in
                        zip(seen, _FAST.delays()[:2])]  # scheduled delays

        def always():
            raise IOError("permanent")

        with pytest.raises(IOError, match="permanent"):
            retry(always, _FAST)

    def test_non_matching_exception_not_retried(self):
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            retry(boom, _FAST)
        assert calls["n"] == 1


# ----------------------------------------------------------------------
# preemption-safe fit: kill mid-epoch, restart, bitwise-identical params
# ----------------------------------------------------------------------
class TestPreemptionResume:
    def test_resume_matches_uninterrupted_bitwise(self, tmp_path):
        epochs, steps_per_epoch = 3, 4  # 64/16

        # ground truth: plain fit, no harness at all
        ref = MultiLayerNetwork(_mlp()).init()
        ref.fit(_iter(), epochs=epochs)

        # run killed mid-epoch 1 (global step 7 of 12), ckpt every 2 —
        # the latest checkpoint (step 6) is OLDER than the kill point,
        # so the restart must also REDO step 7 identically
        net = MultiLayerNetwork(_mlp()).init()
        inj = FaultInjector().killAfterStep(7)
        events = ResilienceListener()
        net.setListeners(events)
        rf = ResilientFit(net, tmp_path / "ck", saveEveryNIterations=2,
                          keepLast=2, retryPolicy=_FAST, injector=inj)
        with pytest.raises(Preemption):
            rf.fit(_iter(), epochs=epochs)
        assert ("preempt", 7) in inj.events
        assert net._iteration == 7  # died mid-epoch 1
        assert ck.latest_step(tmp_path / "ck") == 6

        # "restart": fresh process state — new net, new harness, same dir
        net2 = MultiLayerNetwork(_mlp()).init()
        events2 = ResilienceListener()
        net2.setListeners(events2)
        rf2 = ResilientFit(net2, tmp_path / "ck", saveEveryNIterations=2,
                           keepLast=2, retryPolicy=_FAST)
        rf2.fit(_iter(), epochs=epochs)

        assert events2.restores == 1
        assert net2._iteration == epochs * steps_per_epoch
        _tree_equal(ref._params, net2._params)       # bitwise
        _tree_equal(ref._upd_states, net2._upd_states)

    def test_keep_last_n_rotation_and_latest_step(self, tmp_path):
        net = MultiLayerNetwork(_mlp()).init()
        rf = ResilientFit(net, tmp_path / "ck", saveEveryNIterations=1,
                          keepLast=2, retryPolicy=_FAST)
        rf.fit(_iter(), epochs=2)  # 8 saves, keep 2
        kept = sorted(p.name for p in (tmp_path / "ck").iterdir()
                      if p.name.startswith("step_"))
        assert kept == ["step_7", "step_8"]
        assert ck.latest_step(tmp_path / "ck") == 8

    def test_atomic_save_never_exposes_torn_checkpoint(self, tmp_path):
        # a staged-but-uncommitted save (preempted mid-write) must be
        # invisible to latest_step and swept by gc
        d = tmp_path / "ck"
        net = MultiLayerNetwork(_mlp()).init()
        net.fit(_iter())
        ck.ShardedModelSerializer.writeModel(net, ck.step_path(d, 4))
        torn = ck.step_path(d, 9) + ".tmp-123-456"
        (tmp_path / "ck").mkdir(exist_ok=True)
        import os

        os.makedirs(torn)
        with open(os.path.join(torn, "manifest.json"), "w") as f:
            f.write("{")  # half-written
        assert ck.latest_step(d) == 4
        restored = ck.ShardedModelSerializer.restore(ck.step_path(d, 4))
        _tree_equal(net._params, restored._params)
        ck.gc_checkpoints(d, keepLast=5)
        assert not os.path.exists(torn)

    def test_manifest_extra_roundtrip(self, tmp_path):
        net = MultiLayerNetwork(_mlp()).init()
        net.fit(_iter())
        p = ck.step_path(tmp_path, 1)
        ck.ShardedModelSerializer.writeModel(
            net, p, extra={"batch_in_epoch": 3})
        assert ck.read_manifest(p)["extra"] == {"batch_in_epoch": 3}


# ----------------------------------------------------------------------
# non-finite step guard
# ----------------------------------------------------------------------
class TestNanGuard:
    def test_poisoned_step_skipped_not_applied(self, tmp_path):
        net = MultiLayerNetwork(_mlp()).init()
        events = ResilienceListener()
        net.setListeners(events)
        inj = FaultInjector().poisonStep(2)  # third step is NaN
        rf = ResilientFit(net, injector=inj, retryPolicy=_FAST)

        import jax

        snap = {}

        class Snapshot(ResilienceListener):
            # params BEFORE the poisoned step, grabbed via the listener
            # stream (iteration 2 done == about to run step at it=2)
            def iterationDone(self, model, iteration, epoch):
                if iteration == 2:
                    snap["params"] = jax.tree_util.tree_map(
                        lambda a: np.asarray(a).copy(), model._params)

        net.addListeners(Snapshot())
        rf.fit(_iter(), epochs=1)

        assert events.skippedSteps == 1
        assert [e for e in events.events if e[0] == "skip"] \
            and events.events[0][1] == 3  # skip surfaced at iteration 3
        assert ("poison", 2) in inj.events
        assert "params" in snap
        # the NaN update was NOT applied: training continued finite
        for leaf in jax.tree_util.tree_leaves(net._params):
            assert np.isfinite(np.asarray(leaf)).all()
        assert net._iteration == 4  # all batches consumed, one skipped

    def test_params_frozen_across_skip(self):
        # sharper version of the above: compare directly around the skip
        import jax

        net = MultiLayerNetwork(_mlp()).init()
        inj = FaultInjector().poisonStep(1)
        rf = ResilientFit(net, injector=inj, retryPolicy=_FAST)
        before, after = {}, {}

        class Grab:
            def iterationDone(self, model, iteration, epoch):
                c = jax.tree_util.tree_map(
                    lambda a: np.asarray(a).copy(), model._params)
                if iteration == 1:
                    before["p"] = c
                elif iteration == 2:  # right after the skipped step
                    after["p"] = c

            def __getattr__(self, _):
                return lambda *a, **k: None

        net.setListeners(Grab())
        rf.fit(_iter(), epochs=1)
        _tree_equal(before["p"], after["p"])

    def test_consecutive_bad_steps_abort(self):
        net = MultiLayerNetwork(_mlp()).init()
        inj = FaultInjector().poisonStep(1, 2)
        rf = ResilientFit(net, injector=inj, retryPolicy=_FAST,
                          maxConsecutiveBadSteps=2)
        with pytest.raises(NonFiniteStepError, match="2 consecutive"):
            rf.fit(_iter(), epochs=1)

    def test_guard_overhead_free_path_identical(self):
        # on finite data the guarded trajectory IS the plain trajectory
        a = MultiLayerNetwork(_mlp()).init()
        a.fit(_iter(), epochs=2)
        b = MultiLayerNetwork(_mlp()).init()
        ResilientFit(b, retryPolicy=_FAST).fit(_iter(), epochs=2)
        _tree_equal(a._params, b._params)
        _tree_equal(a._upd_states, b._upd_states)


class TestParallelWrapperGuard:
    def test_guarded_dp_matches_plain_and_skips_nan(self, tmp_path):
        from deeplearning4j_tpu.parallel import ParallelWrapper

        # plain data-parallel run (8-device virtual mesh)
        ref = MultiLayerNetwork(_mlp()).init()
        ParallelWrapper(ref).fit(_iter(), epochs=2)

        # guarded run on clean data: identical trajectory
        net = MultiLayerNetwork(_mlp()).init()
        rf = ResilientFit(ParallelWrapper(net), retryPolicy=_FAST)
        rf.fit(_iter(), epochs=2)
        _tree_equal(ref._params, net._params)

        # guarded run with one poisoned step: skipped, training survives
        import jax

        net2 = MultiLayerNetwork(_mlp()).init()
        events = ResilienceListener()
        net2.setListeners(events)
        inj = FaultInjector().poisonStep(3)
        rf2 = ResilientFit(ParallelWrapper(net2), tmp_path / "ck",
                           saveEveryNIterations=4, retryPolicy=_FAST,
                           injector=inj)
        rf2.fit(_iter(), epochs=2)
        assert events.skippedSteps == 1 and events.saves == 2
        for leaf in jax.tree_util.tree_leaves(net2._params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_threshold_compression_trains_under_guard(self):
        """ISSUE 11: the threshold step is wrappable now — its residual
        rides the updater-state carry, so the non-finite guard rolls it
        back with the rest of the state on a skipped step."""
        from deeplearning4j_tpu.parallel import ParallelWrapper

        net = MultiLayerNetwork(_mlp()).init()
        pw = ParallelWrapper(net, gradient_compression="threshold",
                             threshold=1e-2)
        rf = ResilientFit(pw, retryPolicy=_FAST)
        rf.fit(_iter(), epochs=1)
        assert np.isfinite(net.score())
        assert rf.skippedSteps == 0

    def test_parameter_averaging_rejected_not_silently_replaced(self):
        # PATM's local-steps+periodic-pmean semantics live in its own
        # _fit_batch; wrapping it must refuse, not quietly run sync DP
        from deeplearning4j_tpu.parallel import (
            ParameterAveragingTrainingMaster,
        )

        net = MultiLayerNetwork(_mlp()).init()
        pm = ParameterAveragingTrainingMaster(net, averagingFrequency=5)
        rf = ResilientFit(pm, retryPolicy=_FAST)
        with pytest.raises(ValueError, match="ParameterAveraging"):
            rf.fit(_iter(), epochs=1)


# ----------------------------------------------------------------------
# data-path faults
# ----------------------------------------------------------------------
class TestDataFaults:
    def test_iterator_ioerror_retried_through_fit(self, tmp_path):
        net = MultiLayerNetwork(_mlp()).init()
        inj = FaultInjector().failOnBatch(1, times=2)
        rf = ResilientFit(net, injector=inj, retryPolicy=_FAST)
        rf.fit(inj.wrapIterator(_iter()), epochs=1)
        assert net._iteration == 4  # no batch lost to the two faults
        assert [e for e in inj.events if e[0] == "data_fault"] == \
            [("data_fault", 1), ("data_fault", 1)]
        # same trajectory as a fault-free run: the retry re-fetched the
        # SAME batch, it did not skip it
        ref = MultiLayerNetwork(_mlp()).init()
        ref.fit(_iter(), epochs=1)
        _tree_equal(ref._params, net._params)

    def test_retrying_iterator_standalone(self):
        inj = FaultInjector().failOnBatch(0, times=1).failOnBatch(2, times=3)
        it = RetryingDataSetIterator(inj.wrapIterator(_iter()),
                                     policy=_FAST)
        n = 0
        for _ in it:
            n += 1
        assert n == 4
        assert it.retries == 4

    def test_retries_exhausted_raises_original(self):
        inj = FaultInjector().failOnBatch(0, times=10)
        it = RetryingDataSetIterator(inj.wrapIterator(_iter()),
                                     policy=_FAST)
        it.reset()
        assert it.hasNext()
        with pytest.raises(IOError, match="injected data fault"):
            it.next()

    def test_dying_iterator_not_silently_truncated(self):
        # an iterator that raises once then latches exhausted (async
        # wrapper semantics) must surface the error — NOT let the retry
        # swallow it and record a truncated epoch as complete
        class DiesMidEpoch:
            def __init__(self):
                self.base = _iter()
                self.dead = False
                self.raised = False

            def reset(self):
                self.base.reset()

            def hasNext(self):
                if self.dead:
                    return False
                if self.base._cursor >= 32 and not self.raised:
                    self.raised, self.dead = True, True
                    raise IOError("producer died")
                return self.base.hasNext()

            def next(self, num=None):
                return self.base.next()

        net = MultiLayerNetwork(_mlp()).init()
        rf = ResilientFit(net, retryPolicy=_FAST)
        with pytest.raises(IOError, match="producer died"):
            rf.fit(DiesMidEpoch(), epochs=1)
        assert net._epoch == 0  # epoch NOT recorded complete

    def test_random_faults_seed_deterministic(self):
        a = FaultInjector(seed=5).randomIOFaults(100, rate=0.2)
        b = FaultInjector(seed=5).randomIOFaults(100, rate=0.2)
        c = FaultInjector(seed=6).randomIOFaults(100, rate=0.2)
        assert set(a._io_faults) == set(b._io_faults)
        assert set(a._io_faults) != set(c._io_faults)
        assert 5 <= len(a._io_faults) <= 40  # ~20 of 100


# ----------------------------------------------------------------------
# serving tier: /healthz + request deadline
# ----------------------------------------------------------------------
def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


class TestServingResilience:
    def test_healthz_on_real_servers(self, tmp_path):
        from deeplearning4j_tpu.clustering import NearestNeighborsServer
        from deeplearning4j_tpu.optimize.ui import UIServer

        log = tmp_path / "s.jsonl"
        log.write_text(json.dumps(
            {"type": "stats", "iteration": 0, "score": 1.0}) + "\n")
        ui = UIServer().attach(str(log)).start(port=0)
        srv = NearestNeighborsServer(
            points=np.random.RandomState(0).randn(16, 4)).start(port=0)
        try:
            for s in (ui, srv):
                status, body = _get(f"http://127.0.0.1:{s.port}/healthz")
                assert status == 200
                assert json.loads(body) == {"status": "ok"}
            # drain: readiness flips to 503 without stopping the server
            srv.setReady(False)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{srv.port}/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read().decode()) == {
                "status": "unready"}
            srv.setReady(True)
            status, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
            assert status == 200
        finally:
            ui.stop()
            srv.stop()

    def test_request_deadline_returns_503_not_hang(self):
        from deeplearning4j_tpu.util.httpserve import (
            HttpServerOwner, JsonHandler,
        )

        class SlowOwner(HttpServerOwner):
            def start(self, port=0, requestDeadline=None):
                class Handler(JsonHandler):
                    def handle_GET(self):
                        if self.path == "/fast":
                            return self._json({"ok": True})
                        time.sleep(30)  # pathological handler
                        return self._json({"ok": "late"})

                return self._serve(Handler, port,
                                   requestDeadline=requestDeadline)

        srv = SlowOwner().start(port=0, requestDeadline=0.3)
        try:
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{srv.port}/slow", timeout=10)
            elapsed = time.monotonic() - t0
            assert ei.value.code == 503
            assert "deadline" in json.loads(ei.value.read().decode())["error"]
            assert elapsed < 5  # released promptly, not after 30 s
            # server still serves, and /healthz is never deadline-bound
            assert _get(f"http://127.0.0.1:{srv.port}/fast")[0] == 200
            assert _get(f"http://127.0.0.1:{srv.port}/healthz")[0] == 200
        finally:
            srv.stop()


# ----------------------------------------------------------------------
# async prefetch worker faults
# ----------------------------------------------------------------------
class TestAsyncIteratorFaults:
    def test_worker_exception_prompt_and_no_thread_leak(self):
        import threading

        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.runtime.async_iterator import (
            AsyncDataSetIterator,
        )

        class Explodes:
            def __init__(self):
                self.n = 0

            def reset(self):
                self.n = 0

            def hasNext(self):
                return True

            def next(self):
                self.n += 1
                if self.n > 3:
                    raise IOError("backing store went away")
                return DataSet(np.zeros((4, 2), np.float32),
                               np.zeros((4, 2), np.float32))

        before = threading.active_count()
        ait = AsyncDataSetIterator(Explodes(), queueSize=4,
                                   forcePython=True)
        t0 = time.monotonic()
        with pytest.raises(IOError, match="backing store"):
            while ait.hasNext():
                ait.next()
        assert time.monotonic() - t0 < 5  # propagated promptly, no stall
        # the raising worker thread is joined, not leaked
        deadline = time.monotonic() + 3
        while threading.active_count() > before and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= before
        assert ait._thread is None

    def test_reset_after_worker_error_recovers(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.runtime.async_iterator import (
            AsyncDataSetIterator,
        )

        class FailsOnce:
            def __init__(self):
                self.runs = 0
                self.n = 0

            def reset(self):
                self.runs += 1
                self.n = 0

            def hasNext(self):
                return self.n < 4

            def next(self):
                self.n += 1
                if self.runs == 1 and self.n == 2:
                    raise IOError("transient")
                return DataSet(np.full((2, 2), self.n, np.float32),
                               np.zeros((2, 2), np.float32))

        ait = AsyncDataSetIterator(FailsOnce(), forcePython=True)
        with pytest.raises(IOError):
            while ait.hasNext():
                ait.next()
        ait.reset()  # second pass is clean
        got = 0
        while ait.hasNext():
            ait.next()
            got += 1
        assert got == 4
        ait.close()

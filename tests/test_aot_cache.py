"""AOT compilation + persistent executable cache gates (runtime/aot.py,
docs/COMPILE.md).

What must hold:

- cache keys: a config change or a dtype-policy change is a MISS (two
  different programs must never share an executable), an equal config
  at an equal signature is a HIT;
- staleness: a package-version bump invalidates on-disk artifacts, a
  corrupted file falls back to a fresh compile — a bad cache can cost
  a compile, never correctness or a crash;
- parity: a warm-started fit is BITWISE identical to a cold-started
  one on all three network types (stripping donation from the cached
  artifact is a buffer-assignment change, not a math change);
- the donated-buffer segfault documented in tests/conftest.py (jaxlib
  0.4.36 + jax_compilation_cache_dir) does not reproduce under this
  cache: >1200 warm dispatches of a deserialized executable with
  call-time re-donation run clean;
- warm start: a SECOND process against a populated cache precompiles
  and takes its first optimizer step on a zoo model in < 1 s on CPU;
- serving buckets: request batches canonicalise to a fixed bucket set,
  one executable per bucket (the RetraceSentinel budget).
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.runtime import aot


# ----------------------------------------------------------------------
# subjects
# ----------------------------------------------------------------------

def _mln(seed=7, lr=0.1, nout=16, dtype=None):
    from deeplearning4j_tpu.ndarray import DataType
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer)

    b = (NeuralNetConfiguration.Builder().seed(seed)
         .updater(Nesterovs(lr, 0.9)))
    if dtype is not None:
        b = b.dataType(dtype)
    conf = (b.list()
            .layer(DenseLayer(nOut=nout, activation="relu"))
            .layer(OutputLayer(nOut=4, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(8)).build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    return MultiLayerNetwork(conf).init()


def _graph(seed=3):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer)
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Nesterovs(0.1, 0.9)).graphBuilder()
            .addInputs("in")
            .addLayer("d", DenseLayer(nOut=16, activation="relu"), "in")
            .addLayer("out", OutputLayer(nOut=4, activation="softmax",
                                         lossFunction="mcxent"), "d")
            .setOutputs("out")
            .setInputTypes(InputType.feedForward(8)).build())
    return ComputationGraph(conf).init()


def _samediff():
    from deeplearning4j_tpu.autodiff.samediff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.nn.updaters import Sgd

    sd = SameDiff.create()
    x = sd.placeHolder("x", jnp.float64, 8, 5)
    y = sd.placeHolder("y", jnp.float64, 8, 1)
    w = sd.var("w", np.zeros((5, 1)))
    sd.loss.meanSquaredError(y, sd.nn.linear(x, w, name="p"), name="l")
    sd.setTrainingConfig(TrainingConfig.Builder()
                         .updater(Sgd(learningRate=0.05))
                         .dataSetFeatureMapping("x")
                         .dataSetLabelMapping("y").build())
    return sd


def _batch():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 8).astype("float32")
    y = np.eye(4, dtype="float32")[rng.randint(0, 4, 8)]
    return x, y


@pytest.fixture
def fresh_cache(tmp_path):
    """A disk-backed cache installed as THE session cache for the test
    (the suite-wide memory cache from conftest is restored after)."""
    prev = aot._SESSION
    cache = aot.enable(str(tmp_path / "aotx"))
    yield cache
    aot._SESSION = prev


@pytest.fixture
def no_cache():
    """AOT disabled: the plain donated-jit path (the cold oracle)."""
    prev = aot._SESSION
    aot.disable()
    yield
    aot._SESSION = prev


# ----------------------------------------------------------------------
# keys
# ----------------------------------------------------------------------

class TestKeys:
    def test_equal_config_equal_key_different_config_miss(self,
                                                          fresh_cache):
        r1 = _mln(seed=7, lr=0.1).precompile(batchSize=8)
        r2 = _mln(seed=7, lr=0.1).precompile(batchSize=8)
        r3 = _mln(seed=7, lr=0.05).precompile(batchSize=8)  # lr differs
        assert r1["train_step"]["key"] == r2["train_step"]["key"]
        assert r2["train_step"]["status"] == "warm"
        assert r3["train_step"]["key"] != r1["train_step"]["key"]
        assert r3["train_step"]["status"] == "cold"

    def test_dtype_policy_change_misses(self, fresh_cache):
        from deeplearning4j_tpu.ndarray import DataType

        k32 = _mln(dtype=DataType.FLOAT).precompile(
            batchSize=8)["train_step"]["key"]
        kbf = _mln(dtype=DataType.BFLOAT16).precompile(
            batchSize=8)["train_step"]["key"]
        assert k32 != kbf

    def test_tail_mode_toggle_misses(self, fresh_cache):
        from deeplearning4j_tpu.nn import losses as _losses

        k_compute = _mln().precompile(batchSize=8)["train_step"]["key"]
        old = _losses._TAIL_MODE
        _losses._TAIL_MODE = "wide"
        try:
            k_wide = _mln().precompile(batchSize=8)["train_step"]["key"]
        finally:
            _losses._TAIL_MODE = old
        assert k_compute != k_wide

    def test_every_autotune_knob_separates_keys(self, fresh_cache):
        """ISSUE 12 small-fix regression gate: the cache key must
        incorporate the autotune arbiter's chosen knob values — a tuned
        run and a stock run must NEVER share an executable. Flipping
        EACH registered knob off its current value must change the key
        (companion of TestKeys tail-mode / TestTrainerPrecompile
        sharded-vs-replicated separations)."""
        from deeplearning4j_tpu.runtime import autotune as at

        net = _mln()
        base_key = net.precompile(batchSize=8)["train_step"]["key"]
        for knob in at.KNOBS:
            alt = next(c for c in knob.candidates if c != knob.get())
            with at.applied({knob.name: alt}):
                k = _mln().precompile(batchSize=8)["train_step"]["key"]
            assert k != base_key, (
                f"knob {knob.name}={alt} produced the SAME cache key "
                "as the stock config — tuned and stock runs would "
                "share an executable")

    def test_batch_signature_change_misses(self, fresh_cache):
        k8 = _mln().precompile(batchSize=8)["train_step"]["key"]
        k16 = _mln().precompile(batchSize=16)["train_step"]["key"]
        assert k8 != k16

    def test_shape_dtype_struct_warm_primes_real_calls(self,
                                                       fresh_cache):
        """warm() with ShapeDtypeStructs must land on the SAME key a
        real concrete-array call computes — otherwise the advertised
        abstract precompile silently buys nothing."""
        net = _mln()
        x, y = _batch()
        key = jax.random.fold_in(
            jax.random.key(net.conf.seed ^ 0x5EED), 0)
        sds = lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                             jnp.asarray(a).dtype)
        args_abstract = (
            jax.tree_util.tree_map(sds, net._params),
            jax.tree_util.tree_map(sds, net._upd_states),
            jax.tree_util.tree_map(sds, net._states),
            sds(jnp.asarray(0, jnp.int32)), sds(jnp.asarray(x)),
            sds(jnp.asarray(y)), sds(key), None, None)
        k_abs, status, _ = net._jit_train.warm(*args_abstract)
        assert status == "cold"
        misses = fresh_cache.stats["misses"]
        net.fit(x, y)  # first real call: must hit, not recompile
        assert fresh_cache.stats["misses"] == misses


# ----------------------------------------------------------------------
# staleness / corruption
# ----------------------------------------------------------------------

class TestInvalidation:
    def test_version_bump_invalidates_disk(self, fresh_cache,
                                           monkeypatch):
        rep = _mln().precompile(batchSize=8)
        key = rep["train_step"]["key"]
        assert key in fresh_cache
        fresh_cache.clear_memory()
        monkeypatch.setattr(aot, "_package_version", lambda: "999.0")
        # the key itself embeds the version, so a lookup under the OLD
        # key must also reject the artifact by its stored meta
        assert fresh_cache.get(key) is None
        assert fresh_cache.stats["stale"] == 1
        assert key not in fresh_cache  # removed from disk

    def test_corrupted_file_falls_back_to_fresh_compile(self,
                                                        fresh_cache):
        rep = _mln().precompile(batchSize=8)
        key = rep["train_step"]["key"]
        path = fresh_cache._path(key)
        with open(path, "wb") as fh:
            fh.write(b"not a pickle")
        fresh_cache.clear_memory()
        assert fresh_cache.get(key) is None
        assert fresh_cache.stats["corrupt"] == 1
        # and the network recovers by compiling fresh
        rep2 = _mln().precompile(batchSize=8)
        assert rep2["train_step"]["status"] == "cold"
        x, y = _batch()
        _mln().fit(x, y)  # trains clean through the rebuilt entry

    def test_truncated_payload_is_corrupt_not_crash(self, fresh_cache):
        rep = _mln().precompile(batchSize=8)
        key = rep["train_step"]["key"]
        path = fresh_cache._path(key)
        data = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(data[: len(data) // 2])
        fresh_cache.clear_memory()
        assert fresh_cache.get(key) is None
        assert fresh_cache.stats["corrupt"] >= 1


# ----------------------------------------------------------------------
# parity: warm == cold, bitwise
# ----------------------------------------------------------------------

def _fit_mln(net, steps=4):
    x, y = _batch()
    for _ in range(steps):
        net.fit(x, y)
    return [np.asarray(leaf) for leaf in
            jax.tree_util.tree_leaves(net._params)]


class TestWarmColdParity:
    def test_multilayer_bitwise(self, tmp_path, no_cache):
        cold = _fit_mln(_mln())
        prev = aot._SESSION
        try:
            aot.enable(str(tmp_path / "c1"))
            net = _mln()
            net.precompile(batchSize=8)
            warm_first = _fit_mln(net)
            # second process simulation: memory dropped, disk only
            aot.session_cache().clear_memory()
            net2 = _mln()
            rep = net2.precompile(batchSize=8)
            assert rep["train_step"]["status"] == "warm"
            warm_disk = _fit_mln(net2)
        finally:
            aot._SESSION = prev
        for c, w1, w2 in zip(cold, warm_first, warm_disk):
            np.testing.assert_array_equal(c, w1)
            np.testing.assert_array_equal(c, w2)

    def test_multilayer_fit_dataset_bitwise(self, tmp_path, no_cache):
        from deeplearning4j_tpu.data import DataSetIterator

        rng = np.random.RandomState(2)
        xs = rng.randn(32, 8).astype("float32")
        ys = np.eye(4, dtype="float32")[rng.randint(0, 4, 32)]

        def run(precompiled):
            net = _mln()
            if precompiled:
                net.precompile(batchSize=8, stepsPerSync=2)
            net.fitDataSet(DataSetIterator(xs, ys, 8), stepsPerSync=2)
            return [np.asarray(leaf) for leaf in
                    jax.tree_util.tree_leaves(net._params)]

        cold = run(False)
        prev = aot._SESSION
        try:
            aot.enable(str(tmp_path / "c2"))
            warm = run(True)
        finally:
            aot._SESSION = prev
        for c, w in zip(cold, warm):
            np.testing.assert_array_equal(c, w)

    def test_graph_bitwise(self, tmp_path, no_cache):
        x, y = _batch()

        def run():
            g = _graph()
            for _ in range(4):
                g.fit(x, y)
            return [np.asarray(leaf) for leaf in
                    jax.tree_util.tree_leaves(g._params)]

        cold = run()
        prev = aot._SESSION
        try:
            aot.enable(str(tmp_path / "c3"))
            _graph().precompile(batchSize=8)   # populate
            aot.session_cache().clear_memory()  # force disk warm path
            warm = run()
        finally:
            aot._SESSION = prev
        for c, w in zip(cold, warm):
            np.testing.assert_array_equal(c, w)

    def test_samediff_bitwise(self, tmp_path, no_cache):
        rng = np.random.RandomState(1)
        X = rng.rand(8, 5)
        Y = X @ np.ones((5, 1))

        def run(precompiled):
            sd = _samediff()
            if precompiled:
                sd.precompile(features=X, labels=Y)
            sd.fit(features=X, labels=Y, epochs=3)
            return np.asarray(sd.getVariable("w").getArr().toNumpy())

        cold = run(False)
        prev = aot._SESSION
        try:
            aot.enable(str(tmp_path / "c4"))
            warm = run(True)
            aot.session_cache().clear_memory()
            warm_disk = run(True)
        finally:
            aot._SESSION = prev
        np.testing.assert_array_equal(cold, warm)
        np.testing.assert_array_equal(cold, warm_disk)


# ----------------------------------------------------------------------
# the donated-buffer repro (conftest note) under the new cache
# ----------------------------------------------------------------------

class TestDonationWorkaround:
    def test_1200_warm_dispatches_no_segfault(self, fresh_cache):
        """The documented jaxlib failure mode: warm-cache runs die
        deserializing donated-buffer executables after ~1200 hits.
        Under this cache the artifact carries no donation (re-donation
        happens at call time), so >1200 warm dispatches of a
        DESERIALIZED executable must run clean."""
        net = _mln()
        net.precompile(batchSize=8)
        fresh_cache.clear_memory()        # force the deserialized path
        net2 = _mln()
        rep = net2.precompile(batchSize=8)
        assert rep["train_step"]["status"] == "warm"
        x, y = _batch()
        for _ in range(1250):
            net2.fit(x, y)
        assert np.isfinite(net2.score())

    def test_call_time_redonation_invalidates_inputs(self, fresh_cache):
        """The donated-jit contract callers rely on — input buffers are
        dead after the step — is preserved by the call-time deletion."""
        net = _mln()
        net.precompile(batchSize=8)
        old_leaf = net._params[0]["W"]
        x, y = _batch()
        net.fit(x, y)
        assert old_leaf.is_deleted()

    def test_sentinel_still_counts_with_warm_cache(self, fresh_cache):
        """RetraceSentinel.install bypasses the cache (a hit would hide
        the trace the counter exists to count): exactly one compile is
        still observed even when the cache is hot."""
        from deeplearning4j_tpu.analysis.retrace import RetraceSentinel

        _mln().precompile(batchSize=8)    # hot cache for this program
        net = _mln()
        sent = RetraceSentinel(max_compiles=1).install(net, "step")
        x, y = _batch()
        for _ in range(3):
            net.fit(x, y)
        assert sent.compiles("step") == 1


# ----------------------------------------------------------------------
# second-process warm start (the zero→aha metric)
# ----------------------------------------------------------------------

_CHILD = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import numpy as np
    from deeplearning4j_tpu.zoo import LeNet
    from deeplearning4j_tpu.runtime import aot

    jax.numpy.zeros((1,)).block_until_ready()   # backend init, not ours
    net = LeNet(numClasses=10, inputShape=(1, 28, 28)).init()
    x = np.zeros((8, 1, 28, 28), np.float32)
    y = np.eye(10, dtype=np.float32)[np.zeros(8, int)]
    t0 = time.perf_counter()
    rep = net.precompile(batchSize=8)
    net.fit(x, y)
    wall = time.perf_counter() - t0
    statuses = {k: v["status"] for k, v in rep.items()}
    print("WALL", wall)
    print("STATUSES", statuses)
    sys.exit(0 if (wall < 1.0 and
                   statuses.get("train_step") == "warm") else 3)
""")


class TestSecondProcessWarmStart:
    def test_zoo_model_warm_start_under_1s(self, tmp_path):
        """Populate the persistent cache for a zoo model, then a FRESH
        interpreter precompiles + takes its first optimizer step in
        < 1 s on CPU (vs multi-second XLA compiles cold)."""
        cache_dir = str(tmp_path / "zoo_cache")
        prev = aot._SESSION
        try:
            aot.enable(cache_dir)
            from deeplearning4j_tpu.zoo import LeNet

            net = LeNet(numClasses=10, inputShape=(1, 28, 28)).init()
            rep = net.precompile(batchSize=8)
            assert rep["train_step"]["status"] == "cold"
        finally:
            aot._SESSION = prev
        env = dict(os.environ)
        env["DL4J_TPU_AOT_CACHE"] = cache_dir
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, (
            f"warm second-process start failed:\n{out.stdout}\n"
            f"{out.stderr[-2000:]}")


# ----------------------------------------------------------------------
# shape buckets + serving
# ----------------------------------------------------------------------

class TestBuckets:
    def test_bucket_batch_maths(self):
        assert aot.bucket_batch(1) == 1
        assert aot.bucket_batch(3) == 4
        assert aot.bucket_batch(33) == 64
        assert aot.bucket_batch(1024) == 1024
        assert aot.bucket_batch(1500) == 2048  # multiples of the top
        with pytest.raises(ValueError):
            aot.bucket_batch(0)
        assert aot.sentinel_budget((1, 8, 64)) == 3
        assert aot.sentinel_budget((1, 8, 64), entries=2) == 6

    def test_parallel_inference_one_compile_per_bucket(self,
                                                       fresh_cache):
        from deeplearning4j_tpu.parallel.inference import ParallelInference
        from deeplearning4j_tpu.parallel.mesh import build_mesh

        net = _mln()
        mesh = build_mesh({"data": 2})
        pi = ParallelInference(net, mesh=mesh, batchBuckets=(8, 16))
        rep = pi.precompile()
        assert set(rep) == {8, 16}
        misses = fresh_cache.stats["misses"]
        for b in (3, 5, 7, 8):        # all land in the 8-bucket
            out = pi.output(np.zeros((b, 8), np.float32))
            assert out.shape()[0] == b
        for b in (9, 12):             # 16-bucket
            assert pi.output(
                np.zeros((b, 8), np.float32)).shape()[0] == b
        assert fresh_cache.stats["misses"] == misses  # zero new compiles

    def test_httpserve_warmup_gates_readiness(self):
        import json
        import threading
        import time
        import urllib.request

        from deeplearning4j_tpu.clustering.server import (
            NearestNeighborsServer)

        release = threading.Event()
        srv = NearestNeighborsServer(
            np.random.RandomState(0).rand(16, 4))
        srv.start(port=0, warmup=release.wait)
        try:
            url = f"http://127.0.0.1:{srv.port}/healthz"
            try:
                urllib.request.urlopen(url, timeout=5)
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 503    # not ready until warmup returns
            release.set()
            deadline = time.time() + 5
            while time.time() < deadline:
                try:
                    body = json.load(urllib.request.urlopen(url,
                                                            timeout=5))
                    assert body["status"] == "ok"
                    break
                except urllib.error.HTTPError:
                    time.sleep(0.02)
            else:
                pytest.fail("server never became ready after warmup")
        finally:
            srv.stop()


# ----------------------------------------------------------------------
# trainers
# ----------------------------------------------------------------------

class TestTrainerPrecompile:
    def test_parallel_wrapper_warm_matches_cold(self, tmp_path,
                                                no_cache):
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelWrapper

        mesh = build_mesh({"data": 2})
        x, y = _batch()

        def run(precompiled, wu):
            net = _mln()
            pw = ParallelWrapper(net, mesh=build_mesh({"data": 2}),
                                 weight_update=wu)
            if precompiled:
                rep = pw.precompile(batchSize=8)
                assert rep["pw_train_step"]["status"] in ("cold", "warm")
            for _ in range(3):
                pw.fit(x, y)
            return [np.asarray(leaf) for leaf in
                    jax.tree_util.tree_leaves(net._params)]

        for wu in ("replicated", "sharded"):
            cold = run(False, wu)
            prev = aot._SESSION
            try:
                aot.enable(str(tmp_path / f"pw_{wu}"))
                warm = run(True, wu)
            finally:
                aot._SESSION = prev
            for c, w in zip(cold, warm):
                np.testing.assert_array_equal(c, w)

    def test_sharded_vs_replicated_keys_differ(self, fresh_cache):
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.trainer import ParallelWrapper

        reps = {}
        for wu in ("replicated", "sharded"):
            pw = ParallelWrapper(_mln(), mesh=build_mesh({"data": 2}),
                                 weight_update=wu)
            reps[wu] = pw.precompile(batchSize=8)["pw_train_step"]["key"]
        assert reps["replicated"] != reps["sharded"]

"""Serving tier: live UIServer dashboard + NearestNeighborsServer.

Reference strategy: upstream's deeplearning4j-ui TestVertxUI and
nearestneighbors-server NearestNeighborsTest drive the real HTTP
endpoints and parse the responses — same here (stdlib urllib against
127.0.0.1, ephemeral ports, no mocks).
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (NearestNeighborsServer,
                                           RandomProjectionLSH, VPTree)
from deeplearning4j_tpu.optimize.ui import UIServer


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def _post(url, obj):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read().decode())


@pytest.fixture
def stats_log(tmp_path):
    p = tmp_path / "stats.jsonl"
    with open(p, "w") as fh:
        for i in range(8):
            fh.write(json.dumps({"type": "stats", "iteration": i,
                                 "score": 2.0 / (i + 1),
                                 "iterationsPerSec": 10.0 + i,
                                 "time": 100.0 + i}) + "\n")
        fh.write(json.dumps({"type": "epochEnd", "epoch": 0}) + "\n")
    return p


class TestUIServerLive:
    def test_dashboard_and_polling_roundtrip(self, stats_log):
        ui = UIServer().attach(str(stats_log)).start(port=0)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            status, html_doc = _get(base + "/")
            assert status == 200
            assert "http-equiv='refresh'" in html_doc
            assert "score vs iteration" in html_doc
            assert "0.25" in html_doc  # final score 2/8

            status, body = _get(base + "/train/0/updates?since=0")
            upd = json.loads(body)
            assert status == 200 and upd["next"] == 9
            assert upd["records"][0]["score"] == 2.0

            # live append -> the polling route sees exactly the new tail
            with open(stats_log, "a") as fh:
                fh.write(json.dumps({"type": "stats", "iteration": 8,
                                     "score": 0.2}) + "\n")
            status, body = _get(base + f"/train/0/updates?since={upd['next']}")
            upd2 = json.loads(body)
            assert [r["iteration"] for r in upd2["records"]] == [8]
            assert upd2["next"] == 10

            status, body = _get(base + "/sources")
            assert json.loads(body)["sources"] == [str(stats_log)]
        finally:
            ui.stop()
        assert ui.port is None

    def test_unknown_source_404(self, stats_log):
        ui = UIServer().attach(str(stats_log)).start(port=0)
        try:
            for bad in ("/train/5", "/train/-1"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(f"http://127.0.0.1:{ui.port}{bad}")
                assert ei.value.code == 404, bad
        finally:
            ui.stop()

    def test_client_errors_are_4xx(self, stats_log):
        """Malformed paths/params are the CLIENT's fault: 400, not 500."""
        ui = UIServer().attach(str(stats_log)).start(port=0)
        try:
            for bad in ("/train/abc", "/train/0/updates?since=abc"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(f"http://127.0.0.1:{ui.port}{bad}")
                assert ei.value.code == 400, bad
        finally:
            ui.stop()

    def test_updates_short_form(self, stats_log):
        """Docs advertise /train/updates as shorthand for source 0."""
        ui = UIServer().attach(str(stats_log)).start(port=0)
        try:
            status, body = _get(
                f"http://127.0.0.1:{ui.port}/train/updates?since=8")
            upd = json.loads(body)
            assert status == 200 and upd["next"] == 9
            assert upd["records"][0]["type"] == "epochEnd"
        finally:
            ui.stop()


class TestNearestNeighborsServer:
    def _corpus(self, n=64, d=8):
        return np.random.RandomState(0).randn(n, d)

    def test_knnnew_matches_bruteforce(self):
        X = self._corpus()
        srv = NearestNeighborsServer(points=X).start(port=0)
        try:
            q = np.random.RandomState(1).randn(8)
            status, resp = _post(f"http://127.0.0.1:{srv.port}/knnnew",
                                 {"point": q.tolist(), "k": 5})
            assert status == 200 and len(resp["results"]) == 5
            got = [r["index"] for r in resp["results"]]
            want = np.argsort(np.linalg.norm(X - q, axis=1))[:5].tolist()
            assert got == want
            dists = [r["distance"] for r in resp["results"]]
            assert dists == sorted(dists)
        finally:
            srv.stop()

    def test_knn_excludes_self(self):
        X = self._corpus()
        srv = NearestNeighborsServer(points=X).start(port=0)
        try:
            status, resp = _post(f"http://127.0.0.1:{srv.port}/knn",
                                 {"index": 3, "k": 4})
            assert status == 200
            idxs = [r["index"] for r in resp["results"]]
            assert 3 not in idxs and len(idxs) == 4
            want = np.argsort(np.linalg.norm(X - X[3], axis=1))[1:5].tolist()
            assert idxs == want
        finally:
            srv.stop()

    def test_status_and_errors(self):
        X = self._corpus(n=16, d=4)
        srv = NearestNeighborsServer(points=X).start(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status, body = _get(base + "/status")
            st = json.loads(body)
            assert st == {"numPoints": 16, "dims": 4, "index": "VPTree"}
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/knnnew", {"point": [1.0, 2.0], "k": 3})
            assert ei.value.code == 400  # wrong dims -> readable error
            assert "dims" in json.loads(ei.value.read().decode())["error"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/knn", {"k": 3})  # missing index
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/knnnew", [1, 2, 3])  # non-object body
            assert ei.value.code == 400
            assert "object" in \
                json.loads(ei.value.read().decode())["error"]
        finally:
            srv.stop()

    def test_lsh_backed_index(self):
        X = self._corpus(n=128, d=16)
        lsh = RandomProjectionLSH(hashLength=4, numTables=6, inDimension=16)
        lsh.index(X)
        srv = NearestNeighborsServer(index=lsh, corpus=X).start(port=0)
        try:
            status, resp = _post(f"http://127.0.0.1:{srv.port}/knnnew",
                                 {"point": X[7].tolist(), "k": 3})
            assert status == 200
            # the query IS corpus row 7 — any sane LSH recalls its bucket
            assert resp["results"][0]["index"] == 7
            assert resp["results"][0]["distance"] < 1e-6
        finally:
            srv.stop()

    def test_ctor_validation(self):
        with pytest.raises(ValueError, match="exactly one"):
            NearestNeighborsServer()
        with pytest.raises(ValueError, match="exactly one"):
            NearestNeighborsServer(points=np.eye(3), index=VPTree(np.eye(3)))

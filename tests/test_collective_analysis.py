"""SPMD collective-safety verifier tests (analysis/collectives.py,
pass 7 — ISSUE 14).

Matrix: every COL01-COL06 code triggered by a deliberately broken
input (the PR 2/3 pattern), the safe twins of each hazard proven
unflagged (the CG while_loop, symmetric cond branches, well-formed
rings), the declarative CollectiveContract covering ALL FOUR
gradient_compression modes + the ZeRO-composed path + the canonical
linalg routines, and the back-compat proof that
linalg.collective_counts (now a re-export of the hoisted walker)
reports the identical counts.

Cost discipline: every check here is ONE jax.make_jaxpr trace — zero
XLA compiles. The trainer-step subjects are traced once per module
(module-scoped fixture) and the zero-compile claim is proven live with
CompileWatch over the session AOT cache.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.analysis import collectives as colan
from deeplearning4j_tpu.analysis.diagnostics import ALL_CODES
from deeplearning4j_tpu.parallel._compat import shard_map
from deeplearning4j_tpu.parallel.mesh import build_mesh, DATA_AXIS

DP = 8


@pytest.fixture(scope="module")
def dmesh():
    return build_mesh({DATA_AXIS: DP}, jax.devices())


def _smap(body, mesh, in_specs, out_specs):
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)


def _codes(report):
    return set(report.codes())


# ======================================================================
# signature extraction + collective_counts back-compat
# ======================================================================

class TestSignature:
    def test_ordered_sites_with_context_and_bytes(self, dmesh):
        def body(x):
            g = lax.all_gather(x, DATA_AXIS, tiled=True)

            def step(i, c):
                return c + lax.ppermute(
                    c, DATA_AXIS, [(j, (j + 1) % DP) for j in range(DP)])

            l = lax.fori_loop(0, 4, step, x)
            return lax.psum(g.sum() + l.sum(), DATA_AXIS)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P())
        sig = colan.collective_signature(
            f, jnp.ones((DP, 4), jnp.float32))
        prims = [s.prim for s in sig]
        assert prims == ["all_gather", "ppermute", "psum"]
        # the ppermute site sits inside the fori_loop's scan, inside
        # the shard_map
        pp = sig.sites[1]
        assert "shard_map" in pp.context and "scan" in pp.context
        assert pp.perm is not None and len(pp.perm) == DP
        # per-chip bytes: the all_gather output is [DP, 4] f32
        assert sig.sites[0].out_bytes == DP * 4 * 4
        assert sig.axes() == {DATA_AXIS}

    def test_collective_counts_reexport_unchanged(self, dmesh):
        """linalg.collective_counts is the hoisted walker — identical
        counts, sites-not-dispatches semantics preserved."""
        from deeplearning4j_tpu import linalg

        def body(x):
            def step(i, c):
                return c + lax.ppermute(
                    c, DATA_AXIS, [(j, (j + 1) % DP) for j in range(DP)])

            return lax.psum(lax.fori_loop(0, 3, step, x), DATA_AXIS)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(None, None))
        x = jnp.ones((DP, 4))
        counts = linalg.collective_counts(f, x)
        # the in-loop ppermute is ONE site even over 3 iterations
        assert counts == {"ppermute": 1, "psum": 1}
        assert counts == colan.collective_signature(f, x).counts()


# ======================================================================
# COL01 — collectives under data-dependent control flow
# ======================================================================

class TestCol01ControlFlow:
    def test_divergent_cond_predicate_flags(self, dmesh):
        def body(x):
            # predicate from the SHARDED block: replicas disagree
            return lax.cond(x.sum() > 0,
                            lambda v: lax.psum(v, DATA_AXIS),
                            lambda v: v, x)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert "COL01" in _codes(rep), rep.format()

    def test_uniform_pred_asymmetric_branches_flag(self, dmesh):
        def body(x):
            s = lax.psum(x, DATA_AXIS)
            return lax.cond(s.sum() > 0,
                            lambda v: lax.pmax(v, DATA_AXIS),
                            lambda v: v, x)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert "COL01" in _codes(rep), rep.format()

    def test_uniform_pred_symmetric_branches_clean(self, dmesh):
        def body(x):
            s = lax.psum(x, DATA_AXIS)
            return lax.cond(s.sum() > 0,
                            lambda v: lax.pmax(v, DATA_AXIS),
                            lambda v: lax.pmax(-v, DATA_AXIS), x)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert rep.ok, rep.format()

    def test_divergent_while_predicate_flags(self, dmesh):
        def body(x):
            def cond(c):
                return c[0] < 10.0  # local partial sum: diverges

            def step(c):
                return (c[0] + c[1].sum()
                        + lax.psum(c[1], DATA_AXIS).sum() * 0.0, c[1])

            out, _ = lax.while_loop(cond, step, (x.sum(), x))
            return out

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P())
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert "COL01" in _codes(rep), rep.format()

    def test_reduced_while_predicate_clean(self, dmesh):
        """The CG shape: every term reaching the predicate passed
        through a psum — replica-uniform, no flag."""
        def body(x):
            def cond(c):
                return (c[0] < 10.0) & (c[2] < 5)

            def step(c):
                acc = c[0] + lax.psum(c[1], DATA_AXIS).sum()
                return (acc.astype(c[0].dtype), c[1], c[2] + 1)

            out, _, _ = lax.while_loop(
                cond, step, (jnp.zeros((), x.dtype), x, jnp.int32(0)))
            return out

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P())
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert rep.ok, rep.format()

    def test_real_cg_lstsq_clean(self, dmesh):
        """The REAL distributed CG (linalg/solvers._build_lstsq): psum
        inside a convergence-predicated while_loop, proven safe — and
        matching its declared contract."""
        from deeplearning4j_tpu.linalg.solvers import _build_lstsq

        f = _build_lstsq(dmesh, DATA_AXIS, None, 0.0, 1e-6, 16)
        rep = colan.verify_program(
            f, jnp.ones((4 * DP, 4)), jnp.ones((4 * DP, 1)),
            mesh=dmesh, contract=colan.linalg_contract("lstsq"))
        assert rep.ok, rep.format()
        assert rep.signature.counts() == {"psum": 3}

    def test_divergent_trip_count_poisons_downstream(self, dmesh):
        """A collective-FREE while whose trip count diverges (bounded
        by axis_index) must poison its outputs: a second loop bounded
        by the first one's result deadlocks mid-psum, and COL01 must
        see through the laundering (code-review regression)."""
        def body(x):
            i0 = lax.axis_index(DATA_AXIS)
            trips = lax.while_loop(lambda i: i < i0,
                                   lambda i: i + 1, jnp.int32(0))

            def cond(c):
                return c[1] < trips

            def step(c):
                return (c[0] + lax.psum(x, DATA_AXIS).sum(), c[1] + 1)

            out, _ = lax.while_loop(
                cond, step, (jnp.zeros((), x.dtype), jnp.int32(0)))
            return out

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P())
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert "COL01" in _codes(rep), rep.format()

    def test_hazard_inside_scan_reported_once(self, dmesh):
        """One hazard inside a scan body yields ONE diagnostic, not
        one per fixpoint iteration (code-review regression — the
        bench/CI gates count errors)."""
        def body(x):
            def step(c, _):
                out = lax.cond(x.sum() > 0,
                               lambda v: lax.psum(v, DATA_AXIS),
                               lambda v: v, x)
                return c + out.sum(), None

            acc, _ = lax.scan(step, jnp.zeros((), x.dtype),
                              jnp.arange(3))
            return acc

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P())
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        col01 = [d for d in rep.errors if d.code == "COL01"]
        assert len(col01) == 1, rep.format()

    def test_static_fori_loop_clean(self, dmesh):
        """A static-trip fori_loop (lowers to scan) communicates
        safely — the SUMMA ring shape."""
        def body(x):
            def step(i, c):
                return c + lax.ppermute(
                    c, DATA_AXIS, [(j, (j + 1) % DP) for j in range(DP)])

            return lax.fori_loop(0, DP, step, x)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)))
        assert rep.ok, rep.format()


# ======================================================================
# COL02 / COL06 — axis sanity and ring shape
# ======================================================================

class TestCol02Axes:
    def test_axis_absent_from_requested_mesh(self, dmesh):
        def body(x):
            return lax.psum(x, DATA_AXIS)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(None, None))
        # the program reduces over "data"; validate against a mesh
        # that names its axes differently (the drifted-deploy shape)
        rep = colan.verify_program(f, jnp.ones((DP, 4)),
                                   mesh={"rows": DP})
        assert "COL02" in _codes(rep), rep.format()

    def test_axes_present_clean(self, dmesh):
        def body(x):
            return lax.psum(x, DATA_AXIS)

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(None, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)), mesh=dmesh)
        assert rep.ok, rep.format()

    def test_signature_only_path(self):
        sig = colan.CollectiveSignature([colan.CollectiveSite(
            "psum", ("nodes",), "float32", 64, ("shard_map",))])
        rep = colan.check_signature(sig, mesh_axes={"data", "model"})
        assert _codes(rep) == {"COL02"}


class TestCol06Rings:
    def _ring_site(self, perm):
        return colan.CollectiveSignature([colan.CollectiveSite(
            "ppermute", (DATA_AXIS,), "float32", 64, (), perm=perm)])

    def test_duplicate_destination_flags(self):
        rep = colan.check_signature(
            self._ring_site(((0, 1), (1, 1), (2, 3))),
            mesh_axes={DATA_AXIS})
        assert "COL06" in _codes(rep)

    def test_duplicate_source_flags(self):
        rep = colan.check_signature(
            self._ring_site(((0, 1), (0, 2))), mesh_axes={DATA_AXIS})
        assert "COL06" in _codes(rep)

    def test_self_cycle_flags(self):
        rep = colan.check_signature(
            self._ring_site(((0, 0), (1, 2), (2, 1))),
            mesh_axes={DATA_AXIS})
        assert any(d.code == "COL06" and "self-cycle" in d.message
                   for d in rep.errors), rep.format()

    def test_proper_ring_clean_from_real_trace(self, dmesh):
        def body(x):
            return lax.ppermute(
                x, DATA_AXIS, [(j, (j + 1) % DP) for j in range(DP)])

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)), mesh=dmesh)
        assert rep.ok, rep.format()

    def test_broken_ring_flagged_from_real_trace(self, dmesh):
        # (j, j) instead of (j, j+1): the classic ring-arithmetic slip
        def body(x):
            return lax.ppermute(
                x, DATA_AXIS, [(j, j) for j in range(DP)])

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
        rep = colan.verify_program(f, jnp.ones((DP, 4)), mesh=dmesh)
        assert "COL06" in _codes(rep), rep.format()


# ======================================================================
# COL03 — quantized-accumulator agreement
# ======================================================================

class TestCol03AccDtype:
    def _sig(self, dtype):
        return colan.CollectiveSignature([colan.CollectiveSite(
            "psum", (DATA_AXIS,), dtype, 64, ())])

    def test_int16_correct_through_dp256(self):
        assert colan.check_acc_dtype(self._sig("int16"), 8).ok
        assert colan.check_acc_dtype(self._sig("int16"), 256).ok

    def test_int16_overflows_past_dp256(self):
        rep = colan.check_acc_dtype(self._sig("int16"), 512)
        assert "COL03" in _codes(rep), rep.format()

    def test_int32_required_and_accepted_past_dp256(self):
        assert colan.check_acc_dtype(self._sig("int32"), 512).ok
        # int32 at dp=8 is over-wide vs the shared definition: drift
        rep = colan.check_acc_dtype(self._sig("int32"), 8)
        assert "COL03" in _codes(rep)

    def test_bill_disagreement_flags(self):
        rep = colan.check_acc_dtype(self._sig("int16"), 8,
                                    billed_acc_bytes=4)
        assert any(d.code == "COL03" and "bill" in d.where
                   for d in rep.errors), rep.format()

    def test_bill_shares_the_runtime_definition(self):
        """compressed_hlo_collective_bytes derives its accumulator
        width from _acc_dtype — the three-party agreement by
        construction (one 100-elem int8 leaf: 8 B scale pmax + 2n acc
        psum at the dp-correct width)."""
        from deeplearning4j_tpu.parallel.sharding import (
            compressed_hlo_collective_bytes,
        )

        assert compressed_hlo_collective_bytes([100], 8, "int8") \
            == 8 + 2 * 100 * 2
        assert compressed_hlo_collective_bytes([100], 512, "int8") \
            == 8 + 2 * 100 * 4

    def test_quantized_contract_demands_integer_reduce(self, dmesh):
        """A program whose COUNTS satisfy the int8 contract but whose
        reductions all run in float (the silent-widening regression)
        fails COL03 — the count alone must not green-light it
        (code-review regression)."""
        def body(x):
            s = lax.pmax(x, DATA_AXIS)                    # "scale"
            a = lax.psum(x, DATA_AXIS)                    # float, not int!
            loss = lax.psum(x.sum(), DATA_AXIS)
            return s.sum() + a.sum() + loss

        f = _smap(body, dmesh, (P(DATA_AXIS, None),), P())
        rep = colan.verify_program(
            f, jnp.ones((DP, 4), jnp.float32), mesh=dmesh, dp=DP,
            contract=colan.compression_contract("int8", 1))
        assert "COL03" in _codes(rep), rep.format()

    def test_lowered_step_acc_dtype_verified(self, compressed_subjects):
        """The REAL int8 step's integer psum dtype agrees with
        expected_acc_dtype(dp) — checked by verify_program's COL03 leg
        (dp=8: int16)."""
        sig = compressed_subjects["int8"]["signature"]
        int_psums = [s for s in sig if s.prim == "psum"
                     and s.dtype.startswith("int")]
        assert int_psums, "int8 step lost its integer psum"
        assert all(s.dtype == "int16" for s in int_psums)
        assert colan.check_acc_dtype(sig, DP).ok


# ======================================================================
# COL04 — CollectiveContract drift
# ======================================================================

class TestCol04Contracts:
    def test_count_drift_flags(self):
        c = colan.compression_contract("int8", 4)
        got = {"pmax": 4, "psum": 3}   # lost the loss pmean + one leaf
        rep = c.check(got)
        assert "COL04" in _codes(rep), rep.format()

    def test_undeclared_collective_flags(self):
        c = colan.compression_contract("threshold", 2)
        got = {"all_gather": 4, "psum": 1, "ppermute": 1}
        rep = c.check(got)
        assert any("undeclared" in d.message for d in rep.errors), \
            rep.format()

    def test_dense_contract_rejects_explicit_collectives(self):
        c = colan.compression_contract(None, 4)
        assert not c.check({"psum": 1}).ok
        assert c.check({}).ok

    def test_range_bounds(self):
        c = colan.CollectiveContract("r", {"psum": (2, None)})
        assert c.check({"psum": 5}).ok
        assert not c.check({"psum": 1}).ok

    def test_axis_restriction(self):
        c = colan.CollectiveContract("a", {"psum": 1},
                                     axes=(DATA_AXIS,))
        sig = colan.CollectiveSignature([colan.CollectiveSite(
            "psum", ("model",), "float32", 4, ())])
        assert not c.check(sig).ok

    def test_unknown_mode_and_routine_raise(self):
        with pytest.raises(ValueError, match="gradient_compression"):
            colan.compression_contract("sparse", 4)
        with pytest.raises(ValueError, match="linalg routine"):
            colan.linalg_contract("qr")


# ======================================================================
# COL05 — bill-vs-measured divergence
# ======================================================================

class TestCol05Bill:
    def test_within_tolerance_clean(self):
        assert colan.check_bill(105, 100, rel=0.10).ok
        assert colan.check_bill(100, 100).ok

    def test_divergence_flags_both_directions(self):
        assert "COL05" in _codes(colan.check_bill(115, 100, rel=0.10))
        assert "COL05" in _codes(colan.check_bill(85, 100, rel=0.10))

    def test_zero_bill_with_traffic_flags(self):
        rep = colan.check_bill(512, 0)
        assert "COL05" in _codes(rep)
        assert colan.check_bill(0, 0).ok


# ======================================================================
# declared contracts over the REAL trainer + linalg programs
# (one trace per subject, zero compiles — CompileWatch-proven)
# ======================================================================

def _tiny_mlp():
    from deeplearning4j_tpu.nn import (
        DenseLayer, InputType, NeuralNetConfiguration, OutputLayer, Sgd,
    )

    return (NeuralNetConfiguration.Builder()
            .seed(7).updater(Sgd(0.05)).activation("tanh").list()
            .layer(DenseLayer(nOut=16))
            .layer(DenseLayer(nOut=16))
            .layer(OutputLayer(nOut=4, activation="softmax"))
            .setInputType(InputType.feedForward(8)).build())


@pytest.fixture(scope="module")
def compressed_subjects(dmesh):
    """One TRACE per gradient_compression mode (+ the ZeRO-composed
    form): the signature subjects every contract test shares. Proven
    compile-free against the session AOT cache."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.runtime.aot import CompileWatch

    rng = np.random.RandomState(0)
    x = rng.randn(2 * DP, 8).astype("float32")
    y = np.eye(4, dtype="float32")[rng.randint(0, 4, 2 * DP)]
    specs = (
        (None, None, {}),
        ("int8", "int8", {}),
        ("block_int8", "block_int8", {}),
        ("threshold", "threshold", {"threshold": 1e-2}),
        ("block_int8+zero", "block_int8",
         {"weight_update": "sharded", "min_shard_size": 64}),
        ("hierarchical", "hierarchical",
         {"threshold": 1e-2, "compressionGroupSize": 4}),
    )
    out = {}
    with CompileWatch() as watch:
        for name, mode, kw in specs:
            net = MultiLayerNetwork(_tiny_mlp()).init()
            pw = ParallelWrapper(net, mesh=dmesh,
                                 gradient_compression=mode, **kw)
            pw._place_replicated()
            leaves = jtu.tree_leaves(net._params)
            args = (net._params, net._upd_states, net._states,
                    jnp.asarray(0, jnp.int32),
                    pw._shard_batch(jnp.asarray(x)),
                    pw._shard_batch(jnp.asarray(y)),
                    jax.random.key(0), None, None)
            out[name] = {
                "net": net, "pw": pw, "n_leaves": len(leaves),
                "n_eligible": sum(1 for l in leaves
                                  if pw._zero is not None
                                  and pw._zero.eligible(l)),
                "signature": colan.collective_signature(
                    pw.trainStep(), *args),
                "args": args,
            }
    # make_jaxpr is trace-only: the whole subject build must pay ZERO
    # XLA compiles (the session-cache budget obligation in ISSUE 14)
    watch.assert_no_compiles("collective-signature subject build")
    return out


class TestTrainerContracts:
    """COL04 over all four gradient_compression modes + the composed
    ZeRO path — the scattered hand asserts now live HERE, as declared
    contracts (the dryrun checks the same declarations)."""

    @pytest.mark.parametrize("mode", [None, "int8", "block_int8",
                                      "threshold"])
    def test_mode_matches_declared_contract(self, mode,
                                            compressed_subjects):
        sub = compressed_subjects[mode]
        c = colan.compression_contract(mode, sub["n_leaves"])
        rep = c.check(sub["signature"])
        assert rep.ok, rep.format()

    def test_composed_zero_contract(self, compressed_subjects):
        sub = compressed_subjects["block_int8+zero"]
        assert sub["n_eligible"] > 0
        c = colan.compression_contract("block_int8", sub["n_leaves"],
                                       n_eligible=sub["n_eligible"])
        rep = c.check(sub["signature"])
        assert rep.ok, rep.format()

    def test_hierarchical_matches_declared_contract(
            self, compressed_subjects):
        """COL04 over the 2-hop hierarchical step (the tier-1 gate the
        tentpole adds): the declared two-hop signature — per leaf one
        hop-1 reduce_scatter, three all_gathers (hop-2 idx + value,
        hop-3 fan-back), one scale pmax, plus the single loss pmean —
        must match the traced step EXACTLY, per-hop counts and axes."""
        sub = compressed_subjects["hierarchical"]
        L = sub["n_leaves"]
        c = colan.compression_contract("hierarchical", L)
        rep = c.check(sub["signature"])
        assert rep.ok, rep.format()
        # exact per-hop counts, asserted directly so a miscounted
        # contract cannot mask a miscounted program
        counts = sub["signature"].counts()
        assert counts["reduce_scatter"] == L          # hop 1 per leaf
        assert counts["all_gather"] == 3 * L          # hop 2 (x2) + hop 3
        assert counts["pmax"] == L                    # hop-1 scale sync
        assert counts["psum"] == 1                    # the loss pmean
        # the two hops ride DIFFERENT axes of the 2-D mesh
        hop1_axes = {ax for s in sub["signature"]
                     if s.prim in ("reduce_scatter", "psum_scatter")
                     for ax in s.axes}
        gather_axes = {ax for s in sub["signature"]
                       if s.prim == "all_gather" for ax in s.axes}
        assert hop1_axes == {"intra"}
        assert gather_axes == {"group", "intra"}

    def test_hierarchical_full_verify_clean(self, compressed_subjects):
        """One-stop COL01/02/03/06 + contract over the hierarchical
        step. dp is the GROUP size: the hop-1 integer sum spans only the
        group's lanes, so the COL03 accumulator-dtype rule keys off
        group_size, not the full data-parallel degree."""
        sub = compressed_subjects["hierarchical"]
        pw = sub["pw"]
        rep = colan.verify_program(
            pw.trainStep(), *sub["args"], mesh=pw._hmesh,
            dp=pw.compression_group,
            contract=colan.compression_contract(
                "hierarchical", sub["n_leaves"]))
        assert rep.ok, rep.format()

    def test_full_verify_clean_per_mode(self, compressed_subjects,
                                        dmesh):
        """The one-stop pass (COL01/02/03/06 + contract) over the int8
        and threshold steps: the package's own trainers must be
        hazard-free."""
        for mode in ("int8", "threshold"):
            sub = compressed_subjects[mode]
            rep = colan.verify_program(
                sub["pw"].trainStep(), *sub["args"], mesh=dmesh, dp=DP,
                contract=colan.compression_contract(
                    mode, sub["n_leaves"]))
            assert rep.ok, (mode, rep.format())

    def test_drifted_program_fails_contract(self, compressed_subjects,
                                            dmesh):
        """A wrapped step that sneaks ONE extra collective in is
        caught — the silent-communication-change regression the
        contracts exist for."""
        sub = compressed_subjects["int8"]

        def drifted(*args):
            out = sub["pw"].trainStep()(*args)
            extra = _smap(lambda v: lax.pmax(v, DATA_AXIS), dmesh,
                          (P(),), P())(jnp.zeros(()))
            return (*out[:-1], out[-1] + extra)

        c = colan.compression_contract("int8", sub["n_leaves"])
        rep = c.check(colan.collective_signature(drifted, *sub["args"]))
        assert "COL04" in _codes(rep), rep.format()


class TestLinalgContracts:
    """COL04 over the canonical distributed-linalg routines (>= 3 —
    acceptance): SUMMA 2-D GEMM, Gram, covariance, transpose-B matmul
    and the CG lstsq (the latter in TestCol01ControlFlow)."""

    @pytest.fixture(scope="class")
    def mesh2(self):
        return build_mesh({"data": 4, "model": 2}, jax.devices())

    def test_matmul2d(self, mesh2):
        from deeplearning4j_tpu.linalg.distributed import _summa_2d_body

        f = _smap(functools.partial(_summa_2d_body, row_axis="data",
                                    col_axis="model", n_cols=2),
                  mesh2, (P("data", "model"),) * 2, P("data", "model"))
        rep = colan.verify_program(
            f, jnp.ones((8, 8)), jnp.ones((8, 4)), mesh=mesh2,
            contract=colan.linalg_contract("matmul2d"))
        assert rep.ok, rep.format()

    def test_matmul1d(self, dmesh):
        from deeplearning4j_tpu.linalg.distributed import _summa_1d_body

        f = _smap(functools.partial(_summa_1d_body, row_axis=DATA_AXIS,
                                    n_rows=DP),
                  dmesh, (P(DATA_AXIS, None),) * 2, P(DATA_AXIS, None))
        rep = colan.verify_program(
            f, jnp.ones((DP * 2, DP * 2)), jnp.ones((DP * 2, 4)),
            mesh=dmesh, contract=colan.linalg_contract("matmul1d"))
        assert rep.ok, rep.format()

    def test_gram_and_covariance(self, dmesh):
        from deeplearning4j_tpu.linalg.distributed import _build_gram

        rep = colan.verify_program(
            _build_gram(dmesh, DATA_AXIS, None), jnp.ones((DP * 2, 4)),
            mesh=dmesh, contract=colan.linalg_contract("gram"))
        assert rep.ok, rep.format()

    def test_routine_drift_is_caught(self, dmesh):
        """gram checked against the WRONG declaration (matmul2d's)
        fails — contracts discriminate between routines."""
        from deeplearning4j_tpu.linalg.distributed import _build_gram

        rep = colan.verify_program(
            _build_gram(dmesh, DATA_AXIS, None), jnp.ones((DP * 2, 4)),
            mesh=dmesh, contract=colan.linalg_contract("matmul2d"))
        assert "COL04" in _codes(rep), rep.format()


# ======================================================================
# acceptance: every COL code fires on broken input, clean corpus passes
# ======================================================================

@pytest.mark.lint
def test_acceptance_all_col_codes_covered(dmesh):
    triggered = set()

    def bad_cond(x):
        return lax.cond(x.sum() > 0,
                        lambda v: lax.psum(v, DATA_AXIS),
                        lambda v: v, x)

    f = _smap(bad_cond, dmesh, (P(DATA_AXIS, None),), P(DATA_AXIS, None))
    triggered |= _codes(colan.verify_program(f, jnp.ones((DP, 4))))

    def psum_only(x):
        return lax.psum(x, DATA_AXIS)

    f2 = _smap(psum_only, dmesh, (P(DATA_AXIS, None),), P(None, None))
    triggered |= _codes(colan.verify_program(f2, jnp.ones((DP, 4)),
                                             mesh={"rows": DP}))

    sig16 = colan.CollectiveSignature([colan.CollectiveSite(
        "psum", (DATA_AXIS,), "int16", 64, ())])
    triggered |= _codes(colan.check_acc_dtype(sig16, 512))
    triggered |= _codes(colan.compression_contract("int8", 4)
                        .check({"pmax": 4, "psum": 3}))
    triggered |= _codes(colan.check_bill(150, 100))
    triggered |= _codes(colan.check_signature(
        colan.CollectiveSignature([colan.CollectiveSite(
            "ppermute", (DATA_AXIS,), "float32", 8, (),
            perm=((0, 0),))]), mesh_axes={DATA_AXIS}))

    assert {"COL01", "COL02", "COL03", "COL04", "COL05",
            "COL06"} <= triggered, triggered
    assert triggered <= set(ALL_CODES)

"""Data pipeline tests: normalizers, built-in iterators, record readers,
transform pipelines.

Mirrors the reference's nd4j-dataset / datavec tests
(NormalizerStandardizeTest, CSVRecordReaderTest, TransformProcessTest...).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    DataSet, DataSetIterator, NormalizerStandardize, NormalizerMinMaxScaler,
    ImagePreProcessingScaler, VGG16ImagePreProcessor, IrisDataSetIterator,
    MnistDataSetIterator, Cifar10DataSetIterator, CSVRecordReader,
    CollectionRecordReader, Schema, TransformProcess,
    RecordReaderDataSetIterator,
)


# ------------------------------------------------------------- normalizers
class TestNormalizerStandardize:
    def test_zero_mean_unit_var(self):
        rng = np.random.RandomState(0)
        f = rng.randn(200, 5) * np.array([1, 2, 3, 4, 5.0]) + np.arange(5)
        ds = DataSet(f.astype(np.float32), np.zeros((200, 2), np.float32))
        n = NormalizerStandardize().fit(ds)
        n.preProcess(ds)
        out = ds.getFeatures().toNumpy()
        np.testing.assert_allclose(out.mean(0), 0, atol=1e-4)
        np.testing.assert_allclose(out.std(0), 1, atol=1e-3)

    def test_streaming_fit_equals_full_fit(self):
        rng = np.random.RandomState(1)
        f = rng.randn(120, 3).astype(np.float32) * 4 + 7
        l = np.zeros((120, 2), np.float32)
        full = NormalizerStandardize().fit(DataSet(f, l))
        it = DataSetIterator(f, l, 32, pad_final=False)
        stream = NormalizerStandardize().fit(it)
        np.testing.assert_allclose(stream._mean, full._mean, rtol=1e-6)
        np.testing.assert_allclose(stream._std, full._std, rtol=1e-5)

    def test_revert_round_trip(self):
        rng = np.random.RandomState(2)
        f = (rng.randn(50, 4) * 3 + 1).astype(np.float32)
        ds = DataSet(f.copy(), np.zeros((50, 2), np.float32))
        n = NormalizerStandardize().fit(ds)
        n.preProcess(ds)
        back = n.revertFeatures(ds.getFeatures()).toNumpy()
        np.testing.assert_allclose(back, f, atol=1e-4)

    def test_cnn_4d_per_channel(self):
        rng = np.random.RandomState(3)
        f = rng.rand(20, 3, 8, 8).astype(np.float32) * np.array([1, 10, 100]).reshape(1, 3, 1, 1)
        ds = DataSet(f, np.zeros((20, 2), np.float32))
        n = NormalizerStandardize().fit(ds)
        n.preProcess(ds)
        out = ds.getFeatures().toNumpy()
        np.testing.assert_allclose(out.mean((0, 2, 3)), 0, atol=1e-4)
        np.testing.assert_allclose(out.std((0, 2, 3)), 1, atol=1e-3)

    def test_fit_label(self):
        rng = np.random.RandomState(4)
        f = rng.randn(60, 2).astype(np.float32)
        l = (rng.randn(60, 1) * 9 + 5).astype(np.float32)
        ds = DataSet(f, l)
        n = NormalizerStandardize().fitLabel(True).fit(ds)
        n.preProcess(ds)
        np.testing.assert_allclose(ds.getLabels().toNumpy().mean(), 0, atol=1e-4)

    def test_save_load(self, tmp_path):
        rng = np.random.RandomState(5)
        ds = DataSet(rng.randn(30, 3).astype(np.float32), np.zeros((30, 1), np.float32))
        n = NormalizerStandardize().fit(ds)
        p = str(tmp_path / "norm.npz")
        n.save(p)
        n2 = NormalizerStandardize.load(p)
        np.testing.assert_allclose(n2._mean, n._mean)
        np.testing.assert_allclose(n2._std, n._std)


class TestMinMaxAndImageScalers:
    def test_minmax_range(self):
        rng = np.random.RandomState(6)
        f = (rng.randn(100, 4) * 5).astype(np.float32)
        ds = DataSet(f, np.zeros((100, 1), np.float32))
        n = NormalizerMinMaxScaler(-1.0, 1.0).fit(ds)
        n.preProcess(ds)
        out = ds.getFeatures().toNumpy()
        np.testing.assert_allclose(out.min(0), -1, atol=1e-5)
        np.testing.assert_allclose(out.max(0), 1, atol=1e-5)
        back = n.revertFeatures(ds.getFeatures()).toNumpy()
        np.testing.assert_allclose(back, f, atol=1e-3)

    def test_image_scaler(self):
        f = np.array([[0.0, 127.5, 255.0]], np.float32)
        ds = DataSet(f, None)
        ImagePreProcessingScaler().fit(ds).preProcess(ds)
        np.testing.assert_allclose(ds.getFeatures().toNumpy(), [[0, 0.5, 1.0]], atol=1e-5)

    def test_vgg_preprocessor(self):
        f = np.zeros((2, 3, 4, 4), np.float32)
        ds = DataSet(f, None)
        VGG16ImagePreProcessor().preProcess(ds)
        out = ds.getFeatures().toNumpy()
        np.testing.assert_allclose(out[0, :, 0, 0], -VGG16ImagePreProcessor.MEANS)


# --------------------------------------------------------------- iterators
class TestBuiltinIterators:
    def test_iris(self):
        it = IrisDataSetIterator(batchSize=50)
        ds = it.next()
        assert ds.getFeatures().shape() == (50, 4)
        assert ds.getLabels().shape() == (50, 3)
        assert it.totalExamples() == 150

    def test_mnist_shapes(self):
        it = MnistDataSetIterator(batchSize=32, train=True, numExamples=200)
        ds = it.next()
        assert ds.getFeatures().shape() == (32, 784)
        assert ds.getLabels().shape() == (32, 10)
        f = ds.getFeatures().toNumpy()
        assert 0.0 <= f.min() and f.max() <= 1.0

    def test_mnist_cnn_shape(self):
        it = MnistDataSetIterator(batchSize=16, numExamples=64, reshapeToCnn=True)
        assert it.next().getFeatures().shape() == (16, 1, 28, 28)

    def test_cifar_shapes(self):
        it = Cifar10DataSetIterator(batchSize=8, numExamples=64)
        ds = it.next()
        assert ds.getFeatures().shape() == (8, 3, 32, 32)
        assert ds.getLabels().shape() == (8, 10)

    def test_mnist_deterministic(self):
        a = MnistDataSetIterator(batchSize=16, numExamples=32, shuffle=False, seed=7)
        b = MnistDataSetIterator(batchSize=16, numExamples=32, shuffle=False, seed=7)
        np.testing.assert_array_equal(a.next().getFeatures().toNumpy(),
                                      b.next().getFeatures().toNumpy())

    def test_mnist_is_learnable(self):
        """Synthetic-or-real, a linear probe must beat chance easily —
        guards the synthetic generator's class-conditional structure."""
        it = MnistDataSetIterator(batchSize=512, numExamples=512, shuffle=False)
        ds = it.next()
        f = ds.getFeatures().toNumpy()
        y = ds.getLabels().toNumpy().argmax(-1)
        w = np.linalg.lstsq(np.c_[f, np.ones(len(f))],
                            np.eye(10)[y], rcond=None)[0]
        acc = (np.c_[f, np.ones(len(f))].dot(w).argmax(-1) == y).mean()
        assert acc > 0.5, f"linear probe acc {acc} barely above chance"


# ----------------------------------------------------------------- records
class TestRecordReaders:
    def test_csv_reader(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("# header\n1.5,2,hello\n3.5,4,world\n")
        rr = CSVRecordReader(skipNumLines=1).initialize(p)
        assert rr.next() == [1.5, 2, "hello"]
        assert rr.next() == [3.5, 4, "world"]
        assert not rr.hasNext()
        rr.reset()
        assert rr.hasNext()

    def test_reader_to_dataset_iterator_classification(self, tmp_path):
        p = tmp_path / "d.csv"
        rows = ["%f,%f,%d" % (i * 0.1, i * 0.2, i % 3) for i in range(30)]
        p.write_text("\n".join(rows))
        rr = CSVRecordReader().initialize(p)
        it = RecordReaderDataSetIterator(rr, batchSize=10, labelIndex=2,
                                         numPossibleLabels=3)
        ds = it.next()
        assert ds.getFeatures().shape() == (10, 2)
        assert ds.getLabels().shape() == (10, 3)
        np.testing.assert_allclose(ds.getLabels().toNumpy().sum(-1), 1.0)

    def test_reader_regression(self):
        rr = CollectionRecordReader([[1.0, 2.0, 10.0], [3.0, 4.0, 20.0]])
        it = RecordReaderDataSetIterator(rr, batchSize=2, labelIndex=2,
                                         regression=True)
        ds = it.next()
        np.testing.assert_allclose(ds.getLabels().toNumpy(), [[10.0], [20.0]])

    def test_image_record_reader(self, tmp_path):
        from PIL import Image
        from deeplearning4j_tpu.data import ImageRecordReader

        for cls, color in [("cats", (255, 0, 0)), ("dogs", (0, 0, 255))]:
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                Image.new("RGB", (10, 12), color).save(d / f"{i}.png")
        rr = ImageRecordReader(height=8, width=8, channels=3).initialize(tmp_path)
        assert rr.getLabels() == ["cats", "dogs"]
        rec = rr.next()
        assert rec[0].shape == (3, 8, 8) and rec[1] == 0
        it = RecordReaderDataSetIterator(rr, batchSize=6)
        ds = it.next()
        assert ds.getFeatures().shape() == (6, 3, 8, 8)
        assert ds.getLabels().shape() == (6, 2)


class TestTransformProcess:
    def _schema(self):
        return (Schema.Builder()
                .addColumnsDouble("a", "b")
                .addColumnCategorical("cat", "x", "y", "z")
                .addColumnString("junk")
                .build())

    def test_remove_and_math(self):
        tp = (TransformProcess.Builder(self._schema())
              .removeColumns("junk")
              .doubleMathOp("a", "Multiply", 2.0)
              .categoricalToInteger("cat")
              .build())
        out = tp.execute([[1.0, 2.0, "y", "drop"], [3.0, 4.0, "z", "drop"]])
        assert out == [[2.0, 2.0, 1], [6.0, 4.0, 2]]
        assert tp.getFinalSchema().getColumnNames() == ["a", "b", "cat"]

    def test_one_hot(self):
        tp = (TransformProcess.Builder(self._schema())
              .removeColumns("junk")
              .categoricalToOneHot("cat")
              .build())
        out = tp.execute([[1.0, 2.0, "y"]])
        assert out == [[1.0, 2.0, 0, 1, 0]]
        assert tp.getFinalSchema().numColumns() == 5

    def test_filter(self):
        tp = (TransformProcess.Builder(self._schema())
              .filter(lambda r: r["a"] > 2.0)
              .build())
        out = tp.execute([[1.0, 0.0, "x", ""], [5.0, 0.0, "x", ""]])
        assert len(out) == 1 and out[0][0] == 1.0


# -------------------------------------------- iterator + normalizer wiring
class TestIteratorPreprocessorWiring:
    def test_normalizer_as_preprocessor(self):
        rng = np.random.RandomState(9)
        f = (rng.randn(64, 3) * 10 + 4).astype(np.float32)
        l = np.zeros((64, 2), np.float32)
        it = DataSetIterator(f, l, 16)
        n = NormalizerStandardize().fit(it)
        it.setPreProcessor(n)
        batch = it.next().getFeatures().toNumpy()
        assert abs(batch.mean()) < 1.0  # roughly centered after transform


class TestReviewRegressions:
    def test_fit_ignores_padding_and_preprocessor(self):
        rng = np.random.RandomState(10)
        f = (rng.randn(20, 3) * 5 + 2).astype(np.float32)
        l = np.zeros((20, 1), np.float32)
        # batch 16 pads the final 4-row batch to 16 by repeating the last row
        it = DataSetIterator(f, l, 16)  # pad_final defaults True
        n = NormalizerStandardize().fit(it)
        np.testing.assert_allclose(n._mean, f.mean(0), rtol=1e-5)
        # re-fitting with the preprocessor installed must see RAW data
        it.setPreProcessor(n)
        n2 = NormalizerStandardize().fit(it)
        np.testing.assert_allclose(n2._mean, f.mean(0), rtol=1e-5)

    def test_synthetic_train_test_share_templates(self):
        tr = MnistDataSetIterator(batchSize=256, numExamples=256, train=True,
                                  shuffle=False, seed=3)
        te = MnistDataSetIterator(batchSize=256, numExamples=256, train=False,
                                  shuffle=False, seed=3)
        if not tr.isSynthetic:
            pytest.skip("real MNIST present")
        dtr = tr._f, tr._l
        dte = te._f, te._l
        # linear probe trained on train split must transfer to test split
        Xtr, Ytr = dtr[0].reshape(256, -1), dtr[1].argmax(-1)
        Xte, Yte = dte[0].reshape(256, -1), dte[1].argmax(-1)
        w = np.linalg.lstsq(np.c_[Xtr, np.ones(256)], np.eye(10)[Ytr], rcond=None)[0]
        acc = (np.c_[Xte, np.ones(256)].dot(w).argmax(-1) == Yte).mean()
        assert acc > 0.4, f"train->test transfer {acc}: splits use different templates"

    def test_normalizer_promotes_uint8(self):
        f = np.arange(12, dtype=np.uint8).reshape(4, 3)
        ds = DataSet(f, np.zeros((4, 1), np.float32))
        # DataSet wraps to device array; use raw numpy apply path instead
        n = NormalizerStandardize().fit(DataSet(f.astype(np.float32), np.zeros((4, 1), np.float32)))
        out = n._apply(f, label=False)
        assert np.issubdtype(out.dtype, np.floating)
        assert out.min() < 0  # negatives preserved, not wrapped

    def test_random_iterator_lazy_and_deterministic(self):
        from deeplearning4j_tpu.data import RandomDataSetIterator
        it = RandomDataSetIterator(3, (4, 5), (4, 2), seed=9)
        b1 = [it.next().getFeatures().toNumpy() for _ in range(3)]
        assert not it.hasNext()
        it.reset()
        b2 = [it.next().getFeatures().toNumpy() for _ in range(3)]
        for a, b in zip(b1, b2):
            np.testing.assert_array_equal(a, b)
        assert not np.array_equal(b1[0], b1[1])

    def test_one_hot_unknown_state_raises(self):
        sch = Schema.Builder().addColumnCategorical("c", "x", "y").build()
        tp = TransformProcess.Builder(sch).categoricalToOneHot("c").build()
        with pytest.raises(ValueError, match="not in states"):
            tp.execute([["X"]])


class TestTransformDSL:
    """Joins, reducers, condition filters, DataAnalysis (reference:
    datavec-api transform.join/reduce/condition/analysis) against pandas
    oracles."""

    def _schemas(self):
        from deeplearning4j_tpu.data import Schema

        left = (Schema.Builder().addColumnString("user")
                .addColumnDouble("amount").build())
        right = (Schema.Builder().addColumnString("user")
                 .addColumnCategorical("tier", "gold", "basic").build())
        lrecs = [["ann", 10.0], ["bob", 5.0], ["ann", 2.5], ["eve", 1.0]]
        rrecs = [["ann", "gold"], ["bob", "basic"], ["zoe", "basic"]]
        return left, right, lrecs, rrecs

    def _pd_join(self, lrecs, rrecs, how):
        import pandas as pd

        ld = pd.DataFrame(lrecs, columns=["user", "amount"])
        rd = pd.DataFrame(rrecs, columns=["user", "tier"])
        return ld.merge(rd, on="user", how=how)

    @pytest.mark.parametrize("jtype,how", [("Inner", "inner"),
                                           ("LeftOuter", "left"),
                                           ("RightOuter", "right"),
                                           ("FullOuter", "outer")])
    def test_join_matches_pandas(self, jtype, how):
        from deeplearning4j_tpu.data import Join, executeJoin

        left, right, lrecs, rrecs = self._schemas()
        join = (Join.Builder(jtype).setJoinColumns("user")
                .setSchemas(left, right).build())
        schema, out = executeJoin(join, lrecs, rrecs)
        assert schema.getColumnNames() == ["user", "amount", "tier"]
        oracle = self._pd_join(lrecs, rrecs, how)
        got = sorted((r[0], -1.0 if r[1] is None else r[1], r[2] or "")
                     for r in out)
        want = sorted((u, -1.0 if a != a else a, t if t == t else "")
                      for u, a, t in oracle.itertuples(index=False))
        assert got == want

    def test_join_validates_columns(self):
        from deeplearning4j_tpu.data import Join

        left, right, _, _ = self._schemas()
        with pytest.raises(ValueError, match="missing from right"):
            (Join.Builder("Inner").setJoinColumns("amount")
             .setSchemas(left, right).build())
        with pytest.raises(ValueError, match="unknown join type"):
            Join.Builder("CrossApply")

    def test_reducer_matches_pandas_groupby(self):
        import pandas as pd

        from deeplearning4j_tpu.data import Reducer, ReduceOp, Schema

        schema = (Schema.Builder().addColumnString("k")
                  .addColumnDouble("x").addColumnDouble("y").build())
        rng = np.random.RandomState(0)
        recs = [[rng.choice(["a", "b", "c"]), float(rng.randn()),
                 float(rng.randn())] for _ in range(50)]
        red = (Reducer.Builder(ReduceOp.Mean).keyColumns("k")
               .sumColumns("x").stdevColumns("y").build())
        out_schema, out = red.execute(schema, recs)
        assert out_schema.getColumnNames() == ["k", "sum(x)", "stdev(y)"]
        df = pd.DataFrame(recs, columns=["k", "x", "y"])
        g = df.groupby("k")
        for k, sx, sy in out:
            assert sx == pytest.approx(g["x"].sum()[k])
            assert sy == pytest.approx(g["y"].std()[k])  # pandas = sample

    def test_reducer_count_min_max_first_last(self):
        from deeplearning4j_tpu.data import Reducer, ReduceOp, Schema

        schema = (Schema.Builder().addColumnString("k")
                  .addColumnDouble("v").addColumnString("tag").build())
        recs = [["a", 3.0, "p"], ["a", 1.0, "q"], ["b", 7.0, "r"]]
        red = (Reducer.Builder(ReduceOp.TakeLast).keyColumns("k")
               .countColumns("v").build())
        out_schema, out = red.execute(schema, recs)
        assert out_schema.getColumnNames() == ["k", "count(v)", "tag"]
        assert out == [["a", 2, "q"], ["b", 1, "r"]]
        red2 = (Reducer.Builder(ReduceOp.Min).keyColumns("k")
                .maxColumns("v").takeFirstColumns("tag").build())
        _, out2 = red2.execute(schema, recs)
        assert out2 == [["a", 3.0, "p"], ["b", 7.0, "r"]]
        with pytest.raises(ValueError, match="key column"):
            red.execute((Schema.Builder().addColumnDouble("z").build()),
                        [[1.0]])

    def test_condition_filter_in_transform_process(self):
        from deeplearning4j_tpu.data import (ConditionFilter, ConditionOp,
                                             DoubleColumnCondition,
                                             CategoricalColumnCondition,
                                             Schema, TransformProcess)

        schema = (Schema.Builder().addColumnDouble("amount")
                  .addColumnCategorical("tier", "gold", "basic").build())
        recs = [[10.0, "gold"], [0.5, "basic"], [3.0, "basic"],
                [0.1, "gold"]]
        tp = (TransformProcess.Builder(schema)
              .filter(ConditionFilter(DoubleColumnCondition(
                  "amount", ConditionOp.LessThan, 1.0)))
              .build())
        assert tp.execute(recs) == [[10.0, "gold"], [3.0, "basic"]]
        tp2 = (TransformProcess.Builder(schema)
               .filter(ConditionFilter(CategoricalColumnCondition(
                   "tier", ConditionOp.InSet, {"basic"})))
               .build())
        assert tp2.execute(recs) == [[10.0, "gold"], [0.1, "gold"]]
        with pytest.raises(ValueError, match="ConditionOp"):
            DoubleColumnCondition("amount", "Approximately", 1.0)

    def test_data_analysis_summary(self):
        from deeplearning4j_tpu.data import Schema, analyze

        schema = (Schema.Builder().addColumnDouble("x")
                  .addColumnCategorical("c", "u", "v").build())
        recs = [[1.0, "u"], [-2.0, "v"], [0.0, "u"], [None, None]]
        da = analyze(schema, recs)
        ax = da.getColumnAnalysis("x")
        assert ax.min == -2.0 and ax.max == 1.0
        assert ax.mean == pytest.approx(-1 / 3)
        assert ax.countMissing == 1 and ax.countZero == 1 \
            and ax.countNegative == 1
        ac = da.getColumnAnalysis("c")
        assert ac.mapOfUniqueAndCounts == {"u": 2, "v": 1}
        assert "'x' (double)" in repr(da)
        with pytest.raises(ValueError, match="no analysis"):
            da.getColumnAnalysis("nope")


class TestTransformBreadth:
    """Round-4 column-transform additions (reference: datavec-api
    transform.{string,column,doubletransform} classes)."""

    def _schema(self):
        from deeplearning4j_tpu.data import Schema

        return (Schema.Builder().addColumnString("name")
                .addColumnDouble("a").addColumnDouble("b")
                .addColumnInteger("code").build())

    def _recs(self):
        return [["x", 2.0, 4.0, 0], ["y ", 3.0, 6.0, 1], ["x", 1.0, 0.5, 2]]

    def test_string_and_categorical_retypes(self):
        from deeplearning4j_tpu.data import TransformProcess

        tp = (TransformProcess.Builder(self._schema())
              .stringMapTransform("name", {"y ": "y"})
              .appendStringColumnTransform("name", "_v1")
              .stringToCategorical("name", ["x_v1", "y_v1"])
              .integerToCategorical("code", ["lo", "mid", "hi"])
              .build())
        out = tp.execute(self._recs())
        assert [r[0] for r in out] == ["x_v1", "y_v1", "x_v1"]
        assert [r[3] for r in out] == ["lo", "mid", "hi"]
        fs = tp.getFinalSchema()
        assert fs.getType("name") == "categorical"
        assert fs.getMeta("code") == ["lo", "mid", "hi"]
        tp_bad = (TransformProcess.Builder(self._schema())
                  .stringToCategorical("name", ["x"]).build())
        with pytest.raises(ValueError, match="not in states"):
            tp_bad.execute(self._recs())

    def test_derived_and_structural_columns(self):
        from deeplearning4j_tpu.data import TransformProcess

        tp = (TransformProcess.Builder(self._schema())
              .doubleColumnsMathOp("ratio", "Divide", "a", "b")
              .addConstantColumn("ds", "string", "train")
              .duplicateColumn("a", "a_copy")
              .reorderColumns("ds", "name")
              .build())
        out = tp.execute(self._recs())
        fs = tp.getFinalSchema()
        assert fs.getColumnNames() == ["ds", "name", "a", "b", "code",
                                       "ratio", "a_copy"]
        assert out[0] == ["train", "x", 2.0, 4.0, 0, 0.5, 2.0]
        tp2 = (TransformProcess.Builder(self._schema())
               .removeAllColumnsExceptFor("a", "code").build())
        assert tp2.getFinalSchema().getColumnNames() == ["a", "code"]
        assert tp2.execute(self._recs())[1] == [3.0, 1]
        with pytest.raises(ValueError, match="unknown"):
            (TransformProcess.Builder(self._schema())
             .reorderColumns("nope").build().execute(self._recs()))
        with pytest.raises(ValueError, match="unknown"):
            (TransformProcess.Builder(self._schema())
             .removeAllColumnsExceptFor("labl").build()
             .execute(self._recs()))
        # Divide by zero: Java double semantics, not ZeroDivisionError
        tp3 = (TransformProcess.Builder(self._schema())
               .doubleColumnsMathOp("r", "Divide", "a", "b").build())
        out3 = tp3.execute([["x", 1.0, 0.0, 0], ["y", 0.0, 0.0, 1]])
        assert out3[0][-1] == float("inf")
        assert out3[1][-1] != out3[1][-1]  # NaN

    def test_conditional_replace_and_missing(self):
        from deeplearning4j_tpu.data import (ConditionOp,
                                             DoubleColumnCondition,
                                             TransformProcess)

        recs = [["x", 2.0, float("nan"), 0], ["y", -5.0, 1.0, None],
                ["z", 1.0, "", 2]]
        tp = (TransformProcess.Builder(self._schema())
              .conditionalReplaceValueTransform(
                  "a", 0.0, DoubleColumnCondition(
                      "a", ConditionOp.LessThan, 0.0))
              .replaceMissingWithValue("b", -1.0)
              .replaceMissingWithValue("code", 9)
              .build())
        out = tp.execute(recs)
        assert out[1][1] == 0.0 and out[0][1] == 2.0
        assert out[0][2] == -1.0 and out[1][3] == 9
        assert out[2][2] == -1.0  # "" = CSVRecordReader's missing field


class TestSequenceRecords:
    """CSVSequenceRecordReader + SequenceRecordReaderDataSetIterator
    (reference: datavec sequence readers feeding recurrent nets)."""

    def _write_seqs(self, tmp_path, lengths, nfeat=3):
        fdir = tmp_path / "features"
        ldir = tmp_path / "labels"
        fdir.mkdir()
        ldir.mkdir()
        rng = np.random.RandomState(0)
        for i, T in enumerate(lengths):
            feats = rng.rand(T, nfeat)
            labs = rng.randint(0, 2, (T, 1))
            (fdir / f"seq_{i}.csv").write_text(
                "\n".join(",".join(f"{v:.6f}" for v in row) for row in feats))
            (ldir / f"seq_{i}.csv").write_text(
                "\n".join(str(int(v[0])) for v in labs))
        return str(fdir), str(ldir)

    def test_reader_per_file_sequences(self, tmp_path):
        from deeplearning4j_tpu.data import CSVSequenceRecordReader

        fdir, _ = self._write_seqs(tmp_path, [4, 6])
        rr = CSVSequenceRecordReader().initialize(fdir)
        s0 = rr.next()
        s1 = rr.next()
        assert len(s0) == 4 and len(s1) == 6 and len(s0[0]) == 3
        assert not rr.hasNext()
        rr.reset()
        assert rr.hasNext()

    def test_iterator_pads_and_masks(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVSequenceRecordReader,
                                             SequenceRecordReaderDataSetIterator)

        fdir, ldir = self._write_seqs(tmp_path, [4, 6, 5])
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(ldir),
            miniBatchSize=3, numPossibleLabels=2)
        ds = it.next()
        x = ds.getFeatures().toNumpy()
        y = ds.getLabels().toNumpy()
        m = ds.getFeaturesMaskArray().toNumpy()
        assert x.shape == (3, 3, 6) and y.shape == (3, 2, 6)
        np.testing.assert_array_equal(m.sum(1), [4, 6, 5])
        # padding region is zero and one-hot labels sum to 1 on real steps
        assert x[0, :, 4:].sum() == 0
        np.testing.assert_array_equal(y[0, :, :4].sum(0), np.ones(4))
        assert y[0, :, 4:].sum() == 0

    def test_trains_masked_rnn(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVSequenceRecordReader,
                                             SequenceRecordReaderDataSetIterator)
        from deeplearning4j_tpu.nn import (NeuralNetConfiguration, InputType,
                                           MultiLayerNetwork, LSTM,
                                           RnnOutputLayer, Adam)

        fdir, ldir = self._write_seqs(tmp_path, [4, 6, 5, 7])
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                .list().layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2, activation="softmax",
                                      lossFunction="mcxent"))
                .setInputType(InputType.recurrent(3)).build())
        net = MultiLayerNetwork(conf).init()
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(ldir),
            miniBatchSize=4, numPossibleLabels=2)
        for _ in range(3):
            net.fit(it)
        assert np.isfinite(net.score())

    def test_misaligned_readers_rejected(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVSequenceRecordReader,
                                             SequenceRecordReaderDataSetIterator)

        fdir, _ = self._write_seqs(tmp_path, [4, 6])
        (tmp_path / "b").mkdir()
        _, ldir = self._write_seqs(tmp_path / "b", [5, 6])
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(ldir),
            miniBatchSize=2, numPossibleLabels=2)
        with pytest.raises(ValueError, match="aligned"):
            it.next()

    def test_ragged_regression_label_width_rejected(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVSequenceRecordReader,
                                             SequenceRecordReaderDataSetIterator)

        fdir, _ = self._write_seqs(tmp_path, [3, 3])
        ldir = tmp_path / "rlabels"
        ldir.mkdir()
        # sequence 0 has 2 label columns, sequence 1 has 3 — must raise
        # the iterator's descriptive error, not a numpy broadcast error
        (ldir / "seq_0.csv").write_text("0.1,0.2\n0.3,0.4\n0.5,0.6")
        (ldir / "seq_1.csv").write_text("0.1,0.2,0.9\n0.3,0.4,0.9\n0.5,0.6,0.9")
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(str(ldir)),
            miniBatchSize=2, regression=True)
        with pytest.raises(ValueError, match="label width"):
            it.next()

    def test_edge_cases_rejected_clearly(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVSequenceRecordReader,
                                             SequenceRecordReaderDataSetIterator)

        fdir, ldir = self._write_seqs(tmp_path, [3, 3])
        # subdirectory in the source dir is skipped, not opened
        (tmp_path / "features" / "sub").mkdir()
        rr = CSVSequenceRecordReader().initialize(fdir)
        assert len(rr._files) == 2
        # exhausted next() is loud
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(ldir),
            miniBatchSize=2, numPossibleLabels=2)
        it.next()
        with pytest.raises(ValueError, match="exhausted"):
            it.next()
        # out-of-range label is loud
        (tmp_path / "l2").mkdir()
        for i in range(2):
            (tmp_path / "l2" / f"seq_{i}.csv").write_text("7\n0\n1")
        it2 = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(str(tmp_path / "l2")),
            miniBatchSize=2, numPossibleLabels=2)
        with pytest.raises(ValueError, match="outside"):
            it2.next()
        # mismatched file counts are loud
        (tmp_path / "l3").mkdir()
        (tmp_path / "l3" / "seq_0.csv").write_text("0\n1\n0")
        it3 = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(str(tmp_path / "l3")),
            miniBatchSize=1, numPossibleLabels=2)
        with pytest.raises(ValueError, match="different sequence counts"):
            it3.next()
        # regression + numPossibleLabels=None constructs fine
        SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fdir),
            CSVSequenceRecordReader().initialize(ldir),
            miniBatchSize=2, numPossibleLabels=None, regression=True)

    def test_empty_sequence_file_and_zero_batch_rejected(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVSequenceRecordReader,
                                             SequenceRecordReaderDataSetIterator)

        fdir, ldir = self._write_seqs(tmp_path, [3])
        (tmp_path / "features" / "seq_z.csv").write_text("")
        rr = CSVSequenceRecordReader().initialize(fdir)
        rr.next()  # seq_0 fine
        with pytest.raises(ValueError, match="empty sequence file"):
            rr.next()
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(ldir),
            CSVSequenceRecordReader().initialize(ldir),
            miniBatchSize=1, numPossibleLabels=2)
        with pytest.raises(ValueError, match="positive"):
            it.next(0)


class TestDatasetIteratorVariants:
    """FashionMnist/Emnist iterators (reference: the corresponding
    deeplearning4j-datasets iterators): idx-or-synthetic loading with
    the right class counts."""

    def test_fashion_mnist_shapes(self):
        from deeplearning4j_tpu.data import FashionMnistDataSetIterator

        it = FashionMnistDataSetIterator(32, train=True, numExamples=96)
        ds = it.next()
        assert ds.getFeatures().shape() == (32, 784)
        assert ds.getLabels().shape() == (32, 10)

    def test_emnist_class_counts_and_validation(self):
        from deeplearning4j_tpu.data import EmnistDataSetIterator

        it = EmnistDataSetIterator("letters", 16, numExamples=64,
                                   reshapeToCnn=True)
        ds = it.next()
        assert ds.getFeatures().shape() == (16, 1, 28, 28)
        assert ds.getLabels().shape() == (16, 26)
        assert EmnistDataSetIterator("balanced", 8, numExamples=16
                                     ).next().getLabels().shape() == (8, 47)
        with pytest.raises(ValueError, match="unknown EMNIST"):
            EmnistDataSetIterator("bogus", 8)


class TestUtilityIterators:
    """KFoldIterator / MultipleEpochsIterator / ViewIterator (reference:
    org.deeplearning4j.datasets.iterator KFoldIterator,
    MultipleEpochsIterator, impl.ViewIterator)."""

    def _ds(self, n=10):
        f = np.arange(n * 3, dtype="float32").reshape(n, 3)
        l = np.eye(2, dtype="float32")[np.arange(n) % 2]
        from deeplearning4j_tpu.data import DataSet
        return DataSet(f, l)

    def test_kfold_partition(self):
        from deeplearning4j_tpu.data import KFoldIterator
        ds = self._ds(10)
        it = KFoldIterator(3, ds)   # fold sizes 4,3,3
        seen_test_rows = []
        folds = 0
        while it.hasNext():
            train = it.next()
            test = it.testFold()
            folds += 1
            assert train.numExamples() + test.numExamples() == 10
            tr = train.getFeatures().toNumpy()[:, 0]
            te = test.getFeatures().toNumpy()[:, 0]
            assert not set(tr) & set(te)  # disjoint
            seen_test_rows.extend(te.tolist())
        assert folds == 3
        # every example held out exactly once across folds
        assert sorted(seen_test_rows) == [float(3 * i) for i in range(10)]

    def test_kfold_sizes_first_folds_larger(self):
        from deeplearning4j_tpu.data import KFoldIterator
        it = KFoldIterator(3, self._ds(10))
        sizes = [it.next().numExamples() for _ in range(3)]
        assert sizes == [6, 7, 7]  # tests are 4,3,3

    def test_kfold_validation(self):
        from deeplearning4j_tpu.data import KFoldIterator
        with pytest.raises(ValueError, match="k must be"):
            KFoldIterator(1, self._ds(10))
        with pytest.raises(ValueError, match="exceeds"):
            KFoldIterator(20, self._ds(10))

    def test_multiple_epochs_replays(self):
        from deeplearning4j_tpu.data import (DataSetIterator,
                                             MultipleEpochsIterator)
        f = np.arange(8, dtype="float32").reshape(4, 2)
        l = np.eye(2, dtype="float32")[[0, 1, 0, 1]]
        it = MultipleEpochsIterator(3, DataSetIterator(f, l, 2))
        batches = [b for b in it]
        assert len(batches) == 6  # 2 batches/epoch x 3 epochs
        assert it.totalExamples() == 12
        # resets cleanly for a second pass
        assert len([b for b in it]) == 6

    def test_multiple_epochs_trains_like_epochs_arg(self):
        from deeplearning4j_tpu.data import (DataSetIterator,
                                             MultipleEpochsIterator)
        from deeplearning4j_tpu.nn import (NeuralNetConfiguration,
                                           DenseLayer, OutputLayer,
                                           MultiLayerNetwork, Adam)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype("float32")
        Y = np.eye(2, dtype="float32")[(X.sum(1) > 0).astype(int)]

        def build():
            conf = (NeuralNetConfiguration.Builder().seed(3)
                    .updater(Adam(1e-2)).list()
                    .layer(DenseLayer(nIn=4, nOut=8, activation="tanh"))
                    .layer(OutputLayer(nOut=2, activation="softmax"))
                    .build())
            return MultiLayerNetwork(conf).init()

        a = build()
        a.fit(DataSetIterator(X, Y, 32), epochs=4)
        b = build()
        b.fit(MultipleEpochsIterator(4, DataSetIterator(X, Y, 32)))
        assert abs(a.score() - b.score()) < 1e-5

    def test_view_iterator(self):
        from deeplearning4j_tpu.data import ViewIterator
        it = ViewIterator(self._ds(10), 4)
        b1 = it.next()
        assert b1.numExamples() == 4
        np.testing.assert_allclose(
            b1.getFeatures().toNumpy()[:, 0], [0.0, 3.0, 6.0, 9.0])

    def test_kfold_reset_clears_test_fold(self):
        from deeplearning4j_tpu.data import KFoldIterator
        it = KFoldIterator(3, self._ds(9))
        while it.hasNext():
            it.next()
        it.reset()
        with pytest.raises(RuntimeError, match="next"):
            it.testFold()

    def test_multiple_epochs_normalizer_stats_unbiased(self):
        # NormalizerStandardize.fit must see one UNPADDED pass, not
        # numEpochs padded replays
        from deeplearning4j_tpu.data import (DataSetIterator,
                                             MultipleEpochsIterator)
        from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
        f = np.arange(10, dtype="float32").reshape(5, 2)  # odd vs batch 2
        l = np.eye(2, dtype="float32")[[0, 1, 0, 1, 0]]
        n1, n2 = NormalizerStandardize(), NormalizerStandardize()
        n1.fit(DataSetIterator(f, l, 2))
        n2.fit(MultipleEpochsIterator(3, DataSetIterator(f, l, 2)))
        np.testing.assert_allclose(np.asarray(n1._mean), np.asarray(n2._mean))
        np.testing.assert_allclose(np.asarray(n1._std), np.asarray(n2._std))


class TestMiniBatchFileIterator:
    """MiniBatchFileDataSetIterator (reference: org.deeplearning4j
    .datasets.iterator.MiniBatchFileDataSetIterator)."""

    def _ds(self, n=10):
        from deeplearning4j_tpu.data import DataSet
        f = np.arange(n * 2, dtype="float32").reshape(n, 2)
        l = np.eye(2, dtype="float32")[np.arange(n) % 2]
        return DataSet(f, l)

    def test_batches_roundtrip_from_disk(self, tmp_path):
        import os
        from deeplearning4j_tpu.data import MiniBatchFileDataSetIterator
        it = MiniBatchFileDataSetIterator(self._ds(10), 4,
                                          rootDir=tmp_path / "mb")
        assert len(os.listdir(it.rootDir())) == 3  # 4+4+2
        batches = [b for b in it]
        # final batch PADS to the fixed shape with a zero label-mask
        # over the pad rows (module invariant: one XLA executable)
        assert [b.numExamples() for b in batches] == [4, 4, 4]
        lm = batches[-1].getLabelsMaskArray().toNumpy()
        np.testing.assert_allclose(lm, [1, 1, 0, 0])
        all_f = np.concatenate([b.getFeatures().toNumpy()
                                for b in batches[:2]]
                               + [batches[2].getFeatures().toNumpy()[:2]])
        np.testing.assert_allclose(all_f,
                                   self._ds(10).getFeatures().toNumpy())
        assert it.totalExamples() == 10
        assert it.inputColumns() == 2 and it.totalOutcomes() == 2
        # second pass re-reads the same files
        assert len([b for b in it]) == 3

    def test_masks_persist(self, tmp_path):
        from deeplearning4j_tpu.data import (DataSet,
                                             MiniBatchFileDataSetIterator)
        f = np.zeros((5, 2, 3), "float32")
        l = np.zeros((5, 2, 3), "float32")
        fm = np.arange(15, dtype="float32").reshape(5, 3)
        it = MiniBatchFileDataSetIterator(
            DataSet(f, l, featuresMask=fm), 5, rootDir=tmp_path / "mbm")
        b = it.next()
        np.testing.assert_allclose(b.getFeaturesMaskArray().toNumpy(), fm)

    def test_composes_with_normalizer_and_epochs(self, tmp_path):
        from deeplearning4j_tpu.data import (
            DataSetIterator, MiniBatchFileDataSetIterator,
            MultipleEpochsIterator)
        from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
        ds = self._ds(10)
        it = MiniBatchFileDataSetIterator(ds, 4, rootDir=tmp_path / "mbn")
        n1, n2 = NormalizerStandardize(), NormalizerStandardize()
        n1.fit(MultipleEpochsIterator(2, it))
        n2.fit(DataSetIterator(ds.getFeatures().toNumpy(),
                               ds.getLabels().toNumpy(), 4))
        np.testing.assert_allclose(np.asarray(n1._mean),
                                   np.asarray(n2._mean))

    def test_next_num_rejected(self, tmp_path):
        from deeplearning4j_tpu.data import MiniBatchFileDataSetIterator
        it = MiniBatchFileDataSetIterator(self._ds(8), 4,
                                          rootDir=tmp_path / "mbx")
        with pytest.raises(ValueError, match="re-batch"):
            it.next(3)

    def test_delete_on_exhaust(self, tmp_path):
        import os
        from deeplearning4j_tpu.data import MiniBatchFileDataSetIterator
        it = MiniBatchFileDataSetIterator(self._ds(6), 3,
                                          rootDir=tmp_path / "mb2",
                                          delete_on_exhaust=True)
        list(it)
        assert os.listdir(it.rootDir()) == []

    def test_delete_on_exhaust_reset_raises(self, tmp_path):
        from deeplearning4j_tpu.data import MiniBatchFileDataSetIterator
        it = MiniBatchFileDataSetIterator(self._ds(6), 3,
                                          rootDir=tmp_path / "mbr",
                                          delete_on_exhaust=True)
        assert len([b for b in it]) == 2
        with pytest.raises(RuntimeError, match="delete_on_exhaust"):
            it.reset()


class TestTransformProcessJson:
    """TransformProcess.toJson/fromJson (reference: DataVec
    TransformProcess JSON persistence)."""

    def _schema(self):
        return (Schema.Builder().addColumnDouble("x")
                .addColumnCategorical("c", "a", "b")
                .addColumnString("s").build())

    def test_roundtrip_execution_parity(self):
        from deeplearning4j_tpu.data import TransformProcess as TP
        tp = (TP.Builder(self._schema())
              .doubleMathOp("x", "Multiply", 3.0)
              .categoricalToOneHot("c")
              .appendStringColumnTransform("s", "_z")
              .build())
        tp2 = TP.fromJson(tp.toJson())
        rows = [[1.0, "a", "p"], [2.0, "b", "q"]]
        assert tp2.execute([list(r) for r in rows]) == \
            tp.execute([list(r) for r in rows])
        assert tp2.getFinalSchema().getColumnNames() == \
            tp.getFinalSchema().getColumnNames()

    def test_condition_filter_roundtrips(self):
        from deeplearning4j_tpu.data import TransformProcess as TP
        from deeplearning4j_tpu.data.transform import (
            ColumnCondition, ConditionFilter, ConditionOp)
        tp = (TP.Builder(self._schema())
              .filter(ConditionFilter(ColumnCondition(
                  "c", ConditionOp.InSet, {"b"})))
              .build())
        tp2 = TP.fromJson(tp.toJson())
        out = tp2.execute([[1.0, "a", "p"], [2.0, "b", "q"]])
        assert out == [[1.0, "a", "p"]]  # 'b' rows removed

    def test_conditional_replace_roundtrips(self):
        from deeplearning4j_tpu.data import TransformProcess as TP
        from deeplearning4j_tpu.data.transform import (
            ColumnCondition, ConditionOp)
        tp = (TP.Builder(self._schema())
              .conditionalReplaceValueTransform(
                  "x", -1.0, ColumnCondition("x", ConditionOp.GreaterThan,
                                             5.0))
              .build())
        tp2 = TP.fromJson(tp.toJson())
        assert tp2.execute([[9.0, "a", "p"]]) == [[-1.0, "a", "p"]]

    def test_raw_callable_filter_refuses_loudly(self):
        from deeplearning4j_tpu.data import TransformProcess as TP
        tp = (TP.Builder(self._schema())
              .filter(lambda rec: rec["x"] > 0)
              .build())
        with pytest.raises(ValueError, match="cannot be serialized"):
            tp.toJson()

    def test_json_is_plain_data(self):
        import json
        from deeplearning4j_tpu.data import TransformProcess as TP
        tp = (TP.Builder(self._schema())
              .removeColumns("s").renameColumn("x", "y").build())
        d = json.loads(tp.toJson())
        assert [e["op"] for e in d["steps"]] == ["removeColumns",
                                                 "renameColumn"]
        assert d["initialSchema"]["columns"][0] == ["x", "double", None]

    def test_builder_mutation_after_build_stays_consistent(self):
        # _steps/_spec/_unserializable share storage: a builder mutated
        # after build() must not leave the process executing steps its
        # serialized form omits
        from deeplearning4j_tpu.data import TransformProcess as TP
        b = TP.Builder(self._schema())
        tp = b.build()
        b.filter(lambda rec: rec["x"] > 0)
        assert tp.execute([[1.0, "a", "p"], [-1.0, "b", "q"]]) == \
            [[-1.0, "b", "q"]]  # the filter runs
        with pytest.raises(ValueError, match="cannot be serialized"):
            tp.toJson()        # ...so serialization must refuse

    def test_int_keyed_mapping_roundtrips(self):
        from deeplearning4j_tpu.data import TransformProcess as TP
        s = Schema.Builder().addColumnInteger("i").build()
        tp = TP.Builder(s).stringMapTransform("i", {1: 99}).build()
        tp2 = TP.fromJson(tp.toJson())
        assert tp2.execute([[1], [2]]) == tp.execute([[1], [2]]) == \
            [[99], [2]]

    def test_arg_mutation_after_record_does_not_leak(self):
        from deeplearning4j_tpu.data import TransformProcess as TP
        m = {"a": "b"}
        tp = TP.Builder(self._schema()).stringMapTransform("s", m).build()
        m["a"] = "CHANGED"
        tp2 = TP.fromJson(tp.toJson())
        assert tp.execute([[1.0, "a", "a"]]) == \
            tp2.execute([[1.0, "a", "a"]]) == [[1.0, "a", "b"]]

    def test_numpy_scalar_arg_serializes(self):
        import numpy as _np
        from deeplearning4j_tpu.data import TransformProcess as TP
        tp = (TP.Builder(self._schema())
              .doubleMathOp("x", "Multiply", _np.float64(2.0)).build())
        tp2 = TP.fromJson(tp.toJson())  # must NOT be "unserializable"
        assert tp2.execute([[3.0, "a", "p"]])[0][0] == 6.0


class TestRecordReaderMultiDataSetIterator:
    """Multi-input/-output reader batches (reference:
    org.deeplearning4j.datasets.datavec.RecordReaderMultiDataSetIterator)."""

    def _csv(self, tmp_path, name, rows):
        p = tmp_path / name
        p.write_text("\n".join(",".join(str(v) for v in r) for r in rows))
        return CSVRecordReader().initialize(p)

    def test_two_readers_sliced_inputs_onehot_output(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        rr1 = self._csv(tmp_path, "a.csv",
                        [[i * 0.1, i * 0.2, i * 0.3] for i in range(10)])
        rr2 = self._csv(tmp_path, "b.csv",
                        [[i * 1.0, i % 3] for i in range(10)])
        it = (RecordReaderMultiDataSetIterator.Builder(4)
              .addReader("a", rr1).addReader("b", rr2)
              .addInput("a", 0, 1)        # two columns
              .addInput("b", 0, 0)        # one column
              .addOutputOneHot("b", 1, 3)
              .build())
        mds = it.next()
        f = mds.getFeatures()
        assert len(f) == 2
        assert f[0].shape() == (4, 2) and f[1].shape() == (4, 1)
        l = mds.getLabels()
        assert len(l) == 1 and l[0].shape() == (4, 3)
        np.testing.assert_allclose(l[0].toNumpy().sum(-1), 1.0)
        np.testing.assert_allclose(f[0].toNumpy()[1], [0.1, 0.2], rtol=1e-6)

    def test_whole_record_input_and_range_output(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        rr = self._csv(tmp_path, "c.csv",
                       [[i, i + 1, i * 0.5] for i in range(6)])
        it = (RecordReaderMultiDataSetIterator.Builder(6)
              .addReader("r", rr)
              .addInput("r", 0, 1)
              .addOutput("r", 2, 2)
              .build())
        mds = it.next()
        assert mds.getLabels()[0].shape() == (6, 1)
        np.testing.assert_allclose(mds.getLabels()[0].toNumpy()[:, 0],
                                   [0, 0.5, 1.0, 1.5, 2.0, 2.5])

    def test_count_mismatch_raises(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        rr1 = self._csv(tmp_path, "d.csv", [[1, 2]] * 4)
        rr2 = self._csv(tmp_path, "e.csv", [[1, 0]] * 5)
        with pytest.raises(ValueError, match="record count"):
            (RecordReaderMultiDataSetIterator.Builder(2)
             .addReader("x", rr1).addReader("y", rr2)
             .addInput("x").addOutputOneHot("y", 1, 2).build())

    def test_validation_errors(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        B = RecordReaderMultiDataSetIterator.Builder
        rr = self._csv(tmp_path, "f.csv", [[1, 2]] * 3)
        with pytest.raises(ValueError, match="unknown reader"):
            B(2).addReader("r", rr).addInput("nope")
        with pytest.raises(ValueError, match="addInput"):
            B(2).addReader("r", rr).addOutput("r", 0, 0).build()
        rr2 = self._csv(tmp_path, "g.csv", [[1, 9]] * 3)
        with pytest.raises(ValueError, match="outside"):
            (B(2).addReader("r", rr2).addInput("r", 0, 0)
             .addOutputOneHot("r", 1, 3).build())

    def test_feeds_two_input_graph(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer,
                                           InputType, MergeVertex,
                                           NeuralNetConfiguration,
                                           OutputLayer, Adam)
        rng = np.random.RandomState(0)
        a = rng.randn(48, 3)
        b = rng.randn(48, 2)
        y = ((a.sum(1) + b.sum(1)) > 0).astype(int)
        rr1 = self._csv(tmp_path, "ga.csv", a.round(4).tolist())
        rr2 = self._csv(tmp_path, "gb.csv",
                        [[*row.round(4), int(lab)]
                         for row, lab in zip(b, y)])
        it = (RecordReaderMultiDataSetIterator.Builder(16)
              .addReader("a", rr1).addReader("b", rr2)
              .addInput("a")
              .addInput("b", 0, 1)
              .addOutputOneHot("b", 2, 2)
              .build())
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
                .graphBuilder()
                .addInputs("inA", "inB")
                .addLayer("dA", DenseLayer(nIn=3, nOut=8,
                                           activation="tanh"), "inA")
                .addLayer("dB", DenseLayer(nIn=2, nOut=8,
                                           activation="tanh"), "inB")
                .addVertex("merge", MergeVertex(), "dA", "dB")
                .addLayer("out", OutputLayer(nOut=2, activation="softmax"),
                          "merge")
                .setOutputs("out")
                .setInputTypes(InputType.feedForward(3),
                               InputType.feedForward(2))
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(40):
            net.fit(it)
        out = net.outputSingle(a.astype("float32"), b.astype("float32"))
        acc = (np.asarray(out.toNumpy()).argmax(1) == y).mean()
        assert acc > 0.9, acc


class TestExistingMiniBatchIterator:
    def test_reads_writer_output(self, tmp_path):
        from deeplearning4j_tpu.data import (
            DataSet, ExistingMiniBatchDataSetIterator,
            MiniBatchFileDataSetIterator)
        f = np.arange(12, dtype="float32").reshape(6, 2)
        l = np.eye(2, dtype="float32")[np.arange(6) % 2]
        MiniBatchFileDataSetIterator(DataSet(f, l), 3,
                                     rootDir=tmp_path / "mb")
        it = ExistingMiniBatchDataSetIterator(tmp_path / "mb")
        batches = [b for b in it]
        assert len(batches) == 2
        np.testing.assert_allclose(
            np.concatenate([b.getFeatures().toNumpy() for b in batches]), f)

    def test_missing_dir_and_empty(self, tmp_path):
        from deeplearning4j_tpu.data import ExistingMiniBatchDataSetIterator
        with pytest.raises(ValueError, match="not a directory"):
            ExistingMiniBatchDataSetIterator(tmp_path / "nope")
        (tmp_path / "empty").mkdir()
        with pytest.raises(ValueError, match="no files matching"):
            ExistingMiniBatchDataSetIterator(tmp_path / "empty")

    def test_interop_surface_and_padding(self, tmp_path):
        from deeplearning4j_tpu.data import (
            DataSet, DataSetIterator, ExistingMiniBatchDataSetIterator,
            MiniBatchFileDataSetIterator, MultipleEpochsIterator)
        from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
        f = np.arange(14, dtype="float32").reshape(7, 2)
        l = np.eye(2, dtype="float32")[np.arange(7) % 2]
        MiniBatchFileDataSetIterator(DataSet(f, l), 3,
                                     rootDir=tmp_path / "mb7")
        it = ExistingMiniBatchDataSetIterator(tmp_path / "mb7")
        assert it.batch() == 3 and it.totalExamples() == 7
        assert it.inputColumns() == 2 and it.totalOutcomes() == 2
        batches = [b for b in it]
        # final short file pads at read time with a zero label mask
        assert [b.numExamples() for b in batches] == [3, 3, 3]
        np.testing.assert_allclose(
            batches[-1].getLabelsMaskArray().toNumpy(), [1, 0, 0])
        # wraps in MultipleEpochsIterator, and normalizer stats are
        # unpadded + preprocessor-free
        meit = MultipleEpochsIterator(2, it)
        assert meit.batch() == 3
        n1, n2 = NormalizerStandardize(), NormalizerStandardize()
        it.setPreProcessor(n1)
        n1.fit(it)
        n2.fit(DataSetIterator(f, l, 3))
        np.testing.assert_allclose(np.asarray(n1._mean),
                                   np.asarray(n2._mean))
        with pytest.raises(ValueError, match="re-batch"):
            it.next(2)

    def test_ragged_row_diagnostic(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVRecordReader,
                                             RecordReaderMultiDataSetIterator)
        p = tmp_path / "ragged.csv"
        p.write_text("1,2,3\n4,5\n6,7,8\n")
        # subclass defeats the exact-type bulk fast path so the row loop
        # (whose diagnostics we are testing) actually runs
        class SlowCSV(CSVRecordReader):
            pass
        rr = SlowCSV().initialize(p)
        # the shortest row (2 cols) governs the valid range, so a spec
        # reaching col 2 fails loudly up front instead of IndexError
        # mid-parse
        with pytest.raises(ValueError, match="shortest row"):
            (RecordReaderMultiDataSetIterator.Builder(2)
             .addReader("r", rr).addInput("r", 0, 2)
             .addOutputOneHot("r", 0, 9).build())


class TestSequenceMultiReader:
    """addSequenceReader in RecordReaderMultiDataSetIterator (reference
    overload): sequence specs produce padded+masked [B, C, T] arrays."""

    def _seq_files(self, tmp_path, name, seqs):
        d = tmp_path / name
        d.mkdir()
        for i, rows in enumerate(seqs):
            (d / f"seq_{i:02d}.csv").write_text(
                "\n".join(",".join(str(v) for v in r) for r in rows))
        from deeplearning4j_tpu.data import CSVSequenceRecordReader
        return CSVSequenceRecordReader().initialize(d)

    def test_padded_masked_ncw(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        srr = self._seq_files(tmp_path, "s1", [
            [[1, 10], [2, 20], [3, 30]],     # T=3
            [[4, 40]],                        # T=1
        ])
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addSequenceReader("s", srr)
              .addInput("s", 0, 0)
              .addOutput("s", 1, 1)
              .build())
        mds = it.next()
        f = mds.getFeatures()[0].toNumpy()
        assert f.shape == (2, 1, 3)          # NCW, padded to Tmax=3
        np.testing.assert_allclose(f[0, 0], [1, 2, 3])
        np.testing.assert_allclose(f[1, 0], [4, 0, 0])
        fm = mds.getFeaturesMaskArrays()[0].toNumpy()
        np.testing.assert_allclose(fm, [[1, 1, 1], [1, 0, 0]])
        lm = mds.getLabelsMaskArrays()[0].toNumpy()
        np.testing.assert_allclose(lm, fm)

    def test_per_step_onehot_labels(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        srr = self._seq_files(tmp_path, "s2", [
            [[0.5, 0], [0.6, 2]],
            [[0.7, 1], [0.8, 1]],
        ])
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .addSequenceReader("s", srr)
              .addInput("s", 0, 0)
              .addOutputOneHot("s", 1, 3)
              .build())
        l = it.next().getLabels()[0].toNumpy()
        assert l.shape == (2, 3, 2)          # [B, classes, T]
        np.testing.assert_allclose(l[0, :, 0], [1, 0, 0])
        np.testing.assert_allclose(l[0, :, 1], [0, 0, 1])
        np.testing.assert_allclose(l[1, :, 0], [0, 1, 0])

    def test_mixed_static_and_sequence_trains_graph(self, tmp_path):
        from deeplearning4j_tpu.data import (CSVRecordReader,
                                             RecordReaderMultiDataSetIterator)
        from deeplearning4j_tpu.nn import (ComputationGraph, DenseLayer,
                                           InputType, MergeVertex,
                                           NeuralNetConfiguration,
                                           OutputLayer, Adam)
        from deeplearning4j_tpu.nn.conf.recurrent import LSTM, LastTimeStep
        rng = np.random.RandomState(1)
        n, T = 32, 4
        seqs = rng.rand(n, T, 1).round(3)
        static = rng.randn(n, 2).round(3)
        y = ((seqs.sum((1, 2)) + static.sum(1)) > 2.0).astype(int)
        srr = self._seq_files(tmp_path, "s3",
                              [s.tolist() for s in seqs])
        p = tmp_path / "static.csv"
        p.write_text("\n".join(
            ",".join(str(v) for v in row) + f",{int(l)}"
            for row, l in zip(static, y)))
        it = (RecordReaderMultiDataSetIterator.Builder(16)
              .addSequenceReader("seq", srr)
              .addReader("st", CSVRecordReader().initialize(p))
              .addInput("seq")
              .addInput("st", 0, 1)
              .addOutputOneHot("st", 2, 2)
              .build())
        conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-2))
                .graphBuilder()
                .addInputs("inSeq", "inSt")
                .addLayer("rnn", LastTimeStep(LSTM(nIn=1, nOut=8)), "inSeq")
                .addLayer("dSt", DenseLayer(nIn=2, nOut=8,
                                            activation="tanh"), "inSt")
                .addVertex("m", MergeVertex(), "rnn", "dSt")
                .addLayer("out", OutputLayer(nOut=2, activation="softmax"),
                          "m")
                .setOutputs("out")
                .setInputTypes(InputType.recurrent(1, T),
                               InputType.feedForward(2))
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(30):
            net.fit(it)
        assert np.isfinite(net.score())
        out = net.outputSingle(
            np.transpose(seqs, (0, 2, 1)).astype("float32"),
            static.astype("float32"))
        acc = (np.asarray(out.toNumpy()).argmax(1) == y).mean()
        assert acc > 0.85, acc

    def test_inconsistent_seq_widths_raise(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        srr = self._seq_files(tmp_path, "s4",
                              [[[1, 2]], [[1, 2, 3]]])
        with pytest.raises(ValueError, match="inconsistent"):
            (RecordReaderMultiDataSetIterator.Builder(2)
             .addSequenceReader("s", srr)
             .addInput("s").addOutput("s", 0, 0).build())

    def test_padded_final_batch_masks_none_entries(self, tmp_path):
        # a None-mask label padded with duplicate rows must gain a
        # zero-tail mask — unmasked duplicates would count in the loss
        from deeplearning4j_tpu.data.multidataset import MultiDataSetIterator
        seqf = np.random.RandomState(0).rand(3, 1, 2).astype("float32")
        seql = np.ones((3, 2, 2), "float32")
        statl = np.eye(2, dtype="float32")[[0, 1, 0]]
        mask = np.ones((3, 2), "float32")
        it = MultiDataSetIterator([seqf], [seql, statl], 2,
                                  featuresMasks=[mask],
                                  labelsMasks=[mask, None])
        it.next()
        mds = it.next()  # final short batch (1 real + 1 pad)
        lms = mds.getLabelsMaskArrays()
        assert lms[1] is not None
        np.testing.assert_allclose(lms[1].toNumpy(), [1.0, 0.0])

    def test_ragged_sequence_diagnostic(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderMultiDataSetIterator
        d = tmp_path / "rg"
        d.mkdir()
        (d / "seq_00.csv").write_text("1,2\n1,2,3")
        from deeplearning4j_tpu.data import CSVSequenceRecordReader
        srr = CSVSequenceRecordReader().initialize(d)
        with pytest.raises(ValueError, match="ragged sequence"):
            (RecordReaderMultiDataSetIterator.Builder(1)
             .addSequenceReader("s", srr)
             .addInput("s").addOutput("s", 0, 0).build())


class TestMultipleEpochsEmptyUnderlying:
    """ADVICE r4: hasNext()==True must guarantee next() succeeds even
    when the underlying iterator is EMPTY and epochs remain."""

    class _Empty:
        def reset(self):
            pass

        def hasNext(self):
            return False

        def next(self, num=None):
            raise StopIteration

    def test_empty_underlying_contract(self):
        from deeplearning4j_tpu.data.dataset import MultipleEpochsIterator

        it = MultipleEpochsIterator(3, self._Empty())
        assert not it.hasNext()
        with pytest.raises(StopIteration):
            it.next()
        assert list(iter(it)) == []

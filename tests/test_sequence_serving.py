"""Iteration-level sequence serving gates (serving/sequence.py,
nn/multilayer.py rnnStepBatched, docs/SERVING.md "Sequence serving").

What must hold:

- parity: slot-batched per-step outputs are BITWISE equal to serial
  ``rnnTimeStep`` per slot — ragged lengths, mid-sequence refills and
  zero-padded slots included (fixed slot bucket: within one bucket
  parity is structural);
- scheduling: early-exit slots are refilled from the queue
  MID-SEQUENCE, per-request deadlines are honored at every STEP
  boundary (queued or mid-flight), occupancy accounting is exact;
- compile discipline: ``warm()`` precompiles one executable per slot
  bucket and a whole mixed-length serve pays ZERO further compiles
  (CompileWatch);
- throughput: iteration-level scheduling beats run-to-completion
  (gang) batching by >= 2x aggregate decode throughput on a
  straggler-skewed workload — deterministically in dispatch counts AND
  in wall clock (the ISSUE 15 acceptance gate);
- the scheduler exposes the MicroBatcher's deterministic test seam:
  ManualClock + thread-less ``poll()``/``drain()``, zero sleeps.
"""

import threading

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.runtime import aot
from deeplearning4j_tpu.serving import (
    DeadlineExceededError, ManualClock, ModelHost, QueueFullError,
    SequenceScheduler, ServingClosedError, greedy_onehot_feedback,
)


# ----------------------------------------------------------------------
# subjects
# ----------------------------------------------------------------------

def _rnn_net(seed=7):
    """LSTM + GRU + RnnOutputLayer — one carry of each shape."""
    from deeplearning4j_tpu.nn import (InputType, NeuralNetConfiguration,
                                       Nesterovs)
    from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.recurrent import GRU, LSTM
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Nesterovs(0.1, 0.9)).list()
            .layer(LSTM(nOut=8))
            .layer(GRU(nOut=8))
            .layer(RnnOutputLayer(nOut=5, activation="softmax",
                                  lossFunction="mcxent"))
            .setInputType(InputType.recurrent(4, 6)).build())
    return MultiLayerNetwork(conf).init()


def _char_net(seed=3, vocab=5):
    """vocab-in/vocab-out char-rnn shape (generation feedback tests)."""
    from deeplearning4j_tpu.nn import (InputType, NeuralNetConfiguration,
                                       Nesterovs)
    from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.recurrent import LSTM
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Nesterovs(0.1, 0.9)).list()
            .layer(LSTM(nOut=8))
            .layer(RnnOutputLayer(nOut=vocab, activation="softmax",
                                  lossFunction="mcxent"))
            .setInputType(InputType.recurrent(vocab, 6)).build())
    return MultiLayerNetwork(conf).init()


def _seqs(lens, seed=0, width=4):
    rng = np.random.RandomState(seed)
    return [rng.randn(t, width).astype(np.float32) for t in lens]


def _serial_oracle(net, seqs):
    """Per-sequence serial rnnTimeStep outputs (the bitwise bar)."""
    outs = []
    for s in seqs:
        net.rnnClearPreviousState()
        outs.append(np.concatenate(
            [np.asarray(net.rnnTimeStep(s[t:t + 1]).jax())
             for t in range(s.shape[0])], axis=0))
    net.rnnClearPreviousState()
    return outs


def _sched(net, **kw):
    kw.setdefault("slot_buckets", (4,))
    kw.setdefault("queue_limit", 32)
    clk = kw.pop("clock", None) or ManualClock()
    return SequenceScheduler(net, clock=clk, start_thread=False,
                             **kw), clk


@pytest.fixture
def fresh_cache():
    """Fresh MEMORY-ONLY session cache (hermetic miss counting)."""
    prev = aot._SESSION
    cache = aot._SESSION = aot.ExecutableCache(None)
    yield cache
    aot._SESSION = prev


# ----------------------------------------------------------------------
# the functional slot-batched step (nn/multilayer.py)
# ----------------------------------------------------------------------

class TestCarryAPI:
    def test_carry_spec_shapes(self):
        net = _rnn_net()
        assert net.rnnCarrySpec() == (("h", "c"), ("h",), ())
        zeros = net.rnnCarryZeros(3)
        assert sorted(zeros[0]) == ["c", "h"]
        assert zeros[0]["h"].shape == (3, 8)
        assert sorted(zeros[1]) == ["h"] and zeros[2] == {}

    def test_non_stepwise_nets_rejected_loudly(self):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           NeuralNetConfiguration,
                                           Nesterovs, OutputLayer)
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.recurrent import (Bidirectional,
                                                          LSTM)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        bidi = (NeuralNetConfiguration.Builder().seed(1)
                .updater(Nesterovs(0.1, 0.9)).list()
                .layer(Bidirectional(layer=LSTM(nOut=8)))
                .layer(RnnOutputLayer(nOut=4, activation="softmax",
                                      lossFunction="mcxent"))
                .setInputType(InputType.recurrent(4, 6)).build())
        with pytest.raises(ValueError, match="Bidirectional"):
            MultiLayerNetwork(bidi).init().rnnCarrySpec()

        ff = (NeuralNetConfiguration.Builder().seed(1)
              .updater(Nesterovs(0.1, 0.9)).list()
              .layer(DenseLayer(nOut=8, activation="relu"))
              .layer(OutputLayer(nOut=4, activation="softmax",
                                 lossFunction="mcxent"))
              .setInputType(InputType.feedForward(4)).build())
        with pytest.raises(ValueError, match="no recurrent layers"):
            MultiLayerNetwork(ff).init().rnnCarrySpec()
        with pytest.raises(ValueError, match="no recurrent layers"):
            SequenceScheduler(MultiLayerNetwork(ff).init())

    def test_step_batched_bitwise_vs_rnn_time_step(self):
        """One jitted slot-batched step == the eager stateful path,
        bitwise, carried state included — the foundation the whole
        scheduler's parity rests on."""
        net = _rnn_net()
        rng = np.random.RandomState(1)
        xs = rng.randn(3, 2, 4).astype(np.float32)  # [B=3, T=2, F]
        net.rnnClearPreviousState()
        want = [np.asarray(net.rnnTimeStep(xs[:, t]).jax())
                for t in range(2)]
        net.rnnClearPreviousState()
        carries = [jax.tree_util.tree_map(np.asarray, d)
                   for d in net.rnnCarryZeros(3)]
        for t in range(2):
            out, nc = net.rnnStepBatched(xs[:, t], carries)
            np.testing.assert_array_equal(np.asarray(out), want[t])
            carries = [{k: np.asarray(v) for k, v in d.items()}
                       for d in nc]


# ----------------------------------------------------------------------
# scheduler matrix: deterministic (ManualClock, no thread, no sleeps)
# ----------------------------------------------------------------------

class TestSchedulerDeterministic:
    def test_ragged_lengths_bitwise_and_occupancy(self):
        net = _rnn_net()
        lens = [5, 2, 7, 1, 3, 4]
        seqs = _seqs(lens, seed=0)
        oracle = _serial_oracle(net, seqs)
        sched, _ = _sched(net)
        reqs = [sched.submit(s, wait=False) for s in seqs]
        polls = 0
        while sched.poll():
            polls += 1
        for r, want in zip(reqs, oracle):
            assert r.done and r.error is None
            np.testing.assert_array_equal(r.result, want)
        st = sched.stats
        assert st["completed"] == len(seqs)
        # occupancy accounting is exact: the live-slot sum over all
        # dispatches is the total token count, and every bucket is 4
        assert st["slot_steps"] == sum(lens)
        assert sum(n for n, _ in sched.occupancy) == sum(lens)
        assert all(b == 4 for _, b in sched.occupancy)
        assert st["dispatches"] == polls == len(sched.occupancy)
        # 6 sequences through 4 slots: at least 2 admissions landed
        # while other sequences were mid-flight
        assert st["refills"] >= 2
        sched.close()

    def test_refill_mid_sequence_reuses_freed_slot(self):
        net = _rnn_net()
        seqs = _seqs([3, 1, 2], seed=1)
        oracle = _serial_oracle(net, seqs)
        sched, _ = _sched(net, slot_buckets=(2,))
        reqs = [sched.submit(s, wait=False) for s in seqs]
        assert sched.poll() == 2          # seqs 0,1 admitted; 1 done
        assert reqs[1].done and not reqs[0].done
        assert sched.active_slots == 1    # slot freed by early exit
        assert sched.poll() == 2          # seq 2 refilled MID-sequence
        assert sched.stats["refills"] == 1
        sched.drain()
        for r, want in zip(reqs, oracle):
            np.testing.assert_array_equal(r.result, want)
        sched.close()

    def test_deadline_expires_at_step_boundary_and_frees_slot(self):
        net = _rnn_net()
        sched, clk = _sched(net, slot_buckets=(1,))
        doomed = sched.submit(_seqs([6], seed=2)[0], wait=False,
                              deadline=clk() + 0.5)
        queued = sched.submit(_seqs([2], seed=3)[0], wait=False)
        assert sched.poll() == 1          # doomed steps once
        assert doomed.steps_done == 1 and not doomed.done
        clk.advance(1.0)                  # deadline passes MID-FLIGHT
        assert sched.poll() == 1          # expiry freed the slot;
        #                                   queued was admitted SAME tick
        assert isinstance(doomed.error, DeadlineExceededError)
        assert "mid-sequence" in str(doomed.error)
        sched.drain()
        assert queued.done and queued.error is None
        st = sched.stats
        assert st["expired"] == 1 and st["completed"] == 1
        sched.close()

    def test_queued_deadline_expires_without_a_slot(self):
        net = _rnn_net()
        sched, clk = _sched(net, slot_buckets=(1,))
        hog = sched.submit(_seqs([4], seed=4)[0], wait=False)
        doomed = sched.submit(_seqs([1], seed=5)[0], wait=False,
                              deadline=clk() + 0.5)
        sched.poll()
        clk.advance(1.0)
        sched.drain()
        assert hog.done and hog.error is None
        assert isinstance(doomed.error, DeadlineExceededError)
        assert "before a slot" in str(doomed.error)
        # the doomed sequence never wasted a dispatch
        assert sched.stats["slot_steps"] == 4
        sched.close()

    def test_queue_full_and_close_contracts(self):
        net = _rnn_net()
        sched, _ = _sched(net, queue_limit=2)
        r1 = sched.submit(_seqs([2], seed=6)[0], wait=False)
        sched.submit(_seqs([2], seed=7)[0], wait=False)
        with pytest.raises(QueueFullError, match="queueLimit=2"):
            sched.submit(_seqs([1], seed=8)[0], wait=False)
        assert sched.stats["rejected"] == 1
        sched.poll()                       # both admitted, one step in
        sched.close(drain=False)
        assert isinstance(r1.error, ServingClosedError)
        with pytest.raises(ServingClosedError):
            sched.submit(_seqs([1], seed=9)[0], wait=False)

    def test_submit_validation(self):
        net = _rnn_net()
        sched, _ = _sched(net)
        with pytest.raises(ValueError, match="feature width"):
            sched.submit(np.zeros((2, 3), np.float32), wait=False)
        with pytest.raises(ValueError, match="steps >= 1"):
            sched.submit(np.zeros((0, 4), np.float32), wait=False)
        with pytest.raises(ValueError, match="feedback"):
            sched.submit(np.zeros((2, 4), np.float32), wait=False,
                         extra_steps=3)
        with pytest.raises(ValueError, match="admission"):
            SequenceScheduler(net, admission="magic")
        sched.close()

    def test_dispatch_failure_fails_live_slots(self):
        net = _rnn_net()
        sched, _ = _sched(net)
        reqs = [sched.submit(s, wait=False) for s in _seqs([3, 2],
                                                           seed=10)]
        sched.poll()
        net_step, net._jit_rnn_step = net._jit_rnn_step, None  # break it
        try:
            assert sched.poll() == 0
        finally:
            net._jit_rnn_step = net_step
        for r in reqs:
            assert isinstance(r.error, TypeError)
            with pytest.raises(TypeError):
                r.wait(0)
        assert sched.stats["errors"] == 2
        sched.close()

    def test_generation_feedback_bitwise(self):
        """Closed-loop generation (prompt + extra_steps with greedy
        one-hot feedback) matches the serial rnnTimeStep + argmax loop
        bitwise."""
        net = _char_net()
        vocab = 5
        rng = np.random.RandomState(11)
        prompt = np.eye(vocab, dtype=np.float32)[
            rng.randint(0, vocab, 2)]
        extra = 3
        # serial oracle: stateful stepping with greedy re-feed
        net.rnnClearPreviousState()
        outs, x = [], prompt[0]
        for t in range(2 + extra):
            y = np.asarray(net.rnnTimeStep(x[None, :]).jax())[0]
            outs.append(y)
            x = prompt[t + 1] if t + 1 < 2 else \
                np.eye(vocab, dtype=np.float32)[int(np.argmax(y))]
        net.rnnClearPreviousState()
        sched, _ = _sched(net, feedback=greedy_onehot_feedback(vocab))
        req = sched.submit(prompt, wait=False, extra_steps=extra)
        sched.drain()
        assert req.result.shape == (2 + extra, vocab)
        np.testing.assert_array_equal(req.result, np.stack(outs))
        sched.close()

    def test_raising_feedback_fails_request_not_scheduler(self):
        """A feedback that raises (or returns a wrong-width row) fails
        ITS sequence and frees the slot; the other slots and later
        submits keep serving — user feedback bugs must never kill the
        scheduler (the wait contract: no caller blocked forever)."""
        net = _char_net()
        vocab = 5
        prompt = np.eye(vocab, dtype=np.float32)[[0, 1]]
        sched, _ = _sched(net)
        good = sched.submit(prompt, wait=False)
        boom = sched.submit(prompt, wait=False, extra_steps=2,
                            feedback=lambda row: 1 / 0)
        wide = sched.submit(prompt, wait=False, extra_steps=1,
                            feedback=lambda row: np.zeros(
                                vocab + 3, np.float32))
        sched.drain()
        assert good.result.shape == (2, vocab)
        with pytest.raises(ZeroDivisionError):
            boom.wait(0)
        with pytest.raises(ValueError, match="feedback row"):
            wide.wait(0)
        assert sched.active_slots == 0 and sched.depth == 0
        assert sched.stats["errors"] == 2
        # the scheduler still serves after the user-code failures
        again = sched.submit(prompt, wait=False)
        sched.drain()
        assert again.result.shape == (2, vocab)
        sched.close()


# ----------------------------------------------------------------------
# compile discipline
# ----------------------------------------------------------------------

class TestCompileDiscipline:
    def test_warm_then_zero_steady_state_compiles(self, fresh_cache):
        """warm() pays exactly one compile per slot bucket; a whole
        ragged mixed-length serve after it — refills, early exits,
        occupancy swings — pays ZERO (the CompileWatch gate the fleet
        soak and bench leg reuse)."""
        net = _rnn_net()
        sched, _ = _sched(net, slot_buckets=(2, 4))
        rep = sched.warm()
        assert {b: r["status"] for b, r in rep.items()} == \
            {2: "cold", 4: "cold"}
        assert fresh_cache.stats["misses"] == 2
        with aot.CompileWatch(fresh_cache) as watch:
            reqs = [sched.submit(s, wait=False)
                    for s in _seqs([5, 1, 3, 2, 4, 1, 2], seed=12)]
            sched.drain()
        assert all(r.done and r.error is None for r in reqs)
        watch.assert_no_compiles("mixed-length sequence serve")
        # warming again is free
        assert {b: r["status"] for b, r in sched.warm().items()} == \
            {2: "warm", 4: "warm"}
        sched.close()


# ----------------------------------------------------------------------
# the acceptance gate: iteration-level >= 2x run-to-completion
# ----------------------------------------------------------------------

class TestIterationVsGang:
    #: straggler-skewed workload (the bench serving_fleet twin): short
    #: sequences interleaved with long stragglers, so every gang batch
    #: pads its short members to a straggler's length
    LENS = [24, 2, 2, 2, 2, 2] * 4

    def _run(self, admission, seqs):
        net = _rnn_net()
        sched = SequenceScheduler(net, slot_buckets=(8,),
                                  queue_limit=64, admission=admission,
                                  clock=ManualClock(),
                                  start_thread=False)
        sched.warm()
        import time as _time

        t0 = _time.perf_counter()
        reqs = [sched.submit(s, wait=False) for s in seqs]
        sched.drain()
        wall = _time.perf_counter() - t0
        st = sched.stats
        assert all(r.done and r.error is None for r in reqs)
        results = [r.result for r in reqs]
        sched.close()
        return st, wall, results

    def test_iteration_level_2x_gang_and_bitwise(self):
        """ISSUE 15 acceptance: >= 2x aggregate decode throughput vs
        run-to-completion batching on a mixed-length workload, per-slot
        outputs bitwise equal to serial rnnTimeStep in BOTH modes. The
        dispatch-count ratio is deterministic; the wall-clock ratio is
        measured with a retry shield against CI-rig noise."""
        seqs = _seqs(self.LENS, seed=13)
        oracle = _serial_oracle(_rnn_net(), seqs)
        best = 0.0
        for attempt in range(3):
            st_step, wall_step, res_step = self._run("step", seqs)
            st_gang, wall_gang, res_gang = self._run("gang", seqs)
            # same work, bitwise identical results
            assert st_step["slot_steps"] == st_gang["slot_steps"] \
                == sum(self.LENS)
            for got, want in zip(res_step, oracle):
                np.testing.assert_array_equal(got, want)
            for got, want in zip(res_gang, oracle):
                np.testing.assert_array_equal(got, want)
            # deterministic half of the gate: iteration-level re-forms
            # the batch every step, so it needs >= 2x fewer dispatches
            assert st_gang["dispatches"] \
                >= 2 * st_step["dispatches"], (st_step, st_gang)
            assert st_step["refills"] > 0       # the lever that does it
            assert st_gang["refills"] == 0      # gang never refills
            tok_step = st_step["slot_steps"] / wall_step
            tok_gang = st_gang["slot_steps"] / wall_gang
            best = max(best, tok_step / tok_gang)
            if best >= 2.0:
                break
        assert best >= 2.0, (
            f"iteration-level sustained only {best:.2f}x "
            f"run-to-completion decode throughput "
            f"({st_step['dispatches']} vs {st_gang['dispatches']} "
            "dispatches)")


# ----------------------------------------------------------------------
# host integration: sequence models behind ModelHost
# ----------------------------------------------------------------------

class TestHostSequenceModels:
    def test_register_submit_policy_snapshot(self, fresh_cache):
        host = ModelHost()
        try:
            net = _rnn_net()
            rep = host.register_sequence("charlstm", net,
                                         slotBuckets=(4,))
            assert rep["version"] == 1
            assert {b: r["status"] for b, r in rep["warm"].items()} \
                == {4: "cold"}
            pol = host.describe()["charlstm"]
            assert pol["kind"] == "sequence"
            assert pol["slotBuckets"] == [4]
            assert pol["featureSize"] == 4
            with pytest.raises(ValueError, match="swap_sequence"):
                host.register_sequence("charlstm", net)
            with pytest.raises(ValueError, match="registered"):
                host.register("charlstm", net)

            seq = _seqs([4], seed=14)[0]
            want = _serial_oracle(net, [seq])[0]
            got = host.submit_sequence("charlstm", seq)
            np.testing.assert_array_equal(np.asarray(got), want)

            snap = host.metrics_snapshot()
            # PR 13 schema intact, fleet view additive
            assert set(snap) == {"registry", "models", "sequences"}
            view = snap["sequences"]["charlstm"]
            assert view["version"] == 1
            assert view["stats"]["completed"] == 1
            assert view["queue_depth"] == 0
            assert view["active_slots"] == 0
            assert view["slot_occupancy"]["dispatches"] >= 4
            assert host.queued_work("charlstm") == 0
            assert host.queued_work("ghost") is None
            assert "charlstm" in host and "charlstm" in host.names()
        finally:
            host.close()

    def test_swap_sequence_zero_compiles_and_new_weights(self,
                                                         fresh_cache):
        host = ModelHost()
        try:
            net1 = _rnn_net()
            net2 = _rnn_net()   # identical conf -> identical cache keys
            net2._params = jax.tree_util.tree_map(lambda a: a * 1.5,
                                                  net2._params)
            seq = _seqs([3], seed=15)[0]
            want2 = _serial_oracle(net2, [seq])[0]
            host.register_sequence("m", net1, slotBuckets=(4,))
            host.submit_sequence("m", seq)
            with aot.CompileWatch(fresh_cache) as watch:
                rep = host.swap_sequence("m", net2)
                got = host.submit_sequence("m", seq)
            assert rep["version"] == 2
            assert {b: r["status"] for b, r in rep["warm"].items()} \
                == {4: "warm"}
            watch.assert_no_compiles("sequence rolling swap")
            np.testing.assert_array_equal(np.asarray(got), want2)
            with pytest.raises(KeyError, match="register_sequence"):
                host.swap_sequence("ghost", net2)
        finally:
            host.close()

    def test_register_sequence_warm_failure_closes_scheduler(
            self, fresh_cache, monkeypatch):
        """A failed warm() must not leak the half-built model: its
        scheduler thread is joined, its telemetry series released, and
        the name is immediately re-registrable."""
        from deeplearning4j_tpu.serving import host as host_mod

        net = _rnn_net()
        captured = {}

        def bad_warm(self, cache=None):
            captured["sm"] = self
            raise RuntimeError("warm kaboom")

        monkeypatch.setattr(host_mod.ServedSequenceModel, "warm",
                            bad_warm)
        host = ModelHost()
        try:
            with pytest.raises(RuntimeError, match="warm kaboom"):
                host.register_sequence("s", net, slotBuckets=(2,))
            sched = captured["sm"].scheduler
            assert sched._thread is None      # joined, not leaked
            monkeypatch.undo()
            host.register_sequence("s", net, slotBuckets=(2,))
            assert host.kind("s") == "sequence"
        finally:
            host.close()

    def test_http_generate_route(self, fresh_cache):
        import json
        import urllib.error
        import urllib.request

        from deeplearning4j_tpu.serving import InferenceServer

        host = ModelHost()
        net = _rnn_net()
        host.register_sequence("charlstm", net, slotBuckets=(4,))
        srv = InferenceServer(host).start(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            seq = _seqs([3], seed=16)[0]
            want = _serial_oracle(net, [seq])[0]

            def post(url, obj):
                req = urllib.request.Request(
                    url, data=json.dumps(obj).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read().decode())

            status, body = post(base + "/v1/models/charlstm:generate",
                                {"steps": seq.tolist()})
            assert status == 200 and body["steps"] == 3
            np.testing.assert_array_equal(
                np.asarray(body["outputs"], np.float32), want)
            # policy table carries the sequence row
            with urllib.request.urlopen(base + "/v1/models",
                                        timeout=10) as r:
                table = json.loads(r.read().decode())["models"]
            assert table["charlstm"]["kind"] == "sequence"
            for url, obj, code in [
                    (base + "/v1/models/ghost:generate",
                     {"steps": seq.tolist()}, 404),
                    (base + "/v1/models/charlstm:generate", {}, 400),
                    (base + "/v1/models/charlstm:generate",
                     {"steps": np.zeros((2, 3)).tolist()}, 400)]:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    post(url, obj)
                assert ei.value.code == code, url
        finally:
            srv.stop(close_host=True)

    def test_threaded_scheduler_serves_blocking_submits(self,
                                                        fresh_cache):
        """clock=None -> the background iteration loop serves blocking
        submit() callers from handler threads (the production mode)."""
        net = _rnn_net()
        host = ModelHost()
        host.register_sequence("m", net, slotBuckets=(4,))
        seqs = _seqs([3, 5, 2, 4], seed=17)
        oracle = _serial_oracle(net, seqs)
        got = [None] * len(seqs)

        def client(i):
            got[i] = np.asarray(
                host.submit_sequence("m", seqs[i], deadline_s=30.0))

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(seqs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        try:
            for g, want in zip(got, oracle):
                assert g is not None
                np.testing.assert_array_equal(g, want)
        finally:
            host.close()


# ----------------------------------------------------------------------
# non-f32 dtype policies (docs/SERVING.md: the bf16 1-ulp note)
# ----------------------------------------------------------------------

class TestNonF32Policies:

    @staticmethod
    def _bf16_net(seed=7):
        from deeplearning4j_tpu.ndarray.dtype import DataType
        from deeplearning4j_tpu.nn import (InputType,
                                           NeuralNetConfiguration,
                                           Nesterovs)
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.recurrent import GRU, LSTM
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Nesterovs(0.1, 0.9))
                .dataType(DataType.BFLOAT16).list()
                .layer(LSTM(nOut=8))
                .layer(GRU(nOut=8))
                .layer(RnnOutputLayer(nOut=5, activation="softmax",
                                      lossFunction="mcxent"))
                .setInputType(InputType.recurrent(4, 6)).build())
        return MultiLayerNetwork(conf).init()

    def test_bf16_carries_live_in_compute_dtype(self, fresh_cache):
        """Regression: the slot table hardcoded float32 carries, so a
        bf16 model's cell math ran f32-promoted — every step diverged
        from what the model computes. Carries must live in the compute
        dtype; the batched trajectory is then BITWISE the jitted
        functional drive (same bucket, zero-padded), and within 1 bf16
        ulp of the eager serial rnnTimeStep (XLA fusion moves the
        narrow-dtype roundings — the documented limit)."""
        import jax.numpy as jnp

        net = self._bf16_net()
        bf16 = np.dtype(jnp.bfloat16)
        sched, clk = _sched(net)
        assert np.dtype(sched._carry_dtype) == bf16

        seqs = _seqs([3, 6, 4], seed=1)
        reqs = [sched.submit(s, wait=False) for s in seqs]
        sched.drain()
        got = [np.asarray(r.wait(5)) for r in reqs]
        assert all(g.dtype == bf16 for g in got)

        for s, g in zip(seqs, got):
            # deterministic reference: solo zero-padded functional
            # drive through the SAME bucket-4 executable
            S = sched.max_slots
            carry = [{k: np.zeros((S, 8), bf16) for k in keys}
                     for keys in net.rnnCarrySpec()]
            ref = []
            for st in s:
                x = np.zeros((S, s.shape[1]), np.float32)
                x[0] = st
                y, nc = net.rnnStepBatched(x, carry)
                ref.append(np.array(np.asarray(y))[0])
                carry = []
                for d in nc:
                    col = {k: np.array(np.asarray(v), copy=True)
                           for k, v in d.items()}
                    for k in col:
                        col[k][1:] = 0   # free slots re-zeroed, like _gather
                    carry.append(col)
            np.testing.assert_array_equal(np.stack(ref), g)
            # eager serial reference: 1-ulp band, not bitwise
            net.rnnClearPreviousState()
            serial = np.stack(
                [np.array(np.asarray(net.rnnTimeStep(st[None, :, None])))[0, :, 0]
                 for st in s])
            np.testing.assert_allclose(
                serial.astype(np.float32), g.astype(np.float32),
                atol=2 * 2.0 ** -9, rtol=0)
        net.rnnClearPreviousState()

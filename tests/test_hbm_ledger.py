"""Per-op HBM ledger + roofline floor (util/hbm_ledger.py).

The ledger is validated against XLA's own cost model: on this backend
the ENTRY-walk total must reproduce compiled.cost_analysis()["bytes
accessed"] (observed exact on XLA:CPU — both charge each instruction
its operands + results). The floor is validated arithmetically and as
a genuine lower bound on the compiled step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.util.hbm_ledger import (boundary_activation_elems,
                                                ledger, ledger_for_compiled,
                                                train_step_floor)


def _cost_bytes(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float((ca or {}).get("bytes accessed", 0.0))


class TestLedger:
    def test_single_matmul_accounting(self):
        f = jax.jit(lambda x, w: x @ w)
        one = jnp.ones((1024, 1024), jnp.float32)  # conftest enables x64
        c = f.lower(one, one).compile()
        led = ledger(c.as_text())
        # 3 x 4 MiB buffers (x, w, out) — exact up to tiny epilogue ops
        assert led["total_bytes"] == pytest.approx(3 * 1024 * 1024 * 4,
                                                   rel=0.05)
        assert "dot" in led["by_opcode"]

    def test_extended_dtypes_and_unknown_dtype_raises(self):
        # TPU modules carry dtypes CPU ones never show (u16 rng state,
        # f8 buffers): they must be priced, and anything NOT in the
        # table must raise rather than silently rank as free
        led = ledger("ENTRY e {\n"
                     "  %a = u16[1024]{0} iota(), iota_dimension=0\n"
                     "  %b = f8e4m3fn[64,64]{1,0} convert(%a)\n"
                     "}")
        by = led["by_opcode"]
        assert by["iota"] == 2048
        assert by["convert"] == 64 * 64 + 2048
        with pytest.raises(ValueError, match="unknown HLO dtype"):
            ledger("ENTRY e {\n  %a = q77[8]{0} iota()\n}")

    def test_subbyte_dtypes_priced_packed(self):
        # s4 packs two per byte (ShapeUtil::ByteSizeOf): 1001 elems ->
        # ceil(1001/2) = 501 bytes, not 1001
        led = ledger("ENTRY e {\n  %a = s4[1001]{0} iota()\n}")
        assert led["by_opcode"]["iota"] == 501

    def test_lenet_step_matches_xla_cost_analysis(self):
        from deeplearning4j_tpu.ndarray import DataType
        from deeplearning4j_tpu.zoo import LeNet

        net = LeNet(numClasses=10, inputShape=(1, 28, 28),
                    dataType=DataType.BFLOAT16).init()
        B = 64
        x = jnp.ones((B, 1, 28, 28), jnp.bfloat16)
        y = jnp.asarray(np.eye(10, dtype="float32")[np.zeros(B, int)])
        comp = jax.jit(net._train_step).lower(
            net._params, net._upd_states, net._states,
            jnp.asarray(0, jnp.int32), x, y, jax.random.key(0),
            None, None).compile()
        led = ledger_for_compiled(comp, top=5)
        assert led["total_bytes"] == pytest.approx(_cost_bytes(comp),
                                                   rel=0.01)
        # ranked descending, fusions dominate a fused conv net
        tops = [r["bytes"] for r in led["top"]]
        assert tops == sorted(tops, reverse=True)
        assert max(led["by_opcode"], key=led["by_opcode"].get) == "fusion"
        # every row decomposes: bytes = out + in
        for r in led["top"]:
            assert r["bytes"] == r["out_bytes"] + r["in_bytes"]


class TestFloor:
    def _lenet(self):
        from deeplearning4j_tpu.ndarray import DataType
        from deeplearning4j_tpu.zoo import LeNet

        return LeNet(numClasses=10, inputShape=(1, 28, 28),
                     dataType=DataType.BFLOAT16).init()

    def test_terms_arithmetic_and_param_count(self):
        net = self._lenet()
        fl = train_step_floor(net, (64, 1, 28, 28), optimizer_slots=1)
        assert fl["floor_bytes"] == sum(fl["terms"].values())
        assert fl["param_count"] == net.numParams()
        P, cb, pb = fl["param_count"], 2, 4
        assert fl["terms"]["params_master_rw"] == 2 * P * pb
        assert fl["terms"]["params_compute_copy"] == 3 * P * cb
        assert fl["terms"]["grads_wr"] == 2 * P * pb
        assert fl["terms"]["input_read"] == 64 * 28 * 28 * cb
        assert fl["terms"]["activations_4touch"] == \
            4 * fl["boundary_activation_elems"] * cb

    def test_fp32_net_has_no_phantom_cast_copy(self):
        """compute dtype == param dtype: no separate cast copy exists,
        so the floor must charge direct master reads instead (else the
        'floor' can exceed real fp32 programs)."""
        from deeplearning4j_tpu.ndarray import DataType
        from deeplearning4j_tpu.zoo import LeNet

        net = LeNet(numClasses=10, inputShape=(1, 28, 28),
                    dataType=DataType.FLOAT).init()
        fl = train_step_floor(net, (64, 1, 28, 28), optimizer_slots=1)
        P = fl["param_count"]
        assert fl["terms"]["params_compute_copy"] == 2 * P * 4

    def test_floor_is_a_lower_bound_on_compiled_step(self):
        net = self._lenet()
        B = 64
        x = jnp.ones((B, 1, 28, 28), jnp.bfloat16)
        y = jnp.asarray(np.eye(10, dtype="float32")[np.zeros(B, int)])
        comp = jax.jit(net._train_step).lower(
            net._params, net._upd_states, net._states,
            jnp.asarray(0, jnp.int32), x, y, jax.random.key(0),
            None, None).compile()
        fl = train_step_floor(net, (B, 1, 28, 28), optimizer_slots=1)
        assert fl["floor_bytes"] < _cost_bytes(comp)

    def test_boundaries_on_computation_graph(self):
        """The spy-based shape recording must work on ComputationGraph
        (the flagship ResNet-50 is one) and restore layer.forward."""
        from deeplearning4j_tpu.ndarray import DataType
        from deeplearning4j_tpu.nn import Nesterovs
        from deeplearning4j_tpu.zoo import ResNet50

        net = ResNet50(numClasses=10, inputShape=(3, 32, 32),
                       updater=Nesterovs(0.1, 0.9),
                       dataType=DataType.BFLOAT16,
                       dataFormat="NHWC").init()
        acts = boundary_activation_elems(net, (2, 32, 32, 3))
        # ResNet-50: 53 convs + stem pool
        assert len(acts) == 54
        assert all(a > 0 for a in acts)
        # spies removed: class methods are back in charge
        assert all("forward" not in l.__dict__
                   for n in net.conf.nodes.values()
                   if (l := getattr(n, "payload", None)) is not None)

    def test_resnet50_b128_headline_floor(self):
        """Pin the headline floor the bench reports: ResNet-50 b128
        NHWC bf16 + Nesterovs. Recomputed here from the model so the
        BENCH_NOTES number (11.85 GB/step vs 46.8 measured, ~3.9x
        headroom) is reproducible by CI, not copied."""
        from deeplearning4j_tpu.ndarray import DataType
        from deeplearning4j_tpu.nn import Nesterovs
        from deeplearning4j_tpu.zoo import ResNet50

        net = ResNet50(numClasses=1000, inputShape=(3, 224, 224),
                       updater=Nesterovs(0.1, 0.9),
                       dataType=DataType.BFLOAT16,
                       dataFormat="NHWC").init()
        fl = train_step_floor(net, (128, 224, 224, 3), optimizer_slots=1)
        assert fl["param_count"] == 25_557_032
        assert fl["floor_bytes"] == pytest.approx(11.85e9, rel=0.01)
        assert 46.8e9 / fl["floor_bytes"] == pytest.approx(3.95, abs=0.1)

"""Chaos-hardening gates (runtime/chaos.py, serving/breaker.py, and
the fleet failure domains in serving/fleet.py — docs/RESILIENCE.md
"Chaos harness", docs/SERVING.md "Failure domains").

What must hold:

- determinism: the same seed produces the SAME fault sequence
  (``plan.events``) over the same traffic — chaos runs are replayable,
  never sleeps-and-hope;
- the fault kinds (raise / wedge / slow / corrupt) each do exactly
  what they schedule, with an injectable sleep so no test blocks;
- the circuit breaker walks closed -> open -> half-open -> closed at
  EXACTLY the ManualClock-predicted steps;
- a quarantined replica serves only probes and is re-admitted after
  exactly ``readmit_after`` consecutive probe successes;
- the retry budget caps failover amplification at ratio + burst;
- brownout sheds ONLY requests whose deadline is already hopeless;
- the chaos soak: a live fleet under a seeded plan (wedged + flapping
  + slow replica) completes with ZERO client-visible non-injected
  failures and ZERO steady-state compiles (CompileWatch);
- the armed-but-quiet harness costs <= 1.03x the disarmed serving
  path (best-of-trials medians);
- the checkpoint content digest: a digest-mismatched snapshot is
  treated as ABSENT and ResilientFit falls back to the previous one.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.runtime import aot, chaos
from deeplearning4j_tpu.runtime.chaos import (
    ChaosError, ChaosPlan, fault_point,
)
from deeplearning4j_tpu.serving import (
    BrownoutController, CircuitBreaker, DeadlineExceededError,
    FleetRouter, ManualClock, ModelHost, ReplicaHealth, RetryBudget,
)

pytestmark = pytest.mark.faults


def _mln(seed=7, nout=16):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Nesterovs(0.1, 0.9)).list()
            .layer(DenseLayer(nOut=nout, activation="relu"))
            .layer(OutputLayer(nOut=4, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(8)).build())
    return MultiLayerNetwork(conf).init()


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, 8).astype(np.float32)


@pytest.fixture
def fresh_cache():
    prev = aot._SESSION
    cache = aot._SESSION = aot.ExecutableCache(None)
    yield cache
    aot._SESSION = prev


@pytest.fixture(autouse=True)
def _always_disarmed():
    """No test may leak an armed plan into the next."""
    chaos.disarm()
    yield
    chaos.disarm()


def _fleet(n_replicas, net, *, router_kw=None, **kw):
    kw.setdefault("batchBuckets", (8,))
    kw.setdefault("maxWaitMs", 1.0)
    fleet = FleetRouter(**(router_kw or {}))
    rids = [fleet.add_replica(ModelHost()) for _ in range(n_replicas)]
    fleet.register("m", net, **kw)
    return fleet, rids


def _count_dispatches(hosts, name="m"):
    """Per-replica dispatch counters (the serving counters in
    telemetry are labeled by MODEL, so they aggregate over replicas —
    wrap each replica's batcher dispatch to see where traffic lands).
    Serial submits coalesce 1:1, so dispatch calls == requests."""
    hits = {}
    for rid, host in hosts.items():
        hits[rid] = 0
        b = host.model(name).batcher

        def counted(feats, _rid=rid, _orig=b._dispatch):
            hits[_rid] += 1
            return _orig(feats)

        b._dispatch = counted
    return hits


# ----------------------------------------------------------------------
# ChaosPlan: determinism + fault kinds
# ----------------------------------------------------------------------
class TestChaosPlanDeterminism:
    def _drive(self, plan, n=40):
        """Fixed traffic: n invocations across two seams, injected
        raises swallowed. Returns the plan's replay record."""
        with plan:
            for i in range(n):
                seam = "fleet.dispatch" if i % 2 else "queue.dispatch"
                try:
                    fault_point(seam, payload=i)
                except ChaosError:
                    pass
        return list(plan.events)

    def _plan(self, seed):
        return (ChaosPlan(seed=seed, sleep=lambda s: None)
                .random_raises("fleet.dispatch", rate=0.3, window=20)
                .random_slows("queue.dispatch", rate=0.3, window=20,
                              seconds=0.01)
                .raise_n("queue.dispatch", at=1))

    def test_same_seed_same_traffic_identical_fault_sequence(self):
        ev_a = self._drive(self._plan(seed=5))
        ev_b = self._drive(self._plan(seed=5))
        assert ev_a == ev_b
        assert ev_a, "the seeded plan must actually fire"
        # every event is (seam, kind, ordinal)
        assert all(len(e) == 3 for e in ev_a)

    def test_different_seed_different_schedule(self):
        scheds = {json.dumps(self._plan(seed=s).schedule(),
                             sort_keys=True) for s in range(6)}
        assert len(scheds) > 1

    def test_schedule_is_fixed_before_arming(self):
        """random_* rules draw their ordinals at SCHEDULE time from
        the seeded RNG — the replay record is a pure function of the
        schedule plus each seam's invocation order."""
        a = self._plan(seed=9).schedule()
        b = self._plan(seed=9).schedule()
        assert a == b

    def test_disarmed_is_identity_and_armed_skips_ruleless_seams(self):
        payload = object()
        assert fault_point("fleet.dispatch", payload) is payload
        plan = ChaosPlan(seed=0).raise_n("queue.dispatch", at=0)
        with plan:
            # a seam with no rules takes the armed fast path: payload
            # untouched, invocation NOT counted, nothing fired
            assert fault_point("fleet.dispatch", payload) is payload
            with pytest.raises(ChaosError):
                fault_point("queue.dispatch")
        assert plan.fired("fleet.dispatch") == 0
        assert plan.fired("queue.dispatch") == 1
        assert chaos.armed_plan() is None  # __exit__ disarmed

    def test_arm_disarm_roundtrip(self):
        plan = ChaosPlan()
        assert chaos.arm(plan) is plan
        assert chaos.armed_plan() is plan
        assert chaos.disarm() is plan
        assert chaos.disarm() is None


class TestFaultKinds:
    def test_raise_n_exact_ordinals_and_custom_exc(self):
        class Boom(OSError):
            pass

        plan = ChaosPlan().raise_n("aot.disk_read", times=2, at=1,
                                   exc=Boom, message="disk gone")
        with plan:
            fault_point("aot.disk_read")            # ordinal 0: clean
            for _ in range(2):                      # ordinals 1, 2
                with pytest.raises(Boom, match="disk gone"):
                    fault_point("aot.disk_read")
            fault_point("aot.disk_read")            # ordinal 3: clean
        assert plan.events == [("aot.disk_read", "raise", 1),
                               ("aot.disk_read", "raise", 2)]

    def test_slow_and_wedge_use_injected_sleep(self):
        slept = []
        plan = (ChaosPlan(sleep=slept.append)
                .slow("queue.dispatch", 0.25, at=0)
                .wedge("queue.dispatch", 7.0, at=1))
        with plan:
            fault_point("queue.dispatch")
            fault_point("queue.dispatch")
        assert slept == [0.25, 7.0]

    def test_wedge_release_event_unblocks(self):
        release = threading.Event()
        release.set()  # pre-released: the wedge returns immediately
        plan = ChaosPlan().wedge("sequence.step", 60.0, at=0,
                                 release=release)
        t0 = time.monotonic()
        with plan:
            fault_point("sequence.step")
        assert time.monotonic() - t0 < 5.0
        assert plan.events == [("sequence.step", "wedge", 0)]

    def test_corrupt_default_and_custom_mutate(self):
        plan = (ChaosPlan()
                .corrupt("host.submit", at=0)
                .corrupt("aot.disk_read", at=0)
                .corrupt("checkpoint.write", at=0,
                         mutate=lambda p: p * 10))
        with plan:
            arr = fault_point("host.submit",
                              np.ones(4, dtype=np.float32))
            path = fault_point("aot.disk_read", "/tmp/x.bin")
            n = fault_point("checkpoint.write", 4)
        assert np.isnan(arr[0]) and not np.isnan(arr[1:]).any()
        assert path == "/tmp/x.bin.chaos-corrupt"
        assert n == 40

    def test_fired_counts_reach_telemetry(self):
        from deeplearning4j_tpu.runtime import telemetry

        plan = ChaosPlan().raise_n("server.request", times=3)
        with plan:
            for _ in range(3):
                with pytest.raises(ChaosError):
                    fault_point("server.request")
        child = telemetry.get_registry().counter(
            "dl4j_chaos_injections_total",
            "chaos faults fired, by seam and kind",
            labels=("seam", "kind")).labels(seam="server.request",
                                            kind="raise")
        assert child.value >= 3
        assert plan.fired() == 3


# ----------------------------------------------------------------------
# breaker / quarantine / budget / brownout (pure units, ManualClock)
# ----------------------------------------------------------------------
class TestCircuitBreaker:
    def test_exact_manualclock_transitions(self):
        clk = ManualClock()
        br = CircuitBreaker(window=8, failure_ratio=0.5, min_samples=4,
                            open_for_s=10.0, close_after=2, clock=clk)
        # below min_samples nothing can trip, even at 100% failure
        assert br.record(False) == "closed"
        assert br.record(False) == "closed"
        assert br.record(True) == "closed"
        # 4th sample: 3 failures / 4 samples >= 0.5 -> OPEN, now
        assert br.record(False) == "open"
        assert br.opened_total == 1 and not br.allow()
        clk.advance(9.999)
        assert br.state == "open"          # one tick early: still open
        clk.advance(0.001)
        assert br.state == "half_open"     # exactly open_for_s
        assert br.allow()
        assert br.record(True) == "half_open"  # 1 of close_after=2
        assert br.record(True) == "closed"
        assert br.snapshot()["window"] == []   # re-closed clean

    def test_half_open_failure_retrips_immediately(self):
        clk = ManualClock()
        br = CircuitBreaker(window=4, failure_ratio=0.5, min_samples=2,
                            open_for_s=5.0, close_after=2, clock=clk)
        br.record(False), br.record(False)
        assert br.state == "open"
        clk.advance(5.0)
        assert br.record(False) == "open"  # half-open probe failed
        assert br.opened_total == 2
        clk.advance(4.999)
        assert br.state == "open"          # the clock restarted

    def test_successes_never_trip(self):
        br = CircuitBreaker(window=4, min_samples=1, clock=ManualClock())
        for _ in range(50):
            assert br.record(True) == "closed"


class TestReplicaHealthQuarantine:
    def test_readmission_after_exact_probe_streak(self):
        h = ReplicaHealth(readmit_after=3, clock=ManualClock())
        assert h.admissible()
        h.quarantine()
        assert h.quarantined and not h.admissible()
        assert h.note_probe(True) is False   # streak 1
        assert h.note_probe(True) is False   # streak 2
        assert h.note_probe(False) is False  # failure RESETS the streak
        for _ in range(2):
            assert h.note_probe(True) is False
        assert h.note_probe(True) is True    # 3 consecutive: readmitted
        assert not h.quarantined and h.admissible()
        assert h.breaker.state == "closed"   # re-admission starts clean

    def test_probe_ignored_when_not_quarantined(self):
        h = ReplicaHealth(readmit_after=1, clock=ManualClock())
        assert h.note_probe(True) is False


class TestRetryBudget:
    def test_burst_then_ratio_cap(self):
        b = RetryBudget(ratio=0.5, burst=2.0)
        assert b.try_spend() and b.try_spend()  # the burst
        assert not b.try_spend()                # empty: fail fast
        b.note_request()                        # +0.5
        assert not b.try_spend()
        b.note_request()                        # +0.5 -> 1.0
        assert b.try_spend()
        snap = b.snapshot()
        assert snap["spent"] == 3 and snap["denied"] == 2
        assert snap["requests"] == 2

    def test_deposits_capped_at_burst(self):
        b = RetryBudget(ratio=1.0, burst=1.0)
        for _ in range(100):
            b.note_request()
        assert b.try_spend()
        assert not b.try_spend()  # the bucket never exceeded burst


class TestBrownout:
    def test_sheds_only_hopeless_deadlines(self):
        bo = BrownoutController(est_item_s=0.1)
        assert not bo.should_shed(4, deadline_s=0.5)   # 0.4 <= 0.5
        assert bo.should_shed(6, deadline_s=0.5)       # 0.6 > 0.5
        assert not bo.should_shed(1000, deadline_s=None)
        assert bo.snapshot() == {"shed": 1, "admitted": 2,
                                 "est_item_s": 0.1, "margin": 1.0}

    def test_no_estimate_never_sheds(self):
        bo = BrownoutController()   # no static estimate
        assert bo.estimate_wait_s(10) is None
        assert not bo.should_shed(10 ** 6, deadline_s=1e-9)
        # the measured estimate kicks in when the caller has one
        assert bo.should_shed(10, deadline_s=0.5, measured_item_s=0.1)

    def test_margin_scales_the_estimate(self):
        bo = BrownoutController(est_item_s=0.1, margin=2.0)
        assert bo.estimate_wait_s(5) == pytest.approx(1.0)


# ----------------------------------------------------------------------
# fleet failure domains (live hosts)
# ----------------------------------------------------------------------
class TestFleetFailureDomains:
    def test_failover_on_injected_dispatch_fault(self, fresh_cache):
        """An injected dispatch-path raise on the first replica is
        absorbed by failover, counted under its error class, and
        charges that replica's breaker."""
        fleet, rids = _fleet(2, _mln())
        try:
            lab = fleet._m_failover.labels(model="m",
                                           error="ChaosError")
            before = lab.value
            with ChaosPlan().raise_n("fleet.dispatch", at=0):
                out = fleet.submit("m", _rows(2, seed=1))
            assert np.asarray(out).shape == (2, 4)
            assert lab.value == before + 1
            # exactly one replica took the charge
            charged = [r for r in rids
                       if False in fleet.health(r).snapshot()["window"]]
            assert len(charged) == 1
        finally:
            fleet.close()

    def test_breaker_opens_and_recovers_at_exact_clock_steps(
            self, fresh_cache):
        """Fleet-wide chaos trips every breaker at the predicted
        record; recovery walks open -> half-open -> closed at exactly
        the ManualClock-predicted steps, mirrored into the gauge."""
        clk = ManualClock()
        fleet, rids = _fleet(
            2, _mln(), router_kw=dict(
                clock=clk,
                breaker=dict(window=4, failure_ratio=0.5,
                             min_samples=2, open_for_s=10.0,
                             close_after=1)))
        try:
            plan = ChaosPlan().raise_n("fleet.dispatch", times=10 ** 6)
            with plan:
                for _ in range(2):      # 2 failures per replica: trip
                    with pytest.raises(ChaosError):
                        fleet.submit("m", _rows(1))
            for r in rids:
                assert fleet.health(r).breaker.state == "open"
                assert fleet._m_breaker.labels(replica=r).value == 2.0
            # fail open: ALL replicas barred still serves (disarmed)
            out = fleet.submit("m", _rows(1, seed=2))
            assert np.asarray(out).shape == (1, 4)
            clk.advance(10.0)           # exactly open_for_s
            for r in rids:
                assert fleet.health(r).breaker.state == "half_open"
            fleet.submit("m", _rows(1, seed=3))  # close_after=1
            states = {fleet.health(r).breaker.state for r in rids}
            assert "closed" in states   # the serving replica re-closed
        finally:
            fleet.close()

    def test_open_breaker_excludes_replica_from_ranking(
            self, fresh_cache):
        clk = ManualClock()
        fleet, (ra, rb) = _fleet(
            2, _mln(), router_kw=dict(
                clock=clk, breaker=dict(min_samples=1, window=4,
                                        failure_ratio=0.5,
                                        open_for_s=30.0)))
        try:
            fleet.health(ra).record(False)      # trip ra directly
            assert fleet.health(ra).breaker.state == "open"
            hosts = dict(fleet._hosts())
            hits = _count_dispatches(hosts)
            for i in range(4):
                fleet.submit("m", _rows(1, seed=10 + i))
            assert hits[ra] == 0        # every request avoided ra
            assert hits[rb] >= 1
        finally:
            fleet.close()

    def test_quarantine_probe_readmission_cycle(self, fresh_cache):
        fleet, (ra, rb) = _fleet(
            2, _mln(), router_kw=dict(readmit_after=3))
        try:
            fleet.quarantine(rb)
            assert fleet._m_breaker.labels(replica=rb).value == 2.0
            hosts = dict(fleet._hosts())
            hits = _count_dispatches(hosts)
            fleet.submit("m", _rows(1))     # organic traffic: ra only
            assert hits[rb] == 0
            fleet.set_probe("m", _rows(1, seed=4))
            ticks = [fleet.probe_tick() for _ in range(3)]
            flat = [r for t in ticks for r in t]
            assert [r["ok"] for r in flat] == [True] * 3
            assert [r["readmitted"] for r in flat] == [False, False,
                                                       True]
            assert not fleet.health(rb).quarantined
            assert fleet._m_breaker.labels(replica=rb).value == 0.0
            assert fleet.probe_tick() == []  # nobody quarantined now
            # only the 3 probe canaries ever reached the quarantined
            # replica
            assert hits[rb] == 3
        finally:
            fleet.close()

    def test_brownout_sheds_hopeless_admits_feasible(self, fresh_cache):
        fleet, (ra,) = _fleet(1, _mln(), queueLimit=8)
        try:
            bo = fleet.set_brownout("m", est_item_s=10.0)
            shed_lab = fleet._m_shed.labels(model="m")
            base = shed_lab.value
            # wedge the only replica so work actually queues
            host = dict(fleet._hosts())[ra]
            b = host.model("m").batcher
            orig = b._dispatch
            release = threading.Event()
            b._dispatch = lambda f: (release.wait(30), orig(f))[1]
            threading.Thread(target=lambda: host.submit("m", _rows(1)),
                             daemon=True).start()
            deadline = time.time() + 10
            while fleet._queued_work(host, "m") < 1 \
                    and time.time() < deadline:
                time.sleep(0.01)
            # >= 1 queued item x 10 s/item >> 0.5 s: hopeless, shed NOW
            with pytest.raises(DeadlineExceededError, match="brownout"):
                fleet.submit("m", _rows(1, seed=5), deadline_s=0.5)
            assert shed_lab.value == base + 1 and bo.shed == 1
            release.set()
            # an idle queue admits the same deadline
            host.model("m").batcher  # drain
            while fleet._queued_work(host, "m") > 0 \
                    and time.time() < deadline:
                time.sleep(0.01)
            out = fleet.submit("m", _rows(1, seed=6), deadline_s=30.0)
            assert np.asarray(out).shape == (1, 4)
            assert shed_lab.value == base + 1      # nothing else shed
            # deadline-less requests are never brownout candidates
            fleet.submit("m", _rows(1, seed=7))
        finally:
            release.set()
            fleet.close()

    def test_hedged_dispatch_second_replica_wins(self, fresh_cache):
        """Slow the primary's coalesced dispatch (chaos seam); the
        hedge fires at the mark, the second replica answers first and
        wins, and the result is still correct."""
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        feats = _rows(2, seed=8)
        want = np.asarray(ParallelInference(
            net, batchBuckets=(8,)).output(feats).jax())
        fleet, rids = _fleet(2, net)
        try:
            fleet.submit("m", _rows(1))    # warm both code paths
            fleet.set_hedge("m", after_s=0.02)
            hedges = fleet._m_hedges.labels(model="m")
            wins = fleet._m_hedge_wins.labels(model="m")
            h0, w0 = hedges.value, wins.value
            # ordinal 0 = the primary's dispatch (the hedge only
            # exists 20 ms later): slow it well past the mark
            with ChaosPlan().slow("queue.dispatch", 0.5, at=0):
                got = np.asarray(fleet.submit("m", feats))
            np.testing.assert_array_equal(got, want)
            assert hedges.value == h0 + 1
            assert wins.value == w0 + 1
        finally:
            fleet.close()

    def test_hedge_not_fired_when_primary_is_fast(self, fresh_cache):
        fleet, _ = _fleet(2, _mln())
        try:
            fleet.submit("m", _rows(1))
            fleet.set_hedge("m", after_s=5.0)
            hedges = fleet._m_hedges.labels(model="m")
            h0 = hedges.value
            out = fleet.submit("m", _rows(2, seed=9))
            assert np.asarray(out).shape == (2, 4)
            assert hedges.value == h0      # primary answered in time
        finally:
            fleet.close()


# ----------------------------------------------------------------------
# the chaos soak + the overhead gate
# ----------------------------------------------------------------------
class TestChaosSoak:
    def test_soak_zero_noninjected_failures_zero_compiles(
            self, fresh_cache):
        """The acceptance soak: a 3-replica fleet under a seeded plan
        (a wedged dispatch, flapping dispatch-path raises, seeded slow
        batches) serves every request bitwise-correctly, surfaces ZERO
        client-visible errors (the raises are absorbed by budget-capped
        failover — counted, exactly), and pays ZERO steady-state
        compiles."""
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        n_threads, n_each = 3, 20
        feats = {(t, i): _rows(1 + (t + i) % 4, seed=100 + t * 50 + i)
                 for t in range(n_threads) for i in range(n_each)}
        oracle = ParallelInference(net, batchBuckets=(8,))
        want = {k: np.asarray(oracle.output(v).jax())
                for k, v in feats.items()}

        fleet, rids = _fleet(3, net, queueLimit=64)
        failures = []

        def client(t):
            for i in range(n_each):
                k = (t, i)
                try:
                    got = np.asarray(fleet.submit("m", feats[k]))
                except Exception as e:   # noqa: BLE001 - the assertion
                    failures.append((k, repr(e)))
                    continue
                if not np.array_equal(got, want[k]):
                    failures.append((k, "wrong answer"))

        # flapping: sparse raise ordinals (spaced far wider than the
        # in-flight window) so a single request can never draw two
        # consecutive injected raises across its failover attempts —
        # zero client-visible failures is DETERMINISTIC, not lucky
        plan = ChaosPlan(seed=11)
        for at in (3, 17, 31, 45):
            plan.raise_n("fleet.dispatch", at=at)
        plan.wedge("queue.dispatch", 0.25, at=5)       # wedged replica
        plan.random_slows("queue.dispatch", rate=0.10, window=60,
                          seconds=0.01)                # slow replica
        lab = fleet._m_failover.labels(model="m", error="ChaosError")
        fo0 = lab.value
        try:
            fleet.submit("m", _rows(2, seed=999))      # warm
            with aot.CompileWatch(fresh_cache) as watch:
                with plan:
                    ts = [threading.Thread(target=client, args=(t,))
                          for t in range(n_threads)]
                    for th in ts:
                        th.start()
                    for th in ts:
                        th.join(timeout=120)
            assert not failures, failures[:5]
            assert watch.misses == 0
            raises = plan.fired("fleet.dispatch")
            assert raises == 4                      # all ordinals hit
            # every injected raise became exactly one counted failover
            assert lab.value - fo0 == raises
            assert plan.fired("queue.dispatch") >= 1
            # amplification stayed inside the ratio cap
            snap = fleet._budget("m").snapshot()
            assert snap["spent"] <= snap["ratio"] * snap["requests"] \
                + snap["burst"]
        finally:
            fleet.close()

    def test_armed_quiet_harness_overhead_within_3pct(
            self, fresh_cache):
        """The fast-path gate: a plan armed with rules only on an
        UNTOUCHED seam must cost <= 1.03x the disarmed serving path
        (best-of-trials medians — the bench `serving_chaos` leg gates
        the same ratio end-to-end)."""
        fleet, _ = _fleet(1, _mln(), maxWaitMs=0.1)
        feats = _rows(1, seed=12)
        quiet = ChaosPlan().raise_n("checkpoint.write", times=10 ** 6)

        def trial(n=120):
            samples = []
            for _ in range(n):
                t0 = time.perf_counter()
                fleet.submit("m", feats)
                samples.append(time.perf_counter() - t0)
            return float(np.median(samples))

        try:
            for _ in range(30):       # warm executables + code paths
                fleet.submit("m", feats)
            disarmed, armed = [], []
            for _ in range(4):        # interleave against drift
                disarmed.append(trial())
                with quiet:
                    armed.append(trial())
            ratio = min(armed) / min(disarmed)
            assert ratio <= 1.03, (
                f"armed-but-quiet harness cost {ratio:.4f}x the "
                f"disarmed path (gate: 1.03x); medians "
                f"disarmed={disarmed} armed={armed}")
        finally:
            fleet.close()


# ----------------------------------------------------------------------
# checkpoint digest + the chaos checkpoint seams
# ----------------------------------------------------------------------
class TestCheckpointDigest:
    def _mlp_net(self, seed=42):
        from deeplearning4j_tpu.nn import (Adam, DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer)

        conf = (NeuralNetConfiguration.Builder()
                .seed(seed).updater(Adam(1e-2)).activation("relu")
                .list()
                .layer(DenseLayer(nOut=16))
                .layer(OutputLayer(nOut=3, activation="softmax"))
                .setInputType(InputType.feedForward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    def _iter(self, n=64, batch=16, seed=0):
        from deeplearning4j_tpu.data import DataSetIterator

        rng = np.random.RandomState(seed)
        x = rng.randn(n, 4).astype("float32")
        y = np.eye(3, dtype="float32")[rng.randint(0, 3, n)]
        return DataSetIterator(x, y, batch)

    def _tamper(self, step_dir):
        """Flip the recorded digest — the on-disk state no longer
        hashes to what the manifest promises."""
        mpath = os.path.join(step_dir, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        assert "digest" in manifest
        manifest["digest"] = "0" * len(manifest["digest"])
        with open(mpath, "w") as f:
            json.dump(manifest, f)

    def test_digest_rides_the_commit_and_verifies(self, tmp_path):
        from deeplearning4j_tpu.util import sharded_checkpoint as ck

        net = self._mlp_net()
        net.fit(self._iter())
        p = ck.step_path(tmp_path, 1)
        ck.ShardedModelSerializer.writeModel(net, p)
        digest = ck.read_manifest(p)["digest"]
        assert len(digest) == 64        # sha256 hex
        restored = ck.ShardedModelSerializer.restore(p)
        got = np.asarray(restored.output(_rows(2, seed=1)[:, :4]))
        assert got.shape == (2, 3)
        # the digest is a function of the STATE, not the step
        p2 = ck.step_path(tmp_path, 2)
        ck.ShardedModelSerializer.writeModel(net, p2)
        assert ck.read_manifest(p2)["digest"] == digest

    def test_tampered_digest_raises_on_restore(self, tmp_path):
        from deeplearning4j_tpu.util import sharded_checkpoint as ck

        net = self._mlp_net()
        p = ck.step_path(tmp_path, 1)
        ck.ShardedModelSerializer.writeModel(net, p)
        self._tamper(p)
        with pytest.raises(ck.CheckpointDigestError):
            ck.ShardedModelSerializer.restore(p)

    def test_resilient_fit_falls_back_past_corrupt_snapshot(
            self, tmp_path):
        """The satellite gate: the newest checkpoint fails its digest
        -> treated as ABSENT, the resume walks back to the previous
        snapshot, and the replayed run still matches the no-fault
        reference bitwise."""
        import jax

        from deeplearning4j_tpu.runtime.resilience import (
            ResilientFit, RetryPolicy,
        )
        from deeplearning4j_tpu.util import sharded_checkpoint as ck

        fast = RetryPolicy(maxRetries=3, initialDelay=0.001,
                           maxDelay=0.004, sleep=lambda s: None)
        ref = self._mlp_net()
        ref.fit(self._iter(), epochs=2)

        net = self._mlp_net()
        rf = ResilientFit(net, tmp_path / "ck", saveEveryNIterations=2,
                          keepLast=3, retryPolicy=fast)
        rf.fit(self._iter(), epochs=2)   # 8 steps: ckpts 4, 6, 8 kept
        steps = ck.complete_steps(tmp_path / "ck")
        assert steps == [4, 6, 8]
        self._tamper(ck.step_path(tmp_path / "ck", 8))

        net2 = self._mlp_net()
        rf2 = ResilientFit(net2, tmp_path / "ck",
                           saveEveryNIterations=2, keepLast=3,
                           retryPolicy=fast)
        rf2.fit(self._iter(), epochs=2)  # resumes from 6, replays 7-8
        fa = jax.tree_util.tree_leaves(ref._params)
        fb = jax.tree_util.tree_leaves(net2._params)
        assert len(fa) == len(fb)
        for u, v in zip(fa, fb):
            np.testing.assert_array_equal(np.asarray(u), np.asarray(v))

    def test_all_snapshots_corrupt_means_fresh_start(self, tmp_path):
        from deeplearning4j_tpu.runtime.resilience import (
            ResilientFit, RetryPolicy,
        )
        from deeplearning4j_tpu.util import sharded_checkpoint as ck

        fast = RetryPolicy(maxRetries=2, initialDelay=0.001,
                           maxDelay=0.002, sleep=lambda s: None)
        net = self._mlp_net()
        ResilientFit(net, tmp_path / "ck", saveEveryNIterations=4,
                     keepLast=2, retryPolicy=fast).fit(self._iter())
        for s in ck.complete_steps(tmp_path / "ck"):
            self._tamper(ck.step_path(tmp_path / "ck", s))
        net2 = self._mlp_net()
        rf2 = ResilientFit(net2, tmp_path / "ck",
                           saveEveryNIterations=4, keepLast=2,
                           retryPolicy=fast)
        rf2.fit(self._iter())            # fresh start, no crash
        assert net2._iteration == 4

    def test_chaos_checkpoint_seams_ride_the_retry(self, tmp_path):
        """An injected IO-shaped raise on checkpoint.write /
        checkpoint.restore is absorbed by the SAME retry() the organic
        transient faults ride (retryOn = IOError/OSError/Timeout) —
        the `exc` override models the fault class the seam sees in
        production."""
        from deeplearning4j_tpu.runtime.resilience import (
            ResilientFit, RetryPolicy,
        )
        from deeplearning4j_tpu.util import sharded_checkpoint as ck

        class DiskFault(ChaosError, OSError):
            """Injected, but shaped like the transient it simulates."""

        fast = RetryPolicy(maxRetries=3, initialDelay=0.001,
                           maxDelay=0.004, sleep=lambda s: None)
        net = self._mlp_net()
        with ChaosPlan().raise_n("checkpoint.write", at=0,
                                 exc=DiskFault):
            ResilientFit(net, tmp_path / "ck", saveEveryNIterations=4,
                         keepLast=2,
                         retryPolicy=fast).fit(self._iter())
        assert ck.latest_step(tmp_path / "ck") == 4
        net2 = self._mlp_net()
        with ChaosPlan().raise_n("checkpoint.restore", at=0,
                                 exc=DiskFault) as plan:
            ResilientFit(net2, tmp_path / "ck", saveEveryNIterations=4,
                         keepLast=2,
                         retryPolicy=fast).fit(self._iter(), epochs=2)
        assert plan.fired("checkpoint.restore") == 1
        assert net2._iteration == 8      # resumed from 4, continued

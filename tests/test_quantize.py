"""Weight-only int8 inference quantization (nn/quantize.py) — the
bench int8_inference leg's machinery, pinned on CPU."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.quantize import (dequantize_params,
                                            int8_infer_fn, param_bytes,
                                            quantize_leaf_int8,
                                            quantize_params_int8)


class TestLeafQuantization:
    def test_roundtrip_error_bounded_per_channel(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(16, 8).astype("float32") * 3.0)
        q, s = quantize_leaf_int8(w)
        assert q.dtype == jnp.int8
        assert s.shape == (8,)  # per output channel
        deq = np.asarray(q, np.float32) * np.asarray(s)
        # symmetric absmax: error <= scale/2 per element
        err = np.abs(deq - np.asarray(w))
        assert np.all(err <= np.asarray(s) / 2 + 1e-7)

    def test_zero_tensor_safe(self):
        q, s = quantize_leaf_int8(jnp.zeros((4, 4), jnp.float32))
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.isfinite(np.asarray(s)))

    def test_vector_uses_per_tensor_scale(self):
        q, s = quantize_leaf_int8(jnp.asarray([1.0, -2.0, 0.5]))
        assert np.asarray(s).shape == ()
        assert np.asarray(q)[1] == -127


class TestTreeQuantization:
    def test_structure_preserved_and_bytes_quartered(self):
        rng = np.random.RandomState(1)
        params = [{"W": jnp.asarray(rng.randn(32, 16).astype("float32")),
                   "b": jnp.asarray(np.zeros(16, "float32"))},
                  {}]
        qp, sc = quantize_params_int8(params)
        assert jax.tree_util.tree_structure(qp) == \
            jax.tree_util.tree_structure(params)
        assert qp[0]["W"].dtype == jnp.int8
        # vector leaves (biases, BN gamma/beta) pass through unquantized
        assert qp[0]["b"].dtype == jnp.float32
        # fp32 -> int8: 4x cut on the matrix weight bytes; the bias
        # vector rides along at full width
        b_bytes = 16 * 4
        assert (param_bytes(qp) - b_bytes) * 4 \
            <= param_bytes(params) - b_bytes + 4 * 16
        deq = dequantize_params(qp, sc, jnp.float32)
        np.testing.assert_allclose(np.asarray(deq[0]["W"]),
                                   np.asarray(params[0]["W"]),
                                   atol=float(np.max(np.asarray(sc[0]["W"]))
                                              / 2) + 1e-6)

    def test_int8_infer_agrees_on_small_net(self):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           OutputLayer, Sgd)

        conf = (NeuralNetConfiguration.Builder().seed(2).updater(Sgd(0.1))
                .activation("relu").list()
                .layer(DenseLayer(nOut=32))
                .layer(OutputLayer(nOut=5, activation="softmax",
                                   lossFunction="mcxent"))
                .setInputType(InputType.feedForward(12)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(16, 12).astype("float32"))
        infer, qp, sc = int8_infer_fn(net)
        o8 = np.asarray(infer(qp, sc, x))
        o32 = np.asarray(net._forward_infer(net._params,
                                            net._strip_carries(net._states),
                                            x))
        # int8 weights perturb logits slightly; class decisions hold on
        # a comfortably-margined random net
        assert np.mean(np.argmax(o8, -1) == np.argmax(o32, -1)) >= 0.9
        np.testing.assert_allclose(o8, o32, atol=0.05)

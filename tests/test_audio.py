"""Audio feature extraction (reference: datavec-data-audio) — STFT/mel/
MFCC against numpy/scipy oracles, WAV reading via stdlib wave files."""

import wave

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    SpectrogramTransform, MelSpectrogramTransform, MFCCTransform,
    WavFileRecordReader, mel_filterbank,
)


def _tone(freq, n=4000, rate=16000, amp=0.5):
    t = np.arange(n) / rate
    return (amp * np.sin(2 * np.pi * freq * t)).astype("float32")


class TestSpectrogram:
    def test_matches_numpy_stft_oracle(self):
        x = np.random.RandomState(0).randn(2, 1000).astype("float32")
        t = SpectrogramTransform(frameLength=256, frameStep=128)
        out = np.asarray(t.apply(x))
        n_frames = 1 + (1000 - 256) // 128
        assert out.shape == (2, n_frames, 129)
        win = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(256) / 256)
        for f in range(n_frames):
            seg = x[0, f * 128:f * 128 + 256] * win
            oracle = np.abs(np.fft.rfft(seg)) ** 2
            np.testing.assert_allclose(out[0, f], oracle, rtol=1e-4,
                                       atol=1e-4)

    def test_tone_peaks_at_its_bin(self):
        x = _tone(1000.0)[None, :]  # 1 kHz at 16 kHz rate
        t = SpectrogramTransform(frameLength=512, frameStep=256)
        out = np.asarray(t.apply(x))
        peak_bin = out.mean(1)[0].argmax()
        assert abs(peak_bin * 16000 / 512 - 1000.0) < 16000 / 512

    def test_guards(self):
        with pytest.raises(ValueError, match="fftLength"):
            SpectrogramTransform(frameLength=256, fftLength=128)
        with pytest.raises(ValueError, match="shorter"):
            SpectrogramTransform(frameLength=256).apply(
                np.zeros((1, 100), "float32"))
        with pytest.raises(ValueError, match="B, T"):
            SpectrogramTransform().apply(np.zeros(1000, "float32"))


class TestMelAndMFCC:
    def test_filterbank_properties(self):
        fb = mel_filterbank(20, 512, 16000)
        assert fb.shape == (257, 20)
        assert (fb >= 0).all()
        # each filter is a triangle: a unique peak, nonzero support
        assert (fb.max(0) > 0).all()
        # filters are ordered in frequency
        peaks = fb.argmax(0)
        assert (np.diff(peaks) > 0).all()
        with pytest.raises(ValueError, match="nyquist"):
            mel_filterbank(10, 512, 16000, fmin=0, fmax=9000)

    def test_mel_against_manual_projection(self):
        x = np.random.RandomState(1).randn(1, 2000).astype("float32")
        m = MelSpectrogramTransform(numMel=24, sampleRate=16000,
                                    frameLength=400, frameStep=160,
                                    fftLength=512, logScale=False)
        power = np.asarray(SpectrogramTransform(400, 160, 512).apply(x))
        fb = mel_filterbank(24, 512, 16000)
        np.testing.assert_allclose(np.asarray(m.apply(x)), power @ fb,
                                   rtol=1e-4, atol=1e-4)

    def test_mfcc_dct_matches_scipy(self):
        from scipy.fft import dct as scipy_dct

        x = np.random.RandomState(2).randn(1, 2000).astype("float32")
        t = MFCCTransform(numCoeffs=13, numMel=26, sampleRate=16000,
                          frameLength=400, frameStep=160, fftLength=512)
        out = np.asarray(t.apply(x))
        assert out.shape[-1] == 13
        logmel = np.asarray(MelSpectrogramTransform(
            numMel=26, sampleRate=16000, frameLength=400, frameStep=160,
            fftLength=512).apply(x))
        oracle = scipy_dct(logmel, type=2, norm="ortho", axis=-1)[..., :13]
        np.testing.assert_allclose(out, oracle, rtol=1e-3, atol=1e-3)

    def test_mfcc_guards(self):
        with pytest.raises(ValueError, match="numCoeffs"):
            MFCCTransform(numCoeffs=30, numMel=20)
        with pytest.raises(ValueError, match="logScale"):
            MFCCTransform(numCoeffs=5, numMel=20, logScale=False)


class TestWavReader:
    def _write_wav(self, path, data, rate=16000, width=2, nch=1):
        with wave.open(str(path), "wb") as w:
            w.setnchannels(nch)
            w.setsampwidth(width)
            w.setframerate(rate)
            if width == 2:
                w.writeframes((data * 32767).astype("<i2").tobytes())
            else:
                w.writeframes(((data * 127) + 128).astype("u1").tobytes())

    def test_reads_labels_and_roundtrips(self, tmp_path):
        (tmp_path / "yes").mkdir()
        (tmp_path / "no").mkdir()
        a = _tone(440, n=800)
        b = _tone(880, n=600)
        self._write_wav(tmp_path / "yes" / "a.wav", a)
        self._write_wav(tmp_path / "no" / "b.wav", b)
        rr = WavFileRecordReader(length=800).initialize(tmp_path)
        assert rr.getLabels() == ["no", "yes"] and rr.numLabels() == 2
        assert rr.sampleRate == 16000
        recs = []
        while rr.hasNext():
            recs.append(rr.next())
        by_label = {rr.getLabels()[r[1]]: r for r in recs}
        np.testing.assert_allclose(by_label["yes"][0], a, atol=2e-4)
        # shorter file zero-padded to the static length
        assert len(by_label["no"][0]) == 800
        np.testing.assert_allclose(by_label["no"][0][600:], 0.0)
        rr.reset()
        assert rr.hasNext()

    def test_feeds_record_reader_dataset_iterator(self, tmp_path):
        from deeplearning4j_tpu.data import RecordReaderDataSetIterator

        for lab, freq in (("lo", 500.0), ("hi", 2000.0)):
            (tmp_path / lab).mkdir()
            for i in range(3):
                self._write_wav(tmp_path / lab / f"{i}.wav",
                                _tone(freq, n=400))
        it = RecordReaderDataSetIterator(
            WavFileRecordReader(length=400).initialize(tmp_path),
            batchSize=6)
        ds = it.next()
        assert ds.getFeatures().shape() == (6, 400)
        y = np.asarray(ds.getLabels().jax())
        assert y.shape == (6, 2)
        np.testing.assert_allclose(y.sum(1), 1.0)

    def test_mixed_sample_rates_rejected(self, tmp_path):
        (tmp_path / "x").mkdir()
        self._write_wav(tmp_path / "x" / "a.wav", _tone(440, n=200))
        self._write_wav(tmp_path / "x" / "b.wav", _tone(440, n=200),
                        rate=8000)
        with pytest.raises(ValueError, match="mixed sample rates"):
            WavFileRecordReader().initialize(tmp_path)

    def test_stereo_averaged_and_8bit(self, tmp_path):
        (tmp_path / "x").mkdir()
        stereo = np.stack([_tone(440, n=200), -_tone(440, n=200)], 1).ravel()
        self._write_wav(tmp_path / "x" / "s.wav", stereo, nch=2)
        self._write_wav(tmp_path / "x" / "e.wav", _tone(440, n=200), width=1)
        rr = WavFileRecordReader().initialize(tmp_path)
        assert rr.getLabels() == ["x"]
        waves = [rr.next()[0], rr.next()[0]]  # sorted: e.wav, s.wav
        mono8, stereo = waves
        # stereo L = -R: mono average cancels to ~0
        assert float(np.abs(stereo).max()) < 1e-3
        assert float(np.abs(mono8).max()) > 0.2  # the 8-bit mono tone

    def test_empty_dir_loud(self, tmp_path):
        (tmp_path / "cls").mkdir()
        with pytest.raises(ValueError, match="no .wav"):
            WavFileRecordReader().initialize(tmp_path)

    def test_mel_dead_filters_rejected(self):
        with pytest.raises(ValueError, match="all-zero"):
            mel_filterbank(80, 256, 16000)


class TestEndToEnd:
    def test_mfcc_frontend_trains_classifier(self):
        # two synthetic 'keywords' (tones) -> MFCC -> dense classifier
        from deeplearning4j_tpu.nn import (NeuralNetConfiguration, InputType,
                                           MultiLayerNetwork, DenseLayer,
                                           OutputLayer, Adam)

        rng = np.random.RandomState(3)
        X, y = [], []
        for _ in range(40):
            f = 500.0 if rng.rand() < 0.5 else 2000.0
            w = _tone(f, n=1600) + rng.randn(1600).astype("float32") * 0.05
            X.append(w)
            y.append(0 if f == 500.0 else 1)
        feats = np.asarray(MFCCTransform(
            numCoeffs=13, numMel=26, frameLength=400, frameStep=160,
            fftLength=512).apply(np.stack(X)))
        flat = feats.reshape(len(X), -1)
        labels = np.eye(2, dtype="float32")[y]
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(nOut=16, activation="relu"))
                .layer(OutputLayer(nOut=2, activation="softmax",
                                   lossFunction="mcxent"))
                .setInputType(InputType.feedForward(flat.shape[1])).build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(30):
            net.fit(flat.astype("float32"), labels)
        from deeplearning4j_tpu.evaluation import Evaluation

        ev = Evaluation(2)
        ev.eval(labels, np.asarray(net.output(flat.astype("float32")).jax()))
        assert ev.accuracy() == 1.0, ev.accuracy()

"""Serialization tests — config + model round trips, checkpoint/resume,
workspace shim, profiler.

Mirrors the reference's ModelSerializerTest / config JSON round-trip
tests: restored network == original network (outputs bit-for-bit), and
resumed training matches uninterrupted training exactly (the rng is
derived from (seed, iteration), so a true full-state checkpoint shows
zero divergence).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    NeuralNetConfiguration, InputType, MultiLayerNetwork, ComputationGraph,
    DenseLayer, OutputLayer, ConvolutionLayer, SubsamplingLayer, LSTM,
    RnnOutputLayer, BatchNormalization, DropoutLayer, ElementWiseVertex,
    Adam, Nesterovs, WeightInit,
)
from deeplearning4j_tpu.data import DataSet, NormalizerStandardize
from deeplearning4j_tpu.util import (
    ModelSerializer, TrainingCheckpoint, MemoryWorkspace, WorkspaceManager,
    OpProfiler,
)


def _data(n=64, nin=4, nout=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, nin).astype("float32")
    w = rng.randn(nin, nout)
    yi = np.argmax(x @ w, axis=1)
    return x, np.eye(nout, dtype="float32")[yi]


def _mlp_conf(seed=42):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .weightInit(WeightInit.XAVIER).activation("relu").list()
            .layer(DenseLayer(nOut=16))
            .layer(BatchNormalization())
            .layer(DropoutLayer(0.9))
            .layer(OutputLayer(nOut=3, activation="softmax", lossFunction="mcxent"))
            .setInputType(InputType.feedForward(4)).build())


class TestModelSerializerMLN:
    def test_output_round_trip(self, tmp_path):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        for _ in range(5):
            net.fit(x, y)
        p = str(tmp_path / "model.npz")
        ModelSerializer.writeModel(net, p)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())
        assert net2.getIterationCount() == net.getIterationCount()

    def test_resumed_training_is_bit_exact(self, tmp_path):
        """Train 10; vs train 5 + checkpoint + restore + train 5."""
        x, y = _data()
        ref = MultiLayerNetwork(_mlp_conf()).init()
        for _ in range(10):
            ref.fit(x, y)

        net = MultiLayerNetwork(_mlp_conf()).init()
        for _ in range(5):
            net.fit(x, y)
        p = str(tmp_path / "ckpt.npz")
        ModelSerializer.writeModel(net, p, saveUpdater=True)
        resumed = ModelSerializer.restoreMultiLayerNetwork(p)
        for _ in range(5):
            resumed.fit(x, y)
        np.testing.assert_array_equal(ref.output(x).toNumpy(),
                                      resumed.output(x).toNumpy())

    def test_without_updater_state(self, tmp_path):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(x, y)
        p = str(tmp_path / "m.npz")
        ModelSerializer.writeModel(net, p, saveUpdater=False)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p, loadUpdater=False)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())

    def test_normalizer_round_trip(self, tmp_path):
        x, y = _data()
        ds = DataSet(x, y)
        norm = NormalizerStandardize().fit(ds)
        net = MultiLayerNetwork(_mlp_conf()).init()
        p = str(tmp_path / "m.npz")
        ModelSerializer.writeModel(net, p, normalizer=norm)
        norm2 = ModelSerializer.restoreNormalizer(p)
        np.testing.assert_allclose(norm2._mean, norm._mean)
        np.testing.assert_allclose(norm2._std, norm._std)

    def test_add_normalizer_later(self, tmp_path):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        p = str(tmp_path / "m.npz")
        ModelSerializer.writeModel(net, p)
        assert ModelSerializer.restoreNormalizer(p) is None
        norm = NormalizerStandardize().fit(DataSet(x, y))
        ModelSerializer.addNormalizerToModel(p, norm)
        norm2 = ModelSerializer.restoreNormalizer(p)
        np.testing.assert_allclose(norm2._mean, norm._mean)
        # model still restores after the rewrite
        net2 = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())

    def test_wrong_type_raises(self, tmp_path):
        net = MultiLayerNetwork(_mlp_conf()).init()
        p = str(tmp_path / "m.npz")
        ModelSerializer.writeModel(net, p)
        with pytest.raises(ValueError, match="MultiLayerNetwork"):
            ModelSerializer.restoreComputationGraph(p)


class TestModelSerializerCNNAndRNN:
    def test_cnn_round_trip(self, tmp_path):
        rng = np.random.RandomState(0)
        x = rng.rand(8, 1, 12, 12).astype("float32")
        y = np.eye(4, dtype="float32")[rng.randint(0, 4, 8)]
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Nesterovs(0.01, 0.9))
                .list()
                .layer(ConvolutionLayer(nOut=4, kernelSize=(3, 3), activation="relu"))
                .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(nOut=4, activation="softmax"))
                .setInputType(InputType.convolutional(12, 12, 1)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y)
        p = str(tmp_path / "cnn.npz")
        ModelSerializer.writeModel(net, p)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())

    def test_lstm_round_trip(self, tmp_path):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 3, 7).astype("float32")
        y = np.zeros((4, 2, 7), "float32")
        y[:, 0] = 1.0
        conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-2)).list()
                .layer(LSTM(nOut=8))
                .layer(RnnOutputLayer(nOut=2, activation="softmax"))
                .setInputType(InputType.recurrent(3)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y)
        p = str(tmp_path / "lstm.npz")
        ModelSerializer.writeModel(net, p)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())


class TestModelSerializerCG:
    def test_graph_round_trip(self, tmp_path):
        x, y = _data()
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                .graphBuilder()
                .addInputs("in")
                .addLayer("d1", DenseLayer(nOut=16, activation="relu"), "in")
                .addLayer("d2", DenseLayer(nOut=16, activation="identity"), "d1")
                .addVertex("res", ElementWiseVertex("add"), "d1", "d2")
                .addLayer("out", OutputLayer(nOut=3, activation="softmax"), "res")
                .setOutputs("out")
                .setInputTypes(InputType.feedForward(4))
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(3):
            net.fit(x, y)
        p = str(tmp_path / "graph.npz")
        ModelSerializer.writeModel(net, p)
        net2 = ModelSerializer.restoreComputationGraph(p)
        np.testing.assert_array_equal(net.outputSingle(x).toNumpy(),
                                      net2.outputSingle(x).toNumpy())
        # resumed training matches
        net.fit(x, y)
        net2.fit(x, y)
        np.testing.assert_array_equal(net.outputSingle(x).toNumpy(),
                                      net2.outputSingle(x).toNumpy())


class TestTrainingCheckpoint:
    def test_full_resume_with_extra(self, tmp_path):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(x, y)
        norm = NormalizerStandardize().fit(DataSet(x, y))
        p = str(tmp_path / "ck.npz")
        TrainingCheckpoint.save(net, p, normalizer=norm,
                                extra={"best_score": 0.5, "epoch": 1})
        net2, norm2, extra = TrainingCheckpoint.load(p)
        assert extra["best_score"] == 0.5
        np.testing.assert_allclose(norm2._mean, norm._mean)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())


class TestWorkspace:
    def test_scoping(self):
        assert WorkspaceManager.getCurrentWorkspace() is None
        with MemoryWorkspace("A") as a:
            assert WorkspaceManager.getCurrentWorkspace() is a
            with MemoryWorkspace("B") as b:
                assert WorkspaceManager.getCurrentWorkspace() is b
            assert WorkspaceManager.getCurrentWorkspace() is a
        assert WorkspaceManager.getCurrentWorkspace() is None

    def test_corruption_detection(self):
        a = MemoryWorkspace("A").__enter__()
        b = MemoryWorkspace("B").__enter__()
        with pytest.raises(RuntimeError, match="corruption"):
            a.__exit__(None, None, None)
        b.__exit__(None, None, None)
        a.__exit__(None, None, None)

    def test_scope_out(self):
        with WorkspaceManager.scopeOutOfWorkspaces():
            pass


class TestProfiler:
    def test_sections_and_compile_split(self):
        prof = OpProfiler.getInstance()
        prof.reset()
        import time
        for _ in range(3):
            with prof.section("step"):
                time.sleep(0.001)
        assert prof.invocations("step") == 3
        assert prof.compileTime("step") > 0
        assert prof.timeSpent("step") > 0  # 2 steady calls
        assert "step" in prof.printOutDashboard()


class TestConfigJson:
    def test_mln_conf_round_trip(self):
        x, y = _data()
        conf = _mlp_conf()
        text = conf.toJson()
        conf2 = type(conf).fromJson(text)
        a = MultiLayerNetwork(conf).init()
        b = MultiLayerNetwork(conf2).init()  # same seed -> same init
        np.testing.assert_array_equal(a.output(x).toNumpy(), b.output(x).toNumpy())

    def test_graph_conf_round_trip(self):
        x, y = _data()
        conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-2))
                .graphBuilder().addInputs("in")
                .addLayer("d", DenseLayer(nOut=8, activation="relu"), "in")
                .addLayer("out", OutputLayer(nOut=3, activation="softmax"), "d")
                .setOutputs("out").setInputTypes(InputType.feedForward(4)).build())
        conf2 = type(conf).fromJson(conf.toJson())
        a = ComputationGraph(conf).init()
        b = ComputationGraph(conf2).init()
        np.testing.assert_array_equal(a.outputSingle(x).toNumpy(),
                                      b.outputSingle(x).toNumpy())

    def test_net_save_load_methods(self, tmp_path):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(x, y)
        p = str(tmp_path / "n.npz")
        net.save(p)
        net2 = MultiLayerNetwork.load(p)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())


class TestReviewRegressions:
    def test_extensionless_path_round_trip(self, tmp_path):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.save(str(tmp_path / "model"))  # numpy appends .npz on save
        net2 = MultiLayerNetwork.load(str(tmp_path / "model"))
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())

    def test_fromjson_wrong_root_type_raises(self):
        conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-2))
                .graphBuilder().addInputs("in")
                .addLayer("out", OutputLayer(nOut=3, activation="softmax"), "in")
                .setOutputs("out").setInputTypes(InputType.feedForward(4)).build())
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        with pytest.raises(TypeError, match="expected MultiLayerConfiguration"):
            MultiLayerConfiguration.fromJson(conf.toJson())

    def test_decode_rejects_lookalike_package(self):
        from deeplearning4j_tpu.util import serde
        with pytest.raises(ValueError, match="refusing"):
            serde.decode({"__o": "deeplearning4j_tpu_evil.mod:Cls", "attrs": {}}, [])

    def test_restore_skips_random_init(self, tmp_path, monkeypatch):
        x, y = _data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(x, y)
        p = str(tmp_path / "m.npz")
        ModelSerializer.writeModel(net, p)
        import deeplearning4j_tpu.nn.multilayer as mln_mod
        def boom(self):
            raise AssertionError("restore must not call init()")
        monkeypatch.setattr(mln_mod.MultiLayerNetwork, "init", boom)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_array_equal(net.output(x).toNumpy(),
                                      net2.output(x).toNumpy())

"""Serving-fleet gates (serving/fleet.py, docs/SERVING.md "Sequence
serving + the fleet").

What must hold:

- routing: requests land on the LEAST-LOADED replica; a full replica
  sheds to its peers (failover) and only a fleet-wide full queue
  surfaces QueueFullError;
- rolling deploys: swap_all rolls replicas one at a time under live
  concurrent load with zero failed requests and zero request-path
  compiles (the per-host zero-5xx contract held fleet-wide);
- autoscaling: SLO'd models produce scale_up/scale_down DECISIONS from
  live queue depth + measured p99, delivered through the on_scale
  callback surface (no processes are spawned — decisions only);
- observability: the fleet snapshot (per-replica queue depth + slot
  occupancy, per-model aggregates) is ADDITIVE over the per-host PR 13
  snapshot schema bench.py consumes;
- loadgen: the closed-loop client mode (slow-client storm) is seeded,
  blocks on responses, and records per-error-class counts.
"""

import threading
import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.runtime import aot
from deeplearning4j_tpu.serving import (
    FleetRouter, ModelHost, ModelSLO, QueueFullError, loadgen,
)
from deeplearning4j_tpu.serving.fleet import (
    scenario_diurnal_ramp, scenario_hot_model_skew,
    scenario_slow_client_storm,
)


def _mln(seed=7, nout=16):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Nesterovs(0.1, 0.9)).list()
            .layer(DenseLayer(nOut=nout, activation="relu"))
            .layer(OutputLayer(nOut=4, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(8)).build())
    return MultiLayerNetwork(conf).init()


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, 8).astype(np.float32)


@pytest.fixture
def fresh_cache():
    prev = aot._SESSION
    cache = aot._SESSION = aot.ExecutableCache(None)
    yield cache
    aot._SESSION = prev


def _fleet(n_replicas, net, **kw):
    kw.setdefault("batchBuckets", (8,))
    kw.setdefault("maxWaitMs", 1.0)
    fleet = FleetRouter()
    rids = [fleet.add_replica(ModelHost()) for _ in range(n_replicas)]
    fleet.register("m", net, **kw)
    return fleet, rids


class TestModelSLO:
    def test_validation_and_dict(self):
        slo = ModelSLO(p99_ms=50, queue_high=8, queue_low=1,
                       min_replicas=2, max_replicas=6)
        assert slo.as_dict()["p99_ms"] == 50.0
        with pytest.raises(ValueError, match="scale-down band"):
            ModelSLO(queue_high=1.0, queue_low=4.0)


class TestFleetRouting:
    def test_replica_lifecycle_errors(self, fresh_cache):
        fleet = FleetRouter()
        rid = fleet.add_replica(ModelHost(), replica_id="a")
        with pytest.raises(ValueError, match="already attached"):
            fleet.add_replica(ModelHost(), replica_id="a")
        with pytest.raises(KeyError, match="unknown replica"):
            fleet.remove_replica("ghost")
        with pytest.raises(KeyError, match="no replica serves"):
            fleet.submit("nope", _rows(1))
        fleet.remove_replica(rid)
        assert fleet.replica_ids() == []
        fleet.close()

    def test_least_loaded_dispatch_avoids_wedged_replica(self,
                                                         fresh_cache):
        """Wedge replica A's dispatcher so its queue holds work; the
        router must send new traffic to idle replica B."""
        fleet, (ra, rb) = _fleet(2, _mln(), queueLimit=8)
        try:
            hosts = dict(fleet._hosts())
            ba = hosts[ra].model("m").batcher
            orig = ba._dispatch
            release = threading.Event()
            ba._dispatch = lambda f: (release.wait(30), orig(f))[1]
            # occupy A: one in-flight + one queued
            for _ in range(2):
                threading.Thread(
                    target=lambda: hosts[ra].submit("m", _rows(1)),
                    daemon=True).start()
            deadline = time.time() + 10
            while fleet._queued_work(hosts[ra], "m") < 1 \
                    and time.time() < deadline:
                time.sleep(0.01)
            assert fleet._queued_work(hosts[ra], "m") >= 1
            # new traffic routes to the idle replica and completes
            # (requests inside the wedged dispatch count too — see
            # test_wedged_dispatch_still_counts_as_outstanding)
            # immediately even though A is wedged
            out = fleet.submit("m", _rows(2, seed=3))
            assert np.asarray(out).shape == (2, 4)
            bb = hosts[rb].model("m").batcher
            assert bb.stats["requests"] >= 1
            release.set()
        finally:
            release.set()
            fleet.close()

    def test_failover_on_full_queue_then_fleet_wide_429(self,
                                                        fresh_cache):
        fleet, (ra, rb) = _fleet(2, _mln(), queueLimit=1)
        try:
            hosts = dict(fleet._hosts())
            releases = []
            for rid in (ra, rb):
                b = hosts[rid].model("m").batcher
                orig = b._dispatch
                release = threading.Event()
                entered = threading.Event()
                b._dispatch = (lambda en, rel, o: lambda f:
                               (en.set(), rel.wait(30), o(f))[2])(
                                   entered, release, orig)
                releases.append(release)
                # wedge: one IN-FLIGHT (proven by `entered`), then one
                # request filling the 1-deep queue
                threading.Thread(
                    target=lambda h=hosts[rid]: h.submit("m", _rows(1)),
                    daemon=True).start()
                assert entered.wait(20)
                threading.Thread(
                    target=lambda h=hosts[rid]: h.submit("m", _rows(1)),
                    daemon=True).start()
                deadline = time.time() + 10
                while b.depth < 1 and time.time() < deadline:
                    time.sleep(0.01)
                assert b.depth == 1
            lab = fleet._m_failover.labels(model="m",
                                           error="QueueFullError")
            reg_before = lab.value
            with pytest.raises(QueueFullError):
                fleet.submit("m", _rows(1, seed=9))
            # the router tried the peer before giving up, and the
            # failover was counted under its error class
            assert lab.value == reg_before + 1
            for ev in releases:
                ev.set()
        finally:
            for ev in releases:
                ev.set()
            fleet.close()


class TestFleetRollingSwap:
    def test_swap_all_zero_errors_zero_compiles_under_load(
            self, fresh_cache):
        """Fleet-wide rolling deploy mid-soak: every response is
        bitwise one of the two versions, nothing fails, and with the
        new version's executables already hot the whole soak pays zero
        compiles (CompileWatch)."""
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net1 = _mln()
        net2 = _mln()   # identical conf -> identical cache keys
        net2._params = jax.tree_util.tree_map(lambda a: a * 1.5,
                                              net2._params)
        o1 = ParallelInference(net1, batchBuckets=(8,))
        o2 = ParallelInference(net2, batchBuckets=(8,))
        n_threads, n_each = 3, 16
        feats = {(t, i): _rows(1 + (t + i) % 4, seed=50 + t * 100 + i)
                 for t in range(n_threads) for i in range(n_each)}
        want1 = {k: np.asarray(o1.output(v).jax())
                 for k, v in feats.items()}
        want2 = {k: np.asarray(o2.output(v).jax())
                 for k, v in feats.items()}

        fleet, _ = _fleet(2, net1, queueLimit=256)
        failures, versions = [], set()
        swap_at = threading.Event()

        def client(t):
            for i in range(n_each):
                if t == 0 and i == 3:
                    swap_at.set()
                k = (t, i)
                try:
                    got = np.asarray(fleet.submit("m", feats[k]))
                except Exception as e:
                    failures.append((k, repr(e)))
                    continue
                if np.array_equal(got, want1[k]):
                    versions.add(1)
                elif np.array_equal(got, want2[k]):
                    versions.add(2)
                else:
                    failures.append((k, "matches NEITHER version"))

        try:
            with aot.CompileWatch(fresh_cache) as watch:
                ts = [threading.Thread(target=client, args=(t,))
                      for t in range(n_threads)]
                for t in ts:
                    t.start()
                assert swap_at.wait(30)
                rep = fleet.swap_all("m", net2)
                for t in ts:
                    t.join(timeout=60)
            assert not failures, failures[:5]
            assert {r["version"] for r in rep.values()} == {2}
            assert all(
                {b: d["status"] for b, d in r["warm"].items()}
                == {8: "warm"} for r in rep.values())
            watch.assert_no_compiles("fleet rolling swap soak")
            assert 2 in versions
        finally:
            fleet.close()

    def test_swap_all_covers_sequence_models(self, fresh_cache):
        """swap_all routes by each host's registration kind: a
        sequence model registered fleet-wide rolls with the same
        zero-compile warm-then-flip, and an unregistered name raises
        before any replica is touched."""
        from deeplearning4j_tpu.nn import (InputType,
                                           NeuralNetConfiguration,
                                           Nesterovs)
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.recurrent import LSTM
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        def rnn(seed=3):
            conf = (NeuralNetConfiguration.Builder().seed(seed)
                    .updater(Nesterovs(0.1, 0.9)).list()
                    .layer(LSTM(nOut=6))
                    .layer(RnnOutputLayer(nOut=3, activation="softmax",
                                          lossFunction="mcxent"))
                    .setInputType(InputType.recurrent(4, 5)).build())
            return MultiLayerNetwork(conf).init()

        net1, net2 = rnn(), rnn()   # identical conf -> same cache keys
        net2._params = jax.tree_util.tree_map(lambda a: a * 1.5,
                                              net2._params)
        fleet = FleetRouter()
        for _ in range(2):
            fleet.add_replica(ModelHost())
        try:
            fleet.register_sequence("seq", net1, slotBuckets=(2,))
            feats = np.random.RandomState(5).randn(3, 4).astype(
                np.float32)
            before = np.asarray(fleet.submit_sequence("seq", feats))
            with aot.CompileWatch(fresh_cache) as watch:
                rep = fleet.swap_all("seq", net2)
                after = np.asarray(fleet.submit_sequence("seq", feats))
            assert {r["version"] for r in rep.values()} == {2}
            watch.assert_no_compiles("sequence swap_all")
            assert not np.array_equal(before, after)  # new weights serve
            with pytest.raises(KeyError, match="register it fleet-wide"):
                fleet.swap_all("ghost", net2)
        finally:
            fleet.close()


class TestAutoscale:
    def test_queue_depth_scale_up_then_idle_scale_down(self,
                                                       fresh_cache):
        fleet, (ra, rb) = _fleet(2, _mln(), queueLimit=64)
        try:
            fleet.set_slo("m", queue_high=2.0, queue_low=0.5,
                          min_replicas=1, max_replicas=4)
            seen = []
            fleet.on_scale(seen.append)
            hosts = dict(fleet._hosts())
            # pile queued work directly onto both replicas' batchers
            # (wait=False keeps them pending; dispatch wedged)
            releases = []
            for rid in (ra, rb):
                b = hosts[rid].model("m").batcher
                orig = b._dispatch
                ev = threading.Event()
                b._dispatch = (lambda e, o: lambda f:
                               (e.wait(30), o(f))[1])(ev, orig)
                releases.append(ev)
                for j in range(6):
                    b.submit(_rows(1, seed=j), wait=False)
            decisions = fleet.autoscale_tick()
            up = [d for d in decisions if d["model"] == "m"][0]
            assert up["action"] == "scale_up"
            assert up["desired_replicas"] == 3
            assert any("queue_high" in r for r in up["reasons"])
            assert seen and seen[-1]["action"] == "scale_up"
            for ev in releases:
                ev.set()
            # drain, then an idle fleet votes scale_down to min
            deadline = time.time() + 20
            while any(fleet._queued_work(h, "m") for _, h
                      in fleet._hosts()) and time.time() < deadline:
                time.sleep(0.02)
            decisions = fleet.autoscale_tick()
            down = [d for d in decisions if d["model"] == "m"][0]
            assert down["action"] == "scale_down"
            assert down["desired_replicas"] == 1
        finally:
            for ev in releases:
                ev.set()
            fleet.close()

    def test_p99_slo_votes_scale_up_and_hold_not_dispatched(
            self, fresh_cache):
        fleet, _ = _fleet(1, _mln())
        try:
            fleet.set_slo("m", p99_ms=0.0001, queue_high=1e9,
                          queue_low=-1.0, max_replicas=3)
            seen = []
            fleet.on_scale(seen.append)
            for i in range(4):
                fleet.submit("m", _rows(1, seed=i))
            d = [x for x in fleet.autoscale_tick()
                 if x["model"] == "m"][0]
            assert d["action"] == "scale_up"
            assert any("p99" in r for r in d["reasons"])
            # a healthy SLO holds — and hold decisions are returned
            # but NOT dispatched to callbacks
            fleet.set_slo("m", p99_ms=None, queue_high=1e9,
                          queue_low=-1.0)
            seen.clear()
            d = [x for x in fleet.autoscale_tick()
                 if x["model"] == "m"][0]
            assert d["action"] == "hold" and not seen
        finally:
            fleet.close()


class TestFleetObservability:
    def test_snapshot_additive_schema(self, fresh_cache):
        net = _mln()
        fleet, (ra, rb) = _fleet(2, net)
        try:
            fleet.submit("m", _rows(2, seed=1))
            snap = fleet.metrics_snapshot()
            assert set(snap) == {"registry", "replicas", "models",
                                 "slos"}
            assert set(snap["replicas"]) == {ra, rb}
            for view in snap["replicas"].values():
                assert set(view) == {"queue_depth", "models",
                                     "sequences"}
                # the nested per-host view is the PR 13 schema
                assert set(view["models"]["m"]) == {
                    "version", "stats", "queue_depth", "occupancy"}
            agg = snap["models"]["m"]
            assert agg["kind"] == "oneshot" and agg["replicas"] == 2
        finally:
            fleet.close()


class TestClosedLoopLoadgen:
    def test_closed_loop_counts_and_error_classes(self):
        calls = []

        def submit(x):
            calls.append(x)
            if int(x[0, 0]) % 3 == 0:
                raise QueueFullError("full")

        rec = loadgen.run_closed_loop(
            submit, lambda c, i: np.full((1, 1), c * 100 + i,
                                         np.float32),
            n_clients=3, requests_per_client=6, think_time_s=0.0,
            seed=0)
        assert rec["mode"] == "closed" and rec["clients"] == 3
        assert rec["requests"] == 18
        assert rec["completed"] + sum(rec["errors"].values()) == 18
        assert rec["errors"].get("QueueFullError", 0) > 0
        assert len(calls) == 18     # every client kept going past errors

    def test_closed_loop_blocks_on_response(self):
        """At most n_clients requests are ever in flight — the closed-
        loop property an open loop does not have."""
        in_flight = [0]
        peak = [0]
        lock = threading.Lock()

        def submit(x):
            with lock:
                in_flight[0] += 1
                peak[0] = max(peak[0], in_flight[0])
            time.sleep(0.002)
            with lock:
                in_flight[0] -= 1

        rec = loadgen.run_closed_loop(
            submit, lambda c, i: np.zeros((1, 1), np.float32),
            n_clients=2, requests_per_client=5, think_time_s=0.0,
            seed=1)
        assert rec["completed"] == 10
        assert peak[0] <= 2

    def test_seeded_think_time_reproducible(self):
        sleeps_a, sleeps_b = [], []
        for sink in (sleeps_a, sleeps_b):
            loadgen.run_closed_loop(
                lambda x: None,
                lambda c, i: np.zeros((1, 1), np.float32),
                n_clients=2, requests_per_client=3, think_time_s=0.01,
                seed=5, sleep=sink.append)
        # clients run concurrently, so compare the multiset: the drawn
        # think times are seed-determined even though arrival order is
        # interleaved
        assert sorted(sleeps_a) == sorted(sleeps_b)
        assert len(sleeps_a) == 6


class TestScenarios:
    def test_slow_client_storm_record(self, fresh_cache):
        fleet, _ = _fleet(2, _mln(), queueLimit=128)
        try:
            rec = scenario_slow_client_storm(
                lambda x: fleet.submit("m", x),
                lambda c, i: _rows(1, seed=c * 10 + i),
                n_clients=6, requests_per_client=3, think_time_s=0.0,
                seed=2)
            assert rec["scenario"] == "slow_client_storm"
            assert rec["completed"] == 18 and rec["errors"] == {}
            assert rec["p99_ms"] is not None
        finally:
            fleet.close()

    def test_slow_client_storm_hedged_rerun(self, fresh_cache):
        """hedged_submit reruns the SAME seeded storm through the
        hedging path and the record gains the fire-rate + p99 delta
        (ISSUE 16 satellite)."""
        fleet, _ = _fleet(2, _mln(), queueLimit=128)
        try:
            hedges = fleet._m_hedges.labels(model="m")
            armed = []

            def hedged_submit(x):
                if not armed:   # arm lazily: the base storm runs clean
                    fleet.set_hedge("m", after_s=10.0)
                    armed.append(1)
                return fleet.submit("m", x)

            rec = scenario_slow_client_storm(
                lambda x: fleet.submit("m", x),
                lambda c, i: _rows(1, seed=c * 10 + i),
                n_clients=4, requests_per_client=3, think_time_s=0.0,
                seed=2, hedged_submit=hedged_submit,
                hedge_stats=lambda: hedges.value)
            h = rec["hedged"]
            assert h["completed"] == 12 and h["errors"] == {}
            # a 10 s mark never fires on this workload: the record
            # still carries the (zero) fire-rate and the p99 delta
            assert h["hedges_fired"] == 0 and h["hedge_rate"] == 0.0
            assert isinstance(h["p99_delta_ms"], float)
        finally:
            fleet.close()

    def test_diurnal_ramp_phases_and_error_classes(self):
        fails = [0]

        def submit(x):
            fails[0] += 1
            if fails[0] % 5 == 0:
                raise QueueFullError("full")

        rec = scenario_diurnal_ramp(
            submit, lambda i: _rows(1, seed=i), base_rate=200.0,
            peak_rate=800.0, phases=3, requests_per_phase=10, seed=3)
        assert rec["scenario"] == "diurnal_ramp"
        assert len(rec["phases"]) == 3
        # the ramp peaks in the middle
        rates = [p["rate_rps"] for p in rec["phases"]]
        assert rates[1] == max(rates)
        assert rec["errors"].get("QueueFullError", 0) > 0
        assert rec["completed"] + sum(rec["errors"].values()) == 30

    def test_hot_model_skew_split(self, fresh_cache):
        net = _mln()
        fleet = FleetRouter([ModelHost()])
        try:
            fleet.register("hot", net, batchBuckets=(8,))
            fleet.register("cold", net, batchBuckets=(8,))
            rec = scenario_hot_model_skew(
                lambda n: (lambda x: fleet.submit(n, x)),
                lambda i: _rows(1, seed=i),
                models=["hot", "cold"], hot_fraction=0.8, rate=500.0,
                n_requests=40, seed=4)
            assert rec["scenario"] == "hot_model_skew"
            assert rec["hot_model"] == "hot"
            hot_n = rec["per_model"]["hot"]["requests"]
            cold_n = rec["per_model"]["cold"]["requests"]
            assert hot_n + cold_n == 40 and hot_n > cold_n
            assert rec["completed"] == 40
            with pytest.raises(ValueError, match=">= 2 models"):
                scenario_hot_model_skew(
                    lambda n: (lambda x: None), lambda i: None,
                    models=["one"])
        finally:
            fleet.close()

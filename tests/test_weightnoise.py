"""Weight noise (reference: conf.weightnoise.{DropConnect, WeightNoise})
— train-time weight perturbation, clean inference, gradients flow."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    NeuralNetConfiguration, InputType, MultiLayerNetwork, DenseLayer,
    OutputLayer, Adam, DropConnect, WeightNoise,
)
from deeplearning4j_tpu.nn.weights import NormalDistribution


def _net(wn=None, global_wn=None, seed=5):
    b = NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
    if global_wn is not None:
        b = b.weightNoise(global_wn)
    conf = (b.list()
            .layer(DenseLayer(nOut=8, activation="tanh", weightNoise=wn))
            .layer(OutputLayer(nOut=2, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(4)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(n, 4).astype("float32"),
            np.eye(2, dtype="float32")[rng.randint(0, 2, n)])


class TestDropConnect:
    def test_retain_one_is_identity_and_inference_clean(self):
        x, y = _data()
        a, b = _net(DropConnect(1.0)), _net(None)
        np.testing.assert_array_equal(np.asarray(a.output(x).jax()),
                                      np.asarray(b.output(x).jax()))
        # inference ignores weight noise entirely
        c = _net(DropConnect(0.3))
        np.testing.assert_array_equal(np.asarray(c.output(x).jax()),
                                      np.asarray(b.output(x).jax()))

    def test_training_perturbed_but_converges(self):
        x, y = _data(64, 1)
        net = _net(DropConnect(0.8))
        losses = []
        for _ in range(60):
            net.fit(x, y)
            losses.append(net.score())
        assert np.isfinite(losses).all()
        assert np.mean(losses[-10:]) < np.mean(losses[:10])

    def test_train_forward_depends_on_key(self):
        net = _net(DropConnect(0.5))
        x, _ = _data()
        h1 = net._run_layers(net._params, net._strip_carries(net._states),
                             x, True, jax.random.key(1), None)[0]
        h2 = net._run_layers(net._params, net._strip_carries(net._states),
                             x, True, jax.random.key(2), None)[0]
        h1b = net._run_layers(net._params, net._strip_carries(net._states),
                              x, True, jax.random.key(1), None)[0]
        np.testing.assert_array_equal(np.asarray(h1), np.asarray(h1b))
        assert not np.array_equal(np.asarray(h1), np.asarray(h2))

    def test_invalid_prob_rejected(self):
        with pytest.raises(ValueError, match="weightRetainProb"):
            DropConnect(0.0)


class TestWeightNoise:
    def test_additive_noise_trains_and_inference_clean(self):
        x, y = _data(32, 2)
        wn = WeightNoise(NormalDistribution(0.0, 0.05))
        net = _net(wn)
        base = _net(None)
        np.testing.assert_array_equal(np.asarray(net.output(x).jax()),
                                      np.asarray(base.output(x).jax()))
        for _ in range(5):
            net.fit(x, y)
        assert np.isfinite(net.score())

    def test_bias_untouched_by_default(self):
        # multiplicative noise with mean 5: if the bias were perturbed,
        # a zero-input forward would change; it must not
        wn = WeightNoise(NormalDistribution(5.0, 0.0), additive=False)
        net = _net(wn)
        x = np.zeros((4, 4), "float32")
        h = net._run_layers(net._params, net._strip_carries(net._states),
                            x, True, jax.random.key(3), None)[0]
        base = net._run_layers(net._params,
                               net._strip_carries(net._states), x, False,
                               None, None)[0]
        np.testing.assert_allclose(np.asarray(h), np.asarray(base),
                                   atol=1e-6)

    def test_global_builder_setting_applies_to_layers(self):
        x, _ = _data()
        net = _net(None, global_wn=DropConnect(0.5))
        assert isinstance(net.layers[0].weightNoise, DropConnect)
        h1 = net._run_layers(net._params, net._strip_carries(net._states),
                             x, True, jax.random.key(1), None)[0]
        h2 = net._run_layers(net._params, net._strip_carries(net._states),
                             x, True, jax.random.key(2), None)[0]
        assert not np.array_equal(np.asarray(h1), np.asarray(h2))


class TestNestedParams:
    def test_bidirectional_wrapper_gets_noise(self):
        # Bidirectional stores nested {'fwd': {...}, 'bwd': {...}} params;
        # weight noise must walk the pytree instead of crashing on dicts
        from deeplearning4j_tpu.nn import (LSTM, Bidirectional,
                                           RnnOutputLayer)

        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                .weightNoise(DropConnect(0.5)).list()
                .layer(Bidirectional(LSTM(nOut=4)))
                .layer(RnnOutputLayer(nOut=2, activation="softmax",
                                      lossFunction="mcxent"))
                .setInputType(InputType.recurrent(3)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).rand(2, 3, 5).astype("float32")
        y = np.zeros((2, 2, 5), "float32")
        y[:, 0, :] = 1
        net.fit(x, y)  # crashed with AttributeError before the pytree walk
        assert np.isfinite(net.score())
        h1 = net._run_layers(net._params, net._strip_carries(net._states),
                             x, True, jax.random.key(1), None)[0]
        h2 = net._run_layers(net._params, net._strip_carries(net._states),
                             x, True, jax.random.key(2), None)[0]
        assert not np.array_equal(np.asarray(h1), np.asarray(h2))

    def test_center_loss_centers_never_perturbed(self):
        from deeplearning4j_tpu.nn.conf.layers import CenterLossOutputLayer
        import jax.numpy as jnp

        wn = WeightNoise(NormalDistribution(5.0, 0.0), applyToBias=True)
        params = {"W": jnp.ones((3, 2)), "b": jnp.zeros(2),
                  "centers": jnp.ones((2, 3))}
        out = wn.apply(params, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(out["centers"]),
                                      np.asarray(params["centers"]))
        assert float(out["W"][0, 0]) == 6.0      # weight perturbed
        assert float(out["b"][0]) == 5.0          # bias: applyToBias=True

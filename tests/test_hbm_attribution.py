"""HBM gap attribution engine + dtype-policy audit + bytes/step gates
(util/hbm_ledger.attribute_ledger / audit_activation_dtypes,
analysis/hbm CLI subjects).

Three layers of proof, cheapest first:

- synthetic HLO modules pin each bin's classification rule in
  isolation (layout relayouts, dtype widening, gradient double-touch,
  collective split) and the floor+bins+uncategorized == total
  invariant exactly;
- one REAL compile per CLI subject (module-scoped fixtures — LeNet and
  the resnet_block both serve the attribution invariant, the
  cost_analysis oracle, the dtype audit and the bytes/step regression
  gate from a single XLA compile each);
- the bytes/step gates pin the CPU ledger total so a future PR cannot
  silently regress the bandwidth bill (ceilings = measured 2026-08-03
  on this container's jaxlib +10% headroom; a breach means the step
  program got fatter, not that the clock drifted).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.util import hbm_ledger as H


def _attr(hlo, **kw):
    kw.setdefault("compute_dtype", jnp.bfloat16)
    kw.setdefault("act_threshold_elems", 1000)
    return H.attribute_ledger(hlo, **kw)


class TestBinsSynthetic:
    def test_layout_bin_takes_full_relayout_bytes(self):
        # transpose + copy at activation scale: full bytes (out + in)
        # land in layout_copies, nothing else
        hlo = ("ENTRY e {\n"
               "  %a = bf16[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %t = bf16[64,64]{0,1} transpose(%a), dimensions={1,0}\n"
               "  %c = bf16[64,64]{1,0} copy(%t)\n"
               "}\n")
        rec = _attr(hlo)
        n = 64 * 64 * 2
        assert rec["bins"]["layout_copies"] == 4 * n  # 2 ops x (out+in)
        assert rec["bins"]["dtype_widening"] == 0
        assert rec["uncategorized_bytes"] == rec["ledger_total_bytes"] \
            - 4 * n

    def test_dtype_widening_charges_the_excess_only(self):
        # a f32 activation-scale tensor in a bf16-policy step: half of
        # every touch is excess (32 -> 16 bits)
        hlo = ("ENTRY e {\n"
               "  %w = f32[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %y = f32[64,64]{1,0} add(%w, %w)\n"
               "}\n")
        rec = _attr(hlo)
        n = 64 * 64 * 4
        # iota row: out excess n/2; add row: out excess n/2 + one
        # distinct read excess n/2
        assert rec["bins"]["dtype_widening"] == n + n // 2
        assert rec["ledger_total_bytes"] == rec["floor_bytes"] \
            + sum(rec["bins"].values()) + rec["uncategorized_bytes"]

    def test_widening_ignores_sub_threshold_and_param_scale(self):
        hlo = ("ENTRY e {\n"
               "  %w = f32[10,10]{1,0} iota(), iota_dimension=0\n"
               "  %y = f32[10,10]{1,0} add(%w, %w)\n"
               "}\n")
        rec = _attr(hlo)  # 100 elems < 1000 threshold: param scale
        assert rec["bins"]["dtype_widening"] == 0

    def test_grad_double_touch_counts_reads_beyond_first(self):
        # one bf16 activation-scale buffer read by THREE consumers in
        # the same scope: 2 extra reads billed
        hlo = ("ENTRY e {\n"
               "  %a = bf16[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %u = bf16[64,64]{1,0} add(%a, %a)\n"
               "  %v = bf16[64,64]{1,0} multiply(%a, %u)\n"
               "  %w = bf16[64,64]{1,0} subtract(%a, %v)\n"
               "}\n")
        rec = _attr(hlo)
        assert rec["bins"]["grad_double_touch"] == 2 * 64 * 64 * 2

    def test_collective_bin_and_weight_update_split(self):
        hlo = ("ENTRY e {\n"
               "  %g = f32[512]{0} iota(), iota_dimension=0\n"
               "  %r = f32[512]{0} all-reduce(%g), to_apply=%add\n"
               "  %a = bf16[2048]{0} iota(), iota_dimension=0\n"
               "  %s = bf16[2048]{0} all-gather(%a), dimensions={0}\n"
               "}\n")
        rec = _attr(hlo)
        # both collectives fully binned (out+in each)
        assert rec["bins"]["collective"] == 2 * 512 * 4 + 2 * 2048 * 2
        kinds = {t["name"]: t for t in rec["bin_top"]["collective"]}
        assert any("[weight_update]" in n for n in kinds)  # param scale
        assert any("[activation]" in n for n in kinds)     # > threshold

    def test_invariant_exact_on_mixed_module(self):
        hlo = ("ENTRY e {\n"
               "  %a = bf16[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %t = bf16[64,64]{0,1} transpose(%a), dimensions={1,0}\n"
               "  %f = f32[64,64]{1,0} convert(%t)\n"
               "  %y = f32[64,64]{1,0} add(%f, %f)\n"
               "  %r = f32[64]{0} all-reduce(%y), to_apply=%add\n"
               "}\n")
        rec = _attr(hlo)
        assert rec["ledger_total_bytes"] == rec["floor_bytes"] \
            + sum(rec["bins"].values()) + rec["uncategorized_bytes"]
        assert rec["ledger_total_bytes"] == H.ledger(hlo)["total_bytes"]


class TestAuditSynthetic:
    def test_wide_activation_buffer_flagged(self):
        hlo = ("ENTRY e {\n"
               "  %a = f32[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %y = f32[64,64]{1,0} add(%a, %a)\n"
               "}\n")
        off = H.audit_activation_dtypes(hlo, compute_dtype=jnp.bfloat16,
                                        act_threshold_elems=1000)
        assert {r["name"] for r in off} == {"a", "y"}
        with pytest.raises(AssertionError, match="activation-scale"):
            H.assert_activation_dtype_clean(
                hlo, compute_dtype=jnp.bfloat16, act_threshold_elems=1000)

    def test_fused_accumulator_convert_is_exempt(self):
        # convert consumed ONLY by a reduce = the jnp.sum(dtype=f32)
        # idiom: sanctioned (fuses into the reduction)
        hlo = ("ENTRY e {\n"
               "  %a = bf16[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %f = f32[64,64]{1,0} convert(%a)\n"
               "  %s = f32[64]{0} reduce(%f, %z), dimensions={1}, "
               "to_apply=%add\n"
               "}\n")
        off = H.audit_activation_dtypes(hlo, compute_dtype=jnp.bfloat16,
                                        act_threshold_elems=1000)
        assert off == []

    def test_convert_with_non_reduce_consumer_still_flagged(self):
        hlo = ("ENTRY e {\n"
               "  %a = bf16[64,64]{1,0} iota(), iota_dimension=0\n"
               "  %f = f32[64,64]{1,0} convert(%a)\n"
               "  %y = f32[64,64]{1,0} add(%f, %f)\n"
               "}\n")
        off = H.audit_activation_dtypes(hlo, compute_dtype=jnp.bfloat16,
                                        act_threshold_elems=1000)
        assert {r["name"] for r in off} == {"f", "y"}


# ---------------------------------------------------------------------
# real compiles: one per subject, shared by every assertion below
# ---------------------------------------------------------------------

#: CPU ledger-total ceilings (re-measured 2026-08-04, ratcheted from
#: +10% to +5% headroom — round 12): the bytes/step regression gate. A
#: breach means the compiled train step moves more bytes than this
#: round shipped — name the regression, don't ship it.
LENET_B64_CEILING = 136_000_000        # measured 129,135,086
RESNET_BLOCK_B32_CEILING = 66_500_000  # measured 63,121,644

#: per-bin ceilings (measured +10% bin headroom; grad_double_touch and
#: collective measured EXACTLY 0 on both subjects — 1 MB epsilon
#: absorbs fusion-naming jitter, anything more is a real regression).
#: These ratchet DOWN as kernels land: the round-12 fused kernels keep
#: the bins at these levels and the gate keeps them there.
LENET_B64_BIN_CEILINGS = {
    "layout_copies": 19_500_000,      # measured 17,551,048
    "dtype_widening": 16_500_000,     # measured 14,745,840
    "grad_double_touch": 1_000_000,   # measured 0
    "collective": 1_000_000,          # measured 0
}
RESNET_BLOCK_B32_BIN_CEILINGS = {
    "layout_copies": 9_800_000,       # measured 8,857,728
    "dtype_widening": 29_500_000,     # measured 26,836,992
    "grad_double_touch": 1_000_000,   # measured 0
    "collective": 1_000_000,          # measured 0
}

#: the TUNED LeNet ceiling (round 12): with the autotune arbiter's CPU
#: winners installed (maxpool_bwd="indices" — the saved-int8-indices
#: single-pass pool backward), the same step moves 69,168,508 bytes,
#: a 46.4% cut vs stock. This gate pins the WON bytes: a change that
#: silently fattens the tuned lowering (or breaks the knob) trips it.
LENET_B64_TUNED_CEILING = 72_700_000   # measured 69,168,508
#: and the tuned step must stay measurably below the stock one
LENET_TUNED_MAX_FRAC_OF_STOCK = 0.65   # measured 0.536


# the compiles live in SESSION-scoped conftest fixtures (one per run,
# shared with any other module that interrogates the same subjects, and
# routed through the AOT executable cache — docs/COMPILE.md)

@pytest.fixture(scope="module")
def lenet_subject(lenet_compiled_subject):
    return lenet_compiled_subject


@pytest.fixture(scope="module")
def resnet_block_subject(resnet_block_compiled_subject):
    return resnet_block_compiled_subject


def _cost_bytes(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float((ca or {}).get("bytes accessed", 0.0))


class TestLeNetGate:
    def test_attribution_invariant_and_cost_oracle(self, lenet_subject):
        net, x_shape, slots, _low, compiled = lenet_subject
        rec = H.attribute_ledger(compiled, net=net, x_shape=x_shape,
                                 optimizer_slots=slots)
        # exact by construction
        assert rec["ledger_total_bytes"] == rec["floor_bytes"] \
            + sum(rec["bins"].values()) + rec["uncategorized_bytes"]
        # and the total reproduces XLA's own cost model within 1%
        assert rec["ledger_total_bytes"] == pytest.approx(
            _cost_bytes(compiled), rel=0.01)
        assert rec["floor_bytes"] > 0
        assert rec["gap_bytes"] > 0

    def test_bytes_per_step_regression_gate(self, lenet_subject):
        _net, _xs, _slots, _low, compiled = lenet_subject
        total = H.ledger_for_compiled(compiled)["total_bytes"]
        assert total <= LENET_B64_CEILING, (
            f"LeNet b64 train step moves {total} bytes on CPU — above "
            f"the ratcheted ceiling {LENET_B64_CEILING}. The bandwidth "
            "bill regressed; run `python -m deeplearning4j_tpu.analysis "
            "--attribution lenet` to see which bin grew.")

    def test_per_bin_ceilings(self, lenet_subject):
        """Round-12 ratchet: each attribution bin individually pinned,
        so a regression names ITS bin instead of hiding in the total
        (grad_double_touch/collective are pinned at ~0 — the fused
        kernels keep them empty and this keeps them kept)."""
        net, x_shape, slots, _low, compiled = lenet_subject
        rec = H.attribute_ledger(compiled, net=net, x_shape=x_shape,
                                 optimizer_slots=slots)
        for bin_name, ceiling in LENET_B64_BIN_CEILINGS.items():
            assert rec["bins"][bin_name] <= ceiling, (
                f"lenet bin {bin_name} = {rec['bins'][bin_name]} "
                f"exceeds its ratcheted ceiling {ceiling}")

    def test_dtype_audit_clean_on_model_lowering(self, lenet_subject):
        net, _xs, _slots, lowered, _c = lenet_subject
        H.assert_activation_dtype_clean(H.pre_opt_hlo(lowered), net=net)


class TestResNetBlockGate:
    def test_attribution_invariant_and_cost_oracle(self,
                                                   resnet_block_subject):
        net, x_shape, slots, _low, compiled = resnet_block_subject
        rec = H.attribute_ledger(compiled, net=net, x_shape=x_shape,
                                 optimizer_slots=slots)
        assert rec["ledger_total_bytes"] == rec["floor_bytes"] \
            + sum(rec["bins"].values()) + rec["uncategorized_bytes"]
        assert rec["ledger_total_bytes"] == pytest.approx(
            _cost_bytes(compiled), rel=0.01)

    def test_bytes_per_step_regression_gate(self, resnet_block_subject):
        _net, _xs, _slots, _low, compiled = resnet_block_subject
        total = H.ledger_for_compiled(compiled)["total_bytes"]
        assert total <= RESNET_BLOCK_B32_CEILING

    def test_per_bin_ceilings(self, resnet_block_subject):
        net, x_shape, slots, _low, compiled = resnet_block_subject
        rec = H.attribute_ledger(compiled, net=net, x_shape=x_shape,
                                 optimizer_slots=slots)
        for bin_name, ceiling in \
                RESNET_BLOCK_B32_BIN_CEILINGS.items():
            assert rec["bins"][bin_name] <= ceiling, (
                f"resnet_block bin {bin_name} = "
                f"{rec['bins'][bin_name]} exceeds its ratcheted "
                f"ceiling {ceiling}")

    def test_dtype_audit_clean_compute_tail_dirty_wide_tail(
            self, resnet_block_subject):
        """THE round-6 contrast: the default compute-dtype BN/loss
        tails pass the audit; flipping to the legacy wide tails on the
        same model fails it — proving the audit detects exactly the
        lowering difference the fix removed (the norm.py docstring's
        promise)."""
        from deeplearning4j_tpu.analysis.hbm import (build_subject,
                                                     lower_train_step)
        from deeplearning4j_tpu.nn import losses as _losses
        from deeplearning4j_tpu.ops import norm as _norm

        net, _xs, _slots, lowered, _c = resnet_block_subject
        H.assert_activation_dtype_clean(H.pre_opt_hlo(lowered), net=net)

        old = (_norm._TAIL_MODE, _losses._TAIL_MODE)
        try:
            _norm._TAIL_MODE = _losses._TAIL_MODE = "wide"
            net2, xs2, _ = build_subject("resnet_block", batch_size=32)
            low2 = lower_train_step(net2, xs2)
            off = H.audit_activation_dtypes(H.pre_opt_hlo(low2), net=net2)
        finally:
            _norm._TAIL_MODE, _losses._TAIL_MODE = old
        assert len(off) > 0  # the wide tail leaks, and the audit sees it


class TestTunedSubjectGate:
    """THE round-12 acceptance gate: with the autotune arbiter's CPU
    winners installed (maxpool_bwd='indices'), the LeNet b64 step's
    attributed bytes drop 46% below stock — and this ceiling keeps the
    won bytes from silently regressing. One extra XLA compile
    (module-scoped); the knob values live in the AOT ambient
    fingerprint, so this compile can never collide with the stock
    subject's cache entry (gated in test_aot_cache)."""

    #: the winners the CPU sweep lands on (pinned here; the full
    #: arbiter run proving it FINDS them is
    #: test_autotune.py::test_lenet_sweep_finds_indices, marked slow)
    TUNED_KNOBS = {"maxpool_bwd": "indices"}

    @pytest.fixture(scope="class")
    def tuned_lenet(self):
        from deeplearning4j_tpu.analysis.hbm import (build_subject,
                                                     compile_train_step,
                                                     lower_train_step)
        from deeplearning4j_tpu.runtime import autotune as at

        with at.applied(self.TUNED_KNOBS):
            net, x_shape, slots = build_subject("lenet", batch_size=64)
            lowered = lower_train_step(net, x_shape)
            compiled = compile_train_step(net, x_shape, lowered=lowered)
        return net, x_shape, slots, compiled

    def test_tuned_bytes_ceiling(self, tuned_lenet, lenet_subject):
        _n, _xs, _sl, compiled = tuned_lenet
        tuned = H.ledger_for_compiled(compiled)["total_bytes"]
        assert tuned <= LENET_B64_TUNED_CEILING, (
            f"TUNED LeNet b64 moves {tuned} bytes — above the "
            f"ratcheted ceiling {LENET_B64_TUNED_CEILING}: the "
            "round-12 pool-backward win regressed")
        stock = H.ledger_for_compiled(
            lenet_subject[4])["total_bytes"]
        assert tuned <= stock * LENET_TUNED_MAX_FRAC_OF_STOCK, (
            f"tuned/stock = {tuned / stock:.3f}: the tuned config no "
            "longer wins measurably over stock")

    def test_tuned_attribution_invariant(self, tuned_lenet):
        net, x_shape, slots, compiled = tuned_lenet
        rec = H.attribute_ledger(compiled, net=net, x_shape=x_shape,
                                 optimizer_slots=slots)
        assert rec["ledger_total_bytes"] == rec["floor_bytes"] \
            + sum(rec["bins"].values()) + rec["uncategorized_bytes"]
        # same analytic floor as stock — the knob changes the LOWERING,
        # not the model's math
        assert rec["floor_bytes"] > 0

    def test_tuned_step_loss_parity_is_bitwise(self, tuned_lenet,
                                               lenet_subject):
        """The indices backward is an exact-math impl swap: one train
        step under the tuned executable produces BITWISE the stock
        step's loss and parameters (the arbiter's parity proof, pinned
        here as a direct gate on the shipped kernel)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.runtime.autotune import _step_args

        net_t, x_shape, _sl, comp_t = tuned_lenet
        net_s = lenet_subject[0]
        comp_s = lenet_subject[4]
        args = _step_args(net_s, x_shape, seed=7)
        # same init on both nets (same seed/config): assert it
        for a, b in zip(jax.tree_util.tree_leaves(net_s._params),
                        jax.tree_util.tree_leaves(net_t._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out_s = comp_s(*args)
        out_t = comp_t(*args)
        for a, b in zip(jax.tree_util.tree_leaves(out_s),
                        jax.tree_util.tree_leaves(out_t)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestWeightUpdateModel:
    def test_dp_weight_update_arithmetic(self):
        from deeplearning4j_tpu.parallel.sharding import \
            dp_weight_update_bytes

        G = 100 * 4  # 100 fp32 grads
        rec = dp_weight_update_bytes(G, dp=4)
        assert rec["allreduce_bytes"] == 2 * 3 * G // 4
        assert rec["update_replicated_bytes"] == 2 * G + 2 * G + G
        assert rec["update_sharded_bytes"] == (2 * G + 2 * G + G) // 4
        assert rec["sharding_saves_bytes"] == \
            rec["update_replicated_bytes"] - rec["update_sharded_bytes"]
        with pytest.raises(ValueError):
            dp_weight_update_bytes(G, dp=0)

    def test_dp1_degenerates_to_zero_collective(self):
        from deeplearning4j_tpu.parallel.sharding import \
            dp_weight_update_bytes

        assert dp_weight_update_bytes(4096, dp=1)["allreduce_bytes"] == 0


class TestCanonicalStaging:
    def test_fit_dataset_parity_and_byte_cut(self):
        """Host-canonical staging (the round-6 layout fix, default ON)
        must train the SAME trajectory as legacy device staging and
        compile a k-loop that moves fewer bytes (no per-step entry
        transpose/convert, fp32->bf16 transfer halved)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.data.dataset import DataSetIterator
        from deeplearning4j_tpu.data.iterators import (iter_stacks,
                                                       stack_datasets)
        from deeplearning4j_tpu.ndarray import DataType
        from deeplearning4j_tpu.nn import multilayer as _ml
        from deeplearning4j_tpu.zoo import LeNet

        B, NB, K = 8, 4, 2
        rng = np.random.RandomState(7)
        X = rng.rand(NB * B, 1, 28, 28).astype("float32")
        Y = np.eye(10, dtype="float32")[rng.randint(0, 10, NB * B)]

        def run(mode):
            old = _ml._CANON_STAGING
            _ml._CANON_STAGING = mode
            try:
                net = LeNet(numClasses=10, inputShape=(1, 28, 28),
                            dataType=DataType.BFLOAT16).init()
                net.fitDataSet(DataSetIterator(X, Y, B), stepsPerSync=K)
                return net
            finally:
                _ml._CANON_STAGING = old

        net_h = run("host")
        net_d = run("device")
        # same trajectory: the host-side cast/transpose is bitwise the
        # in-program one (RTNE both sides)
        for a, b in zip(jax.tree_util.tree_leaves(net_h._params),
                        jax.tree_util.tree_leaves(net_d._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_canonical_staging_removes_entry_transpose(self):
        """Program-structure proof of the layout fix on an fp32 NCHW
        conv net: the device-staged k-loop lowering carries a per-step
        activation-scale entry transpose, the canonical one carries
        none — and the canonical program's cost_analysis bytes are
        never worse. (On XLA:CPU layout assignment can rewrite the
        transpose to a free bitcast, so equality of bytes is allowed;
        on TPU the staged bf16 NHWC feed skips a real relayout+convert,
        which is the bin the attribution named.)"""
        import jax.numpy as jnp

        from deeplearning4j_tpu.data.dataset import DataSetIterator
        from deeplearning4j_tpu.data.iterators import (iter_stacks,
                                                       stack_datasets)
        from deeplearning4j_tpu.nn import (ConvolutionLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           Nesterovs, OutputLayer)
        from deeplearning4j_tpu.nn import multilayer as _ml

        B, NB, K = 8, 4, 2
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Nesterovs(0.1, 0.9))
                .activation("relu").list()
                .layer(ConvolutionLayer(nOut=8, kernelSize=(3, 3)))
                .layer(OutputLayer(nOut=10, activation="softmax",
                                   lossFunction="mcxent"))
                .setInputType(InputType.convolutional(16, 16, 3))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(5)
        X = rng.rand(NB * B, 3, 16, 16).astype("float32")
        Y = np.eye(10, dtype="float32")[rng.randint(0, 10, NB * B)]

        import re

        def lower_loop(canon):
            jl = _ml.fit_dataset_jit(net, K, canonical=canon)
            batches = next(iter_stacks(DataSetIterator(X, Y, B), K))
            xs, ys, fms, lms = (net._stack_canonical(batches) if canon
                                else stack_datasets(batches))
            return jl.lower(net._params, net._upd_states, net._states,
                            jnp.asarray(0, jnp.int32), xs, ys, fms, lms)

        entry_t = re.compile(
            r"=\s*f32\[8,16,16,3\]\S*\s+transpose\(")

        def entry_transposes(lowered):
            return sum(1 for line in H.pre_opt_hlo(lowered).splitlines()
                       if entry_t.search(line))

        low_h, low_d = lower_loop(True), lower_loop(False)
        assert entry_transposes(low_d) > 0   # legacy pays it per step
        assert entry_transposes(low_h) == 0  # canonical never emits it
        hb = _cost_bytes(low_h.compile())
        db = _cost_bytes(low_d.compile())
        assert hb <= db, (hb, db)

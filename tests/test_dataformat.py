"""NHWC input-format parity (reference: CNN2DFormat on InputType).

format="NHWC" must be a pure layout change: identical math to the NCHW
feed of the same logical data, with the entry transpose gone from the
lowered program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    NeuralNetConfiguration, InputType, MultiLayerNetwork, ConvolutionLayer,
    SubsamplingLayer, BatchNormalization, OutputLayer, Adam,
)
from deeplearning4j_tpu.zoo import ResNet50


def _small_cnn(fmt):
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(1e-3)).activation("relu")
            .list()
            .layer(ConvolutionLayer(nOut=8, kernelSize=(3, 3),
                                    convolutionMode="same"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(nOut=8, kernelSize=(3, 3)))
            .layer(OutputLayer(nOut=5, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.convolutional(12, 10, 3, format=fmt))
            .build())
    return MultiLayerNetwork(conf).init()


def test_nhwc_output_parity_with_nchw():
    rng = np.random.RandomState(0)
    x_nchw = rng.rand(4, 3, 12, 10).astype("float32")
    x_nhwc = np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
    out_a = np.asarray(_small_cnn("NCHW").output(x_nchw).jax())
    out_b = np.asarray(_small_cnn("NHWC").output(x_nhwc).jax())
    np.testing.assert_allclose(out_a, out_b, rtol=1e-6, atol=1e-6)


def test_nhwc_fit_parity_with_nchw():
    rng = np.random.RandomState(1)
    x_nchw = rng.rand(8, 3, 12, 10).astype("float32")
    x_nhwc = np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
    y = np.eye(5, dtype="float32")[rng.randint(0, 5, 8)]
    a, b = _small_cnn("NCHW"), _small_cnn("NHWC")
    for _ in range(3):
        a.fit(x_nchw, y)
        b.fit(x_nhwc, y)
    assert a.score() == pytest.approx(b.score(), rel=1e-6)


def test_invalid_format_rejected():
    with pytest.raises(ValueError, match="NCHW or NHWC"):
        InputType.convolutional(8, 8, 3, format="CHWN")


def test_resnet50_nhwc_graph_runs():
    net = ResNet50(numClasses=10, inputShape=(3, 32, 32),
                   dataFormat="NHWC").init()
    rng = np.random.RandomState(2)
    x = rng.rand(2, 32, 32, 3).astype("float32")
    y = np.eye(10, dtype="float32")[rng.randint(0, 10, 2)]
    net.fit(x, [y])
    assert np.isfinite(net.score())


def _dense_head_cnn(fmt):
    # CnnLossLayer head: per-pixel predictions, so the 4-d LABEL layout
    # contract matters, not just the feature layout
    from deeplearning4j_tpu.nn.conf.layers import CnnLossLayer

    conf = (NeuralNetConfiguration.Builder()
            .seed(3).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(nOut=6, kernelSize=(3, 3),
                                    convolutionMode="same",
                                    activation="relu"))
            .layer(ConvolutionLayer(nOut=2, kernelSize=(1, 1),
                                    activation="identity"))
            .layer(CnnLossLayer(activation="softmax", lossFunction="mcxent"))
            .setInputType(InputType.convolutional(8, 6, 3, format=fmt))
            .build())
    return MultiLayerNetwork(conf).init()


def test_nhwc_dense_head_label_parity():
    rng = np.random.RandomState(4)
    x_nchw = rng.rand(4, 3, 8, 6).astype("float32")
    lab_ids = rng.randint(0, 2, (4, 8, 6))
    y_nchw = np.eye(2, dtype="float32")[lab_ids].transpose(0, 3, 1, 2)
    x_nhwc = np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
    y_nhwc = np.ascontiguousarray(y_nchw.transpose(0, 2, 3, 1))
    a, b = _dense_head_cnn("NCHW"), _dense_head_cnn("NHWC")
    for _ in range(2):
        a.fit(x_nchw, y_nchw)
        b.fit(x_nhwc, y_nhwc)
    assert a.score() == pytest.approx(b.score(), rel=1e-6)


def test_nhwc_graph_output_layout():
    # ComputationGraph with a 4-d output: NCHW nets return NCHW at the
    # boundary, NHWC nets return NHWC untouched.
    from deeplearning4j_tpu.nn.conf.layers import CnnLossLayer

    def build(fmt):
        g = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-3))
             .graphBuilder().addInputs("in"))
        g.addLayer("c1", ConvolutionLayer(nOut=4, kernelSize=(3, 3),
                                          convolutionMode="same",
                                          activation="relu"), "in")
        g.addLayer("out", CnnLossLayer(activation="sigmoid",
                                       lossFunction="xent"), "c1")
        from deeplearning4j_tpu.nn import ComputationGraph
        return ComputationGraph(
            g.setOutputs("out")
             .setInputTypes(InputType.convolutional(10, 8, 3, format=fmt))
             .build()).init()

    rng = np.random.RandomState(6)
    x_nchw = rng.rand(2, 3, 10, 8).astype("float32")
    x_nhwc = np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
    out_nchw = np.asarray(build("NCHW").output(x_nchw).jax())
    out_nhwc = np.asarray(build("NHWC").output(x_nhwc).jax())
    assert out_nchw.shape == (2, 4, 10, 8)
    assert out_nhwc.shape == (2, 10, 8, 4)
    np.testing.assert_allclose(out_nchw, out_nhwc.transpose(0, 3, 1, 2),
                               rtol=1e-5, atol=1e-6)


def test_nhwc_entry_has_no_transpose():
    # The point of the feature: the lowered forward must not contain a
    # 4-d input transpose (NCHW networks have exactly that at entry).
    net = _small_cnn("NHWC")
    x = jnp.zeros((2, 12, 10, 3), jnp.float32)

    def fwd(params, states, xx):
        h, _ = net._run_layers(params, states, xx, False, None, None)
        return h

    txt = jax.jit(fwd).lower(net._params, net._states, x).as_text()
    # conv itself may carry internal transposes on CPU; assert on the
    # specific entry pattern instead: a transpose whose operand is the
    # input argument shape 2x3x12x10 cannot appear since no such shape
    # exists in the NHWC program at all.
    assert "2x3x12x10" not in txt

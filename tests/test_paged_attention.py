"""Block-table paged-attention kernel gates (ops/pallas_attention.py
``paged_flash_decode`` / ``paged_flash_prefill`` / ``paged_attend``,
docs/SERVING.md "Paged KV cache").

What must hold (the ISSUE 19 kernel acceptance):

- the paged DECODE kernel (one query row per slot, K/V gathered
  through the slot's block table) is BITWISE equal to the dense flash
  kernel on the same tokens — aligned, padded and bf16 grids, with the
  pool pages physically scattered;
- the chunked-PREFILL kernel (page-sized prompt chunk attending
  causally over the table so far) is bitwise the dense kernel's rows
  for every chunk;
- padded slots behave like the dense kernel's fully-masked rows: zero
  output, the +1e30 lse sentinel, and trailing null-page blocks are
  bitwise no-ops on the accumulators;
- the portable ``paged_attend`` core (the serving step functions'
  attention) accumulates in the same page order: bitwise in bf16,
  <= 1 ulp in f32 vs the kernels.

Everything runs in pallas interpret mode on CPU — the same numerics
contract the dense flash kernel's parity suite uses.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import pallas_attention as pa


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setattr(pa, "_INTERPRET", True)


# interpret-mode pallas churns many tiny single-use executables; the
# shared hygiene fixture drops jax's global caches at module teardown
from conftest import drop_jax_caches_fixture

_drop_jax_caches_after_module = drop_jax_caches_fixture()


# ----------------------------------------------------------------------
# subjects
# ----------------------------------------------------------------------

def _paged_layout(T, page, P, H, D, dtype, rng, start_page=1):
    """Contiguous K/V [1, H, T, D] plus the SAME tokens scattered into
    a paged pool through a randomly permuted block table (physical
    page order deliberately != logical order)."""
    k = rng.standard_normal((1, H, T, D)).astype(np.float32)
    v = rng.standard_normal((1, H, T, D)).astype(np.float32)
    MP = -(-T // page)
    kp = np.zeros((P, page, H, D), np.float32)
    vp = np.zeros((P, page, H, D), np.float32)
    bt = np.zeros((MP,), np.int32)
    order = rng.permutation(np.arange(start_page, P))[:MP]
    for j in range(MP):
        pid = int(order[j])
        bt[j] = pid
        n = min(page, T - j * page)
        kp[pid, :n] = np.moveaxis(k[0, :, j * page:j * page + n], 0, 1)
        vp[pid, :n] = np.moveaxis(v[0, :, j * page:j * page + n], 0, 1)
    return (k.astype(dtype), v.astype(dtype), kp.astype(dtype),
            vp.astype(dtype), bt)


GRIDS = [
    pytest.param(8, 4, np.float32, id="aligned-f32"),
    pytest.param(7, 4, np.float32, id="padded-f32"),
    pytest.param(8, 4, jnp.bfloat16, id="aligned-bf16"),
    pytest.param(7, 4, jnp.bfloat16, id="padded-bf16"),
]


# ----------------------------------------------------------------------
# decode kernel vs the dense flash kernel
# ----------------------------------------------------------------------

class TestPagedDecodeParity:
    @pytest.mark.parametrize("T,page,dtype", GRIDS)
    def test_decode_bitwise_vs_dense_flash(self, T, page, dtype):
        """The block-table decode kernel's output for the last token is
        BITWISE the dense flash kernel's last row (block_q=1,
        block_k=page — identical accumulation order), pool pages
        scattered."""
        rng = np.random.default_rng(0)
        H, D, P = 2, 8, 12
        k, v, kp, vp, bt = _paged_layout(T, page, P, H, D, dtype, rng)
        q_full = rng.standard_normal((1, H, T, D)).astype(
            np.float32).astype(dtype)
        dense, _ = pa._flash_fwd_impl(jnp.asarray(q_full),
                                      jnp.asarray(k), jnp.asarray(v),
                                      True, 1, page, need_lse=False)
        dense_last = np.asarray(dense)[0, :, T - 1, :]
        S, MP = 2, bt.shape[0]
        bts = np.zeros((S, MP), np.int32)
        bts[0] = bt
        sls = np.zeros((S,), np.int32)
        sls[0] = T
        q = np.zeros((S, H, D), dtype)
        q[0] = np.moveaxis(q_full[0, :, T - 1], 0, 0)
        out = pa.paged_flash_decode(jnp.asarray(q), jnp.asarray(kp),
                                    jnp.asarray(vp), bts, sls)
        out = np.asarray(out)
        assert np.array_equal(out[0].view(np.uint8),
                              dense_last.view(np.uint8))

    @pytest.mark.parametrize("T,page,dtype", GRIDS)
    def test_padded_slot_rows_masked_like_dense(self, T, page, dtype):
        """A padded slot (seq_len 0, block table all null page) is the
        dense kernel's fully-masked row: zero output, +1e30 lse
        sentinel — never NaN, never garbage."""
        rng = np.random.default_rng(0)
        H, D, P = 2, 8, 12
        _, _, kp, vp, bt = _paged_layout(T, page, P, H, D, dtype, rng)
        S, MP = 2, bt.shape[0]
        bts = np.zeros((S, MP), np.int32)
        bts[0] = bt
        sls = np.zeros((S,), np.int32)
        sls[0] = T
        q = rng.standard_normal((S, H, D)).astype(np.float32).astype(dtype)
        out, lse = pa.paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), bts, sls,
            need_lse=True)
        assert np.all(np.asarray(out)[1] == 0)
        assert np.all(np.asarray(lse)[1] == pa._LSE_EMPTY)

    def test_trailing_null_pages_are_noops(self):
        """Blocks past a slot's live length run against the null page
        but contribute nothing: extending the block-table width leaves
        the output bitwise identical (the masked-block no-op the
        bounded-pool layout depends on)."""
        rng = np.random.default_rng(2)
        T, page, H, D, P = 12, 4, 2, 8, 16
        _, _, kp, vp, bt = _paged_layout(T, page, P, H, D,
                                         np.float32, rng)
        # poison the null page: a real no-op must mask it, not rely on
        # it being zero
        kp[0] = 7.5
        vp[0] = -3.25
        q = rng.standard_normal((1, H, D)).astype(np.float32)
        sls = np.asarray([T], np.int32)
        out_tight = pa.paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            bt[None], sls)
        wide = np.zeros((1, bt.shape[0] + 3), np.int32)
        wide[0, :bt.shape[0]] = bt
        out_wide = pa.paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            wide, sls)
        assert np.array_equal(np.asarray(out_tight).view(np.uint8),
                              np.asarray(out_wide).view(np.uint8))


# ----------------------------------------------------------------------
# chunked-prefill kernel vs the dense flash kernel
# ----------------------------------------------------------------------

class TestPagedPrefillParity:
    @pytest.mark.parametrize("T,page,dtype", GRIDS)
    def test_prefill_chunks_bitwise_vs_dense_flash(self, T, page, dtype):
        """Every page-sized prompt chunk's attention rows are BITWISE
        the dense flash kernel's rows over the same prefix (block_q =
        block_k = page) — the chunked prefill appends into scattered
        pages yet accumulates in the identical block order."""
        rng = np.random.default_rng(1)
        H, D, P = 2, 8, 12
        k, v, kp, vp, bt = _paged_layout(T, page, P, H, D, dtype, rng)
        q_full = rng.standard_normal((1, H, T, D)).astype(
            np.float32).astype(dtype)
        for c in range(-(-T // page)):
            t0 = c * page
            n_valid = min(page, T - t0)
            Tc = t0 + n_valid
            dense, _ = pa._flash_fwd_impl(
                jnp.asarray(q_full[:, :, :Tc]),
                jnp.asarray(k[:, :, :Tc]), jnp.asarray(v[:, :, :Tc]),
                True, page, page, need_lse=False)
            dense_rows = np.asarray(dense)[0, :, t0:Tc, :]
            qc = np.zeros((page, H, D), dtype)
            qc[:n_valid] = np.moveaxis(q_full[0, :, t0:Tc], 0, 1)
            out = pa.paged_flash_prefill(
                jnp.asarray(qc), jnp.asarray(kp), jnp.asarray(vp),
                bt, t0, n_valid)
            got = np.moveaxis(np.asarray(out)[:n_valid], 0, 1)
            assert np.array_equal(got.view(np.uint8),
                                  dense_rows.view(np.uint8)), \
                f"chunk {c} diverged from the dense kernel"


# ----------------------------------------------------------------------
# the portable core (serving step functions)
# ----------------------------------------------------------------------

class TestPagedAttendCore:
    @pytest.mark.parametrize("T,page,dtype", GRIDS)
    def test_core_matches_kernels_page_order(self, T, page, dtype):
        """``paged_attend`` (what the transformer step twins trace)
        accumulates page-sequentially like the kernels: bitwise in
        bf16, a couple ulp in f32 (XLA fuses the f32 reductions
        slightly differently; the serving-parity gates compare
        core-vs-core, so this tolerance never stacks)."""
        rng = np.random.default_rng(3)
        H, D, P = 2, 8, 12
        _, _, kp, vp, bt = _paged_layout(T, page, P, H, D, dtype, rng)
        q = rng.standard_normal((1, H, D)).astype(np.float32).astype(dtype)
        sls = np.asarray([T], np.int32)
        out = np.asarray(pa.paged_flash_decode(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            bt[None], sls))
        kpg = jnp.asarray(kp)[bt[None]]
        vpg = jnp.asarray(vp)[bt[None]]
        ref = np.asarray(pa.paged_attend(
            jnp.asarray(q[:, None]), kpg, vpg, jnp.asarray(sls),
            jnp.asarray(sls) - 1))[:, 0]
        if dtype == jnp.bfloat16:
            assert np.array_equal(ref.view(np.uint8),
                                  out.view(np.uint8))
        else:
            err = np.max(np.abs(ref.astype(np.float64)
                                - out.astype(np.float64)))
            assert err <= 3e-7, f"core-vs-kernel error {err}"

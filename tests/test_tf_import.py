"""TF frozen-graph import with numeric parity against live TF execution.

Reference: nd4j TFGraphMapper tests — import a GraphDef, run both sides on
the same input, compare. Graphs are produced the way real frozen models
are: tf.function -> get_concrete_function -> convert_variables_to_constants_v2.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from tensorflow.python.framework.convert_to_constants import (  # noqa: E402
    convert_variables_to_constants_v2,
)

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TFGraphMapper, TFImportException, importFrozenTF,
)


def _freeze(model, spec):
    fn = tf.function(model).get_concrete_function(spec)
    frozen = convert_variables_to_constants_v2(fn)
    return frozen.graph.as_graph_def(), frozen


def _placeholder_name(gd):
    return [n.name for n in gd.node if n.op == "Placeholder"][0]


def _last_name(gd):
    consumed = {i.split(":")[0].lstrip("^") for n in gd.node for i in n.input}
    sinks = [n.name for n in gd.node
             if n.op not in ("Const", "NoOp") and n.name not in consumed]
    return sinks[-1]


def _parity(gd, frozen, x, atol=1e-5, rtol=1e-4):
    sd = importFrozenTF(gd.SerializeToString())
    golden = frozen(tf.constant(x))
    golden = np.asarray(golden[0] if isinstance(golden, (list, tuple)) else golden)
    out = TFGraphMapper.outputVariable(sd, _last_name(gd))
    ours = np.asarray(
        out.eval({_placeholder_name(gd): x}).jax())
    np.testing.assert_allclose(ours, golden, atol=atol, rtol=rtol)
    return sd


class TestMLPImport:
    def test_dense_mlp_parity(self):
        tf.keras.utils.set_random_seed(3)
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(32, activation="relu"),
            tf.keras.layers.Dense(16, activation="tanh"),
            tf.keras.layers.Dense(5, activation="softmax"),
        ])
        model.build((4, 12))
        gd, frozen = _freeze(
            model, tf.TensorSpec((4, 12), tf.float32))
        x = np.random.RandomState(0).rand(4, 12).astype("float32")
        _parity(gd, frozen, x)

    def test_imported_graph_is_trainable(self):
        # The import target is a full SameDiff graph: jit, grad, training
        # all work on it — not an inference-only shim.
        tf.keras.utils.set_random_seed(4)
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(8, activation="relu"),
             tf.keras.layers.Dense(3)])
        model.build((8, 6))
        gd, _ = _freeze(model, tf.TensorSpec((8, 6), tf.float32))
        sd = importFrozenTF(gd.SerializeToString())
        out = TFGraphMapper.outputVariable(sd, _last_name(gd))
        # constants imported from the frozen graph can be promoted and
        # trained against a loss
        g = sd.math.square(out).mean()
        g.rename("loss")
        sd.setLossVariables("loss")
        x = np.random.RandomState(1).rand(8, 6).astype("float32")
        grads = sd.calculateGradients({_placeholder_name(gd): x},
                                      *[v.name for v in sd.variables()])
        assert isinstance(grads, dict)


class TestCNNImport:
    def _cnn(self):
        tf.keras.utils.set_random_seed(5)
        return tf.keras.Sequential([
            tf.keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
            tf.keras.layers.MaxPool2D(2),
            tf.keras.layers.Conv2D(12, 3, strides=2, padding="valid"),
            tf.keras.layers.BatchNormalization(),
            tf.keras.layers.ReLU(),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(4, activation="softmax"),
        ])

    def test_small_cnn_parity(self):
        model = self._cnn()
        model.build((2, 16, 16, 3))
        gd, frozen = _freeze(model, tf.TensorSpec((2, 16, 16, 3), tf.float32))
        ops = {n.op for n in gd.node}
        # Keras 3 freezes inference BN into a Rsqrt/Mul/Sub/Add chain
        assert "Conv2D" in ops and "Rsqrt" in ops, ops
        x = np.random.RandomState(2).rand(2, 16, 16, 3).astype("float32")
        _parity(gd, frozen, x)

    def test_fused_batchnorm_parity(self):
        # Keras 3 decomposes BN at freeze time, so drive the FusedBatchNormV3
        # import path with the raw op directly (what older frozen graphs —
        # the ones people actually have .pb files of — contain).
        g, b = np.float32([1.2, 0.8]), np.float32([0.1, -0.2])
        m, v = np.float32([0.3, -0.1]), np.float32([1.5, 0.7])

        @tf.function
        def f(x):
            y, _, _ = tf.raw_ops.FusedBatchNormV3(
                x=x, scale=g, offset=b, mean=m, variance=v,
                epsilon=1e-3, is_training=False)[:3]
            return tf.nn.relu(y)

        gd = f.get_concrete_function(
            tf.TensorSpec((2, 4, 4, 2), tf.float32)).graph.as_graph_def()
        assert "FusedBatchNormV3" in {n.op for n in gd.node}
        x = np.random.RandomState(7).randn(2, 4, 4, 2).astype("float32")
        golden = np.asarray(f(tf.constant(x)))
        sd = importFrozenTF(gd.SerializeToString())
        out = TFGraphMapper.outputVariable(sd, _last_name(gd))
        ours = np.asarray(out.eval({_placeholder_name(gd): x}).jax())
        np.testing.assert_allclose(ours, golden, atol=1e-5, rtol=1e-4)

    def test_same_padded_avgpool_excludes_padding(self):
        # TF divides border windows by the VALID cell count; an
        # include-pad average would be ~0.44-0.67x at the borders

        @tf.function
        def f(x):
            return tf.nn.avg_pool2d(x, ksize=3, strides=2, padding="SAME")

        gd = f.get_concrete_function(
            tf.TensorSpec((1, 6, 6, 2), tf.float32)).graph.as_graph_def()
        x = np.ones((1, 6, 6, 2), np.float32)
        golden = np.asarray(f(tf.constant(x)))
        assert golden.max() == golden.min() == 1.0  # exclude-pad on ones
        sd = importFrozenTF(gd.SerializeToString())
        out = TFGraphMapper.outputVariable(sd, _last_name(gd))
        ours = np.asarray(out.eval({_placeholder_name(gd): x}).jax())
        np.testing.assert_allclose(ours, golden, atol=1e-6)

    def test_depthwise_and_relu6_parity(self):
        tf.keras.utils.set_random_seed(6)
        model = tf.keras.Sequential([
            tf.keras.layers.DepthwiseConv2D(3, padding="same"),
            tf.keras.layers.ReLU(max_value=6.0),
            tf.keras.layers.AveragePooling2D(2),
        ])
        model.build((1, 8, 8, 4))
        gd, frozen = _freeze(model, tf.TensorSpec((1, 8, 8, 4), tf.float32))
        x = (np.random.RandomState(3).rand(1, 8, 8, 4) * 8).astype("float32")
        _parity(gd, frozen, x)


class TestConstDtypes:
    def test_bfloat16_and_half_consts_decode_correctly(self):
        # DT_BFLOAT16 (enum 14) is NOT fp16 — and small fp16/bf16 consts
        # are serialized as raw bit patterns in half_val, not values.
        vals = np.array([1.0, 2.5, -3.0], dtype=np.float32)

        @tf.function
        def f(x):
            b16 = tf.constant(vals, dtype=tf.bfloat16)
            h16 = tf.constant(vals, dtype=tf.float16)
            return x + tf.cast(b16, tf.float32) + tf.cast(h16, tf.float32)

        gd = f.get_concrete_function(
            tf.TensorSpec((3,), tf.float32)).graph.as_graph_def()
        sd = importFrozenTF(gd.SerializeToString())
        out = TFGraphMapper.outputVariable(sd, _last_name(gd))
        x = np.zeros(3, np.float32)
        got = np.asarray(out.eval({_placeholder_name(gd): x}).jax())
        np.testing.assert_allclose(got, 2 * vals, atol=1e-3)


class TestImportErrors:
    def test_unsupported_op_is_loud(self):
        @tf.function
        def f(x):
            return tf.linalg.svd(x)[0]

        gd = f.get_concrete_function(
            tf.TensorSpec((3, 3), tf.float32)).graph.as_graph_def()
        with pytest.raises(TFImportException, match="unsupported TF op"):
            importFrozenTF(gd.SerializeToString())

    def test_unknown_placeholder_dims_need_shapes(self):
        @tf.function
        def f(x):
            return tf.nn.relu(x)

        gd = f.get_concrete_function(
            tf.TensorSpec((None, 4), tf.float32)).graph.as_graph_def()
        with pytest.raises(TFImportException, match="inputShapes"):
            importFrozenTF(gd.SerializeToString())
        name = _placeholder_name(gd)
        sd = importFrozenTF(gd.SerializeToString(),
                            inputShapes={name: (2, 4)})
        x = np.random.RandomState(4).rand(2, 4).astype("float32")
        out = TFGraphMapper.outputVariable(sd, _last_name(gd))
        res = np.asarray(out.eval({name: x}).jax())
        np.testing.assert_allclose(res, np.maximum(x, 0))

"""Transfer learning (reference: deeplearning4j-nn
org.deeplearning4j.nn.transferlearning.TransferLearningMLNTest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    NeuralNetConfiguration, DenseLayer, OutputLayer, MultiLayerNetwork,
    Adam, Sgd, TransferLearning, FineTuneConfiguration, FrozenLayer,
    TransferLearningHelper, ConvolutionLayer, SubsamplingLayer, InputType,
)
from deeplearning4j_tpu.nn.losses import LossFunctions
from deeplearning4j_tpu.data import DataSet

LF = LossFunctions.LossFunction


def _base_net(nOut=3, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(5e-2))
            .list()
            .layer(DenseLayer(nIn=8, nOut=32, activation="relu"))
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(nOut=nOut, activation="softmax", lossFunction=LF.MCXENT))
            .setInputType(InputType.feedForward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(nOut=3, n=96, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8).astype("float32")
    y = np.argmax(X[:, :nOut], axis=1)
    return DataSet(X, np.eye(nOut, dtype="float32")[y])


def _p(net, i, k):
    return np.asarray(net._params[i][k])


class TestFrozenLayers:
    def test_frozen_params_unchanged_by_fit(self):
        net = _base_net()
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1)  # freeze layers 0 and 1
              .build())
        w0, w1 = _p(tl, 0, "W").copy(), _p(tl, 1, "W").copy()
        w2 = _p(tl, 2, "W").copy()
        ds = _data()
        for _ in range(5):
            tl.fit(ds)
        assert np.array_equal(w0, _p(tl, 0, "W"))
        assert np.array_equal(w1, _p(tl, 1, "W"))
        assert not np.array_equal(w2, _p(tl, 2, "W"))

    def test_frozen_net_still_learns_on_top(self):
        net = _base_net()
        tl = TransferLearning.Builder(net).setFeatureExtractor(1).build()
        ds = _data()
        s0 = tl.score(ds)
        for _ in range(40):
            tl.fit(ds)
        assert tl.score(ds) < s0

    def test_frozen_layer_marker(self):
        net = _base_net()
        FrozenLayer(net.layers[0])
        ds = _data()
        w0 = _p(net, 0, "W").copy()
        net.fit(ds)
        assert np.array_equal(w0, _p(net, 0, "W"))


class TestTransferBuilder:
    def test_weights_copied_for_retained_layers(self):
        net = _base_net()
        tl = TransferLearning.Builder(net).setFeatureExtractor(0).build()
        for i in range(3):
            assert np.array_equal(_p(net, i, "W"), _p(tl, i, "W"))

    def test_nout_replace_reinits_and_rewires(self):
        net = _base_net(nOut=3)
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1)
              .nOutReplace(2, 5)  # new 5-class head
              .build())
        assert _p(tl, 2, "W").shape == (16, 5)
        # retained layers keep trained weights
        assert np.array_equal(_p(net, 0, "W"), _p(tl, 0, "W"))
        out = tl.output(_data(nOut=5).getFeatures())
        assert out.shape() == (96, 5)
        # new head trains fine
        ds5 = _data(nOut=5)
        s0 = tl.score(ds5)
        for _ in range(30):
            tl.fit(ds5)
        assert tl.score(ds5) < s0

    def test_nout_replace_mid_layer_rewires_next(self):
        net = _base_net()
        tl = (TransferLearning.Builder(net)
              .nOutReplace(1, 24)
              .build())
        assert _p(tl, 1, "W").shape == (32, 24)
        assert _p(tl, 2, "W").shape == (24, 3)
        # layer 0 retained
        assert np.array_equal(_p(net, 0, "W"), _p(tl, 0, "W"))

    def test_remove_and_add_output_layer(self):
        net = _base_net(nOut=3)
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1)
              .removeOutputLayer()
              .addLayer(DenseLayer(nOut=12, activation="relu"))
              .addLayer(OutputLayer(nOut=7, activation="softmax",
                                    lossFunction=LF.MCXENT))
              .build())
        assert len(tl.layers) == 4
        assert _p(tl, 2, "W").shape == (16, 12)
        assert _p(tl, 3, "W").shape == (12, 7)
        out = tl.output(_data().getFeatures())
        assert out.shape() == (96, 7)

    def test_fine_tune_configuration_applies_to_unfrozen(self):
        net = _base_net()
        ftc = (FineTuneConfiguration.Builder()
               .updater(Sgd(1e-3)).l2(1e-4).seed(123)
               .build())
        tl = (TransferLearning.Builder(net)
              .fineTuneConfiguration(ftc)
              .setFeatureExtractor(0)
              .build())
        assert tl.conf.seed == 123
        from deeplearning4j_tpu.nn.updaters import Sgd as SgdUpd

        assert isinstance(tl.layers[1].updater, SgdUpd)
        assert tl.layers[1].l2 == 1e-4
        # frozen layer untouched by fine-tune overrides
        assert not isinstance(tl.layers[0].updater, SgdUpd)

    def test_cnn_transfer_with_preprocessors(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2))
                .list()
                .layer(ConvolutionLayer(nOut=4, kernelSize=(3, 3), stride=(1, 1)))
                .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(nOut=3, activation="softmax", lossFunction=LF.MCXENT))
                .setInputType(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        tl = (TransferLearning.Builder(net)
              .setFeatureExtractor(1)
              .nOutReplace(2, 6)
              .build())
        x = np.random.RandomState(0).rand(4, 1, 8, 8).astype("float32")
        assert tl.output(x).shape() == (4, 6)
        assert np.array_equal(_p(net, 0, "W"), _p(tl, 0, "W"))


class TestTransferLearningHelper:
    def test_featurize_matches_full_forward(self):
        net = _base_net()
        helper = TransferLearningHelper(net, frozenTill=1)
        ds = _data()
        feat = helper.featurize(ds)
        out_full = net.output(ds.getFeatures()).toNumpy()
        out_feat = helper.outputFromFeaturized(feat.getFeatures()).toNumpy()
        np.testing.assert_allclose(out_full, out_feat, rtol=2e-5, atol=2e-6)

    def test_fit_featurized_trains_top_only(self):
        net = _base_net()
        helper = TransferLearningHelper(net, frozenTill=1)
        ds = _data()
        w0 = _p(net, 0, "W").copy()
        feat = helper.featurize(ds)
        s0 = net.score(ds)
        for _ in range(30):
            helper.fitFeaturized(feat)
        assert np.array_equal(w0, _p(net, 0, "W"))  # bottom untouched
        assert net.score(ds) < s0                    # top learned

    def test_cnn_featurize_layout(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2))
                .list()
                .layer(ConvolutionLayer(nOut=4, kernelSize=(3, 3), stride=(1, 1)))
                .layer(ConvolutionLayer(nOut=6, kernelSize=(3, 3), stride=(1, 1)))
                .layer(OutputLayer(nOut=3, activation="softmax", lossFunction=LF.MCXENT))
                .setInputType(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        helper = TransferLearningHelper(net, frozenTill=0)
        x = np.random.RandomState(0).rand(4, 1, 8, 8).astype("float32")
        ds = DataSet(x, np.eye(3, dtype="float32")[[0, 1, 2, 0]])
        feat = helper.featurize(ds)
        # API layout: NCHW
        assert feat.getFeatures().shape()[1] == 4
        out_full = net.output(x).toNumpy()
        out_feat = helper.outputFromFeaturized(feat.getFeatures()).toNumpy()
        np.testing.assert_allclose(out_full, out_feat, rtol=2e-5, atol=2e-6)


class TestFrozenInferenceMode:
    def test_frozen_bn_stats_do_not_drift(self):
        """A frozen BatchNormalization must run in inference mode during
        fine-tuning: its running mean/var stay exactly as they were
        (reference: FrozenLayer forces the wrapped layer to inference)."""
        from deeplearning4j_tpu.nn import BatchNormalization

        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(5e-2)).list()
                .layer(DenseLayer(nIn=8, nOut=16, activation="relu"))
                .layer(BatchNormalization())
                .layer(OutputLayer(nOut=3, activation="softmax", lossFunction=LF.MCXENT))
                .setInputType(InputType.feedForward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = _data()
        net.fit(ds)  # move running stats off their init values
        tl = TransferLearning.Builder(net).setFeatureExtractor(1).build()
        m0 = np.asarray(tl._states[1]["mean"]).copy()
        v0 = np.asarray(tl._states[1]["var"]).copy()
        assert not np.allclose(m0, 0.0)  # stats actually moved pre-freeze
        for _ in range(5):
            tl.fit(ds)
        np.testing.assert_array_equal(m0, np.asarray(tl._states[1]["mean"]))
        np.testing.assert_array_equal(v0, np.asarray(tl._states[1]["var"]))

    def test_frozen_dropout_inactive(self):
        """Dropout in the frozen prefix must be off during fine-tune: two
        fits from identical initial state produce identical top-layer
        updates regardless of the dropout rng."""
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Sgd(0.1)).list()
                .layer(DenseLayer(nIn=8, nOut=16, activation="relu", dropOut=0.5))
                .layer(OutputLayer(nOut=3, activation="softmax", lossFunction=LF.MCXENT))
                .setInputType(InputType.feedForward(8))
                .build())
        ds = _data()
        outs = []
        for _ in range(2):
            net = MultiLayerNetwork(conf).init()
            tl = TransferLearning.Builder(net).setFeatureExtractor(0).build()
            # different iteration counters => different dropout keys if the
            # frozen layer's dropout were (wrongly) active
            tl._iteration = 7 * len(outs)
            tl.fit(ds)
            outs.append(_p(tl, 1, "W").copy())
        np.testing.assert_array_equal(outs[0], outs[1])


class TestTransferGraphBuilder:
    """TransferLearning.GraphBuilder (reference: the ComputationGraph
    variant) — the classic fine-tune flow on a DAG: freeze the trunk,
    replace the head, graft trained weights."""

    def _graph(self):
        from deeplearning4j_tpu.nn import (NeuralNetConfiguration, InputType,
                                           ComputationGraph, DenseLayer,
                                           OutputLayer, Adam)

        g = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-2))
             .activation("tanh").graphBuilder().addInputs("in")
             .addLayer("trunk1", DenseLayer(nOut=12), "in")
             .addLayer("trunk2", DenseLayer(nOut=10), "trunk1")
             .addLayer("head", OutputLayer(nOut=3, activation="softmax"),
                       "trunk2")
             .setOutputs("head")
             .setInputTypes(InputType.feedForward(6)).build())
        net = ComputationGraph(g).init()
        rng = np.random.RandomState(0)
        x = rng.randn(16, 6).astype("float32")
        y = np.eye(3, dtype="float32")[rng.randint(0, 3, 16)]
        for _ in range(3):
            net.fit(x, y)
        return net

    def test_replace_head_grafts_trunk_and_freezes(self):
        from deeplearning4j_tpu.nn import TransferLearning, OutputLayer

        orig = self._graph()
        t1 = np.asarray(orig._params["trunk1"]["W"]).copy()
        net = (TransferLearning.GraphBuilder(orig)
               .setFeatureExtractor("trunk2")
               .removeVertexKeepConnections("head")
               .addLayer("head", OutputLayer(nOut=5, activation="softmax"),
                         "trunk2")
               .build())
        # trunk weights grafted, head fresh with the new width
        np.testing.assert_array_equal(
            np.asarray(net._params["trunk1"]["W"]), t1)
        assert net._params["head"]["W"].shape[-1] == 5
        assert net.conf.nodes["trunk1"].payload.frozen
        assert net.conf.nodes["trunk2"].payload.frozen
        assert not getattr(net.conf.nodes["head"].payload, "frozen", False)
        # frozen trunk must not move under training; the new head must
        rng = np.random.RandomState(1)
        x = rng.randn(8, 6).astype("float32")
        y = np.eye(5, dtype="float32")[rng.randint(0, 5, 8)]
        h0 = np.asarray(net._params["head"]["W"]).copy()
        for _ in range(3):
            net.fit(x, y)
        np.testing.assert_array_equal(
            np.asarray(net._params["trunk1"]["W"]), t1)
        assert np.abs(np.asarray(net._params["head"]["W"]) - h0).max() > 0

    def test_nout_replace_refreshes_successor(self):
        from deeplearning4j_tpu.nn import TransferLearning

        orig = self._graph()
        net = (TransferLearning.GraphBuilder(orig)
               .nOutReplace("trunk2", 20)
               .build())
        assert net._params["trunk2"]["W"].shape[-1] == 20
        assert net._params["head"]["W"].shape[0] == 20
        # trunk1 untouched -> grafted
        np.testing.assert_array_equal(
            np.asarray(net._params["trunk1"]["W"]),
            np.asarray(orig._params["trunk1"]["W"]))

    def test_dangling_reference_rejected(self):
        from deeplearning4j_tpu.nn import TransferLearning

        orig = self._graph()
        with pytest.raises(ValueError, match="removed vertex"):
            (TransferLearning.GraphBuilder(orig)
             .removeVertexAndConnections("trunk2").build())

    def test_mln_rejected_with_clear_error(self):
        from deeplearning4j_tpu.nn import (TransferLearning,
                                           NeuralNetConfiguration, InputType,
                                           MultiLayerNetwork, DenseLayer,
                                           OutputLayer)

        conf = (NeuralNetConfiguration.Builder().list()
                .layer(DenseLayer(nOut=4))
                .layer(OutputLayer(nOut=2))
                .setInputType(InputType.feedForward(3)).build())
        with pytest.raises(TypeError, match="ComputationGraph"):
            TransferLearning.GraphBuilder(MultiLayerNetwork(conf).init())

    def test_width_change_propagates_through_vertex(self):
        """nOutReplace upstream of a parameterless vertex (the residual
        case) must re-infer the downstream layer's nIn, not crash in XLA."""
        from deeplearning4j_tpu.nn import (NeuralNetConfiguration, InputType,
                                           ComputationGraph, DenseLayer,
                                           OutputLayer, Adam,
                                           TransferLearning)
        from deeplearning4j_tpu.nn.conf.graph import ScaleVertex

        g = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-2))
             .graphBuilder().addInputs("in")
             .addLayer("trunk1", DenseLayer(nOut=12, activation="tanh"), "in")
             .addVertex("scale", ScaleVertex(0.5), "trunk1")
             .addLayer("head", OutputLayer(nOut=3, activation="softmax"),
                       "scale")
             .setOutputs("head")
             .setInputTypes(InputType.feedForward(6)).build())
        orig = ComputationGraph(g).init()
        net = (TransferLearning.GraphBuilder(orig)
               .nOutReplace("trunk1", 20).build())
        assert net._params["head"]["W"].shape[0] == 20
        rng = np.random.RandomState(0)
        x = rng.randn(8, 6).astype("float32")
        y = np.eye(3, dtype="float32")[rng.randint(0, 3, 8)]
        net.fit(x, y)  # would raise a dot_general shape error before
        assert np.isfinite(net.score())

    def test_removed_output_without_set_outputs_rejected(self):
        from deeplearning4j_tpu.nn import TransferLearning, OutputLayer

        orig = self._graph()
        with pytest.raises(ValueError, match="setOutputs"):
            (TransferLearning.GraphBuilder(orig)
             .removeVertexAndConnections("head")
             .addLayer("newhead", OutputLayer(nOut=2, activation="softmax"),
                       "trunk2")
             .build())

    def test_unknown_nout_replace_name_rejected(self):
        from deeplearning4j_tpu.nn import TransferLearning

        orig = self._graph()
        with pytest.raises(ValueError, match="unknown layer"):
            TransferLearning.GraphBuilder(orig).nOutReplace("trnk1", 20)

"""Native prefetch runtime (reference: AsyncDataSetIterator tests in
nd4j / deeplearning4j-core)."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.runtime import (
    AsyncDataSetIterator, AsyncMultiDataSetIterator, NativeRingBuffer,
    PythonRingBuffer, make_ring, native_lib, pack_arrays, unpack_arrays,
    PF_CLOSED, PF_TIMEOUT, PF_TOO_BIG,
)
from deeplearning4j_tpu.data import DataSet, DataSetIterator


class TestPacking:
    def test_roundtrip_mixed(self):
        arrs = [np.arange(12, dtype=np.float32).reshape(3, 4),
                None,
                np.array([[True, False]]),
                np.arange(6, dtype=np.int64).reshape(1, 2, 3)]
        out = unpack_arrays(pack_arrays(arrs))
        assert out[1] is None
        np.testing.assert_array_equal(out[0], arrs[0])
        np.testing.assert_array_equal(out[2], arrs[2])
        np.testing.assert_array_equal(out[3], arrs[3])
        assert out[0].dtype == np.float32 and out[3].dtype == np.int64

    def test_empty_and_scalarish(self):
        out = unpack_arrays(pack_arrays([np.zeros((0, 4), np.float32)]))
        assert out[0].shape == (0, 4)


@pytest.mark.parametrize("ring_cls", [NativeRingBuffer, PythonRingBuffer])
class TestRingBuffer:
    def _make(self, ring_cls, cap=3, slot=1024):
        if ring_cls is NativeRingBuffer and native_lib() is None:
            pytest.skip("no native toolchain")
        return ring_cls(cap, slot)

    def test_fifo_order_and_wrap(self, ring_cls):
        r = self._make(ring_cls)
        for round_ in range(3):  # force wrap-around
            for i in range(3):
                assert r.push(f"item-{round_}-{i}".encode()) == 0
            for i in range(3):
                assert r.pop() == f"item-{round_}-{i}".encode()

    def test_too_big_payload(self, ring_cls):
        r = self._make(ring_cls, slot=16)
        assert r.push(b"x" * 17) == PF_TOO_BIG

    def test_pop_timeout(self, ring_cls):
        r = self._make(ring_cls)
        assert r.pop(timeout_ms=30) == PF_TIMEOUT

    def test_backpressure_blocks_until_pop(self, ring_cls):
        r = self._make(ring_cls, cap=2)
        assert r.push(b"a") == 0
        assert r.push(b"b") == 0
        done = threading.Event()

        def blocked_push():
            r.push(b"c")
            done.set()

        t = threading.Thread(target=blocked_push, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not done.is_set()  # full -> producer blocked
        assert r.pop() == b"a"
        assert done.wait(2.0)
        assert r.pop() == b"b"
        assert r.pop() == b"c"

    def test_close_drains_then_reports_closed(self, ring_cls):
        r = self._make(ring_cls)
        r.push(b"left-over")
        r.close()
        assert r.pop() == b"left-over"
        assert r.pop(timeout_ms=100) == PF_CLOSED
        r.reopen()
        assert r.push(b"fresh") == 0
        assert r.pop() == b"fresh"


def _iter(n=50, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6).astype("float32")
    Y = np.eye(3, dtype="float32")[rng.randint(0, 3, n)]
    return DataSetIterator(X, Y, batch)


@pytest.mark.parametrize("force_python", [False, True])
class TestAsyncDataSetIterator:
    def test_matches_sync_iterator(self, force_python):
        sync, base = _iter(), _iter()
        async_it = AsyncDataSetIterator(base, queueSize=3, forcePython=force_python)
        n = 0
        while sync.hasNext():
            assert async_it.hasNext()
            a, b = sync.next(), async_it.next()
            np.testing.assert_array_equal(a.getFeatures().toNumpy(),
                                          b.getFeatures().toNumpy())
            np.testing.assert_array_equal(a.getLabels().toNumpy(),
                                          b.getLabels().toNumpy())
            n += 1
        assert not async_it.hasNext()
        assert n == 7  # 50/8 -> 6 full + 1 partial batch

    def test_reset_for_multiple_epochs(self, force_python):
        async_it = AsyncDataSetIterator(_iter(), queueSize=2, forcePython=force_python)
        for _ in range(3):
            count = sum(1 for _ in iter(async_it.next, None) if False) if False else 0
            async_it.reset()
            while async_it.hasNext():
                async_it.next()
                count += 1
            assert count == 7

    def test_masks_survive(self, force_python):
        n, batch = 12, 4
        rng = np.random.RandomState(1)
        base = _iter(n, batch)
        # splice masks into the produced batches via a wrapper
        fm = (rng.rand(n, 5) > 0.3).astype("float32")

        class Masked:
            def __init__(self):
                self.it = _iter(n, batch)
                self.i = 0

            def reset(self):
                self.it.reset()
                self.i = 0

            def hasNext(self):
                return self.it.hasNext()

            def next(self):
                ds = self.it.next()
                sl = slice(self.i * batch, (self.i + 1) * batch)
                self.i += 1
                return DataSet(ds.getFeatures(), ds.getLabels(), fm[sl], None)

        ait = AsyncDataSetIterator(Masked(), forcePython=force_python)
        got = []
        while ait.hasNext():
            got.append(ait.next().getFeaturesMaskArray().toNumpy())
        np.testing.assert_array_equal(np.concatenate(got), fm)

    def test_producer_exception_propagates(self, force_python):
        class Exploding:
            def __init__(self):
                self.n = 0

            def reset(self):
                self.n = 0

            def hasNext(self):
                return True

            def next(self):
                self.n += 1
                if self.n > 2:
                    raise RuntimeError("ETL failed")
                return DataSet(np.zeros((4, 2), np.float32),
                               np.zeros((4, 2), np.float32))

        ait = AsyncDataSetIterator(Exploding(), forcePython=force_python)
        with pytest.raises(RuntimeError, match="ETL failed"):
            while ait.hasNext():
                ait.next()

    def test_fit_through_async(self, force_python):
        from deeplearning4j_tpu.nn import (
            NeuralNetConfiguration, DenseLayer, OutputLayer, MultiLayerNetwork, Adam,
        )
        from deeplearning4j_tpu.nn.losses import LossFunctions

        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(1e-2)).list()
                .layer(DenseLayer(nIn=6, nOut=16, activation="tanh"))
                .layer(OutputLayer(nOut=3, activation="softmax",
                                   lossFunction=LossFunctions.LossFunction.MCXENT))
                .build())
        net = MultiLayerNetwork(conf).init()
        ait = AsyncDataSetIterator(_iter(), forcePython=force_python)
        s0 = None
        for ep in range(5):
            net.fit(ait)
            s0 = s0 or net.score()
        assert net.score() < s0


class TestAsyncMulti:
    def test_multidataset_roundtrip(self):
        from deeplearning4j_tpu.data.multidataset import MultiDataSet

        class MDSIter:
            def __init__(self):
                self.i = 0

            def reset(self):
                self.i = 0

            def hasNext(self):
                return self.i < 4

            def next(self):
                self.i += 1
                rng = np.random.RandomState(self.i)
                return MultiDataSet(
                    [rng.rand(4, 3).astype("f4"), rng.rand(4, 2).astype("f4")],
                    [rng.rand(4, 1).astype("f4")])

        ait = AsyncMultiDataSetIterator(MDSIter())
        seen = 0
        while ait.hasNext():
            mds = ait.next()
            rng = np.random.RandomState(seen + 1)
            np.testing.assert_array_equal(mds.getFeatures()[0].toNumpy(),
                                          rng.rand(4, 3).astype("f4"))
            seen += 1
        assert seen == 4


def test_native_lib_builds():
    lib = native_lib()
    if lib is None:
        pytest.skip("no native toolchain available")
    r = make_ring(2, 128)
    assert isinstance(r, NativeRingBuffer)


class TestAsyncMultiMasks:
    def test_masks_preserved(self):
        from deeplearning4j_tpu.data.multidataset import MultiDataSet

        class MaskedMDS:
            def __init__(self):
                self.i = 0

            def reset(self):
                self.i = 0

            def hasNext(self):
                return self.i < 3

            def next(self):
                self.i += 1
                rng = np.random.RandomState(self.i)
                return MultiDataSet(
                    [rng.rand(4, 2, 5).astype("f4")],
                    [rng.rand(4, 1, 5).astype("f4")],
                    [(rng.rand(4, 5) > 0.5).astype("f4")],
                    [(rng.rand(4, 5) > 0.5).astype("f4")])

        ait = AsyncMultiDataSetIterator(MaskedMDS())
        n = 0
        while ait.hasNext():
            mds = ait.next()
            n += 1
            rng = np.random.RandomState(n)
            rng.rand(4, 2, 5); rng.rand(4, 1, 5)
            np.testing.assert_array_equal(
                mds.getFeaturesMaskArrays()[0].toNumpy(),
                (rng.rand(4, 5) > 0.5).astype("f4"))
            np.testing.assert_array_equal(
                mds.getLabelsMaskArrays()[0].toNumpy(),
                (rng.rand(4, 5) > 0.5).astype("f4"))
        assert n == 3

"""Op-semantics tests for the array layer, numpy as oracle.

Mirrors the reference's nd4j op tests (nd4j-backend-impls tests /
Nd4jTestsC): creation, arithmetic, reductions, indexing, broadcasting,
gemm.
"""

import numpy as np
import pytest

from deeplearning4j_tpu import Nd4j, INDArray, DataType
from deeplearning4j_tpu.ndarray.indexing import NDArrayIndex


class TestCreation:
    def test_zeros_ones(self):
        z = Nd4j.zeros(2, 3)
        assert z.shape() == (2, 3)
        assert z.sumNumber() == 0.0
        o = Nd4j.ones(4)
        assert o.sumNumber() == 4.0
        assert o.dataType() == DataType.FLOAT

    def test_create_from_data(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.shape() == (2, 2)
        assert a.getDouble(1, 0) == 3.0

    def test_create_shape_varargs(self):
        a = Nd4j.create(3, 4)
        assert a.shape() == (3, 4)
        assert a.sumNumber() == 0.0

    def test_linspace_arange_eye(self):
        l = Nd4j.linspace(0, 1, 5)
        np.testing.assert_allclose(l.toNumpy(), np.linspace(0, 1, 5), rtol=1e-6)
        a = Nd4j.arange(5)
        np.testing.assert_allclose(a.toNumpy(), np.arange(5))
        e = Nd4j.eye(3)
        assert e.getDouble(0, 0) == 1.0 and e.getDouble(0, 1) == 0.0

    def test_value_array_scalar(self):
        v = Nd4j.valueArrayOf((2, 2), 7.0)
        assert v.meanNumber() == 7.0
        s = Nd4j.scalar(3.0)
        assert float(s) == 3.0

    def test_rand_reproducible(self):
        Nd4j.getRandom().setSeed(42)
        a = Nd4j.rand(3, 3)
        Nd4j.getRandom().setSeed(42)
        b = Nd4j.rand(3, 3)
        assert a.equals(b)
        assert 0.0 <= a.minNumber() and a.maxNumber() < 1.0


class TestArithmetic:
    def test_elementwise(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        b = Nd4j.create([[10.0, 20.0], [30.0, 40.0]])
        np.testing.assert_allclose((a + b).toNumpy(), [[11, 22], [33, 44]])
        np.testing.assert_allclose(a.mul(b).toNumpy(), [[10, 40], [90, 160]])
        np.testing.assert_allclose(b.div(a).toNumpy(), [[10, 10], [10, 10]])
        np.testing.assert_allclose(a.rsub(1.0).toNumpy(), [[0, -1], [-2, -3]])
        np.testing.assert_allclose(a.rdiv(12.0).toNumpy(), [[12, 6], [4, 3]])

    def test_inplace_rebinds(self):
        a = Nd4j.ones(2, 2)
        r = a.addi(1.0)
        assert r is a
        assert a.meanNumber() == 2.0
        a.muli(3.0).subi(1.0)
        assert a.meanNumber() == 5.0

    def test_scalar_broadcast(self):
        a = Nd4j.create([1.0, 2.0, 3.0])
        np.testing.assert_allclose((a * 2.0 + 1.0).toNumpy(), [3, 5, 7])

    def test_row_col_vector_ops(self):
        m = Nd4j.ones(3, 4)
        row = Nd4j.create([0.0, 1.0, 2.0, 3.0])
        col = Nd4j.create([10.0, 20.0, 30.0])
        np.testing.assert_allclose(
            m.addRowVector(row).toNumpy(), 1.0 + np.arange(4)[None, :] * np.ones((3, 4))
        )
        np.testing.assert_allclose(
            m.mulColumnVector(col).toNumpy(), np.array([[10.0] * 4, [20.0] * 4, [30.0] * 4])
        )

    def test_comparison(self):
        a = Nd4j.create([1.0, 5.0, 3.0])
        assert a.gt(2.0).castTo(DataType.INT32).sumNumber() == 2
        assert a.eq(5.0).castTo(DataType.INT32).sumNumber() == 1


class TestReductions:
    def test_full_reductions(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.sumNumber() == 10.0
        assert a.meanNumber() == 2.5
        assert a.maxNumber() == 4.0
        assert a.minNumber() == 1.0

    def test_dimension_reductions(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        np.testing.assert_allclose(a.sum(0).toNumpy(), [4, 6])
        np.testing.assert_allclose(a.sum(1).toNumpy(), [3, 7])
        np.testing.assert_allclose(a.mean(0).toNumpy(), [2, 3])
        np.testing.assert_allclose(a.max(1).toNumpy(), [2, 4])
        assert a.sum(0, keepDims=True).shape() == (1, 2)

    def test_std_bias_corrected(self):
        x = np.array([1.0, 2.0, 3.0, 4.0])
        a = Nd4j.create(x)
        np.testing.assert_allclose(float(a.std()), x.std(ddof=1), rtol=1e-6)
        np.testing.assert_allclose(float(a.std(biasCorrected=False)), x.std(), rtol=1e-6)

    def test_norms_argmax(self):
        a = Nd4j.create([[3.0, -4.0], [0.0, 5.0]])
        np.testing.assert_allclose(float(a.norm1()), 12.0)
        np.testing.assert_allclose(float(a.norm2()), np.sqrt(50.0), rtol=1e-6)
        np.testing.assert_allclose(a.argMax(1).toNumpy(), [0, 1])
        np.testing.assert_allclose(a.argMin(1).toNumpy(), [1, 0])

    def test_cumsum(self):
        a = Nd4j.create([1.0, 2.0, 3.0])
        np.testing.assert_allclose(a.cumsum(0).toNumpy(), [1, 3, 6])


class TestLinalg:
    def test_mmul(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        b = Nd4j.create([[5.0, 6.0], [7.0, 8.0]])
        np.testing.assert_allclose(a.mmul(b).toNumpy(), a.toNumpy() @ b.toNumpy())
        np.testing.assert_allclose((a @ b).toNumpy(), a.toNumpy() @ b.toNumpy())

    def test_gemm_transpose(self):
        a = Nd4j.rand(3, 2)
        b = Nd4j.rand(3, 4)
        out = Nd4j.gemm(a, b, transposeA=True)
        np.testing.assert_allclose(out.toNumpy(), a.toNumpy().T @ b.toNumpy(), rtol=1e-5)

    def test_tensor_mmul(self):
        a = Nd4j.rand(2, 3, 4)
        b = Nd4j.rand(4, 5)
        out = a.tensorMmul(b, axes=([2], [0]))
        np.testing.assert_allclose(
            out.toNumpy(), np.tensordot(a.toNumpy(), b.toNumpy(), axes=([2], [0])), rtol=1e-5
        )

    def test_transpose_permute(self):
        a = Nd4j.rand(2, 3, 4)
        assert a.permute(2, 0, 1).shape() == (4, 2, 3)
        m = Nd4j.rand(2, 5)
        assert m.transpose().shape() == (5, 2)


class TestShapeOps:
    def test_reshape_ravel(self):
        a = Nd4j.arange(12).reshape(3, 4)
        assert a.shape() == (3, 4)
        assert a.ravel().shape() == (12,)
        assert a.reshape(2, 6).shape() == (2, 6)

    def test_concat_stack(self):
        a, b = Nd4j.ones(2, 3), Nd4j.zeros(2, 3)
        assert Nd4j.concat(0, a, b).shape() == (4, 3)
        assert Nd4j.concat(1, a, b).shape() == (2, 6)
        assert Nd4j.vstack(a, b).shape() == (4, 3)
        assert Nd4j.hstack(a, b).shape() == (2, 6)
        assert Nd4j.stack(0, a, b).shape() == (2, 2, 3)

    def test_tile_repeat(self):
        a = Nd4j.create([[1.0, 2.0]])
        assert Nd4j.tile(a, 3, 1).shape() == (3, 2)
        assert a.repeat(1, 2).shape() == (1, 4)

    def test_broadcast(self):
        a = Nd4j.create([1.0, 2.0, 3.0])
        assert a.broadcast(4, 3).shape() == (4, 3)


class TestIndexing:
    def test_basic_get(self):
        a = Nd4j.arange(12).reshape(3, 4)
        row = a.getRow(1)
        np.testing.assert_allclose(row.toNumpy(), [4, 5, 6, 7])
        col = a.getColumn(2)
        np.testing.assert_allclose(col.toNumpy(), [2, 6, 10])

    def test_ndarrayindex_get(self):
        a = Nd4j.arange(24).reshape(4, 6)
        sub = a.get(NDArrayIndex.interval(1, 3), NDArrayIndex.all())
        assert sub.shape() == (2, 6)
        np.testing.assert_allclose(sub.toNumpy(), a.toNumpy()[1:3])
        p = a.get(NDArrayIndex.point(2), NDArrayIndex.interval(0, 4))
        np.testing.assert_allclose(p.toNumpy(), a.toNumpy()[2, 0:4])

    def test_put(self):
        a = Nd4j.zeros(3, 3)
        a.put([NDArrayIndex.point(1), NDArrayIndex.all()], Nd4j.ones(3))
        np.testing.assert_allclose(a.sum(1).toNumpy(), [0, 3, 0])

    def test_putscalar_getdouble(self):
        a = Nd4j.zeros(2, 2)
        a.putScalar(0, 1, 5.0)
        assert a.getDouble(0, 1) == 5.0
        a.putScalar(3, 7.0)  # linear index
        assert a.getDouble(1, 1) == 7.0

    def test_python_getitem(self):
        a = Nd4j.arange(12).reshape(3, 4)
        np.testing.assert_allclose(a[1].toNumpy(), [4, 5, 6, 7])
        np.testing.assert_allclose(a[:, 1].toNumpy(), [1, 5, 9])
        a[0] = 0.0
        assert float(a[0].sum()) == 0.0

    def test_where_replace(self):
        a = Nd4j.create([1.0, -2.0, 3.0, -4.0])
        r = Nd4j.where(a.lt(0.0), Nd4j.zerosLike(a), a)
        np.testing.assert_allclose(r.toNumpy(), [1, 0, 3, 0])

    def test_getrows_slice(self):
        a = Nd4j.arange(12).reshape(3, 4)
        np.testing.assert_allclose(a.getRows(0, 2).toNumpy(), a.toNumpy()[[0, 2]])
        np.testing.assert_allclose(a.slice(1).toNumpy(), a.toNumpy()[1])


class TestDtype:
    def test_cast(self):
        a = Nd4j.create([1.9, 2.1])
        i = a.castTo(DataType.INT32)
        assert i.dataType() == DataType.INT32
        np.testing.assert_allclose(i.toNumpy(), [1, 2])

    def test_bfloat16(self):
        a = Nd4j.ones(2, 2).castTo(DataType.BFLOAT16)
        assert a.dataType() == DataType.BFLOAT16
        assert a.sumNumber() == 4.0

    def test_dup_is_independent(self):
        a = Nd4j.ones(2)
        b = a.dup()
        a.addi(1.0)
        assert b.meanNumber() == 1.0 and a.meanNumber() == 2.0


class TestSort:
    def test_sort(self):
        a = Nd4j.create([3.0, 1.0, 2.0])
        np.testing.assert_allclose(Nd4j.sort(a).toNumpy(), [1, 2, 3])
        np.testing.assert_allclose(Nd4j.sort(a, ascending=False).toNumpy(), [3, 2, 1])


class TestTransforms:
    """Reference: org.nd4j.linalg.ops.transforms.Transforms op tests."""

    def test_elementwise_vs_numpy(self):
        from deeplearning4j_tpu.ndarray import Transforms as T
        x = np.abs(np.random.RandomState(0).randn(3, 4)) + 0.1
        a = Nd4j.create(x)
        for name, oracle in [("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
                             ("abs", np.abs), ("tanh", np.tanh), ("sin", np.sin),
                             ("floor", np.floor), ("sign", np.sign)]:
            np.testing.assert_allclose(getattr(T, name)(a).toNumpy(), oracle(x),
                                       rtol=1e-6, err_msg=name)

    def test_activations(self):
        from deeplearning4j_tpu.ndarray import Transforms as T
        x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0])
        a = Nd4j.create(x)
        np.testing.assert_allclose(T.sigmoid(a).toNumpy(), 1 / (1 + np.exp(-x)), rtol=1e-6)
        np.testing.assert_allclose(T.relu(a).toNumpy(), np.maximum(x, 0))
        np.testing.assert_allclose(T.leakyRelu(a, 0.1).toNumpy(),
                                   np.where(x > 0, x, 0.1 * x), rtol=1e-6)
        np.testing.assert_allclose(T.hardTanh(a).toNumpy(), np.clip(x, -1, 1))

    def test_softmax_rows_sum_to_one(self):
        from deeplearning4j_tpu.ndarray import Transforms as T
        a = Nd4j.randn(4, 7)
        s = T.softmax(a)
        np.testing.assert_allclose(s.toNumpy().sum(-1), np.ones(4), rtol=1e-6)
        np.testing.assert_allclose(np.exp(T.logSoftmax(a).toNumpy()), s.toNumpy(), rtol=1e-5)

    def test_distances(self):
        from deeplearning4j_tpu.ndarray import Transforms as T
        x = Nd4j.create([1.0, 0.0]); y = Nd4j.create([0.0, 1.0])
        assert T.euclideanDistance(x, y) == pytest.approx(np.sqrt(2), rel=1e-6)
        assert T.manhattanDistance(x, y) == pytest.approx(2.0)
        assert T.cosineSim(x, y) == pytest.approx(0.0, abs=1e-6)
        assert T.cosineSim(x, x) == pytest.approx(1.0, rel=1e-6)

    def test_unitvec_ismax(self):
        from deeplearning4j_tpu.ndarray import Transforms as T
        v = T.unitVec(Nd4j.create([3.0, 4.0]))
        np.testing.assert_allclose(v.toNumpy(), [0.6, 0.8], rtol=1e-6)
        m = T.isMax(Nd4j.create([[1.0, 3.0], [5.0, 2.0]]), dimension=1)
        np.testing.assert_allclose(m.toNumpy(), [[0, 1], [1, 0]])

    def test_pow_clip(self):
        from deeplearning4j_tpu.ndarray import Transforms as T
        a = Nd4j.create([1.0, 2.0, 3.0])
        np.testing.assert_allclose(T.pow(a, 2).toNumpy(), [1, 4, 9])
        np.testing.assert_allclose(T.clip(a, 1.5, 2.5).toNumpy(), [1.5, 2.0, 2.5])


class TestFactoryLongTail:
    """Nd4j statics long tail (reference: org.nd4j.linalg.factory.Nd4j):
    kron / argMax / sortWithIndices / average / accumulate."""

    def test_kron(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        b = Nd4j.eye(2)
        np.testing.assert_allclose(
            Nd4j.kron(a, b).toNumpy(),
            np.kron(a.toNumpy(), b.toNumpy()))

    def test_arg_max(self):
        a = Nd4j.create([[1.0, 9.0, 2.0], [8.0, 0.0, 3.0]])
        assert int(Nd4j.argMax(a).toNumpy()) == 1  # flat
        np.testing.assert_array_equal(Nd4j.argMax(a, 1).toNumpy(), [1, 0])
        np.testing.assert_array_equal(Nd4j.argMax(a, 0).toNumpy(), [1, 0, 1])

    def test_sort_with_indices(self):
        a = Nd4j.create([[3.0, 1.0, 2.0]])
        idx, srt = Nd4j.sortWithIndices(a, 1, True)
        np.testing.assert_array_equal(idx.toNumpy(), [[1, 2, 0]])
        np.testing.assert_allclose(srt.toNumpy(), [[1, 2, 3]])
        idx_d, srt_d = Nd4j.sortWithIndices(a, 1, False)
        np.testing.assert_allclose(srt_d.toNumpy(), [[3, 2, 1]])

    def test_average_and_accumulate(self):
        arrs = [Nd4j.valueArrayOf((2, 2), v) for v in (1.0, 2.0, 6.0)]
        np.testing.assert_allclose(Nd4j.average(*arrs).toNumpy(), 3.0)
        np.testing.assert_allclose(Nd4j.average(arrs).toNumpy(), 3.0)
        np.testing.assert_allclose(Nd4j.accumulate(*arrs).toNumpy(), 9.0)
        with pytest.raises(ValueError):
            Nd4j.average()


class TestAllPairDistances:
    """Transforms.all*Distances (reference: the gemm-lowered all-pairs
    kernels in org.nd4j.linalg.ops.transforms.Transforms), scipy oracle."""

    def test_all_pairs_vs_scipy(self):
        from scipy.spatial.distance import cdist
        from deeplearning4j_tpu.ndarray.transforms import Transforms

        rs = np.random.RandomState(0)
        a = rs.randn(7, 5).astype("float32")
        b = rs.randn(4, 5).astype("float32")
        np.testing.assert_allclose(
            Transforms.allEuclideanDistances(a, b, 1).toNumpy(),
            cdist(a, b), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            Transforms.allManhattanDistances(a, b, 1).toNumpy(),
            cdist(a, b, "cityblock"), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            Transforms.allCosineSimilarities(a, b, 1).toNumpy(),
            1.0 - cdist(a, b, "cosine"), rtol=1e-4, atol=1e-4)

    def test_bad_shapes_rejected(self):
        from deeplearning4j_tpu.ndarray.transforms import Transforms

        with pytest.raises(ValueError, match="2-D"):
            Transforms.allEuclideanDistances(np.zeros(3), np.zeros(3))
        with pytest.raises(ValueError, match="dimensions"):
            Transforms.allCosineSimilarities(np.zeros((2, 2)),
                                             np.zeros((2, 2)), 0)


class TestFileIO:
    """Nd4j.writeNpy/readNpy/writeTxt/readTxt/saveBinary (reference:
    org.nd4j.linalg.factory.Nd4j file IO)."""

    def test_npy_roundtrip(self, tmp_path):
        a = Nd4j.randn(3, 4, seed=0)
        p = tmp_path / "a.npy"
        Nd4j.writeNpy(a, p)
        back = Nd4j.readNpy(p)
        np.testing.assert_array_equal(back.toNumpy(), a.toNumpy())
        # numpy itself can read it (ecosystem interop)
        np.testing.assert_array_equal(np.load(p), a.toNumpy())

    def test_binary_roundtrip_extensionless_path(self, tmp_path):
        # np.save(str) appends ".npy" to extension-less paths; the
        # file-object write path must round-trip the EXACT path given
        a = Nd4j.arange(10).reshape(2, 5)
        p = tmp_path / "model.bin"
        Nd4j.saveBinary(a, p)
        assert p.exists() and not (tmp_path / "model.bin.npy").exists()
        np.testing.assert_array_equal(Nd4j.readBinary(p).toNumpy(),
                                      a.toNumpy())

    def test_txt_bool_and_int64_roundtrip(self, tmp_path):
        b = Nd4j.create(np.asarray([[True, False], [False, True]]))
        p = tmp_path / "b.txt"
        Nd4j.writeTxt(b, p)
        back = Nd4j.readTxt(p)
        np.testing.assert_array_equal(back.toNumpy(), b.toNumpy())
        assert back.toNumpy().dtype == np.bool_
        big = Nd4j.create(np.asarray([2**60 + 1, -7]), dtype="int64")
        q = tmp_path / "i.txt"
        Nd4j.writeTxt(big, q)
        np.testing.assert_array_equal(Nd4j.readTxt(q).toNumpy(),
                                      [2**60 + 1, -7])  # no float detour

    def test_txt_roundtrip_exact(self, tmp_path):
        a = Nd4j.create([[1.5, -2.25], [3.0, 1e-7]])
        p = tmp_path / "a.txt"
        Nd4j.writeTxt(a, p)
        back = Nd4j.readTxt(p)
        # repr() round-trips float32 exactly
        np.testing.assert_array_equal(back.toNumpy(), a.toNumpy())
        assert back.toNumpy().dtype == np.float32
        with pytest.raises(ValueError, match="header"):
            q = tmp_path / "bad.txt"
            q.write_text("1 2 3\n")
            Nd4j.readTxt(q)


class TestNameScopes:
    """sd.withNameScope (reference: SameDiff.withNameScope): created
    variables get scope-prefixed names; scopes nest."""

    def test_scoped_names_and_nesting(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd = SameDiff.create()
        x = sd.placeHolder("x", np.float32, 2, 3)
        with sd.withNameScope("enc"):
            w = sd.var("w", 3, 4)
            h = sd.nn.relu(sd.math.mul(x, x), name="act")
            with sd.withNameScope("deep"):
                c = sd.constant(np.float32(2.0), "two")
        assert w.name == "enc/w"
        assert h.name == "enc/act"
        assert c.name == "enc/deep/two"
        assert x.name == "x"  # outside any scope
        # lookups use the full name; graph still executes
        out = sd.getVariable("enc/act").eval({"x": np.ones((2, 3),
                                                          np.float32)})
        np.testing.assert_allclose(np.asarray(out.jax()), 1.0)

    def test_same_leaf_name_in_two_scopes(self):
        from deeplearning4j_tpu.autodiff import SameDiff

        sd = SameDiff.create()
        with sd.withNameScope("a"):
            va = sd.var("w", 2, 2)
        with sd.withNameScope("b"):
            vb = sd.var("w", 2, 2)
        assert va.name == "a/w" and vb.name == "b/w"
        # grads flow to scoped variables (full SameDiff graphs)
        y = sd.math.add(sd.math.sum(va), sd.math.sum(vb))
        y.markAsLoss()
        g = sd.calculateGradients({}, "a/w")
        np.testing.assert_allclose(np.asarray(g["a/w"].jax()), 1.0)


def test_txt_complex_roundtrip(tmp_path):
    c = Nd4j.create(np.asarray([1 + 2j, -0.5j], np.complex64))
    p = tmp_path / "c.txt"
    Nd4j.writeTxt(c, p)
    back = Nd4j.readTxt(p)
    np.testing.assert_allclose(back.toNumpy(), [1 + 2j, -0.5j])
    assert back.toNumpy().dtype == np.complex64


class TestIm2ColCol2Im:
    """Convolution.im2col/col2im (reference:
    org.nd4j.linalg.convolution.Convolution) vs a naive loop oracle."""

    def _oracle_im2col(self, x, kh, kw, sy, sx, ph, pw):
        b, c, h, w = x.shape
        oh = (h + 2 * ph - kh) // sy + 1
        ow = (w + 2 * pw - kw) // sx + 1
        xp = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        out = np.zeros((b, c, kh, kw, oh, ow), x.dtype)
        for i in range(kh):
            for j in range(kw):
                for oi in range(oh):
                    for oj in range(ow):
                        out[:, :, i, j, oi, oj] = \
                            xp[:, :, oi * sy + i, oj * sx + j]
        return out

    def test_im2col_matches_oracle(self):
        from deeplearning4j_tpu.ndarray.convolution import im2col
        rng = np.random.RandomState(0)
        for (kh, kw, sy, sx, ph, pw) in [(3, 3, 1, 1, 0, 0),
                                         (2, 3, 2, 1, 1, 0),
                                         (3, 2, 2, 2, 1, 1)]:
            x = rng.randn(2, 3, 7, 6).astype("float32")
            got = np.asarray(im2col(x, kh, kw, sy, sx, ph, pw))
            want = self._oracle_im2col(x, kh, kw, sy, sx, ph, pw)
            np.testing.assert_allclose(got, want, rtol=1e-6,
                                       err_msg=str((kh, kw, sy, sx)))

    def test_col2im_sums_overlaps(self):
        from deeplearning4j_tpu.ndarray.convolution import col2im, im2col
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 5, 5).astype("float32")
        col = np.asarray(im2col(x, 3, 3, 1, 1, 0, 0))
        back = np.asarray(col2im(col, 1, 1, 0, 0, h=5, w=5))
        # each pixel returns multiplied by the number of windows
        # containing it; the center of a 5x5/3x3/s1 is in 9 windows
        counts = np.asarray(col2im(np.ones_like(col), 1, 1, 0, 0,
                                   h=5, w=5))
        np.testing.assert_allclose(back, x * counts, rtol=1e-6)
        assert counts[0, 0, 2, 2] == 9 and counts[0, 0, 0, 0] == 1

    def test_adjointness(self):
        # <im2col(x), y> == <x, col2im(y)> — the property custom
        # backward passes rely on
        from deeplearning4j_tpu.ndarray.convolution import col2im, im2col
        rng = np.random.RandomState(2)
        x = rng.randn(2, 2, 6, 5).astype("float64")
        y = rng.randn(2, 2, 3, 2, 3, 4).astype("float64")
        lhs = float((np.asarray(im2col(x, 3, 2, 2, 1, 1, 0)) * y).sum())
        rhs = float((x * np.asarray(col2im(y, 2, 1, 1, 0, h=6, w=5))).sum())
        assert abs(lhs - rhs) < 1e-9

    def test_same_mode_geometry(self):
        from deeplearning4j_tpu.ndarray.convolution import im2col
        x = np.zeros((1, 1, 7, 7), "float32")
        col = np.asarray(im2col(x, 3, 3, 2, 2, isSameMode=True))
        assert col.shape == (1, 1, 3, 3, 4, 4)  # ceil(7/2) = 4

    def test_validation(self):
        from deeplearning4j_tpu.ndarray.convolution import col2im, im2col
        with pytest.raises(ValueError, match="NCHW"):
            im2col(np.zeros((3, 4, 5), "float32"), 2, 2)
        with pytest.raises(ValueError, match="does not fit"):
            im2col(np.zeros((1, 1, 3, 3), "float32"), 5, 5)
        col = np.zeros((1, 1, 2, 2, 2, 2), "float32")
        with pytest.raises(ValueError, match="needs the target"):
            col2im(col)
        with pytest.raises(ValueError, match="do not match"):
            col2im(col, h=9, w=9)

    def test_indarray_in_indarray_out(self):
        from deeplearning4j_tpu.ndarray import INDArray, Nd4j
        from deeplearning4j_tpu.ndarray.convolution import col2im, im2col
        x = Nd4j.rand(1, 2, 4, 4)
        col = im2col(x, 2, 2, 2, 2)
        assert isinstance(col, INDArray)
        assert col.shape() == (1, 2, 2, 2, 2, 2)
        back = col2im(col, 2, 2, 0, 0, h=4, w=4)
        assert isinstance(back, INDArray)
        # non-overlapping 2x2/s2 tiling: col2im inverts exactly
        np.testing.assert_allclose(back.toNumpy(), x.toNumpy(), rtol=1e-6)

"""Continuous-batching model server gates (deeplearning4j_tpu/serving/,
docs/SERVING.md).

What must hold:

- parity: micro-batched (coalesced, padded, bucket-dispatched) responses
  are BITWISE equal to per-request ``output()`` — across bucket
  boundaries, for ragged coalesced batches and mixed request sizes;
- compile discipline: at most one compile per (model, bucket) over a
  whole serving run — requests, swaps and soaks included (CompileWatch
  + RetraceSentinel proofs with a hot cache);
- backpressure: a full queue answers QueueFullError/HTTP 429
  immediately, never a hang; per-request deadlines are honored
  end-to-end (queued OR mid-dispatch) as DeadlineExceededError/504;
- rolling swap: the new version warms while the old serves, requests
  never fail and never see a cold compile;
- throughput: under the open-loop load generator, dynamic
  micro-batching sustains >= 3x the serial one-dispatch-per-request
  requests/sec at bounded p99 (the dispatch-bound sharded-mesh regime
  the tier exists for — bench_serving's `amortization` twin).

Latency-path scheduler tests run DETERMINISTICALLY: ManualClock +
thread-less MicroBatcher driven via poll() — no sleeps. These tests
stay on the session memory-only AOT cache (tests/conftest.py): the
fresh caches installed here are memory-only by construction.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.runtime import aot
from deeplearning4j_tpu.serving import (
    DeadlineExceededError, InferenceServer, ManualClock, MicroBatcher,
    ModelHost, QueueFullError, ServingClosedError,
)
from deeplearning4j_tpu.serving import loadgen


# ----------------------------------------------------------------------
# subjects
# ----------------------------------------------------------------------

def _mln(seed=7, nout=16):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Nesterovs(0.1, 0.9)).list()
            .layer(DenseLayer(nOut=nout, activation="relu"))
            .layer(OutputLayer(nOut=4, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(8)).build())
    return MultiLayerNetwork(conf).init()


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, 8).astype(np.float32)


def _mesh(n):
    from deeplearning4j_tpu.parallel.mesh import build_mesh

    return build_mesh({"data": n})


@pytest.fixture
def fresh_cache():
    """A fresh MEMORY-ONLY cache installed as THE session cache, so
    miss counting is hermetic per test (the suite-wide cache from
    conftest is restored after; serving tests never get a disk tier —
    see the conftest note on deserialization fragility)."""
    prev = aot._SESSION
    cache = aot._SESSION = aot.ExecutableCache(None)
    yield cache
    aot._SESSION = prev


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def _post(url, obj, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def _wait_ready(port, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            _get(f"http://127.0.0.1:{port}/healthz", timeout=5)
            return
        except urllib.error.HTTPError:
            time.sleep(0.02)
    pytest.fail("server never became ready")


# ----------------------------------------------------------------------
# micro-batcher scheduler: deterministic (ManualClock, no thread)
# ----------------------------------------------------------------------

class TestMicroBatcherDeterministic:
    def _batcher(self, dispatch=None, **kw):
        kw.setdefault("max_rows", 8)
        kw.setdefault("queue_limit", 4)
        kw.setdefault("max_wait", 0.005)
        clk = kw.pop("clock", None) or ManualClock()
        mb = MicroBatcher(dispatch or (lambda f: f * 2.0),
                          clock=clk, start_thread=False, **kw)
        return mb, clk

    def test_coalesce_slice_and_occupancy(self):
        shapes = []
        mb, clk = self._batcher(lambda f: (shapes.append(f.shape), f * 2.0)[1])
        r1 = mb.submit(_rows(3, 1), wait=False)
        r2 = mb.submit(_rows(2, 2), wait=False)
        clk.advance(0.006)
        assert mb.poll() is None          # everything due dispatched
        assert r1.done and r2.done
        np.testing.assert_array_equal(r1.result, _rows(3, 1) * 2.0)
        np.testing.assert_array_equal(r2.result, _rows(2, 2) * 2.0)
        assert shapes == [(5, 8)]          # ONE coalesced dispatch
        assert mb.stats["dispatches"] == 1 and mb.stats["coalesced"] == 2
        assert mb.occupancy == [(5, 5)]    # identity bucket_for default

    def test_max_wait_holds_partial_batches(self):
        mb, clk = self._batcher()
        r = mb.submit(_rows(1), wait=False)
        w = mb.poll()
        assert w == pytest.approx(0.005)   # full max_wait remains
        clk.advance(0.003)
        assert mb.poll() == pytest.approx(0.002) and not r.done
        clk.advance(0.0021)
        mb.poll()
        assert r.done                      # aged out -> dispatched

    def test_full_bucket_dispatches_without_waiting(self):
        mb, clk = self._batcher()
        r = mb.submit(_rows(8), wait=False)   # == max_rows
        assert mb.poll() is None and r.done   # no clock advance needed

    def test_fifo_prefix_respects_max_rows(self):
        mb, clk = self._batcher(queue_limit=8)
        rs = [mb.submit(_rows(3, i), wait=False) for i in range(3)]
        clk.advance(0.006)
        mb.poll()
        # 3+3 fit in 8; the third 3-row request rides the next dispatch
        assert mb.stats["dispatches"] == 2
        assert mb.occupancy[0][0] == 6 and mb.occupancy[1][0] == 3
        assert all(r.done for r in rs)

    def test_oversized_request_dispatches_alone(self):
        mb, clk = self._batcher()
        small = mb.submit(_rows(2), wait=False)
        big = mb.submit(_rows(11), wait=False)  # > max_rows
        clk.advance(0.006)
        mb.poll()
        assert small.done and big.done
        assert [r for r, _ in mb.occupancy] == [2, 11]

    def test_request_deadline_expires_instead_of_dispatching(self):
        mb, clk = self._batcher()
        doomed = mb.submit(_rows(2), deadline=clk() + 0.001, wait=False)
        alive = mb.submit(_rows(1), wait=False)
        clk.advance(0.006)
        mb.poll()
        assert isinstance(doomed.error, DeadlineExceededError)
        with pytest.raises(DeadlineExceededError):
            doomed.wait(0)
        assert alive.done and alive.error is None
        assert mb.stats["expired"] == 1
        assert mb.stats["dispatched_rows"] == 1  # doomed rows never ran

    def test_queue_full_raises_not_hangs(self):
        mb, _ = self._batcher()
        for i in range(4):
            mb.submit(_rows(1, i), wait=False)
        t0 = time.perf_counter()
        with pytest.raises(QueueFullError, match="queueLimit=4"):
            mb.submit(_rows(1, 9), wait=False)
        assert time.perf_counter() - t0 < 1.0  # immediate, not a hang
        assert mb.stats["rejected"] == 1

    def test_submit_contract_validation(self):
        mb, _ = self._batcher(trailing_shape=(8,),
                              feature_dtype=np.float32)
        with pytest.raises(ValueError, match="does not match"):
            mb.submit(np.zeros((2, 7), np.float32), wait=False)
        with pytest.raises(ValueError, match="rows >= 1"):
            mb.submit(np.zeros((0, 8), np.float32), wait=False)
        r = mb.submit(np.zeros((2, 8), np.float64), wait=False)
        assert r.features.dtype == np.float32  # canonicalised, no retrace

    def test_dispatch_failure_fails_whole_batch(self):
        def boom(f):
            raise RuntimeError("device on fire")

        mb, clk = self._batcher(boom)
        r1 = mb.submit(_rows(1, 1), wait=False)
        r2 = mb.submit(_rows(1, 2), wait=False)
        clk.advance(0.006)
        mb.poll()
        for r in (r1, r2):
            with pytest.raises(RuntimeError, match="device on fire"):
                r.wait(0)
        assert mb.stats["errors"] == 2

    def test_close_drain_false_fails_pending_and_rejects(self):
        mb, _ = self._batcher()
        r = mb.submit(_rows(1), wait=False)
        mb.close(drain=False)
        assert isinstance(r.error, ServingClosedError)
        with pytest.raises(ServingClosedError):
            mb.submit(_rows(1), wait=False)

    def test_flush_ignores_max_wait(self):
        mb, _ = self._batcher()
        r = mb.submit(_rows(2), wait=False)
        mb.flush()                       # no clock advance
        assert r.done


# ----------------------------------------------------------------------
# load generator
# ----------------------------------------------------------------------

class TestLoadGen:
    def test_arrival_offsets_seeded_and_poissonian(self):
        a = loadgen.arrival_offsets(100.0, 2000, seed=3)
        b = loadgen.arrival_offsets(100.0, 2000, seed=3)
        np.testing.assert_array_equal(a, b)       # reproducible
        gaps = np.diff(np.concatenate([[0.0], a]))
        assert abs(gaps.mean() - 0.01) < 0.002    # ~1/rate
        assert (gaps >= 0).all()
        with pytest.raises(ValueError):
            loadgen.arrival_offsets(0, 5)

    def test_summarize_percentiles(self):
        lat = [i / 1000.0 for i in range(1, 101)]  # 1..100 ms
        rec = loadgen.summarize(lat, duration_s=2.0)
        assert rec["requests_per_sec"] == 50.0
        assert rec["p50_ms"] == pytest.approx(50.5, abs=0.5)
        assert rec["p99_ms"] == pytest.approx(99.01, abs=0.5)
        assert rec["max_ms"] == 100.0

    def test_open_loop_counts_errors_by_type(self):
        def submit(x):
            if int(x[0, 0]) % 3 == 0:
                raise QueueFullError("full")

        rec = loadgen.run_open_loop(
            submit, lambda i: np.full((1, 1), i, np.float32),
            rate=5000.0, n_requests=30, seed=0, max_clients=4)
        assert rec["errors"] == {"QueueFullError": 10}
        assert rec["completed"] == 20 and rec["requests"] == 30

    def test_occupancy_summary_math(self):
        mb = MicroBatcher(lambda f: f, max_rows=16, start_thread=False)
        mb.occupancy = [(4, 16), (16, 16), (9, 16)]
        s = mb.occupancy_summary()
        assert s["dispatches"] == 3
        assert s["mean_occupancy"] == pytest.approx(
            (0.25 + 1 + 0.5625) / 3, abs=1e-4)  # summary rounds to 4dp
        assert s["histogram"] == {"0-25%": 1, "25-50%": 0, "50-75%": 1,
                                  "75-100%": 1}


# ----------------------------------------------------------------------
# ParallelInference modes (the Builder fix)
# ----------------------------------------------------------------------

class TestInferenceModes:
    def test_unknown_mode_rejected_loudly(self):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        with pytest.raises(ValueError, match="unknown inferenceMode"):
            ParallelInference(net, mesh=_mesh(2), inferenceMode="TURBO")
        with pytest.raises(ValueError, match="BATCHED"):
            (ParallelInference.Builder(net).workers(2)
             .inferenceMode("nope").build())
        with pytest.raises(ValueError, match="queueLimit"):
            ParallelInference(net, mesh=_mesh(2), queueLimit=0)

    def test_builder_wires_queue_limit_and_mode(self):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        pi = (ParallelInference.Builder(_mln()).workers(2)
              .inferenceMode("BATCHED").queueLimit(7)
              .batchBuckets(8, 16).build())
        try:
            assert pi.inferenceMode == "BATCHED"
            assert pi.queueLimit == 7
            assert pi._ensure_batcher().queue_limit == 7
            assert pi._ensure_batcher().max_rows == 16
        finally:
            pi.close()

    def test_sequential_mode_stays_sync(self):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        pi = ParallelInference(net, mesh=_mesh(2), batchBuckets=(8,),
                               inferenceMode="SEQUENTIAL")
        out = pi.output(_rows(3))
        assert out.shape()[0] == 3
        assert pi._batcher is None   # no queue in the sync modes

    def test_batched_mode_defaults_buckets(self):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        pi = ParallelInference(_mln(), mesh=_mesh(2),
                               inferenceMode="BATCHED")
        assert pi.batchBuckets == tuple(sorted(aot.DEFAULT_BATCH_BUCKETS))

    def test_batched_output_matches_sync_bitwise(self):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        mesh = _mesh(2)
        sync = ParallelInference(net, mesh=mesh, batchBuckets=(8, 16))
        queued = ParallelInference(net, mesh=mesh, batchBuckets=(8, 16),
                                   inferenceMode="BATCHED", queueLimit=64,
                                   maxWaitMs=2.0)
        try:
            sizes = (5, 7, 3, 2, 6, 1)
            xs = [_rows(n, seed=n) for n in sizes]
            want = [np.asarray(sync.output(x).jax()) for x in xs]
            got = [None] * len(xs)

            def run(i):
                got[i] = np.asarray(queued.output(xs[i]).jax())

            ts = [threading.Thread(target=run, args=(i,))
                  for i in range(len(xs))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            for g, w in zip(got, want):
                np.testing.assert_array_equal(g, w)
            st = queued._batcher.stats
            assert st["requests"] == len(xs)
            assert st["dispatches"] <= len(xs)  # coalescing happened
        finally:
            queued.close()


# ----------------------------------------------------------------------
# parity + compile discipline (acceptance gates)
# ----------------------------------------------------------------------

class TestServingParity:
    def test_coalesced_bitwise_across_bucket_boundaries(self, fresh_cache):
        """Mixed request sizes coalesced into a DIFFERENT bucket than
        any of them would use alone (5,7,3 -> 15 rows -> the 16 bucket;
        alone each pads into the 8 bucket): responses must still be
        bitwise-equal to per-request output(). (Same-bucket coalescing
        is bitwise BY CONSTRUCTION — one executable, row-independent
        rows; across buckets it is gated here on the canonical config.
        Known limit, docs/SERVING.md: on a mesh where the bucket change
        alters the per-shard row count, XLA's dot lowering can round 1
        ulp apart.)"""
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        pi = ParallelInference(net, mesh=_mesh(2), batchBuckets=(8, 16))
        pi.precompile()
        assert fresh_cache.stats["misses"] == 2  # one per (model, bucket)
        sizes = (5, 7, 3)
        xs = [_rows(n, seed=10 + n) for n in sizes]
        per = [np.asarray(pi.output(x).jax()) for x in xs]

        mb = MicroBatcher(pi._dispatch_coalesced, max_rows=16,
                          bucket_for=pi._target_batch,
                          clock=ManualClock(), start_thread=False)
        reqs = [mb.submit(x, wait=False) for x in xs]
        mb.flush()
        assert mb.occupancy == [(15, 16)]   # ONE ragged coalesced batch
        for r, w in zip(reqs, per):
            np.testing.assert_array_equal(r.result, w)
        # the whole run (precompile + per-request + coalesced) paid
        # exactly one compile per (model, bucket) — nothing else
        assert fresh_cache.stats["misses"] == 2

    def test_single_input_graph_coalesces_bitwise(self, fresh_cache):
        from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                           NeuralNetConfiguration,
                                           Nesterovs, OutputLayer)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(Nesterovs(0.1, 0.9)).graphBuilder()
                .addInputs("in")
                .addLayer("d", DenseLayer(nOut=16, activation="relu"),
                          "in")
                .addLayer("out", OutputLayer(nOut=4, activation="softmax",
                                             lossFunction="mcxent"), "d")
                .setOutputs("out")
                .setInputTypes(InputType.feedForward(8)).build())
        net = ComputationGraph(conf).init()
        pi = ParallelInference(net, mesh=_mesh(2), batchBuckets=(8,))
        per = [np.asarray(pi.output(_rows(n, seed=n)).jax())
               for n in (3, 4)]
        mb = MicroBatcher(pi._dispatch_coalesced, max_rows=8,
                          clock=ManualClock(), start_thread=False)
        rs = [mb.submit(_rows(n, seed=n), wait=False) for n in (3, 4)]
        mb.flush()
        assert mb.stats["dispatches"] == 1
        for r, w in zip(rs, per):
            np.testing.assert_array_equal(r.result, w)


class TestModelHost:
    def test_register_policy_table_and_duplicate_rejection(self,
                                                           fresh_cache):
        host = ModelHost(mesh=_mesh(2))
        try:
            rep = host.register("mlp", _mln(), batchBuckets=(8,),
                                queueLimit=32, maxWaitMs=1.5)
            assert rep["version"] == 1
            assert {b: d["status"] for b, d in rep["warm"].items()} \
                == {8: "cold"}
            table = host.describe()
            pol = table["mlp"]
            assert pol["dtype"] == "float32" and pol["int8"] is False
            assert pol["batchBuckets"] == [8]
            assert pol["queueLimit"] == 32
            assert pol["exampleShape"] == [8]
            assert pol["mesh"] == {"data": 2}
            with pytest.raises(ValueError, match="swap"):
                host.register("mlp", _mln())
            with pytest.raises(KeyError, match="unknown model"):
                host.model("nope")
        finally:
            host.close()

    def test_int8_model_serves_with_top1_agreement(self, fresh_cache):
        host = ModelHost(mesh=_mesh(2))
        try:
            net = _mln()
            host.register("fp", net, batchBuckets=(8,))
            host.register("q8", net, batchBuckets=(8,), int8=True)
            assert host.describe()["q8"]["int8"] is True
            x = _rows(6, seed=4)
            fp = host.submit("fp", x)
            q8 = host.submit("q8", x)
            assert q8.shape == fp.shape
            np.testing.assert_array_equal(np.argmax(q8, -1),
                                          np.argmax(fp, -1))
        finally:
            host.close()

    def test_rolling_swap_zero_errors_zero_request_path_compiles(
            self, fresh_cache):
        """The swap soak: concurrent clients keep hitting the model
        while a new version warms and swaps in. Bar: every response is
        bitwise one of the two versions' sync oracles, no request
        fails, and — with the second version's executables already hot
        (equal conf -> equal keys) — the ENTIRE soak including the
        swap pays zero compiles, proven by CompileWatch (cache misses)
        AND RetraceSentinel (actual traces)."""
        from deeplearning4j_tpu.analysis.retrace import RetraceSentinel
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        buckets = (8,)
        sentinel = RetraceSentinel(
            max_compiles=aot.sentinel_budget(buckets))
        net1 = _mln()
        net1._forward_infer = sentinel.wrap(net1._forward_infer,
                                            "serving_forward")
        net2 = _mln()   # identical conf -> identical cache keys
        net2._forward_infer = sentinel.wrap(net2._forward_infer,
                                            "serving_forward")
        net2._params = jax.tree_util.tree_map(lambda a: a * 1.5,
                                              net2._params)
        mesh = _mesh(2)
        oracle1 = ParallelInference(net1, mesh=mesh, batchBuckets=buckets)
        oracle2 = ParallelInference(net2, mesh=mesh, batchBuckets=buckets)

        n_threads, n_each = 4, 24
        feats = {(t, i): _rows(1 + (t + i) % 5, seed=100 + t * 1000 + i)
                 for t in range(n_threads) for i in range(n_each)}
        want1 = {k: np.asarray(oracle1.output(v).jax())
                 for k, v in feats.items()}
        want2 = {k: np.asarray(oracle2.output(v).jax())
                 for k, v in feats.items()}
        assert sentinel.compiles("serving_forward") == len(buckets)

        host = ModelHost(mesh=mesh)
        host.register("m", net1, batchBuckets=buckets, queueLimit=256,
                      maxWaitMs=1.0)
        failures = []
        versions_seen = set()
        swap_at = threading.Event()

        def client(t):
            for i in range(n_each):
                if t == 0 and i == 4:
                    swap_at.set()   # swap mid-soak, clients in flight
                k = (t, i)
                try:
                    got = host.submit("m", feats[k])
                except Exception as e:
                    failures.append((k, repr(e)))
                    continue
                if np.array_equal(got, want1[k]):
                    versions_seen.add(1)
                elif np.array_equal(got, want2[k]):
                    versions_seen.add(2)
                else:
                    failures.append((k, "response matches NEITHER "
                                        "version bitwise"))

        with aot.CompileWatch(fresh_cache) as watch:
            ts = [threading.Thread(target=client, args=(t,))
                  for t in range(n_threads)]
            for t in ts:
                t.start()
            assert swap_at.wait(30)
            rep = host.swap("m", net2)
            for t in ts:
                t.join(timeout=60)
        host.close()
        assert not failures, failures[:5]
        assert rep["version"] == 2
        # new version warmed from cache, old kept serving: zero 5xx
        # equivalents and zero compiles anywhere near the request path
        assert {b: d["status"] for b, d in rep["warm"].items()} \
            == {8: "warm"}
        watch.assert_no_compiles("rolling-swap soak")
        assert sentinel.compiles("serving_forward") == len(buckets)
        assert 2 in versions_seen   # the swap actually took effect

    def test_swap_unknown_model_raises(self, fresh_cache):
        host = ModelHost(mesh=_mesh(2))
        try:
            with pytest.raises(KeyError, match="register"):
                host.swap("ghost", _mln())
        finally:
            host.close()


# ----------------------------------------------------------------------
# HTTP front
# ----------------------------------------------------------------------

class TestInferenceServerHTTP:
    def _host(self, **kw):
        host = ModelHost(mesh=_mesh(2))
        kw.setdefault("batchBuckets", (8,))
        kw.setdefault("maxWaitMs", 1.0)
        host.register("m", _mln(), **kw)
        return host

    def test_predict_roundtrip_and_policy_routes(self, fresh_cache):
        host = self._host()
        srv = InferenceServer(host).start(port=0)
        try:
            _wait_ready(srv.port)
            base = f"http://127.0.0.1:{srv.port}"
            x = _rows(3, seed=5)
            want = host.submit("m", x)
            status, body = _post(base + "/v1/models/m:predict",
                                 {"instances": x.tolist()})
            assert status == 200
            assert body["model"] == "m" and body["version"] == 1
            assert body["rows"] == 3
            np.testing.assert_array_equal(
                np.asarray(body["predictions"], np.float32), want)

            status, table = _get(base + "/v1/models")
            assert table["models"]["m"]["batchBuckets"] == [8]
            status, pol = _get(base + "/v1/models/m")
            assert pol["model"] == "m"
        finally:
            srv.stop(close_host=True)

    def test_client_errors_have_status_codes(self, fresh_cache):
        host = self._host()
        srv = InferenceServer(host).start(port=0)
        try:
            _wait_ready(srv.port)
            base = f"http://127.0.0.1:{srv.port}"
            cases = [
                (base + "/v1/models/ghost:predict",
                 {"instances": _rows(1).tolist()}, 404),
                (base + "/v1/models/m:predict", {}, 400),
                (base + "/v1/models/m:predict",
                 {"instances": np.zeros((2, 7)).tolist()}, 400),
                (base + "/v1/nothing", {"instances": []}, 404),
            ]
            for url, body, code in cases:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(url, body)
                assert ei.value.code == code, url
                assert "error" in json.loads(ei.value.read().decode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/v1/models/ghost")
            assert ei.value.code == 404
        finally:
            srv.stop(close_host=True)

    def test_healthz_gated_on_model_warmup(self, fresh_cache):
        host = ModelHost(mesh=_mesh(2))
        host.register("m", _mln(), batchBuckets=(8,), precompile=False)
        gate = threading.Event()
        warmed = []

        def warmup():
            gate.wait(20)
            warmed.append(host.warm_all())

        srv = InferenceServer(host).start(port=0, warmup=warmup)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{srv.port}/healthz")
            assert ei.value.code == 503     # gated until executables hot
            gate.set()
            _wait_ready(srv.port)
            assert warmed and warmed[0]["m"][8]["status"] in (
                "cold", "warm")
        finally:
            srv.stop(close_host=True)

    def test_queue_full_is_429_not_a_hang(self, fresh_cache):
        host = self._host(queueLimit=2)
        srv = InferenceServer(host).start(port=0)
        try:
            _wait_ready(srv.port)
            base = f"http://127.0.0.1:{srv.port}"
            b = host.model("m").batcher
            orig = b._dispatch
            entered = threading.Event()
            release = threading.Event()

            def gated(f):
                entered.set()
                release.wait(30)
                return orig(f)

            b._dispatch = gated
            results = []

            def bg_post(i):
                try:
                    results.append(_post(base + "/v1/models/m:predict",
                                         {"instances": _rows(1, i).tolist()},
                                         timeout=60)[0])
                except urllib.error.HTTPError as e:
                    results.append(e.code)

            t1 = threading.Thread(target=bg_post, args=(0,))
            t1.start()
            assert entered.wait(20)   # request 0 is INSIDE the dispatch
            t23 = [threading.Thread(target=bg_post, args=(i,))
                   for i in (1, 2)]
            for t in t23:
                t.start()
            deadline = time.time() + 10
            while b.depth < 2 and time.time() < deadline:
                time.sleep(0.01)
            assert b.depth == 2       # queue now at queueLimit
            t0 = time.perf_counter()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/v1/models/m:predict",
                      {"instances": _rows(1, 9).tolist()})
            assert ei.value.code == 429
            assert time.perf_counter() - t0 < 5.0  # backpressure, no hang
            release.set()
            t1.join(timeout=30)
            for t in t23:
                t.join(timeout=30)
            assert results.count(200) == 3  # everyone queued got served
        finally:
            release.set()
            srv.stop(close_host=True)

    def test_per_request_deadline_is_504(self, fresh_cache):
        host = self._host(queueLimit=8)
        srv = InferenceServer(host).start(port=0)
        try:
            _wait_ready(srv.port)
            base = f"http://127.0.0.1:{srv.port}"
            b = host.model("m").batcher
            orig = b._dispatch
            release = threading.Event()
            b._dispatch = lambda f: (release.wait(30), orig(f))[1]
            # wedge the dispatcher with a sacrificial request
            threading.Thread(
                target=lambda: _post(base + "/v1/models/m:predict",
                                     {"instances": _rows(1).tolist()},
                                     timeout=60),
                daemon=True).start()
            time.sleep(0.1)
            t0 = time.perf_counter()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base + "/v1/models/m:predict",
                      {"instances": _rows(1, 2).tolist(),
                       "deadlineMs": 200})
            took = time.perf_counter() - t0
            assert ei.value.code == 504
            assert took < 5.0    # released at the deadline, not at drain
            release.set()
        finally:
            release.set()
            srv.stop(close_host=True)


# ----------------------------------------------------------------------
# throughput acceptance: >= 3x serial under the open-loop load generator
# ----------------------------------------------------------------------

class TestThroughputAcceptance:
    def _measure_once(self, host, pi_serial, n_requests, max_clients):
        from deeplearning4j_tpu.parallel.inference import ParallelInference  # noqa: F401

        lock = threading.Lock()

        def serial_submit(x):
            with lock:               # one dispatch per request
                return pi_serial.output(x)

        def one_row(i):
            return _rows(1, seed=i)

        serial_submit(one_row(0))
        host.submit("mlp", one_row(0))
        t0 = time.perf_counter()
        for i in range(24):
            serial_submit(one_row(i))
        rate = 8.0 * 24 / (time.perf_counter() - t0)
        rs = loadgen.run_open_loop(serial_submit, one_row, rate=rate,
                                   n_requests=n_requests, seed=0,
                                   max_clients=max_clients)
        rb = loadgen.run_open_loop(
            lambda x: host.submit("mlp", x), one_row, rate=rate,
            n_requests=n_requests, seed=1, max_clients=max_clients)
        return rs, rb

    def test_microbatching_3x_serial_at_bounded_p99(self, fresh_cache):
        """The serving headline gate (ISSUE 8 acceptance): open-loop
        load, concurrent pooled clients, dispatch-bound regime (the
        batch-dim-sharded 8-device mesh — on TPU every dispatch pays
        launch/tunnel latency; this is its CPU rehearsal). Dynamic
        micro-batching must sustain >= 3x the serial one-dispatch-per-
        request requests/sec at bounded p99, with zero request-path
        compiles."""
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        net = _mln()
        mesh = _mesh(8)
        host = ModelHost(mesh=mesh)
        host.register("mlp", net, batchBuckets=(64, 128),
                      queueLimit=2048, maxWaitMs=3.0)
        pi_serial = ParallelInference(net, mesh=mesh, batchBuckets=(8,))
        pi_serial.precompile()
        try:
            best = None
            for attempt in range(3):   # shield against CI-rig noise
                with aot.CompileWatch(fresh_cache) as watch:
                    rs, rb = self._measure_once(host, pi_serial,
                                                n_requests=256,
                                                max_clients=24)
                assert rs["errors"] == {} and rb["errors"] == {}
                speedup = rb["requests_per_sec"] / rs["requests_per_sec"]
                best = max(best or 0.0, speedup)
                if best >= 3.0:
                    break
            occ = host.model("mlp").batcher.occupancy_summary()
            assert best >= 3.0, (
                f"micro-batching sustained only {best:.2f}x serial "
                f"(serial {rs['requests_per_sec']} rps, batched "
                f"{rb['requests_per_sec']} rps, occupancy {occ})")
            # bounded p99: batching must not trade unbounded tail
            # latency for throughput — the saturated batched tail must
            # undercut the saturated serial tail
            assert rb["p99_ms"] < rs["p99_ms"]
            assert rb["p99_ms"] < 5000.0
            assert occ["mean_rows_per_dispatch"] > 1.5  # really coalesced
            watch.assert_no_compiles("loaded serving window")
        finally:
            host.close()


# ----------------------------------------------------------------------
# long soak (slow leg): sustained load + repeated rolling swaps
# ----------------------------------------------------------------------

@pytest.mark.slow
class TestServingSoak:
    def test_open_loop_soak_with_rolling_swaps(self, fresh_cache):
        """Sustained open-loop load with THREE rolling swaps mid-flight:
        zero failed requests, zero request-path compiles after the
        initial warm, every dispatch bucketed."""
        net_a = _mln()
        net_b = _mln()
        net_b._params = jax.tree_util.tree_map(lambda a: a * 1.25,
                                               net_b._params)
        mesh = _mesh(2)
        host = ModelHost(mesh=mesh)
        host.register("m", net_a, batchBuckets=(8, 32), queueLimit=4096,
                      maxWaitMs=2.0)
        try:
            # net_b's keys are already hot (identical conf -> identical
            # keys), so every swap below must be all-warm
            stop = threading.Event()

            def swapper():
                nets = [net_b, net_a, net_b]
                for n in nets:
                    if stop.wait(1.0):
                        return
                    host.swap("m", n)

            with aot.CompileWatch(fresh_cache) as watch:
                sw = threading.Thread(target=swapper)
                sw.start()
                rec = loadgen.run_open_loop(
                    lambda x: host.submit("m", x),
                    lambda i: _rows(1 + i % 6, seed=i),
                    rate=300.0, n_requests=1200, seed=7,
                    max_clients=16, timeout_s=300.0)
                stop.set()
                sw.join(timeout=30)
            assert rec["errors"] == {}, rec
            assert rec["completed"] == 1200
            watch.assert_no_compiles("serving soak with swaps")
            assert host.model("m").version == 4
        finally:
            host.close()

"""Paged KV-cache serving gates (serving/sequence.py
``PagedSequenceScheduler``, nn/transformer.py, serving/kvcache.py,
docs/SERVING.md "Paged KV cache").

What must hold (the ISSUE 19 serving acceptance):

- parity: within a fixed slot bucket, paged generation — tokens AND
  per-step logits — is BITWISE the serial dense-cache trajectory
  (``dense_serial_trajectory``), ragged prompts, chunked prefill,
  prefix sharing and temperature sampling included (both paths run the
  same ``paged_attend`` core, so parity is structural);
- scheduling: at most ONE page-sized prefill chunk per iteration
  interleaves with the decode batch (a long prompt never stalls
  running generations), deadlines are honored per step and free pages,
  ManualClock + thread-less poll()/drain() is deterministic;
- bounded HBM: pool exhaustion fails the victim request with the typed
  ``KVCacheFullError`` (submit-time when unservable at any load,
  per-slot mid-flight otherwise) while other slots keep generating;
  paged residency at >= 75 % ragged occupancy is <= 0.6x the dense
  twin's reservation (the bench A/B's correctness anchor);
- compile discipline: ``warm()`` precompiles every slot bucket + the
  prefill chunk and a whole ragged serve pays ZERO further compiles;
- sampling: deterministic per (sampler_seed, stream), streams assigned
  in submit order;
- the HTTP tier: ``:generate`` accepts ``{"tokens": ...}`` and maps
  KVCacheFullError to 429.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.nn.transformer import (
    CausalTransformerLM, dense_serial_trajectory,
)
from deeplearning4j_tpu.runtime import aot
from deeplearning4j_tpu.serving import (
    DeadlineExceededError, KVCacheFullError, ManualClock, ModelHost,
    PagedSequenceScheduler, ServingClosedError, greedy_sampler,
    stream_rng, temperature_sampler,
)


# this module traces many model/bucket step twins; the shared hygiene
# fixture drops jax's global caches at module teardown
from conftest import drop_jax_caches_fixture

_drop_jax_caches_after_module = drop_jax_caches_fixture()


@pytest.fixture
def fresh_cache():
    """Fresh MEMORY-ONLY session cache (hermetic miss counting)."""
    prev = aot._SESSION
    cache = aot._SESSION = aot.ExecutableCache(None)
    yield cache
    aot._SESSION = prev


def _lm(vocab=23, max_context=64, page_size=8, seed=3, **kw):
    return CausalTransformerLM(vocab=vocab, d_model=32, n_heads=2,
                               n_layers=2, max_context=max_context,
                               page_size=page_size, seed=seed, **kw)


def _sched(model, **kw):
    kw.setdefault("num_pages", 48)
    kw.setdefault("slot_buckets", (4,))
    clk = kw.pop("clock", None) or ManualClock()
    return PagedSequenceScheduler(model, clock=clk, start_thread=False,
                                  **kw), clk


def _prompts(lens, vocab, seed=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=n).tolist() for n in lens]


# ----------------------------------------------------------------------
# bitwise parity vs the serial dense trajectory
# ----------------------------------------------------------------------

class TestBitwiseVsSerial:
    def test_ragged_batch_bitwise_vs_serial_dense(self):
        """Four ragged prompts generated CONCURRENTLY through the
        paged scheduler produce, per request, bitwise the tokens AND
        logits of the serial dense-slab trajectory at the same bucket —
        chunked prefill, block-table scatter and mid-batch finishes
        included."""
        m = _lm()
        s, _ = _sched(m)
        prompts = _prompts((5, 11, 3, 16), m.vocab)
        reqs = [s.submit(p, max_new_tokens=6, wait=False)
                for p in prompts]
        s.drain()
        for i, p in enumerate(prompts):
            got = reqs[i].wait(1.0)
            toks, logits = dense_serial_trajectory(
                m, p, 6, greedy_sampler(), stream_rng(0, i), bucket=4)
            assert got.tolist() == toks
            assert np.array_equal(reqs[i].logits.view(np.uint8),
                                  logits.view(np.uint8))
        s.close()

    def test_temperature_sampling_bitwise_vs_serial(self):
        """The same holds under temperature/top-k sampling: the serial
        oracle replays the identical (seed, stream) rng, so the drawn
        trajectories coincide token for token."""
        m = _lm()
        smp = temperature_sampler(0.8, top_k=5)
        s, _ = _sched(m, sampler=temperature_sampler(0.8, top_k=5),
                      sampler_seed=42)
        prompts = _prompts((6, 9), m.vocab, seed=5)
        reqs = [s.submit(p, max_new_tokens=5, wait=False)
                for p in prompts]
        s.drain()
        for i, p in enumerate(prompts):
            toks, _ = dense_serial_trajectory(
                m, p, 5, smp, stream_rng(42, i), bucket=4)
            assert reqs[i].wait(1.0).tolist() == toks
        s.close()

    def test_prefix_adoption_stays_bitwise(self):
        """A resubmitted prompt adopts the registered pages (no
        prefill chunks paid) and still generates bitwise the serial
        trajectory — shared full pages are immutable and the tail page
        forks copy-on-write before the first append."""
        m = _lm()
        s, _ = _sched(m)
        p = _prompts((13,), m.vocab, seed=9)[0]
        first = s.submit(p, max_new_tokens=4, wait=False)
        s.drain()
        chunks_before = s.prefill_chunks
        again = s.submit(p, max_new_tokens=4, wait=False)
        s.drain()
        assert s.prefill_chunks == chunks_before  # exact adopt: zero
        assert again.wait(1.0).tolist() == first.wait(1.0).tolist()
        toks, _ = dense_serial_trajectory(
            m, p, 4, greedy_sampler(), stream_rng(0, 1), bucket=4)
        assert again.result.tolist() == toks
        s.close()


# ----------------------------------------------------------------------
# scheduling: interleave, deadlines, determinism seams
# ----------------------------------------------------------------------

class TestScheduling:
    def test_prefill_interleaves_without_stalling_decode(self):
        """A 4-chunk prompt prefills ONE chunk per iteration while an
        already-running generation keeps producing a token every
        iteration — the short request finishes while the long prompt
        is still mid-prefill (bounded prefill work per step)."""
        m = _lm(max_context=64, page_size=8)
        s, _ = _sched(m, slot_buckets=(2,), prefix_sharing=False)
        short = s.submit(_prompts((4,), m.vocab)[0], max_new_tokens=3,
                         wait=False)
        s.poll()   # short: prefill + first decode -> 2 tokens
        long = s.submit(_prompts((32,), m.vocab, seed=2)[0],
                        max_new_tokens=2, wait=False)
        s.poll()   # long chunk 1 of 4; short token 3 -> done
        assert short.done and not long.done
        assert long.prefilled == 8 < 32
        s.drain()
        assert long.wait(1.0).shape == (2,)
        s.close()

    def test_deadline_mid_generation_frees_pages(self):
        m = _lm()
        s, clk = _sched(m, prefix_sharing=False)
        req = s.submit(_prompts((9,), m.vocab)[0], max_new_tokens=30,
                       deadline=5.0, wait=False)
        s.poll()
        s.poll()
        assert s.cache.pages_in_use > 0 and not req.done
        clk.advance(10.0)
        s.poll()
        with pytest.raises(DeadlineExceededError):
            req.wait(1.0)
        assert s.cache.pages_in_use == 0
        assert s.stats["expired"] == 1
        s.close()

    def test_close_without_drain_fails_and_frees(self):
        m = _lm()
        s, _ = _sched(m, prefix_sharing=False)
        req = s.submit(_prompts((6,), m.vocab)[0], max_new_tokens=20,
                       wait=False)
        s.poll()
        s.close(drain=False)
        with pytest.raises(ServingClosedError):
            req.wait(1.0)
        assert s.cache.pages_in_use == 0

    def test_sampling_streams_deterministic_per_seed(self):
        """Same (sampler_seed, submit order) -> identical draws across
        scheduler instances; a different seed diverges."""
        m = _lm()
        smp = temperature_sampler(1.0)
        outs = []
        for seed in (7, 7, 8):
            s, _ = _sched(m, sampler=temperature_sampler(1.0),
                          sampler_seed=seed, prefix_sharing=False)
            r = s.submit(_prompts((8,), m.vocab)[0],
                         max_new_tokens=12, wait=False)
            s.drain()
            outs.append(r.wait(1.0).tolist())
            s.close()
        assert outs[0] == outs[1]
        assert outs[0] != outs[2]

    def test_staging_buffers_reused_across_iterations(self):
        """Decode staging (tokens/lens/block tables) is allocated once
        per bucket and reused every iteration — the alloc-churn
        counter the bench decode leg records."""
        m = _lm()
        s, _ = _sched(m)
        s.submit(_prompts((4,), m.vocab)[0], max_new_tokens=8,
                 wait=False)
        s.drain()
        assert s.staging_reuse_bytes > 0
        s.close()


# ----------------------------------------------------------------------
# bounded HBM: exhaustion + the residency anchor
# ----------------------------------------------------------------------

class TestBoundedHBM:
    def test_unservable_prompt_rejected_at_submit(self):
        m = _lm(max_context=32, page_size=8)
        s, _ = _sched(m, num_pages=3)   # capacity 2 pages = 16 rows
        with pytest.raises(KVCacheFullError):
            s.submit(_prompts((17,), m.vocab)[0], max_new_tokens=1)
        s.close()

    def test_midflight_exhaustion_fails_victim_only(self):
        """When the pool runs dry mid-generation, the slot that needed
        the page fails with the typed error; the other slot keeps its
        pages and completes."""
        m = _lm()
        s, _ = _sched(m, num_pages=5, prefix_sharing=False,
                      slot_buckets=(2,))
        # 2 pages each after prefill+early decode; both need a 3rd at
        # the seq_len-16 boundary and the capacity-4 pool has none left
        p = _prompts((4, 4), m.vocab)
        a = s.submit(p[0], max_new_tokens=14, wait=False)
        b = s.submit(p[1], max_new_tokens=14, wait=False)
        s.drain()
        results = []
        for r in (a, b):
            try:
                results.append(r.wait(1.0).tolist())
            except KVCacheFullError:
                results.append("full")
        assert results.count("full") == 1
        done = [r for r in results if r != "full"]
        assert len(done) == 1 and len(done[0]) == 14
        assert s.stats["errors"] == 1 and s.stats["completed"] == 1
        s.close()

    def test_residency_le_60pct_of_dense_at_75pct_occupancy(self):
        """The acceptance anchor: with >= 75 % of the bucket's slots
        live at RAGGED lengths, the paged pool's live bytes are
        <= 0.6x what the dense twin reserves for the same bucket
        (slots x max_context, paid regardless of load)."""
        m = _lm(max_context=64, page_size=8)
        s, _ = _sched(m, slot_buckets=(8,), num_pages=64,
                      prefix_sharing=False)
        lens = (10, 14, 18, 22, 26, 30)     # 6/8 slots = 75 %
        reqs = [s.submit(p, max_new_tokens=24, wait=False)
                for p in _prompts(lens, m.vocab)]
        for _ in range(20):                 # past all 18 prefill chunks
            s.poll()
        assert s.active_slots == 6
        assert s.occupancy[-1] == (6, 8)
        paged = s.cache.bytes_in_use()
        dense = m.dense_cache_bytes(8)
        assert paged <= 0.6 * dense, \
            f"paged {paged}B vs dense {dense}B = {paged / dense:.2f}x"
        s.drain()
        for r in reqs:
            assert r.wait(1.0).shape == (24,)
        assert s.cache.pages_in_use == 0    # everything returned
        s.close()


# ----------------------------------------------------------------------
# compile discipline
# ----------------------------------------------------------------------

class TestCompileDiscipline:
    def test_warm_then_zero_steady_state_compiles(self, fresh_cache):
        """warm() precompiles one decode executable per slot bucket
        plus the prefill chunk; a whole ragged serve afterwards —
        prefill, decode, prefix adoption, finishes — pays ZERO
        compiles."""
        m = _lm()
        s, _ = _sched(m, slot_buckets=(2, 4))
        s.warm()
        with aot.CompileWatch(fresh_cache) as watch:
            reqs = [s.submit(p, max_new_tokens=5, wait=False)
                    for p in _prompts((3, 9, 17, 6), m.vocab)]
            s.drain()
            for r in reqs:
                r.wait(1.0)
        watch.assert_no_compiles()
        s.close()


# ----------------------------------------------------------------------
# the host + HTTP tier
# ----------------------------------------------------------------------

def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHostAndServer:
    def test_register_generate_policy(self):
        m = _lm()
        host = ModelHost()
        rep = host.register_sequence("lm", m, slotBuckets=(4,),
                                     numPages=32)
        assert rep["version"] == 1
        pol = host.describe()["lm"]
        assert pol["paged"] and pol["pageSize"] == 8 \
            and pol["numPages"] == 32
        out = host.generate("lm", [1, 2, 3], max_new_tokens=4)
        toks, _ = dense_serial_trajectory(
            m, [1, 2, 3], 4, greedy_sampler(), stream_rng(0, 0),
            bucket=4)
        assert out.tolist() == toks
        # feature-path submit on a paged model is a loud 400-class
        # error, not silent nonsense
        with pytest.raises(ValueError):
            host.submit_sequence("lm", np.zeros((3, 4), np.float32))
        host.close()

    def test_http_generate_tokens_and_429_on_full_pool(self):
        from deeplearning4j_tpu.serving import InferenceServer

        m = _lm()
        host = ModelHost()
        host.register_sequence("lm", m, slotBuckets=(2,), numPages=3)
        srv = InferenceServer(host).start(port=0)
        port = srv.port
        try:
            st, body = _post(port, "/v1/models/lm:generate",
                             {"tokens": [1, 2, 3], "maxNewTokens": 3})
            assert st == 200 and len(body["tokens"]) == 3 \
                and body["steps"] == 3
            # capacity 2 pages = 16 rows; a 17-token prompt can never
            # be admitted -> 429, the same backpressure class as a
            # full queue
            st, body = _post(port, "/v1/models/lm:generate",
                             {"tokens": list(range(17)),
                              "maxNewTokens": 1})
            assert st == 429
            assert "pages" in body.get("error", "")
            st, _ = _post(port, "/v1/models/lm:generate",
                          {"tokens": [9999], "maxNewTokens": 1})
            assert st == 400
        finally:
            srv.stop()
            host.close()

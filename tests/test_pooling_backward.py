"""Argmax-routed maxpool backward vs the select-and-scatter oracle.

The custom VJP in ops/pooling.py exists to kill the single largest HBM
consumer in the ResNet-50 train step (206 MB select-and-scatter, see
BENCH_NOTES.md). These tests pin (a) forward parity, (b) exact gradient
parity with JAX's stock reduce_window gradient — including on tied inputs,
where both sides must route to the FIRST maximal window element — and
(c) that the compiled gradient HLO actually contains no select-and-scatter
(anti-silent-fallback, same pattern as tests/test_attention.py's routing
assertion).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import pooling


@pytest.fixture(autouse=True)
def _argmax_impl(monkeypatch):
    """This whole file tests the ARGMAX rewrite. The library default is
    stock (the measured winner on CPU and TPU v5e — BENCH_NOTES.md), so
    without this pin every new-vs-reference parity assertion would
    compare the stock path against itself and pass vacuously."""
    monkeypatch.setattr(pooling, "_BACKWARD_IMPL", "argmax")


CASES = [
    # kernel, stride, padding  (ResNet stem pool = 3x3/2 SAME is the target)
    ((3, 3), (2, 2), "SAME"),
    ((2, 2), (2, 2), "SAME"),
    ((3, 3), (2, 2), ((1, 1), (1, 1))),
    ((2, 2), (2, 2), ((0, 0), (0, 0))),
    ((3, 2), (1, 2), ((0, 1), (1, 0))),  # asymmetric everything
    ((3, 3), (1, 1), "SAME"),            # fully overlapping windows
]


def _loss_pair(kernel, stride, padding):
    def loss_new(x, dy):
        return jnp.sum(pooling.max_pool2d(x, kernel, stride, padding) * dy)

    def loss_ref(x, dy):
        return jnp.sum(
            pooling.max_pool2d_reference(x, kernel, stride, padding) * dy)

    return loss_new, loss_ref


@pytest.mark.parametrize("kernel,stride,padding", CASES)
def test_forward_matches_reference(kernel, stride, padding):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 13, 11, 5))
    y = pooling.max_pool2d(x, kernel, stride, padding)
    y_ref = pooling.max_pool2d_reference(x, kernel, stride, padding)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))


@pytest.mark.parametrize("kernel,stride,padding", CASES)
def test_gradient_matches_select_and_scatter(kernel, stride, padding):
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 13, 11, 5), dtype=jnp.float64)
    loss_new, loss_ref = _loss_pair(kernel, stride, padding)
    dy_shape = pooling.max_pool2d_reference(x, kernel, stride, padding).shape
    dy = jax.random.normal(jax.random.PRNGKey(2), dy_shape, dtype=jnp.float64)
    g_new = jax.grad(loss_new)(x, dy)
    g_ref = jax.grad(loss_ref)(x, dy)
    # atol floor: overlapping windows sum several dy terms in a different
    # association order than select-and-scatter — fp64 ulps, nothing more.
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=0, atol=1e-12)


@pytest.mark.parametrize("kernel,stride,padding", CASES)
def test_gradient_tie_routing_matches(kernel, stride, padding):
    # Integer-valued floats force many intra-window ties (the post-relu
    # regime the ResNet stem pool actually sees: lots of equal zeros).
    # XLA's select-and-scatter ge-select routes to the first maximal
    # element in window order; the argmax backward must do the same.
    key = jax.random.PRNGKey(3)
    x = jnp.floor(
        jax.random.uniform(key, (2, 12, 10, 4), dtype=jnp.float64) * 3.0)
    x = jnp.maximum(x - 1.0, 0.0)  # plenty of exact zeros
    loss_new, loss_ref = _loss_pair(kernel, stride, padding)
    dy_shape = pooling.max_pool2d_reference(x, kernel, stride, padding).shape
    dy = jax.random.normal(jax.random.PRNGKey(4), dy_shape, dtype=jnp.float64)
    g_new = jax.grad(loss_new)(x, dy)
    g_ref = jax.grad(loss_ref)(x, dy)
    # A routing (tie-break) divergence would show up as a FULL dy-sized
    # mismatch at some element, not an ulp — atol=1e-12 still catches it.
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=0, atol=1e-12)


def test_finite_difference_gradcheck():
    # fp64 central differences at a tie-free point.
    rng = np.random.default_rng(7)
    x = np.asarray(
        jax.random.permutation(jax.random.PRNGKey(5), 1 * 8 * 7 * 3),
        dtype=np.float64).reshape(1, 8, 7, 3) * 0.01  # distinct values, no ties

    def loss(xx):
        return jnp.sum(jnp.sin(pooling.max_pool2d(xx, (3, 3), (2, 2), "SAME")))

    g = np.asarray(jax.grad(loss)(jnp.asarray(x)))
    eps = 1e-6
    for _ in range(20):
        i = tuple(rng.integers(0, d) for d in x.shape)
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        fd = (float(loss(jnp.asarray(xp))) - float(loss(jnp.asarray(xm)))) / (2 * eps)
        assert abs(fd - g[i]) < 1e-5, (i, fd, g[i])


def test_no_select_and_scatter_in_grad_hlo():
    # The point of the custom VJP: with the argmax impl selected (the
    # file-wide fixture), the compiled backward must not contain
    # select-and-scatter. Fails loudly if the routing ever bypasses the
    # rewrite (e.g. wrapper bypass).
    def loss(x):
        return jnp.sum(pooling.max_pool2d(x, (3, 3), (2, 2), "SAME") ** 2)

    # Check the pre-optimization StableHLO: the CPU backend later rewrites
    # select_and_scatter into scatter, which would mask the distinction in
    # compiled text (TPU keeps it, and there it is the expensive op).
    x = jnp.ones((2, 16, 16, 4), jnp.float32)
    hlo = jax.jit(jax.grad(loss)).lower(x).as_text()
    assert "select_and_scatter" not in hlo and "scatter" not in hlo

    def loss_ref(x):
        return jnp.sum(
            pooling.max_pool2d_reference(x, (3, 3), (2, 2), "SAME") ** 2)

    hlo_ref = jax.jit(jax.grad(loss_ref)).lower(x).as_text()
    assert "select_and_scatter" in hlo_ref, (
        "oracle lost its select-and-scatter — parity tests no longer "
        "compare against the stock path")


def test_large_window_falls_back_to_reference():
    # >36-element windows route to the stock gradient by design.
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 32, 32, 2))
    y = pooling.max_pool2d(x, (7, 7), (7, 7), ((0, 0), (0, 0)))
    y_ref = pooling.max_pool2d_reference(x, (7, 7), (7, 7), ((0, 0), (0, 0)))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))


def test_forward_mode_ad_documented_behavior():
    # Pinned tradeoff (see max_pool2d docstring): reverse-mode rules out
    # forward-mode through the custom vjp; the reference path keeps it.
    x = jnp.ones((1, 4, 4, 1))
    with pytest.raises(TypeError, match="forward-mode|jvp"):
        jax.jacfwd(lambda t: pooling.max_pool2d(t, (2, 2), (2, 2), "SAME"))(x)
    jac = jax.jacfwd(
        lambda t: pooling.max_pool2d_reference(t, (2, 2), (2, 2), "SAME"))(x)
    assert np.isfinite(np.asarray(jac)).all()


def test_bf16_dtype_preserved():
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 8, 8, 3)).astype(jnp.bfloat16)
    y = pooling.max_pool2d(x, (3, 3), (2, 2), "SAME")
    assert y.dtype == jnp.bfloat16

    def loss(xx):
        return jnp.sum(pooling.max_pool2d(xx, (3, 3), (2, 2), "SAME").astype(jnp.float32))

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.bfloat16


# ======================================================================
# round 12: the saved-indices backward ("indices" impl) — the arbiter's
# CPU winner (LeNet b64: 129.1 -> 69.2 MB attributed bytes, -46%)
# ======================================================================

#: the non-overlapping cases the indices impl owns (stride >= kernel)
NON_OVERLAP_CASES = [
    ((2, 2), (2, 2), "SAME"),
    ((2, 2), (2, 2), ((0, 0), (0, 0))),
    ((2, 2), (3, 3), "SAME"),            # stride > kernel (gaps)
    ((3, 3), (3, 3), "SAME"),
    ((2, 3), (2, 3), ((1, 1), (0, 0))),  # asymmetric + explicit pads
    ((3, 3), (3, 3), ((0, 0), (1, 1))),
]


class TestIndicesImpl:
    @pytest.fixture(autouse=True)
    def _indices_impl(self, monkeypatch):
        monkeypatch.setattr(pooling, "_BACKWARD_IMPL", "indices")

    @pytest.mark.parametrize("kernel,stride,padding", NON_OVERLAP_CASES)
    def test_forward_and_gradient_bitwise(self, kernel, stride, padding):
        """First-match tie rule == select-and-scatter's ge-select, so
        parity is BITWISE (array_equal, not allclose) — non-overlapping
        windows sum nothing, there is no reassociation to forgive."""
        x = jax.random.normal(jax.random.PRNGKey(11), (2, 13, 11, 5),
                              dtype=jnp.float64)
        y = pooling.max_pool2d(x, kernel, stride, padding)
        y_ref = pooling.max_pool2d_reference(x, kernel, stride, padding)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
        loss_new, loss_ref = _loss_pair(kernel, stride, padding)
        dy = jax.random.normal(jax.random.PRNGKey(12), y.shape,
                               dtype=jnp.float64)
        g_new = jax.grad(loss_new)(x, dy)
        g_ref = jax.grad(loss_ref)(x, dy)
        np.testing.assert_array_equal(np.asarray(g_new),
                                      np.asarray(g_ref))

    @pytest.mark.parametrize("kernel,stride,padding", NON_OVERLAP_CASES)
    def test_tie_routing_bitwise(self, kernel, stride, padding):
        x = jnp.floor(jax.random.uniform(
            jax.random.PRNGKey(13), (2, 12, 10, 4),
            dtype=jnp.float64) * 3.0)
        x = jnp.maximum(x - 1.0, 0.0)  # plenty of exact-zero ties
        loss_new, loss_ref = _loss_pair(kernel, stride, padding)
        dy_shape = pooling.max_pool2d_reference(
            x, kernel, stride, padding).shape
        dy = jax.random.normal(jax.random.PRNGKey(14), dy_shape,
                               dtype=jnp.float64)
        np.testing.assert_array_equal(
            np.asarray(jax.grad(loss_new)(x, dy)),
            np.asarray(jax.grad(loss_ref)(x, dy)))

    def test_overlapping_windows_route_to_stock(self):
        """Under 'indices' an overlapping pool (the ResNet stem 3x3/2)
        keeps the stock gradient: the one-pass backward needs each
        input position in at most one window, and the scatter-add form
        measured WORSE than select-and-scatter (131.3 vs 129.1 MB)."""
        assert pooling._choose_pool_bwd((3, 3), (2, 2),
                                        impl="indices") == "stock"
        assert pooling._choose_pool_bwd((2, 2), (2, 2),
                                        impl="indices") == "indices"
        assert pooling._choose_pool_bwd((7, 7), (7, 7),
                                        impl="indices") == "stock"
        x = jnp.ones((2, 16, 16, 4), jnp.float32)

        def loss(xx):
            return jnp.sum(
                pooling.max_pool2d(xx, (3, 3), (2, 2), "SAME") ** 2)

        hlo = jax.jit(jax.grad(loss)).lower(x).as_text()
        assert "select_and_scatter" in hlo  # the stock path, by design

    def test_no_scatter_in_grad_hlo(self):
        """The impl's point: a non-overlapping pool's backward lowers
        to pure elementwise/pad HLO — no select_and_scatter, no
        scatter, and (unlike CPU's select-and-scatter rewrite) no
        standalone activation-scale iota."""
        def loss(x):
            return jnp.sum(
                pooling.max_pool2d(x, (2, 2), (2, 2), "SAME") ** 2)

        x = jnp.ones((2, 16, 16, 4), jnp.float32)
        hlo = jax.jit(jax.grad(loss)).lower(x).as_text()
        assert "select_and_scatter" not in hlo and "scatter" not in hlo

    def test_residual_is_int8_pooled_scale(self):
        """The byte win's mechanism, pinned: the backward's only data
        dependency beyond dy is the int8 winner table at POOLED scale —
        x itself is not a residual (the jaxpr proves it: no f32 input-
        scale tensor flows from the fwd into the bwd closure)."""
        import jax.tree_util as jtu

        x = jax.random.normal(jax.random.PRNGKey(15), (2, 8, 8, 3))
        _, vjp = jax.vjp(
            lambda t: pooling._max_pool2d_indices(
                t, (2, 2), (2, 2), "SAME"), x)
        res_leaves = [l for l in jtu.tree_leaves(vjp)
                      if hasattr(l, "dtype")]
        # residuals: int8 winner table [2,4,4,3] + the zero-byte H,W
        # carrier; nothing at input scale, nothing floating-point
        assert all(l.dtype == jnp.int8 for l in res_leaves), \
            [(l.shape, str(l.dtype)) for l in res_leaves]
        assert all(l.size <= 2 * 4 * 4 * 3 for l in res_leaves)

    def test_fit_trains_identically_to_stock(self):
        """End-to-end: a conv+pool net fit under 'indices' walks the
        BITWISE same trajectory as stock (the arbiter's parity
        contract at network level)."""
        from deeplearning4j_tpu.nn import (ConvolutionLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           Nesterovs, OutputLayer,
                                           SubsamplingLayer)

        def run(impl):
            old = pooling._BACKWARD_IMPL
            pooling._BACKWARD_IMPL = impl
            try:
                conf = (NeuralNetConfiguration.Builder()
                        .seed(21).updater(Nesterovs(0.1, 0.9))
                        .activation("relu").list()
                        .layer(ConvolutionLayer(nOut=4,
                                                kernelSize=(3, 3)))
                        .layer(SubsamplingLayer(poolingType="max",
                                                kernelSize=(2, 2),
                                                stride=(2, 2)))
                        .layer(OutputLayer(nOut=5, activation="softmax",
                                           lossFunction="mcxent"))
                        .setInputType(InputType.convolutional(10, 10, 1))
                        .build())
                net = MultiLayerNetwork(conf).init()
                rng = np.random.RandomState(3)
                x = rng.rand(8, 1, 10, 10).astype("float32")
                y = np.eye(5, dtype="float32")[rng.randint(0, 5, 8)]
                for _ in range(3):
                    net.fit(x, y)
                return net
            finally:
                pooling._BACKWARD_IMPL = old

        net_i, net_s = run("indices"), run("stock")
        for a, b in zip(jax.tree_util.tree_leaves(net_i._params),
                        jax.tree_util.tree_leaves(net_s._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestGlobalMaxIndices:
    @pytest.mark.parametrize("shape,axes", [
        ((4, 6, 6, 3), (1, 2)),      # NHWC spatial
        ((4, 5, 6, 7, 3), (1, 2, 3)),  # NDHWC
        ((4, 3, 9), (2,)),            # NCW time pooling
    ])
    def test_parity_on_tie_free_data(self, shape, axes, monkeypatch):
        monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD", "indices")
        x = jax.random.normal(jax.random.PRNGKey(31), shape,
                              dtype=jnp.float64)
        y = pooling.global_pool(x, "max", axes)
        y_ref = jnp.max(x, axis=axes)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
        g = jax.grad(lambda t: jnp.sum(
            pooling.global_pool(t, "max", axes) ** 2))(x)
        g_ref = jax.grad(lambda t: jnp.sum(
            jnp.max(t, axis=axes) ** 2))(x)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(g_ref))

    def test_tie_semantics_first_match_vs_stock_spread(self,
                                                       monkeypatch):
        """Documented divergence ON TIES ONLY: stock jnp.max autodiff
        SPLITS the cotangent evenly among tied maxima; the indices
        backward routes the whole of it to the FIRST (the
        subsampling-pool / select-and-scatter convention). Both
        conserve mass; they place it differently. Ties at float
        activation scale are measure-zero — tie-free parity above is
        bitwise."""
        x = jnp.ones((1, 3, 1), jnp.float32)  # all tied
        g_stock = jax.grad(
            lambda t: jnp.sum(jnp.max(t, axis=(1,))))(x)
        monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD", "indices")
        g_idx = jax.grad(
            lambda t: jnp.sum(pooling.global_pool(t, "max", (1,))))(x)
        assert float(jnp.sum(g_idx)) == 1.0    # mass conserved
        assert float(jnp.sum(g_stock)) == 1.0  # stock conserves too
        np.testing.assert_array_equal(
            np.asarray(g_idx)[0, :, 0], [1.0, 0.0, 0.0])  # first wins
        np.testing.assert_allclose(
            np.asarray(g_stock)[0, :, 0], [1 / 3] * 3, rtol=1e-6)

    def test_negative_axes_normalized(self, monkeypatch):
        """(-2, -1) is valid for the stock jnp.max path — the indices
        route must normalize rather than crash (review finding)."""
        monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD", "indices")
        x = jax.random.normal(jax.random.PRNGKey(40), (2, 3, 4),
                              dtype=jnp.float64)
        y = pooling.global_pool(x, "max", (-2, -1))
        np.testing.assert_array_equal(np.asarray(y),
                                      np.asarray(jnp.max(x, axis=(1, 2))))
        g = jax.grad(lambda t: jnp.sum(
            pooling.global_pool(t, "max", (-2, -1)) ** 2))(x)
        g_ref = jax.grad(lambda t: jnp.sum(
            jnp.max(t, axis=(1, 2)) ** 2))(x)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(g_ref))

    def test_masked_and_stock_mode_unrouted(self, monkeypatch):
        """The indices route must not touch masked pooling or non-max
        types — they keep the legacy path bit-for-bit."""
        monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD", "indices")
        x = jax.random.normal(jax.random.PRNGKey(33), (2, 4, 6))
        mask = jnp.asarray(
            np.random.RandomState(0).rand(2, 4, 6) > 0.3)
        y = pooling.global_pool(x, "max", (2,), mask=mask)
        monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD", "stock")
        y_ref = pooling.global_pool(x, "max", (2,), mask=mask)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
        for t in ("avg", "sum", "pnorm"):
            monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD",
                                "indices")
            a = pooling.global_pool(x, t, (2,))
            monkeypatch.setattr(pooling, "_GLOBAL_MAXPOOL_BWD", "stock")
            b = pooling.global_pool(x, t, (2,))
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

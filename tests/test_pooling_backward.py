"""Argmax-routed maxpool backward vs the select-and-scatter oracle.

The custom VJP in ops/pooling.py exists to kill the single largest HBM
consumer in the ResNet-50 train step (206 MB select-and-scatter, see
BENCH_NOTES.md). These tests pin (a) forward parity, (b) exact gradient
parity with JAX's stock reduce_window gradient — including on tied inputs,
where both sides must route to the FIRST maximal window element — and
(c) that the compiled gradient HLO actually contains no select-and-scatter
(anti-silent-fallback, same pattern as tests/test_attention.py's routing
assertion).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import pooling


@pytest.fixture(autouse=True)
def _argmax_impl(monkeypatch):
    """This whole file tests the ARGMAX rewrite. The library default is
    stock (the measured winner on CPU and TPU v5e — BENCH_NOTES.md), so
    without this pin every new-vs-reference parity assertion would
    compare the stock path against itself and pass vacuously."""
    monkeypatch.setattr(pooling, "_BACKWARD_IMPL", "argmax")


CASES = [
    # kernel, stride, padding  (ResNet stem pool = 3x3/2 SAME is the target)
    ((3, 3), (2, 2), "SAME"),
    ((2, 2), (2, 2), "SAME"),
    ((3, 3), (2, 2), ((1, 1), (1, 1))),
    ((2, 2), (2, 2), ((0, 0), (0, 0))),
    ((3, 2), (1, 2), ((0, 1), (1, 0))),  # asymmetric everything
    ((3, 3), (1, 1), "SAME"),            # fully overlapping windows
]


def _loss_pair(kernel, stride, padding):
    def loss_new(x, dy):
        return jnp.sum(pooling.max_pool2d(x, kernel, stride, padding) * dy)

    def loss_ref(x, dy):
        return jnp.sum(
            pooling.max_pool2d_reference(x, kernel, stride, padding) * dy)

    return loss_new, loss_ref


@pytest.mark.parametrize("kernel,stride,padding", CASES)
def test_forward_matches_reference(kernel, stride, padding):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 13, 11, 5))
    y = pooling.max_pool2d(x, kernel, stride, padding)
    y_ref = pooling.max_pool2d_reference(x, kernel, stride, padding)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))


@pytest.mark.parametrize("kernel,stride,padding", CASES)
def test_gradient_matches_select_and_scatter(kernel, stride, padding):
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 13, 11, 5), dtype=jnp.float64)
    loss_new, loss_ref = _loss_pair(kernel, stride, padding)
    dy_shape = pooling.max_pool2d_reference(x, kernel, stride, padding).shape
    dy = jax.random.normal(jax.random.PRNGKey(2), dy_shape, dtype=jnp.float64)
    g_new = jax.grad(loss_new)(x, dy)
    g_ref = jax.grad(loss_ref)(x, dy)
    # atol floor: overlapping windows sum several dy terms in a different
    # association order than select-and-scatter — fp64 ulps, nothing more.
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=0, atol=1e-12)


@pytest.mark.parametrize("kernel,stride,padding", CASES)
def test_gradient_tie_routing_matches(kernel, stride, padding):
    # Integer-valued floats force many intra-window ties (the post-relu
    # regime the ResNet stem pool actually sees: lots of equal zeros).
    # XLA's select-and-scatter ge-select routes to the first maximal
    # element in window order; the argmax backward must do the same.
    key = jax.random.PRNGKey(3)
    x = jnp.floor(
        jax.random.uniform(key, (2, 12, 10, 4), dtype=jnp.float64) * 3.0)
    x = jnp.maximum(x - 1.0, 0.0)  # plenty of exact zeros
    loss_new, loss_ref = _loss_pair(kernel, stride, padding)
    dy_shape = pooling.max_pool2d_reference(x, kernel, stride, padding).shape
    dy = jax.random.normal(jax.random.PRNGKey(4), dy_shape, dtype=jnp.float64)
    g_new = jax.grad(loss_new)(x, dy)
    g_ref = jax.grad(loss_ref)(x, dy)
    # A routing (tie-break) divergence would show up as a FULL dy-sized
    # mismatch at some element, not an ulp — atol=1e-12 still catches it.
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=0, atol=1e-12)


def test_finite_difference_gradcheck():
    # fp64 central differences at a tie-free point.
    rng = np.random.default_rng(7)
    x = np.asarray(
        jax.random.permutation(jax.random.PRNGKey(5), 1 * 8 * 7 * 3),
        dtype=np.float64).reshape(1, 8, 7, 3) * 0.01  # distinct values, no ties

    def loss(xx):
        return jnp.sum(jnp.sin(pooling.max_pool2d(xx, (3, 3), (2, 2), "SAME")))

    g = np.asarray(jax.grad(loss)(jnp.asarray(x)))
    eps = 1e-6
    for _ in range(20):
        i = tuple(rng.integers(0, d) for d in x.shape)
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        fd = (float(loss(jnp.asarray(xp))) - float(loss(jnp.asarray(xm)))) / (2 * eps)
        assert abs(fd - g[i]) < 1e-5, (i, fd, g[i])


def test_no_select_and_scatter_in_grad_hlo():
    # The point of the custom VJP: with the argmax impl selected (the
    # file-wide fixture), the compiled backward must not contain
    # select-and-scatter. Fails loudly if the routing ever bypasses the
    # rewrite (e.g. wrapper bypass).
    def loss(x):
        return jnp.sum(pooling.max_pool2d(x, (3, 3), (2, 2), "SAME") ** 2)

    # Check the pre-optimization StableHLO: the CPU backend later rewrites
    # select_and_scatter into scatter, which would mask the distinction in
    # compiled text (TPU keeps it, and there it is the expensive op).
    x = jnp.ones((2, 16, 16, 4), jnp.float32)
    hlo = jax.jit(jax.grad(loss)).lower(x).as_text()
    assert "select_and_scatter" not in hlo and "scatter" not in hlo

    def loss_ref(x):
        return jnp.sum(
            pooling.max_pool2d_reference(x, (3, 3), (2, 2), "SAME") ** 2)

    hlo_ref = jax.jit(jax.grad(loss_ref)).lower(x).as_text()
    assert "select_and_scatter" in hlo_ref, (
        "oracle lost its select-and-scatter — parity tests no longer "
        "compare against the stock path")


def test_large_window_falls_back_to_reference():
    # >36-element windows route to the stock gradient by design.
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 32, 32, 2))
    y = pooling.max_pool2d(x, (7, 7), (7, 7), ((0, 0), (0, 0)))
    y_ref = pooling.max_pool2d_reference(x, (7, 7), (7, 7), ((0, 0), (0, 0)))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))


def test_forward_mode_ad_documented_behavior():
    # Pinned tradeoff (see max_pool2d docstring): reverse-mode rules out
    # forward-mode through the custom vjp; the reference path keeps it.
    x = jnp.ones((1, 4, 4, 1))
    with pytest.raises(TypeError, match="forward-mode|jvp"):
        jax.jacfwd(lambda t: pooling.max_pool2d(t, (2, 2), (2, 2), "SAME"))(x)
    jac = jax.jacfwd(
        lambda t: pooling.max_pool2d_reference(t, (2, 2), (2, 2), "SAME"))(x)
    assert np.isfinite(np.asarray(jac)).all()


def test_bf16_dtype_preserved():
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 8, 8, 3)).astype(jnp.bfloat16)
    y = pooling.max_pool2d(x, (3, 3), (2, 2), "SAME")
    assert y.dtype == jnp.bfloat16

    def loss(xx):
        return jnp.sum(pooling.max_pool2d(xx, (3, 3), (2, 2), "SAME").astype(jnp.float32))

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.bfloat16

"""Compressed gradient collectives (ISSUE 11): Strom-2015 threshold
encoding with error-feedback residuals, EQuARX-style block-quantized
allreduce (PAPERS.md arXiv:2506.17615), and their composition with the
ZeRO sharded weight update.

Proof layers on the virtual 8-device CPU mesh:

- encoder exactness: the fixed-capacity threshold encoder's
  dense + residual == input BITWISE, and a synthetic drain shows the
  transmitted stream + final residual reconstruct the dense gradient
  sum exactly (error feedback loses nothing);
- subject parity: gradient_compression="threshold" trains the LeNet and
  resnet_block attribution subjects to loss parity with the dense psum
  within the documented tolerance (docs/PARALLEL.md), with ONE compile
  per config (RetraceSentinel);
- resilience: ResilientFit mid-epoch preempt+resume under "threshold"
  matches the uninterrupted run bitwise — the residual + live tau ride
  the checkpoint (writeModel trainer_state);
- composition: weight_update="sharded" stacks with "int8"/"block_int8"
  (quantized reduce-scatter -> local 1/dp shard update -> all-gather)
  and matches the replicated compressed path bitwise;
- the bytes bill: measured collective bytes of compiled dp8 steps land
  within 10% of the analytic compressed_hlo_collective_bytes model per
  mode, and block_int8's bytes-on-wire is <= 30% of dense (the tier-1
  ceiling that catches lowering regressions statically).
"""

import numpy as np
import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import pytest

from deeplearning4j_tpu.nn import (
    NeuralNetConfiguration, InputType, MultiLayerNetwork,
    DenseLayer, OutputLayer, Adam, Sgd,
)
from deeplearning4j_tpu.data import DataSetIterator
from deeplearning4j_tpu.ndarray.compression import (
    BasicNDArrayCompressor, threshold_cap, threshold_encode_fixed,
)
from deeplearning4j_tpu.parallel import (
    AdaptiveThresholdAlgorithm, FixedThresholdAlgorithm,
    ParallelWrapper, ResidualClippingPostProcessor, SharedTrainingMaster,
    TargetSparsityThresholdAlgorithm, compressed_hlo_collective_bytes,
    compressed_wire_bytes, data_parallel_mesh, dp_weight_update_bytes,
)

DP = 8


def _mesh():
    return data_parallel_mesh()


def _mlp(seed=42, nin=256, h1=512, h2=256, nout=8, updater=None,
         lr=1e-2):
    return (NeuralNetConfiguration.Builder()
            .seed(seed).updater(updater or Adam(lr)).activation("relu")
            .list()
            .layer(DenseLayer(nOut=h1))
            .layer(DenseLayer(nOut=h2))
            .layer(OutputLayer(nOut=nout, activation="softmax"))
            .setInputType(InputType.feedForward(nin))
            .build())


def _data(n=64, nin=256, nout=8, seed=0):
    rng = np.random.RandomState(seed)
    yi = rng.randint(0, nout, n)
    x = (np.eye(nout)[yi] @ rng.randn(nout, nin)
         + 0.1 * rng.randn(n, nin)).astype("float32")
    return x, np.eye(nout, dtype="float32")[yi]


def _assert_tree_equal(a, b):
    for la, lb in zip(jtu.tree_leaves(a), jtu.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ----------------------------------------------------------------------
# the encoder: exactness is the whole point of error feedback
# ----------------------------------------------------------------------
class TestThresholdEncoder:
    def test_cap_is_static_and_bounded(self):
        assert threshold_cap(100, 0.125) == 13
        assert threshold_cap(1, 0.125) == 1      # never 0
        assert threshold_cap(100, 1.0) == 100
        assert threshold_cap(100, 2.0) == 100    # clamped to n

    def test_invariant_bitwise(self):
        rng = np.random.RandomState(3)
        flat = jnp.asarray(rng.randn(257).astype("float32"))
        tau = jnp.float32(0.4)
        for cap in (1, 8, 64, 257):
            idx, val, dense, res = threshold_encode_fixed(flat, tau, cap)
            assert idx.shape == (cap,) and val.shape == (cap,)
            # residual = input - wire message, computed in one f32
            # subtraction: reconstruction is exact to 1 ulp on arbitrary
            # data (and BITWISE on a representable grid — the exact-
            # arithmetic drain test below pins that)
            np.testing.assert_allclose(np.asarray(dense + res),
                                       np.asarray(flat), rtol=2e-7,
                                       atol=0)
            grid = jnp.round(flat * 4) / 4  # 0.25-grid: subtraction exact
            _, _, gd, gr = threshold_encode_fixed(grid, jnp.float32(0.5),
                                                  cap)
            np.testing.assert_array_equal(np.asarray(gd + gr),
                                          np.asarray(grid))
            # transmitted values are exactly +-tau or 0 (sign encoding)
            v = np.asarray(val)
            assert set(np.unique(np.abs(v))) <= \
                {np.float32(0.0), np.float32(0.4)}
            # nothing below tau transmits
            d = np.asarray(dense)
            sent = np.flatnonzero(d)
            assert np.all(np.abs(np.asarray(flat))[sent] >= 0.4)
            assert len(sent) <= cap

    def test_candidates_are_top_magnitude(self):
        flat = jnp.asarray(
            np.array([0.1, -5.0, 0.2, 3.0, -0.3], np.float32))
        idx, val, dense, _ = threshold_encode_fixed(
            flat, jnp.float32(0.25), 2)
        # capacity 2 picks |.|-largest entries 1 and 3; 0.3 at index 4
        # is above tau but over capacity — it stays in the residual
        assert set(np.asarray(idx).tolist()) == {1, 3}
        d = np.asarray(dense)
        assert d[1] == -0.25 and d[3] == 0.25 and d[4] == 0.0

    def test_degenerate_tiny_leaf_cap_rounds_to_one(self):
        """n < 1/capacity: the cap rounds UP to one pair (never 0 — a
        leaf must always be able to drain). The hierarchical leader hop
        hits this shape routinely: a bias leaf split into group_size
        shards can leave each chip with a handful of elements."""
        for n in (1, 2, 3, 7):
            flat = jnp.asarray(np.full(n, 0.5, np.float32))
            cap = threshold_cap(n, 0.125)
            assert cap == 1
            idx, val, dense, res = threshold_encode_fixed(
                flat, jnp.float32(0.25), cap)
            assert idx.shape == (1,) and val.shape == (1,)
            # exactly one +-tau transmits; the rest stays residual
            assert np.sum(np.abs(np.asarray(dense)) > 0) == 1
            np.testing.assert_allclose(np.asarray(dense + res),
                                       np.asarray(flat), rtol=2e-7)

    def test_degenerate_all_zero_leaf(self):
        """An all-zero gradient leaf (frozen layer, padded shard tail)
        transmits NOTHING — the fixed-capacity slots fill with value 0,
        the scatter-add is a no-op, and the residual stays zero. The
        hierarchical mode's zero-padding of leaves to a group_size
        multiple depends on exactly this."""
        for n in (1, 8, 100):
            flat = jnp.zeros(n, jnp.float32)
            cap = threshold_cap(n, 0.125)
            idx, val, dense, res = threshold_encode_fixed(
                flat, jnp.float32(1e-3), cap)
            assert np.all(np.asarray(val) == 0)
            assert np.all(np.asarray(dense) == 0)
            assert np.all(np.asarray(res) == 0)
            # indices stay in range so the scatter-add is well-defined
            assert np.all((np.asarray(idx) >= 0)
                          & (np.asarray(idx) < n))

    def test_degenerate_leaf_at_min_shard_size(self):
        """A leaf of exactly min_shard_size (2**16) elements — the ZeRO
        eligibility boundary, and a realistic per-chip shard under the
        hierarchical exchange — encodes with a full-size static cap and
        reconstructs to 1 ulp."""
        n = 2 ** 16
        rng = np.random.RandomState(7)
        flat = jnp.asarray(rng.randn(n).astype("float32"))
        cap = threshold_cap(n, 0.125)
        assert cap == n // 8
        idx, val, dense, res = threshold_encode_fixed(
            flat, jnp.float32(0.5), cap)
        assert idx.shape == (cap,)
        np.testing.assert_allclose(np.asarray(dense + res),
                                   np.asarray(flat), rtol=2e-7, atol=0)
        sent = np.asarray(dense)
        nz = np.flatnonzero(sent)
        assert len(nz) <= cap
        assert np.all(np.abs(np.asarray(flat))[nz] >= 0.5)

    def test_drain_reconstructs_dense_sum_exactly(self):
        """Synthetic drain (the acceptance gate): a constant gradient g
        with power-of-two-representable entries and tau=0.5 keeps every
        f32 add/sub exact — after T steps the transmitted stream plus
        the final residual equal T*g BITWISE (dense-equivalence after
        residual drain)."""
        g = jnp.asarray(
            np.array([0.25, -1.5, 0.75, 0.0, 2.0, -0.25, 0.5, -0.75],
                     np.float32))
        tau = jnp.float32(0.5)
        res = jnp.zeros_like(g)
        sent = jnp.zeros_like(g)
        T = 16
        for _ in range(T):
            acc = g + res
            _, _, dense, res = threshold_encode_fixed(acc, tau, 4)
            sent = sent + dense
        np.testing.assert_array_equal(np.asarray(sent + res),
                                      np.asarray(g * T))


# ----------------------------------------------------------------------
# the host-side THRESHOLD codec (satellite: ndarray/compression.py)
# ----------------------------------------------------------------------
class TestThresholdCodec:
    def test_round_trip(self):
        c = BasicNDArrayCompressor.getInstance()
        x = np.array([[0.5, -0.01], [-2.0, 0.003]], np.float32)
        comp = c.compress(x, "THRESHOLD", threshold=0.1)
        assert comp.algo == "THRESHOLD"
        out = c.decompress(comp).toNumpy()
        np.testing.assert_array_equal(
            out, np.array([[0.1, 0.0], [-0.1, 0.0]], np.float32))
        assert out.dtype == np.float32

    def test_matches_step_encoder(self):
        """The codec is the host twin of the step's encoder: at full
        capacity the dense wire message is identical."""
        rng = np.random.RandomState(7)
        x = rng.randn(64).astype("float32")
        tau = 0.5
        c = BasicNDArrayCompressor.getInstance()
        dec = c.decompress(c.compress(x, "THRESHOLD",
                                      threshold=tau)).toNumpy()
        _, _, dense, _ = threshold_encode_fixed(
            jnp.asarray(x), jnp.float32(tau), x.size)
        np.testing.assert_array_equal(dec, np.asarray(dense))

    def test_all_below_tau_short_circuit(self):
        c = BasicNDArrayCompressor.getInstance()
        x = np.full((4, 4), 1e-4, np.float32)
        comp = c.compress(x, "THRESHOLD", threshold=0.5)
        assert comp.extra["indices"].size == 0
        assert comp.compressedBytes() < comp.originalBytes()
        np.testing.assert_array_equal(c.decompress(comp).toNumpy(),
                                      np.zeros((4, 4), np.float32))

    def test_size_zero_short_circuit(self):
        c = BasicNDArrayCompressor.getInstance()
        comp = c.compress(np.zeros((0,), np.float32), "THRESHOLD")
        assert c.decompress(comp).toNumpy().shape == (0,)

    def test_rejections(self):
        c = BasicNDArrayCompressor.getInstance()
        with pytest.raises(ValueError, match="float"):
            c.compress(np.arange(4), "THRESHOLD")
        with pytest.raises(ValueError, match="threshold"):
            c.compress(np.zeros(4, np.float32), "THRESHOLD",
                       threshold=0.0)
        assert "THRESHOLD" in c.getAvailableCompressors()


# ----------------------------------------------------------------------
# subject parity: threshold trains LeNet + resnet_block on the dp8 mesh
# ----------------------------------------------------------------------
@pytest.mark.parametrize("subject", ["lenet", "resnet_block"])
def test_threshold_trains_subject_to_loss_parity(subject):
    """The acceptance gate: gradient_compression='threshold' trains the
    attribution subjects on the 8-virtual-device mesh with ONE compile
    (RetraceSentinel) and tracks the dense run per the documented
    tolerance (docs/PARALLEL.md): LeNet's loss lands within 25%
    relative of the dense loss after 6 steps; the resnet_block subject
    (Nesterovs lr 0.1 — a regime where the dense trajectory itself
    oscillates early) gates on smooth monotone descent of >= 25% over
    8 steps, the threshold mode's actual signature."""
    from deeplearning4j_tpu.analysis.hbm import build_subject
    from deeplearning4j_tpu.analysis.retrace import RetraceSentinel

    B = DP if subject == "lenet" else 2 * DP
    steps = 6 if subject == "lenet" else 8
    losses = {}
    for mode in (None, "threshold"):
        net, x_shape, _ = build_subject(subject, batch_size=B)
        rng = np.random.RandomState(5)
        x = rng.rand(B, *x_shape[1:]).astype("float32")
        y = np.eye(10, dtype="float32")[rng.randint(0, 10, B)]
        kw = {} if mode is None else {
            "threshold": 1e-3, "encodingCapacity": 1.0}
        pw = ParallelWrapper(net, mesh=_mesh(),
                             gradient_compression=mode, **kw)
        sentinel = RetraceSentinel(max_compiles=1)
        pw._place_replicated()
        pw._jit = jax.jit(sentinel.wrap(pw.trainStep(), name="step"),
                          donate_argnums=(0, 1, 2))
        traj = []
        for _ in range(steps):
            pw.fit(x, y)
            traj.append(net.score())
        losses[mode] = traj
        assert np.isfinite(traj[-1]), (subject, mode, traj)
        assert sentinel.compiles("step") == 1
    dense, thr = losses[None], losses["threshold"]
    if subject == "lenet":
        assert abs(thr[-1] - dense[-1]) <= 0.25 * max(dense[-1], 0.5), (
            f"lenet: threshold loss {thr[-1]} vs dense {dense[-1]} — "
            "outside the documented 25% parity tolerance")
    else:
        assert all(b < a for a, b in zip(thr, thr[1:])), (
            f"resnet_block: threshold descent not monotone: {thr}")
        assert thr[-1] <= 0.75 * thr[0], (
            f"resnet_block: threshold improved only {thr[0]}->{thr[-1]}")


# ----------------------------------------------------------------------
# resilience: guard rollback + bitwise preempt/resume with residuals
# ----------------------------------------------------------------------
class TestResilientThreshold:
    def _wrap(self, seed=42):
        net = MultiLayerNetwork(
            _mlp(seed, nin=32, h1=64, h2=32, nout=4,
                 updater=Sgd(0.25))).init()
        return net, ParallelWrapper(net, mesh=_mesh(),
                                    gradient_compression="threshold",
                                    threshold=1e-2)

    def test_mid_epoch_resume_bitwise_with_residuals(self, tmp_path):
        from deeplearning4j_tpu.runtime.resilience import (
            FaultInjector, Preemption, ResilientFit)

        X, Y = _data(DP * 12, nin=32, nout=4)

        def it():
            return DataSetIterator(X, Y, DP * 2)

        n1, w1 = self._wrap()
        ResilientFit(w1).fit(it(), epochs=2)

        d = str(tmp_path / "ck")
        n2, w2 = self._wrap()
        inj = FaultInjector().killAfterStep(7)
        with pytest.raises(Preemption):
            ResilientFit(w2, d, saveEveryNIterations=3,
                         injector=inj).fit(it(), epochs=2)
        n3, w3 = self._wrap()
        ResilientFit(w3, d, saveEveryNIterations=3).fit(it(), epochs=2)
        _assert_tree_equal(n1._params, n3._params)
        # the error-feedback residual and the live tau came back too —
        # without them the resumed trajectory could not be bitwise
        _assert_tree_equal(w1._residual[0], w3._residual[0])
        _assert_tree_equal(w1._residual[1], w3._residual[1])

    def test_checkpoint_carries_trainer_state(self, tmp_path):
        """writeModel(trainer_state=...) round trip: the residual is a
        separate item and the NET state stays canonical (restores into
        any mode)."""
        from deeplearning4j_tpu.util.sharded_checkpoint import (
            ShardedModelSerializer, read_manifest, restore_trainer_state)

        x, y = _data(DP * 2, nin=32, nout=4)
        net, pw = self._wrap()
        pw.fit(x, y)
        p = str(tmp_path / "m")
        ts = pw._ckpt_trainer_state()
        assert ts is not None
        ShardedModelSerializer.writeModel(net, p, trainer_state=ts)
        assert read_manifest(p)["trainerState"] is True
        restored = ShardedModelSerializer.restore(p)
        # canonical plain updater state — NOT the packed threshold carry
        assert not isinstance(restored._upd_states, dict)
        abstract = jtu.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding), ts)
        back = restore_trainer_state(p, abstract)
        _assert_tree_equal(ts, back)

    def test_guard_rolls_back_residual_on_poisoned_step(self):
        from deeplearning4j_tpu.runtime.resilience import (
            FaultInjector, ResilientFit)

        X, Y = _data(DP * 8, nin=32, nout=4)

        n1, w1 = self._wrap()
        inj = FaultInjector().poisonStep(2)
        rf = ResilientFit(w1, injector=inj)
        rf.fit(DataSetIterator(X, Y, DP * 2), epochs=1)
        assert rf.skippedSteps == 1
        # the skipped step's params AND residual match a run that never
        # saw the poisoned batch's effect (the step was rolled back in
        # place, error feedback included)
        for leaf in jtu.tree_leaves(n1._params) \
                + jtu.tree_leaves(w1._residual[0]):
            assert np.isfinite(np.asarray(leaf)).all()


# ----------------------------------------------------------------------
# composition: compressed reduce-scatter x ZeRO sharded update
# ----------------------------------------------------------------------
class TestComposedShardedCompression:
    @pytest.mark.parametrize("mode", ["int8", "block_int8"])
    def test_parity_with_replicated_compressed_path(self, mode):
        """The quantized psum and the quantized reduce-scatter shard
        the SAME integer sums, so the composed path is BITWISE equal to
        the replicated compressed path."""
        x, y = _data()
        nets = {}
        for wu in ("replicated", "sharded"):
            net = MultiLayerNetwork(_mlp()).init()
            pw = ParallelWrapper(net, mesh=_mesh(),
                                 gradient_compression=mode,
                                 weight_update=wu, min_shard_size=1024)
            for _ in range(3):
                pw.fit(x, y)
            nets[wu] = (net, pw)
        _assert_tree_equal(nets["replicated"][0]._params,
                           nets["sharded"][0]._params)

    def test_sharded_state_layout_and_bytes(self):
        """The composed path keeps ZeRO's whole point: per-chip updater
        state is 1/dp for eligible leaves, allocated sharded."""
        x, y = _data()
        net = MultiLayerNetwork(_mlp()).init()
        pw = ParallelWrapper(net, mesh=_mesh(),
                             gradient_compression="block_int8",
                             weight_update="sharded",
                             min_shard_size=1024)
        pw.fit(x, y)
        specs = {str(l.sharding.spec)
                 for l in jtu.tree_leaves(net._upd_states)}
        assert "PartitionSpec('data',)" in specs
        measured = pw._zero.per_chip_state_bytes(net._upd_states)
        full = sum(int(np.prod(l.shape)) * l.dtype.itemsize * 2
                   for p in net._params for l in jtu.tree_leaves(p))
        assert measured < full / 2  # far below the replicated residency

    def test_fit_dataset_k_loop_composes(self):
        """stepsPerSync > 1 with the composed mode: the staged k-loop
        carries the sharded state through the quantized step."""
        X, Y = _data(DP * 8)
        net = MultiLayerNetwork(_mlp()).init()
        pw = ParallelWrapper(net, mesh=_mesh(),
                             gradient_compression="int8",
                             weight_update="sharded",
                             min_shard_size=1024)
        pw.fitDataSet(DataSetIterator(X, Y, DP * 2), stepsPerSync=2)
        assert np.isfinite(net.score())
        assert pw._fit_dataset_syncs == 2


# ----------------------------------------------------------------------
# the measured bytes gate (tier-1 CI ceiling per mode)
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def compiled_compressed_steps():
    """One dp8 compile per compression mode (plus the composed
    block_int8 x sharded form), shared by the measured-bytes gates."""
    x, y = _data()
    out = {}
    for name, kw in (
            ("int8", {"gradient_compression": "int8"}),
            ("block_int8", {"gradient_compression": "block_int8"}),
            ("threshold", {"gradient_compression": "threshold",
                           "threshold": 1e-3}),
            ("block_int8+zero", {"gradient_compression": "block_int8",
                                 "weight_update": "sharded",
                                 "min_shard_size": 1024}),
    ):
        net = MultiLayerNetwork(_mlp()).init()
        pw = ParallelWrapper(net, mesh=_mesh(), **kw)
        pw._place_replicated()
        pw._build_jit()
        xs = pw._shard_batch(jnp.asarray(x))
        ys = pw._shard_batch(jnp.asarray(y))
        low = pw._jit.lower(net._params, net._upd_states, net._states,
                            jnp.asarray(0, jnp.int32), xs, ys,
                            jax.random.key(0), None, None)
        out[name] = (net, pw, low.compile())
    return out


class TestMeasuredCollectiveBytes:
    """Measured collective bytes of the compiled dp8 step within 10% of
    the analytic compressed_hlo_collective_bytes bill — a lowering
    regression (e.g. the integer psum silently widening back to f32)
    fails statically, not on a TPU window."""

    def _measured(self, compiled, net):
        from deeplearning4j_tpu.util.hbm_ledger import attribute_ledger

        rec = attribute_ledger(compiled, net=net, x_shape=(64, 256),
                               optimizer_slots=2, top=80)
        rows = rec["bin_top"]["collective"]
        return sum(t["bytes"] for t in rows)

    def _leaf_elems(self, net):
        return [int(np.prod(l.shape))
                for p in net._params for l in jtu.tree_leaves(p)]

    @pytest.mark.parametrize("mode", ["int8", "block_int8", "threshold"])
    def test_replicated_modes_within_10pct(self, mode,
                                           compiled_compressed_steps):
        from deeplearning4j_tpu.analysis.collectives import check_bill

        net, pw, compiled = compiled_compressed_steps[mode]
        measured = self._measured(compiled, net)
        model = compressed_hlo_collective_bytes(
            self._leaf_elems(net), DP, mode,
            capacity=pw.encoding_capacity)
        # the reusable COL05 gate (analysis.collectives, ISSUE 14)
        rep = check_bill(measured, model, rel=0.10, where=mode)
        assert rep.ok, rep.format()

    def test_composed_mode_within_10pct(self, compiled_compressed_steps):
        from deeplearning4j_tpu.analysis.collectives import check_bill

        net, pw, compiled = compiled_compressed_steps["block_int8+zero"]
        measured = self._measured(compiled, net)
        z = pw._zero
        model = compressed_hlo_collective_bytes(
            self._leaf_elems(net), DP, "block_int8", sharded=True,
            eligible=lambda n: n >= 1024 and n % DP == 0)
        rep = check_bill(measured, model, rel=0.10,
                         where="block_int8+zero")
        assert rep.ok, rep.format()
        assert z is not None

    def test_block_int8_wire_under_30pct_of_dense(self):
        """The headline ceiling: block_int8's logical bytes-on-wire must
        stay at or under 30% of the dense all-reduce."""
        net = MultiLayerNetwork(_mlp()).init()
        G = sum(int(np.prod(l.shape)) * 4
                for p in net._params for l in jtu.tree_leaves(p))
        rec = compressed_wire_bytes(G, DP, "block_int8")
        assert rec["ratio"] <= 0.30, rec
        assert compressed_wire_bytes(G, DP, "int8")["ratio"] <= 0.27


# ----------------------------------------------------------------------
# the analytic bill (hand-computed) + PAR06
# ----------------------------------------------------------------------
class TestCompressedBills:
    def test_wire_hand_computed(self):
        # N = 1000 f32 elements, dp = 8; dense = 2*(7/8)*4000 = 7000
        rec = compressed_wire_bytes(4000, 8, None)
        assert rec["wire_bytes"] == 7000
        rec = compressed_wire_bytes(4000, 8, "int8")
        assert rec["wire_bytes"] == 2 * 7 * (1000 + 4) // 8 == 1757
        rec = compressed_wire_bytes(4000, 8, "block_int8", block=256)
        assert rec["wire_bytes"] == 2 * 7 * (1000 + 16) // 8 == 1778
        # threshold: cap = ceil(0.125*1000) = 125 pairs of 5 bytes,
        # ring-gathered to 7 peers
        rec = compressed_wire_bytes(4000, 8, "threshold")
        assert rec["wire_bytes"] == 7 * 125 * 5 == 4375
        # hierarchical dp8, group 4 (2 groups), block_int8 hop 1:
        #   hop1 (int8 RS)     = 3*(1000 + 4*ceil(1000/256))//4 = 762
        #   hop3 (f32 gather)  = 3*1000*4//4                    = 3000
        #   leader (Strom)     = (2-1)*ceil(250*0.125)*5        = 160
        rec = compressed_wire_bytes(4000, 8, "hierarchical",
                                    group_size=4)
        assert rec["intra_wire_bytes"] == 762 + 3000
        assert rec["leader_wire_bytes"] == 160
        assert rec["wire_bytes"] == 3922
        assert rec["groups"] == 2
        assert rec["flat_threshold_wire_bytes"] == 4375
        with pytest.raises(ValueError, match="gradient_compression"):
            compressed_wire_bytes(4000, 8, "sparse")
        with pytest.raises(ValueError, match="divisor"):
            compressed_wire_bytes(4000, 8, "hierarchical", group_size=3)
        with pytest.raises(ValueError, match="hierarchical"):
            compressed_wire_bytes(4000, 8, "threshold", group_size=4)

    def test_wire_hierarchical_crosses_past_dp128(self):
        """The tentpole's analytic crossover (the reason this mode
        exists): at dp128 the flat threshold wire is ~10x dense, while
        the 2-hop form undercuts BOTH — wire scales with
        capacity x groups, not capacity x dp."""
        rec = compressed_wire_bytes(4000, 128, "hierarchical",
                                    group_size=8)
        flat = compressed_wire_bytes(4000, 128, "threshold")
        assert rec["wire_bytes"] < flat["wire_bytes"]
        assert rec["wire_bytes"] < rec["dense_wire_bytes"]
        assert rec["vs_flat_threshold"] < 0.10
        # when it loses (documented note, PARALLEL.md): at small dp
        # with a SPARSE capacity the near-dense intra hops dominate and
        # flat threshold wins outright
        small = compressed_wire_bytes(4000, 8, "hierarchical",
                                      group_size=4, capacity=0.01)
        small_flat = compressed_wire_bytes(4000, 8, "threshold",
                                           capacity=0.01)
        assert small["wire_bytes"] > small_flat["wire_bytes"]

    def test_dp_weight_update_bytes_compression(self):
        G = 1000 * 4
        rec = dp_weight_update_bytes(G, dp=8, compression="int8")
        assert rec["gradient_compression"] == "int8"
        assert rec["compressed_wire"]["wire_bytes"] == 1757
        s = dp_weight_update_bytes(G, dp=8, opt_state_bytes=2 * G,
                                   sharded=True, compression="int8")
        # gradient half compressed, param all-gather stays dense
        assert s["compressed_reduce_scatter_bytes"] == 1757 // 2
        assert s["collective_wire_bytes_compressed"] == \
            1757 // 2 + s["all_gather_bytes"]
        with pytest.raises(ValueError, match="threshold"):
            dp_weight_update_bytes(G, dp=8, sharded=True,
                                   compression="threshold")

    def test_hlo_bill_threshold_shape(self):
        # one 100-elem leaf at capacity 0.125 -> cap 13; idx + value
        # gathers each charge (dp+1)*cap*4
        assert compressed_hlo_collective_bytes([100], 8, "threshold") \
            == 2 * 9 * 13 * 4
        # int8: scalar pmax (8 B) + int16 psum (4n)
        assert compressed_hlo_collective_bytes([100], 8, "int8") \
            == 8 + 4 * 100

    def test_par06_bills_compressed_wire(self):
        from deeplearning4j_tpu.analysis import validate_plan
        from deeplearning4j_tpu.analysis.partitioning import ShardingPlan

        conf = _mlp()
        r = validate_plan(conf, {"data": 8}, batchSize=64,
                          plan=ShardingPlan(
                              gradient_compression="block_int8"))
        mem = r.plan["memory"]
        assert mem["gradient_compression"] == "block_int8"
        gc = mem["grad_collective"]
        assert gc["mode"] == "block_int8"
        assert 0 < gc["wire_bytes"] < gc["dense_wire_bytes"]
        assert gc["ratio"] <= 0.30
        dense = validate_plan(conf, {"data": 8}, batchSize=64)
        assert dense.plan["memory"]["grad_collective"]["ratio"] == 1.0
        with pytest.raises(ValueError, match="gradient_compression"):
            ShardingPlan(gradient_compression="sparse")
        with pytest.raises(ValueError, match="threshold"):
            ShardingPlan(gradient_compression="threshold",
                         weight_update="sharded")


# ----------------------------------------------------------------------
# thresholdAlgorithm mapping (satellite: Builder -> real configs)
# ----------------------------------------------------------------------
class TestThresholdAlgorithmMapping:
    def _net(self):
        return MultiLayerNetwork(
            _mlp(nin=8, h1=16, h2=8, nout=3, updater=Sgd(0.1))).init()

    def test_fixed_and_adaptive_map_to_config(self):
        m = SharedTrainingMaster(self._net(),
                                 thresholdAlgorithm=FixedThresholdAlgorithm(1e-2))
        assert m.gradient_compression == "threshold"
        assert m.threshold == 1e-2 and m.targetSparsity is None
        m = SharedTrainingMaster(
            self._net(),
            thresholdAlgorithm=AdaptiveThresholdAlgorithm(1e-3, 0.05))
        assert m.threshold == 1e-3 and m.targetSparsity == 0.05
        m = SharedTrainingMaster(
            self._net(),
            thresholdAlgorithm=TargetSparsityThresholdAlgorithm(
                sparsityTarget=0.02, initialThreshold=2e-3))
        assert m.threshold == 2e-3 and m.targetSparsity == 0.02

    def test_unknown_algorithm_raises_naming_the_set(self):
        with pytest.raises(ValueError) as e:
            SharedTrainingMaster(self._net(),
                                 thresholdAlgorithm=object())
        msg = str(e.value)
        for name in ("FixedThresholdAlgorithm",
                     "AdaptiveThresholdAlgorithm",
                     "TargetSparsityThresholdAlgorithm"):
            assert name in msg

    def test_residual_clipping_wired_and_applied(self):
        m = SharedTrainingMaster(
            self._net(), thresholdAlgorithm=1e9,
            residualPostProcessor=ResidualClippingPostProcessor(2.0))
        assert m.residual_clip == 2.0
        assert m.residual_clip_frequency == 1
        # tau = 1e9 transmits nothing; with clipping the residual is
        # bounded by clip*tau... use a small tau to see the bound bite
        net = self._net()
        pw = ParallelWrapper(net, mesh=_mesh(),
                             gradient_compression="threshold",
                             threshold=1e-3, encodingCapacity=0.01,
                             residualClip=3.0)
        x, y = _data(DP * 2, nin=8, nout=3)
        for _ in range(20):
            pw.fit(x, y)
        lim = 3.0 * float(pw._residual[1]) * (1 + 1e-6)
        for leaf in jtu.tree_leaves(pw._residual[0]):
            assert float(jnp.max(jnp.abs(leaf))) <= lim

    def test_residual_post_processor_rejections(self):
        with pytest.raises(ValueError, match="ResidualClipping"):
            SharedTrainingMaster(self._net(), thresholdAlgorithm=1e-2,
                                 residualPostProcessor=object())
        with pytest.raises(ValueError, match="clipValue"):
            ResidualClippingPostProcessor(-1.0)

    def test_spark_builder_binds_real_config(self):
        from deeplearning4j_tpu.parallel import (
            SharedTrainingMasterBuilder)

        tm = (SharedTrainingMasterBuilder()
              .thresholdAlgorithm(AdaptiveThresholdAlgorithm(1e-3, 0.04))
              .residualPostProcessor(ResidualClippingPostProcessor(4.0))
              .encodingCapacity(0.5)
              .build())
        m = tm.bind(self._net(), _mesh())
        assert m.gradient_compression == "threshold"
        assert m.targetSparsity == 0.04
        assert m.residual_clip == 4.0
        assert m.encoding_capacity == 0.5

    def test_capacity_vs_target_validated(self):
        with pytest.raises(ValueError, match="encodingCapacity"):
            ParallelWrapper(self._net(),
                            gradient_compression="threshold",
                            targetSparsity=0.5, encodingCapacity=0.1)
        with pytest.raises(ValueError, match="compressionBlock"):
            ParallelWrapper(self._net(),
                            gradient_compression="block_int8",
                            compressionBlock=0)
        # a non-positive tau would transmit sign(g)*tau with the wrong
        # sign — gradient ASCENT — so it must be rejected up front
        with pytest.raises(ValueError, match="tau"):
            ParallelWrapper(self._net(),
                            gradient_compression="threshold",
                            threshold=-1e-3)
        with pytest.raises(ValueError, match="tau"):
            ParallelWrapper(self._net(),
                            gradient_compression="threshold",
                            threshold=0.0)

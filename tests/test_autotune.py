"""Runtime autotuning arbiter (runtime/autotune.py, docs/AUTOTUNE.md).

Layers of proof, cheapest first:

- registry plumbing: knob get/set/restore, registry <-> AOT ambient
  fingerprint sync (a knob the key cannot see would let a tuned and a
  stock run share an executable), key independence from the current
  knob values;
- store: JSON round trip through a real directory, stale-format and
  corrupt-file recovery, memory-tier reuse;
- the sweep on a TINY conv+pool subject (sub-second compiles): finds
  the indices pool backward on CPU, proves parity, persists — and a
  second-process call (fresh store instance on the same directory)
  recalls the winners with ZERO compiles (aot.CompileWatch gate) and
  zero re-sweeps;
- kernel routing compile-neutrality: a BN+pool network under the fused
  epilogue and tuned pooling still compiles its train step EXACTLY
  once across a multi-step fit (RetraceSentinel);
- the full LeNet-b64 sweep reproducing the banked winner table is
  marked slow (the pinned expectation rides the tier-1 tuned gate in
  test_hbm_attribution instead).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.runtime import aot
from deeplearning4j_tpu.runtime import autotune as at


def _tiny_pool_net(seed=3):
    """conv -> maxpool -> dense-10: the smallest subject whose train
    step the maxpool_bwd knob can rewrite (sub-second XLA compile)."""
    from deeplearning4j_tpu.nn import (ConvolutionLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer, SubsamplingLayer)

    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Nesterovs(0.1, 0.9))
            .activation("relu").list()
            .layer(ConvolutionLayer(nOut=4, kernelSize=(3, 3)))
            .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                    stride=(2, 2)))
            .layer(OutputLayer(nOut=10, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.convolutional(10, 10, 1))
            .build())
    return MultiLayerNetwork(conf).init(), (8, 1, 10, 10)


class TestKnobRegistry:
    def test_registry_matches_ambient_fingerprint(self):
        """Every registered knob must appear in the AOT ambient
        fingerprint under its own name — otherwise installing a tuned
        config could reuse a stock executable (the satellite-fix
        contract; the key-separation direction is gated in
        test_aot_cache)."""
        amb = aot.ambient_fingerprint()
        for knob in at.KNOBS:
            assert knob.name in amb, (
                f"knob {knob.name} missing from aot.ambient_fingerprint"
                " — tuned and stock runs could share an executable")
            assert amb[knob.name] == knob.get()

    def test_get_set_restore(self):
        knob = at._KNOBS_BY_NAME["maxpool_bwd"]
        old = knob.get()
        try:
            prev = knob.set("indices")
            assert prev == old
            assert knob.get() == "indices"
        finally:
            knob.set(old)
        with pytest.raises(ValueError, match="not in"):
            knob.set("definitely-not-an-impl")

    def test_applied_context_restores_on_exception(self):
        before = at.current_knobs()
        with pytest.raises(RuntimeError):
            with at.applied({"maxpool_bwd": "indices",
                             "bn_epilogue": "unfused"}):
                assert at.current_knobs()["maxpool_bwd"] == "indices"
                raise RuntimeError("boom")
        assert at.current_knobs() == before

    def test_install_returns_previous(self):
        before = at.current_knobs()
        old = at.install({"maxpool_bwd": "argmax"})
        try:
            assert old == {"maxpool_bwd": before["maxpool_bwd"]}
            assert at.current_knobs()["maxpool_bwd"] == "argmax"
        finally:
            at.install(old)
        assert at.current_knobs() == before

    def test_unknown_knob_rejected(self):
        net, x_shape = _tiny_pool_net()
        with pytest.raises(ValueError, match="unknown knob"):
            at.autotune(net, x_shape, knobs=["no_such_knob"],
                        store_=at.TuningStore())


class TestKey:
    def test_key_independent_of_current_knob_values(self):
        """The tuned process must look up the SAME record it wrote when
        stock — knob values are the tuning's output, not its key."""
        net, _ = _tiny_pool_net()
        k0 = at.tuning_key(net)
        with at.applied({"maxpool_bwd": "indices",
                         "bn_epilogue": "unfused",
                         "loss_tail": "wide"}):
            assert at.tuning_key(net) == k0

    def test_key_depends_on_program(self):
        net_a, _ = _tiny_pool_net(seed=3)
        net_b, _ = _tiny_pool_net(seed=4)  # different conf JSON
        assert at.tuning_key(net_a) != at.tuning_key(net_b)


class TestStore:
    def test_disk_round_trip_and_second_instance(self, tmp_path):
        st = at.TuningStore(str(tmp_path))
        rec = {"knobs": {"maxpool_bwd": "indices"}, "tuned_bytes": 42}
        st.put("k" * 64, rec)
        # fresh instance on the same dir = the second-process path
        st2 = at.TuningStore(str(tmp_path))
        got = st2.get("k" * 64)
        assert got["knobs"] == {"maxpool_bwd": "indices"}
        assert st2.stats["hits"] == 1

    def test_stale_format_removed(self, tmp_path):
        st = at.TuningStore(str(tmp_path))
        st.put("s" * 64, {"knobs": {}})
        path = st._path("s" * 64)
        import json

        with open(path, "r", encoding="utf-8") as fh:
            rec = json.load(fh)
        rec["tune_format"] = -1
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(rec, fh)
        st2 = at.TuningStore(str(tmp_path))
        assert st2.get("s" * 64) is None
        assert st2.stats["stale"] == 1
        assert not path or not __import__("os").path.exists(path)

    def test_corrupt_file_is_a_miss(self, tmp_path):
        st = at.TuningStore(str(tmp_path))
        with open(st._path("c" * 64), "w") as fh:
            fh.write("{not json")
        assert st.get("c" * 64) is None
        assert st.stats["corrupt"] == 1


class TestParity:
    def test_bitwise_and_tolerance_bands(self):
        ok = at._parity_ok
        assert ok([1.0, 0.5], [1.0, 0.5], 0.0)
        assert not ok([1.0, 0.5], [1.0, 0.5000001], 0.0)
        assert ok([1.0, 0.5], [1.001, 0.5005], 0.05)
        assert not ok([1.0, 0.5], [1.2, 0.5], 0.05)
        assert not ok([1.0, 0.5], [float("nan"), 0.5], 0.05)


class TestSweep:
    def test_tiny_sweep_finds_indices_and_persists(self, tmp_path):
        """The heart of ISSUE 12's acceptance, at tier-1 cost: the
        sweep adopts the indices pool backward on CPU (fewer attributed
        bytes, bitwise parity), persists the record, leaves the process
        knobs untouched — and the second-process call recalls it with
        ZERO compiles and zero re-sweeps."""
        net, x_shape = _tiny_pool_net()
        st = at.TuningStore(str(tmp_path))
        before = at.current_knobs()
        res = at.autotune(net, x_shape, knobs=["maxpool_bwd"],
                          store_=st, steps=2)
        assert res.swept
        assert at.current_knobs() == before  # sweep leaves no trace
        assert res.knobs["maxpool_bwd"] == "indices"
        assert res.tuned_bytes < res.baseline_bytes * 0.9
        adopted = [p for p in res.per_knob if p["verdict"] == "adopted"]
        assert [p["to"] for p in adopted] == ["indices"]

        # second process: fresh store instance on the same directory,
        # fresh AOT watch — the recall must compile NOTHING
        st2 = at.TuningStore(str(tmp_path))
        cache = aot.session_cache() or aot.enable()
        with aot.CompileWatch(cache) as watch:
            res2 = at.autotune(net, x_shape, knobs=["maxpool_bwd"],
                               store_=st2, steps=2)
        watch.assert_no_compiles("second-process autotune recall")
        assert not res2.swept
        assert res2.knobs == res.knobs
        assert res2.tuned_bytes == res.tuned_bytes

    def test_sweep_on_previously_fit_net_still_sees_knobs(self,
                                                          tmp_path):
        """Latent-bug regression (caught while verifying round 12):
        jax's global trace cache keys on bound-method equality, so
        after net.fit() a naive jax.jit(net._train_step).lower() serves
        the STALE pre-flip jaxpr and every candidate reads 'identical'.
        lower_train_step wraps the step in a fresh-identity lambda —
        a sweep on a trained net must still adopt the indices win."""
        import jax.numpy as jnp

        net, x_shape = _tiny_pool_net(seed=11)
        rng = np.random.RandomState(0)
        x = rng.rand(x_shape[0], *x_shape[1:]).astype("float32")
        y = np.eye(10, dtype="float32")[
            rng.randint(0, 10, x_shape[0])]
        for _ in range(2):
            net.fit(x, y)
        st = at.TuningStore(str(tmp_path))
        res = at.autotune(net, x_shape, knobs=["maxpool_bwd"],
                          store_=st, steps=2)
        assert res.knobs["maxpool_bwd"] == "indices"
        assert res.tuned_bytes < res.baseline_bytes * 0.9

    def test_force_resweeps(self, tmp_path):
        net, x_shape = _tiny_pool_net()
        st = at.TuningStore(str(tmp_path))
        at.autotune(net, x_shape, knobs=["maxpool_bwd"], store_=st,
                    steps=2)
        res = at.autotune(net, x_shape, knobs=["maxpool_bwd"],
                          store_=st, steps=2, force=True)
        assert res.swept

    def test_identical_hlo_candidates_skip_compiles(self, tmp_path):
        """A knob that cannot touch this program (flash_bwd on an
        attention-free CNN) must be detected by the HLO hash and cost
        zero compiles/parity runs."""
        net, x_shape = _tiny_pool_net(seed=5)
        st = at.TuningStore(str(tmp_path))
        # bn_tail is also a no-op here: an f32 net's wide/compute
        # tails lower identically (wide_tail is already true for f32)
        res = at.autotune(net, x_shape,
                          knobs=["flash_bwd", "bn_tail"],
                          store_=st, steps=2)
        verdicts = {p["knob"]: p["verdict"] for p in res.per_knob}
        assert verdicts == {"flash_bwd": "identical",
                            "bn_tail": "identical"}
        assert res.knobs["flash_bwd"] == "kernel"  # default kept

    def test_warm_start_installs_winners(self, tmp_path):
        net, x_shape = _tiny_pool_net()
        st = at.TuningStore(str(tmp_path))
        assert at.warm_start(net, store_=st) is None  # no record yet
        at.autotune(net, x_shape, knobs=["maxpool_bwd"], store_=st,
                    steps=2)
        before = at.current_knobs()
        try:
            installed = at.warm_start(net, store_=st)
            assert installed["maxpool_bwd"] == "indices"
            assert at.current_knobs()["maxpool_bwd"] == "indices"
        finally:
            at.install(before)

    def test_precompile_autotune_kwarg(self, tmp_path):
        """net.precompile(autotune=True) warms the TUNED program: the
        persisted knobs are installed before the executables warm."""
        net, x_shape = _tiny_pool_net()
        st = at.TuningStore(str(tmp_path))
        at.autotune(net, x_shape, knobs=["maxpool_bwd"], store_=st,
                    steps=2)
        before = at.current_knobs()
        prev_store = at._STORE
        at._STORE = st
        try:
            net.precompile(batchSize=x_shape[0], entries=("train",),
                           autotune=True)
            assert at.current_knobs()["maxpool_bwd"] == "indices"
        finally:
            at._STORE = prev_store
            at.install(before)


class TestKernelRoutingCompileNeutral:
    def test_single_compile_with_tuned_kernels(self):
        """RetraceSentinel proof (ISSUE 12 satellite): routing through
        the fused BN epilogue + indices pool backward adds ZERO extra
        compiles — a multi-step fit traces the train step exactly
        once, same as stock."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.analysis.retrace import RetraceSentinel
        from deeplearning4j_tpu.nn import (BatchNormalization,
                                           ConvolutionLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           Nesterovs, OutputLayer,
                                           SubsamplingLayer)

        with at.applied({"maxpool_bwd": "indices",
                         "bn_epilogue": "fused",
                         "global_maxpool_bwd": "indices"}):
            conf = (NeuralNetConfiguration.Builder()
                    .seed(9).updater(Nesterovs(0.1, 0.9))
                    .activation("relu").list()
                    .layer(ConvolutionLayer(nOut=4, kernelSize=(3, 3)))
                    .layer(BatchNormalization())
                    .layer(SubsamplingLayer(poolingType="max",
                                            kernelSize=(2, 2),
                                            stride=(2, 2)))
                    .layer(OutputLayer(nOut=5, activation="softmax",
                                       lossFunction="mcxent"))
                    .setInputType(InputType.convolutional(10, 10, 1))
                    .build())
            net = MultiLayerNetwork(conf).init()
            sentinel = RetraceSentinel(max_compiles=1)
            sentinel.install(net)
            rng = np.random.RandomState(0)
            x = rng.rand(8, 1, 10, 10).astype("float32")
            y = np.eye(5, dtype="float32")[rng.randint(0, 5, 8)]
            for _ in range(3):
                net.fit(x, y)
            assert sentinel.compiles("train_step") == 1


class TestBnEpilogue:
    """Fused BN -> activation (-> add) epilogue (ops/norm.py): parity
    against the stock composition, train + inference, every supported
    activation, plus the layer routing and the relu-bitwise contract."""

    def _data(self, seed=0, shape=(8, 6, 6, 5)):
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        C = shape[-1]
        return (jnp.asarray(rng.randn(*shape).astype("float32")),
                jnp.asarray(rng.rand(C).astype("float32") + 0.5),
                jnp.asarray(rng.randn(C).astype("float32")),
                jnp.asarray(rng.randn(C).astype("float32")),
                jnp.asarray(rng.rand(C).astype("float32") + 0.5))

    @pytest.mark.parametrize(
        "act", ["identity", "relu", "leakyrelu", "tanh", "sigmoid"])
    def test_train_fwd_bwd_parity(self, act):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn import activations as _act
        from deeplearning4j_tpu.ops import norm as N

        x, gm, bt, rm, rv = self._data()

        def f_fused(x, gm, bt):
            o, _rm, _rv = N.batch_norm_act(x, gm, bt, rm, rv,
                                           train=True, activation=act)
            return jnp.sum(o ** 2)

        def f_ref(x, gm, bt):
            y, _rm, _rv = N.batch_norm(x, gm, bt, rm, rv, train=True)
            return jnp.sum(_act.get(act)(y) ** 2)

        np.testing.assert_allclose(float(f_fused(x, gm, bt)),
                                   float(f_ref(x, gm, bt)), rtol=1e-6)
        gf = jax.grad(f_fused, argnums=(0, 1, 2))(x, gm, bt)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(x, gm, bt)
        # relu/leakyrelu/identity masks are exact functions of the
        # output sign — bitwise; tanh/sigmoid grad-from-output is
        # ulp-level vs autodiff-through-input
        exact = act in ("identity", "relu", "leakyrelu")
        for a, b in zip(gf, gr):
            if exact:
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
            else:
                np.testing.assert_allclose(np.asarray(a),
                                           np.asarray(b),
                                           rtol=2e-5, atol=2e-5)

    def test_relu_kink_subgradient_matches_registry(self):
        """The dead-channel regression (caught in round 12): an
        all-zero input channel with beta == 0 puts every element at
        the relu kink (y == 0 exactly). The epilogue must reproduce
        jax.nn.relu's grad(0) == 0 — dbeta for that channel is 0, not
        jnp.maximum's half-cotangent."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops import norm as N

        x = jnp.zeros((6, 2), jnp.float32).at[:, 1].set(jnp.asarray(
            np.random.RandomState(0).randn(6).astype("float32")))
        gm = jnp.ones(2, jnp.float32)
        bt = jnp.zeros(2, jnp.float32)  # channel 0 lands AT the kink
        w = jnp.asarray(np.random.RandomState(1).randn(6, 2)
                        .astype("float32"))

        def f_fused(bt):
            o, _m, _v = N._bn_act_train(x, gm, bt, 1e-5, "relu")
            return jnp.sum(w * o)

        def f_legacy(bt):
            y, _m, _v = N._bn_train(x, gm, bt, 1e-5)
            return jnp.sum(w * jax.nn.relu(y))

        gf = jax.grad(f_fused)(bt)
        gl = jax.grad(f_legacy)(bt)
        np.testing.assert_array_equal(np.asarray(gf), np.asarray(gl))
        assert float(gf[0]) == 0.0  # the kink channel: zero, not half

    def test_running_stats_match_stock(self):
        from deeplearning4j_tpu.ops import norm as N

        x, gm, bt, rm, rv = self._data(seed=1)
        _o, rm_f, rv_f = N.batch_norm_act(x, gm, bt, rm, rv, train=True,
                                          activation="relu")
        _y, rm_s, rv_s = N.batch_norm(x, gm, bt, rm, rv, train=True)
        np.testing.assert_array_equal(np.asarray(rm_f), np.asarray(rm_s))
        np.testing.assert_array_equal(np.asarray(rv_f), np.asarray(rv_s))

    def test_inference_parity(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn import activations as _act
        from deeplearning4j_tpu.ops import norm as N

        x, gm, bt, rm, rv = self._data(seed=2)
        o, _m, _v = N.batch_norm_act(x, gm, bt, rm, rv, train=False,
                                     activation="sigmoid")
        y, _m2, _v2 = N.batch_norm(x, gm, bt, rm, rv, train=False)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_act.get("sigmoid")(y)),
            rtol=1e-6, atol=1e-7)

    def test_residual_add_fused(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops import norm as N

        x, gm, bt, rm, rv = self._data(seed=3)
        res = jnp.asarray(np.random.RandomState(9).randn(
            *x.shape).astype("float32"))

        def f_fused(x, res):
            o, _m, _v = N.batch_norm_act(x, gm, bt, rm, rv, train=True,
                                         activation="relu",
                                         residual=res)
            return jnp.sum(o ** 2)

        def f_ref(x, res):
            y, _m, _v = N.batch_norm(x, gm, bt, rm, rv, train=True)
            return jnp.sum(jnp.maximum(y + res, 0) ** 2)

        gf = jax.grad(f_fused, argnums=(0, 1))(x, res)
        gr = jax.grad(f_ref, argnums=(0, 1))(x, res)
        for a, b in zip(gf, gr):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_unsupported_activation_raises_op_level(self):
        from deeplearning4j_tpu.ops import norm as N

        x, gm, bt, rm, rv = self._data(seed=4)
        with pytest.raises(ValueError, match="not epilogue-fusable"):
            N.batch_norm_act(x, gm, bt, rm, rv, train=True,
                             activation="swish")
        assert not N.bn_act_supported("swish")
        assert N.bn_act_supported("relu")

    def test_unfused_knob_is_stock_composition(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn import activations as _act
        from deeplearning4j_tpu.ops import norm as N

        x, gm, bt, rm, rv = self._data(seed=5)
        with at.applied({"bn_epilogue": "unfused"}):
            o, _m, _v = N.batch_norm_act(x, gm, bt, rm, rv, train=True,
                                         activation="relu")
        y, _m2, _v2 = N.batch_norm(x, gm, bt, rm, rv, train=True)
        np.testing.assert_array_equal(
            np.asarray(o), np.asarray(_act.get("relu")(y)))

    def test_bn_layer_trains_bitwise_fused_vs_unfused(self):
        """Network-level: a conv+BN(relu) net walks the BITWISE same
        trajectory under both epilogue modes — including the relu-kink
        subgradient at a dead conv channel (all-zero BN input + zero
        beta puts the WHOLE channel at y == 0 exactly at init; the
        epilogue must reproduce jax.nn.relu's grad(0) == 0 convention,
        which the out>0 strict mask does — the bug this test caught
        during round 12: jnp.maximum's half-gradient at the kink)."""
        import jax

        from deeplearning4j_tpu.nn import (BatchNormalization,
                                           ConvolutionLayer, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration,
                                           Nesterovs, OutputLayer)

        def run(mode):
            with at.applied({"bn_epilogue": mode}):
                conf = (NeuralNetConfiguration.Builder()
                        .seed(17).updater(Nesterovs(0.1, 0.9))
                        .activation("relu").list()
                        .layer(ConvolutionLayer(nOut=4,
                                                kernelSize=(3, 3)))
                        .layer(BatchNormalization())
                        .layer(OutputLayer(nOut=5, activation="softmax",
                                           lossFunction="mcxent"))
                        .setInputType(
                            InputType.convolutional(8, 8, 1))
                        .build())
                net = MultiLayerNetwork(conf).init()
                rng = np.random.RandomState(1)
                x = rng.rand(8, 1, 8, 8).astype("float32")
                y = np.eye(5, dtype="float32")[rng.randint(0, 5, 8)]
                for _ in range(3):
                    net.fit(x, y)
                return net

        net_f, net_u = run("fused"), run("unfused")
        for a, b in zip(jax.tree_util.tree_leaves(net_f._params),
                        jax.tree_util.tree_leaves(net_u._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(net_f._states),
                        jax.tree_util.tree_leaves(net_u._states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rnn_bn_layer_parity(self):
        """The [B,F,T] recurrent BN path (transpose -> BN -> transpose)
        routes through the epilogue too — parity with unfused."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.layers import BatchNormalization

        layer = BatchNormalization()
        layer.activation = "relu"
        layer.nOut = layer.nIn = 4
        import jax

        params, state = layer.initialize(jax.random.key(0),
                                         _FakeRnnInput(4), jnp.float32)
        x = jnp.asarray(np.random.RandomState(2).randn(
            3, 4, 6).astype("float32"))
        y_f, st_f = layer.forward(params, state, x, True, None)
        with at.applied({"bn_epilogue": "unfused"}):
            y_u, st_u = layer.forward(params, state, x, True, None)
        np.testing.assert_array_equal(np.asarray(y_f), np.asarray(y_u))
        for k in ("mean", "var"):
            np.testing.assert_array_equal(np.asarray(st_f[k]),
                                          np.asarray(st_u[k]))


class _FakeRnnInput:
    """Minimal InputType stand-in for layer.initialize (RNN kind)."""

    def __init__(self, size):
        from deeplearning4j_tpu.nn.conf.inputs import InputType

        self.kind = InputType.RNN
        self.size = size


@pytest.mark.slow
class TestFullLeNetSweep:
    def test_lenet_sweep_finds_indices(self, tmp_path):
        """The banked winner table (BENCH autotune leg / the tier-1
        tuned-ceiling gate's pinned knobs): a full-registry sweep of
        the LeNet b64 attribution subject adopts maxpool_bwd=indices
        and nothing else on XLA:CPU, cutting attributed bytes >= 40%."""
        st = at.TuningStore(str(tmp_path))
        res = at.autotune_subject("lenet", store_=st)
        assert res.knobs["maxpool_bwd"] == "indices"
        changed = {p["knob"] for p in res.per_knob
                   if p["verdict"] == "adopted"}
        assert changed == {"maxpool_bwd"}
        assert res.tuned_bytes <= res.baseline_bytes * 0.6
